"""paddle_tpu.serving.gateway: the multi-tenant front door (ISSUE 8) —
replica router (least-outstanding-work + bounded prefix-cache affinity,
crash-loop ejection with journaled re-route, respawn with backoff,
scale-down through drain), tenant quotas (token bucket / concurrency /
weighted fair share, retriable sheds with retry-after), and the HTTP/SSE
streaming gateway (endpoints, 429/503 error taxonomy, SIGTERM drain).

Pools that get ejected, drained, or scaled build their own instances —
like the drain tests in test_serving.py, a drained pool refuses admissions
forever. Tenancy gates are unit-tested without any engine (pure policy).
Heavier load/fairness runs live in ``benches/bench_serving.py --gateway``;
a miniature is here under the ``slow`` marker.
"""
import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import compile_cache, resilience
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (
    ReplicaPool,
    RequestState,
    ServingAPI,
    TenantConfig,
    TenantManager,
    telemetry,
)
from paddle_tpu.serving import metrics as serving_metrics
from paddle_tpu.serving.gateway import Gateway

pytestmark = [pytest.mark.serving, pytest.mark.gateway]

MAX_LEN = 64
POOL_KW = dict(num_slots=4, kv_block_size=8, max_model_len=MAX_LEN)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def pool(model):
    """Shared 2-replica foreground pool for tests that neither drain nor
    eject (those build their own — a drained pool refuses admissions)."""
    p = ReplicaPool(model, replicas=2, **POOL_KW)
    yield p
    p.close()


def _prompt(rng, n):
    return rng.integers(0, 1024, (n,), dtype=np.int32)


def _ref(model, prompt, max_new, stop=None):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=max_new, stop_token_id=stop)
    return np.asarray(out._data)[0]


def _kill_decode(replica):
    """Make one replica's engine die on every decode step: the supervisor
    rebuilds+replays until the crash-loop breaker opens, which is exactly
    the state the router's health policy keys on."""
    def dying():
        raise resilience.ServingDeviceError("injected: replica chip pulled")

    replica.api.engine.decode_step = dying


# ---------------------------------------------------------------- routing


def test_routing_least_outstanding(pool, model):
    """Without pumping, successive submissions alternate replicas (each
    submit raises the outstanding count the next routing decision sees),
    and everything completes with generate() parity."""
    rng = np.random.default_rng(1)
    prompts = [_prompt(rng, n) for n in (5, 7, 6, 9)]
    rrs = [pool.submit(p, max_new_tokens=4, tenant="route")
           for p in prompts]
    placed = [rr._replica_idx for rr in rrs]
    assert placed.count(0) == 2 and placed.count(1) == 2, placed
    pool.run_until_idle()
    for p, rr in zip(prompts, rrs):
        assert rr.state == RequestState.FINISHED
        np.testing.assert_array_equal(rr.output_ids(), _ref(model, p, 4))


def test_streaming_through_pool(pool, model):
    rng = np.random.default_rng(2)
    p = _prompt(rng, 6)
    rr = pool.submit(p, max_new_tokens=5, tenant="route")
    toks = list(pool.stream(rr))
    assert rr.state == RequestState.FINISHED
    np.testing.assert_array_equal(np.concatenate([p, toks]),
                                  _ref(model, p, 5))


def test_api_submit_journal_resumes_token_for_token(model):
    """The router's re-queue primitive: ``ServingAPI.submit(journal=...)``
    resumes a partial stream exactly where it left off — only NEW tokens
    are streamed, and the journal counts toward the budget."""
    api = ServingAPI(model, **POOL_KW)
    rng = np.random.default_rng(3)
    p = _prompt(rng, 7)
    ref = _ref(model, p, 8)
    journal = [int(t) for t in ref[7:10]]  # first 3 generated tokens
    req = api.submit(p, max_new_tokens=8, journal=journal)
    streamed = []
    for tok in api.stream(req):
        streamed.append(tok)
    np.testing.assert_array_equal(req.output_ids(), ref)
    np.testing.assert_array_equal(streamed, ref[10:])  # journal not re-sent
    with pytest.raises(ValueError):
        api.submit(p, max_new_tokens=3, journal=[1, 2, 3])  # exhausted
    api.close()


def test_cache_affinity_bounded(model):
    """A replica whose radix tree holds the prompt's prefix wins routing
    while its load is within the slack; past the slack the cold
    least-loaded replica wins — warm traffic cannot pile up unboundedly."""
    pool = ReplicaPool(model, replicas=2, prefix_cache=True,
                       affinity_slack=1, **POOL_KW)
    try:
        rng = np.random.default_rng(4)
        sysp = _prompt(rng, 16)  # two full 8-token blocks to share

        def with_tail(n):
            return np.concatenate([sysp, _prompt(rng, n)])

        warm = pool.submit(with_tail(3), max_new_tokens=2, tenant="warm")
        assert warm._replica_idx == 0  # empty pool: least-loaded is idx 0
        pool.run_until_idle()  # replica 0's tree now holds the prefix
        a0 = serving_metrics.stats().get("gateway.affinity_routes", 0)
        cold = pool.submit(_prompt(rng, 5), max_new_tokens=2, tenant="cold")
        assert cold._replica_idx == 0  # both idle: (load, idx) order
        # replica 0 is busier (1 outstanding) but warm and within slack=1
        w2 = pool.submit(with_tail(4), max_new_tokens=2, tenant="warm")
        assert w2._replica_idx == 0
        assert serving_metrics.stats()["gateway.affinity_routes"] == a0 + 1
        # now replica 0 holds 2 outstanding: past the slack, the warm
        # preference must NOT starve the cold replica's capacity
        w3 = pool.submit(with_tail(5), max_new_tokens=2, tenant="warm")
        assert w3._replica_idx == 1
        pool.run_until_idle()
        assert all(r.state == RequestState.FINISHED
                   for r in (warm, cold, w2, w3))
    finally:
        pool.close()


# ---------------------------------------------------------------- tenancy


def test_token_bucket_shed_is_retriable():
    tm = TenantManager()
    tm.configure(TenantConfig("t", rate=10.0, burst=20.0))
    tm.admit("t", 16)  # burst covers it
    with pytest.raises(resilience.QuotaExceededError) as ei:
        tm.admit("t", 16)  # bucket holds 4 < 16
    assert ei.value.retry_after > 0
    assert ei.value.tenant == "t"
    # refill at 10 tok/s: after the hinted wait the same request admits
    state = tm._tenants["t"]
    state.refilled_at -= ei.value.retry_after + 0.01
    cfg = tm.admit("t", 16)
    assert cfg.priority == 0
    stats = tm.stats()["t"]
    assert stats["admitted"] == 2 and stats["shed"] == 1


def test_concurrency_quota_and_release():
    tm = TenantManager()
    tm.configure(TenantConfig("c", max_concurrency=2))
    tm.admit("c", 4)
    tm.admit("c", 4)
    with pytest.raises(resilience.QuotaExceededError):
        tm.admit("c", 4)
    tm.release("c", tokens_out=4)
    tm.admit("c", 4)  # freed slot admits again
    assert tm.stats()["c"]["inflight"] == 2
    assert tm.stats()["c"]["tokens_out"] == 4


def test_fair_share_sheds_hog_not_compliant():
    """Under overload (outstanding >= 2x slot capacity — slots plus one
    capacity's worth of queued buffering) the tenant holding more than its
    weight-proportional share of that budget is shed; a compliant tenant
    with headroom still admits."""
    tm = TenantManager()
    tm.configure(TenantConfig("hog", weight=1.0))
    tm.configure(TenantConfig("nice", weight=1.0))
    for _ in range(4):
        tm.admit("hog", 4, outstanding=7, capacity=4)  # below 2x: inert
    tm.admit("nice", 4, outstanding=7, capacity=4)
    # overloaded now: hog holds 4 = its half of the 8-deep budget -> shed
    with pytest.raises(resilience.QuotaExceededError) as ei:
        tm.admit("hog", 4, outstanding=8, capacity=4)
    assert ei.value.retry_after > 0
    # nice holds 1 < its share of 4 -> admitted even under overload
    tm.admit("nice", 4, outstanding=8, capacity=4)
    assert tm.stats()["hog"]["shed"] == 1
    assert tm.stats()["nice"]["shed"] == 0


def test_unknown_tenant_materializes_from_flags():
    keep = paddle.get_flags(["gateway_tenant_rate",
                             "gateway_tenant_burst"])
    paddle.set_flags({"gateway_tenant_rate": 8.0,
                      "gateway_tenant_burst": 8.0})
    try:
        tm = TenantManager()
        tm.admit("anon", 8)
        with pytest.raises(resilience.QuotaExceededError):
            tm.admit("anon", 8)
    finally:
        paddle.set_flags(keep)


# ------------------------------------------------------- health / reroute


def test_crash_loop_ejects_and_reroutes_token_for_token(model):
    """A replica whose supervisor escalates to crash-loop is ejected; its
    in-flight stream re-queues onto the healthy replica from its token
    journal and finishes token-for-token identical (PR 5 replay parity,
    one level up)."""
    keep = paddle.get_flags(["serving_max_rebuilds"])
    paddle.set_flags({"serving_max_rebuilds": 1})
    pool = ReplicaPool(model, replicas=2, respawn_backoff=600, **POOL_KW)
    try:
        rng = np.random.default_rng(5)
        p = _prompt(rng, 8)
        ref = _ref(model, p, 8)
        rr = pool.submit(p, max_new_tokens=8, tenant="x")
        victim = pool._replica_at(rr._replica_idx)
        for _ in range(3):  # a few tokens decode before the chip dies
            pool.pump_once()
        assert not rr.finished
        e0 = serving_metrics.stats().get("gateway.ejected", 0)
        _kill_decode(victim)
        out = pool.result(rr, timeout=60)
        np.testing.assert_array_equal(out, ref)
        assert rr.reroutes == 1
        assert len(pool.healthy_replicas()) == 1
        assert not victim.healthy
        assert serving_metrics.stats()["gateway.ejected"] == e0 + 1
        # the ejected replica is out of rotation: new traffic still serves
        rr2 = pool.submit(_prompt(rng, 5), max_new_tokens=3, tenant="x")
        assert rr2._replica_idx != victim.idx
        pool.run_until_idle()
        assert rr2.state == RequestState.FINISHED
        # scale-down with a dead replica in the pool must retire the DEAD
        # one, never the last healthy survivor (regression: the
        # highest-index rule alone removed the survivor and stranded the
        # pool with zero routable replicas)
        pool.scale_to(1)
        assert victim.removed
        assert len(pool.healthy_replicas()) == 1
    finally:
        pool.close()
        paddle.set_flags(keep)


def test_ejected_replica_respawns_after_backoff(model):
    keep = paddle.get_flags(["serving_max_rebuilds"])
    paddle.set_flags({"serving_max_rebuilds": 1})
    pool = ReplicaPool(model, replicas=2, respawn_backoff=0.01, **POOL_KW)
    try:
        rng = np.random.default_rng(6)
        rr = pool.submit(_prompt(rng, 6), max_new_tokens=6, tenant="x")
        victim = pool._replica_at(rr._replica_idx)
        pool.pump_once()
        _kill_decode(victim)
        gen0 = victim.generation
        r0 = serving_metrics.stats().get("gateway.respawned", 0)
        pool.result(rr, timeout=60)
        assert victim.ejections == 1
        time.sleep(0.05)  # past the backoff
        pool.pump_once()  # respawn happens at the next pump/submit
        assert len(pool.healthy_replicas()) == 2
        assert victim.generation == gen0 + 1
        assert serving_metrics.stats()["gateway.respawned"] == r0 + 1
        # the respawned replica serves again
        rr2 = pool.submit(_prompt(rng, 5), max_new_tokens=3, tenant="x")
        pool.run_until_idle()
        assert rr2.state == RequestState.FINISHED
    finally:
        pool.close()
        paddle.set_flags(keep)


def test_cancel_sticks_across_reroute(model):
    """A cancel acknowledged before a crash must not be resurrected by the
    journaled re-route: the gateway handle carries the flag, so the stream
    ends CANCELLED instead of decoding to completion on a fresh replica."""
    keep = paddle.get_flags(["serving_max_rebuilds"])
    paddle.set_flags({"serving_max_rebuilds": 1})
    pool = ReplicaPool(model, replicas=2, respawn_backoff=600, **POOL_KW)
    try:
        rng = np.random.default_rng(11)
        rr = pool.submit(_prompt(rng, 7), max_new_tokens=12, tenant="c")
        pool.pump_once()
        victim = pool._replica_at(rr._replica_idx)
        rr.cancel()
        _kill_decode(victim)  # the cancel races the crash-loop ejection
        with pytest.raises(RuntimeError, match="cancelled"):
            pool.result(rr, timeout=60)
        assert rr.state == RequestState.CANCELLED
        assert rr.reroutes == 0  # never re-decoded on the survivor
    finally:
        pool.close()
        paddle.set_flags(keep)


# ----------------------------------------------------- drain / scale-down


def test_guard_drain_drains_every_replica(model):
    """A requested preemption (SIGTERM stand-in) drains the WHOLE pool:
    in-flight streams on both replicas finish inside the grace budget and
    new submissions shed with the retriable RequestDrainedError."""
    pool = ReplicaPool(model, replicas=2, **POOL_KW)
    guard = resilience.PreemptionGuard(install=False)
    pool.bind_preemption_guard(guard, grace=30.0)
    rng = np.random.default_rng(7)
    rrs = [pool.submit(_prompt(rng, n), max_new_tokens=4, tenant="g")
           for n in (5, 6)]
    assert {rr._replica_idx for rr in rrs} == {0, 1}
    guard.request("test preemption")
    pool.pump_once()  # the guard poll turns into a gateway-wide drain
    assert all(rr.state == RequestState.FINISHED for rr in rrs)
    for rep in pool.replicas():
        assert rep.api._draining
    with pytest.raises(resilience.RequestDrainedError):
        pool.submit(_prompt(rng, 5), max_new_tokens=2, tenant="g")
    pool.close()


def test_scale_down_routes_through_drain_and_reroutes(model):
    """scale_to(1) drains the retiring replica; with a zero grace budget
    its in-flight stream re-routes onto the survivor and finishes
    token-for-token — autoscaling never drops an accepted stream."""
    pool = ReplicaPool(model, replicas=2, **POOL_KW)
    try:
        rng = np.random.default_rng(8)
        prompts = [_prompt(rng, n) for n in (6, 7)]
        refs = [_ref(model, p, 6) for p in prompts]
        rrs = [pool.submit(p, max_new_tokens=6, tenant="s")
               for p in prompts]
        assert {rr._replica_idx for rr in rrs} == {0, 1}
        for _ in range(2):
            pool.pump_once()  # some tokens land on both replicas
        pool.scale_to(1, grace=0.0)
        st = pool.stats()
        assert st["replicas_total"] == 1
        moved = [rr for rr in rrs if rr.reroutes > 0]
        assert moved, "the retiring replica's stream must have re-routed"
        pool.run_until_idle()
        for rr, ref in zip(rrs, refs):
            assert rr.state == RequestState.FINISHED
            np.testing.assert_array_equal(rr.output_ids(), ref)
        with pytest.raises(ValueError):
            pool.scale_to(0)
    finally:
        pool.close()


def test_atexit_drain_hook_is_idempotent_with_close(model):
    """ISSUE 8 satellite: the atexit hook next to ``_live_apis`` drains
    every live API with zero grace, and an explicit close() before/after
    is a no-op — interpreter shutdown can never strand a pump thread."""
    from paddle_tpu.serving import api as api_mod

    api = ServingAPI(model, **POOL_KW)
    req = api.submit(np.arange(1, 6, dtype=np.int32), max_new_tokens=4)
    api_mod._drain_at_exit()  # what interpreter shutdown runs
    assert api._draining
    assert req.finished  # zero grace: failed retriably, done_event set
    assert isinstance(req.error, resilience.RequestDrainedError)
    api.close()   # idempotent after the hook
    api_mod._drain_at_exit()  # and the hook after close() is a no-op
    assert api._closed


# ------------------------------------------------------------------- HTTP


def test_http_sse_round_trip(model):
    """Loopback front door: submit + SSE stream returns generate()-parity
    tokens; health/stats/cancel endpoints respond; quota shed maps to 429
    with Retry-After; unknown ids 404."""
    tm = TenantManager()
    tm.configure(TenantConfig("metered", rate=6.0, burst=6.0))
    pool = ReplicaPool(model, replicas=2, tenants=tm, background=True,
                       **POOL_KW)
    gw = Gateway(pool, port=0).start()
    base = f"http://127.0.0.1:{gw.port}"
    try:
        health = json.load(urllib.request.urlopen(base + "/healthz",
                                                  timeout=30))
        assert health == {"status": "ok", "replicas_healthy": 2,
                          "replicas_total": 2}
        rng = np.random.default_rng(9)
        p = _prompt(rng, 6)
        ref = _ref(model, p, 6)
        body = json.dumps({"prompt": p.tolist(), "max_new_tokens": 6,
                           "tenant": "free"}).encode()
        toks, done = [], None
        req = urllib.request.Request(base + "/v1/stream", data=body,
                                     method="POST")
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.headers["Content-Type"] == "text/event-stream"
            event = None
            for line in resp:
                line = line.decode().strip()
                if line.startswith("event:"):
                    event = line.split(":", 1)[1].strip()
                elif line.startswith("data:"):
                    d = json.loads(line.split(":", 1)[1])
                    if event == "done":
                        done = d
                    else:
                        toks.append(d["token"])
                    event = None
        np.testing.assert_array_equal(np.concatenate([p, toks]), ref)
        assert done["state"] == "FINISHED" and done["tokens"] == 6

        # submit-then-stream by id (the async path)
        sub = json.load(urllib.request.urlopen(urllib.request.Request(
            base + "/v1/submit", data=body, method="POST"), timeout=60))
        res = json.load(urllib.request.urlopen(
            base + f"/v1/result/{sub['request_id']}?timeout=60",
            timeout=120))
        np.testing.assert_array_equal(res["output_ids"], ref)

        # tenant rate shed -> 429 + Retry-After (retriable taxonomy)
        mbody = json.dumps({"prompt": p.tolist(), "max_new_tokens": 6,
                            "tenant": "metered"}).encode()
        urllib.request.urlopen(urllib.request.Request(
            base + "/v1/submit", data=mbody, method="POST"), timeout=60)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/submit", data=mbody, method="POST"), timeout=60)
        assert ei.value.code == 429
        assert float(ei.value.headers["Retry-After"]) > 0
        assert json.load(ei.value)["retriable"] is True

        # 404 taxonomy + cancel endpoint + stats
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/v1/stream/nope", timeout=30)
        assert ei.value.code == 404
        c = json.load(urllib.request.urlopen(urllib.request.Request(
            base + f"/v1/cancel/{sub['request_id']}", method="POST"),
            timeout=30))
        assert c["cancelled"] is True
        stats = json.load(urllib.request.urlopen(base + "/v1/stats",
                                                 timeout=30))
        assert stats["pool"]["replicas_healthy"] == 2
        assert "metered" in stats["pool"]["tenants"]
        assert stats["serving"].get("gateway.routed", 0) >= 3
    finally:
        gw.close()
    # closed gateway reports unhealthy through the pool it drained
    assert pool._draining or pool._closed


def test_http_drain_maps_to_503(model):
    pool = ReplicaPool(model, replicas=1, background=True, **POOL_KW)
    gw = Gateway(pool, port=0).start()
    base = f"http://127.0.0.1:{gw.port}"
    try:
        pool.drain(grace=0.0)
        body = json.dumps({"prompt": [1, 2, 3],
                           "max_new_tokens": 2}).encode()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/submit", data=body, method="POST"), timeout=30)
        assert ei.value.code == 503
        assert float(ei.value.headers["Retry-After"]) > 0
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/healthz", timeout=30)
        assert ei.value.code == 503
    finally:
        gw.close()


# ------------------------------------------------- observability (ISSUE 17)

_COMPILE_KEYS = ("serving.decode_compiles", "serving.prefill_compiles",
                 "serving.cow_compiles", "serving.restore_compiles")

_PROM_LINE = re.compile(
    r"^[a-zA-Z_][a-zA-Z0-9_]*(\{[^{}]*\})? -?\d+(\.\d+)?([eE][+-]?\d+)?$")


def test_http_metrics_scrape_concurrent_with_sse_under_churn(model):
    """``GET /v1/metrics`` scraped in a loop while SSE streams decode:
    every scrape is valid Prometheus text exposition, the scrapes cause
    ZERO serving compiles (the export plane reads host-side counters —
    it must never touch a traced region), and ``/v1/trace/<request_id>``
    serves the finished request's span timeline over HTTP."""
    keep = paddle.get_flags(["serving_telemetry"])
    paddle.set_flags({"serving_telemetry": True})
    telemetry.reset_tracelog()
    pool = ReplicaPool(model, replicas=2, background=True, **POOL_KW)
    gw = Gateway(pool, port=0).start()
    base = f"http://127.0.0.1:{gw.port}"
    try:
        rng = np.random.default_rng(21)
        # warm both replicas at the churn shape so the scraped window is
        # compile-free (same prompt length -> same prefill bucket)
        warm = [pool.submit(_prompt(rng, 6), max_new_tokens=4, tenant="m")
                for _ in range(4)]
        for rr in warm:
            pool.result(rr, timeout=60)
        cc0 = compile_cache.stats()

        scrapes, errors = [], []
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                try:
                    with urllib.request.urlopen(base + "/v1/metrics",
                                                timeout=30) as resp:
                        ctype = resp.headers["Content-Type"]
                        assert ctype.startswith(
                            "text/plain; version=0.0.4"), ctype
                        scrapes.append(resp.read().decode())
                except Exception as e:  # noqa: BLE001 — surfaced below
                    errors.append(e)
                    return
                time.sleep(0.002)

        th = threading.Thread(target=scraper, daemon=True)
        th.start()
        body = json.dumps({"prompt": _prompt(rng, 6).tolist(),
                           "max_new_tokens": 5, "tenant": "m"}).encode()
        for _ in range(4):  # churn: live SSE streams under the scraper
            req = urllib.request.Request(base + "/v1/stream", data=body,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=120) as resp:
                for _line in resp:
                    pass
        # one request by id so /v1/trace has a finished timeline to serve
        sub = json.load(urllib.request.urlopen(urllib.request.Request(
            base + "/v1/submit", data=body, method="POST"), timeout=60))
        json.load(urllib.request.urlopen(
            base + f"/v1/result/{sub['request_id']}?timeout=60",
            timeout=120))
        stop.set()
        th.join(timeout=30)
        assert not errors, errors[0]
        assert scrapes  # the scraper did overlap the streams

        cc1 = compile_cache.stats()
        assert sum(cc1.get(k, 0) - cc0.get(k, 0)
                   for k in _COMPILE_KEYS) == 0

        last = scrapes[-1]
        for line in last.splitlines():
            if not line or line.startswith("#"):
                continue
            assert _PROM_LINE.match(line) or "+Inf" in line, line
        assert "paddle_serving_tokens_generated" in last
        assert "paddle_latency_ttft_seconds_bucket" in last
        assert "paddle_gateway_replica_outstanding" in last

        tr = json.load(urllib.request.urlopen(
            base + f"/v1/trace/{sub['request_id']}", timeout=30))
        assert tr["enabled"] is True and tr["trace_id"].startswith("t")
        kinds = [e["event"] for e in tr["events"]]
        assert kinds[0] == telemetry.SUBMITTED
        assert telemetry.FIRST_TOKEN in kinds
        assert kinds[-1] == telemetry.FINISHED
        # unknown ids stay a clean 404, not a crash in the export plane
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/v1/trace/nope", timeout=30)
        assert ei.value.code == 404
    finally:
        gw.close()
        paddle.set_flags(keep)
        telemetry.reset_tracelog()


def test_stats_snapshot_consistent_under_concurrent_eject(model):
    """Regression: the router's ``stats()`` snapshot is taken under ONE
    lock — scrapers hammering it while replicas are ejected and respawned
    must never observe a torn picture where the healthy/capacity headline
    disagrees with the per-replica rows it was (supposedly) derived from.
    (The old implementation read ``healthy_replicas()`` outside the rows
    pass; an eject between the two reads skewed ``capacity_slots``.)"""
    keep = paddle.get_flags(["serving_max_rebuilds"])
    paddle.set_flags({"serving_max_rebuilds": 1})
    pool = ReplicaPool(model, replicas=2, respawn_backoff=0.01, **POOL_KW)
    torn = []
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            st = pool.stats()
            routable = sum(1 for row in st["replicas"]
                           if row["healthy"] and not row["draining"]
                           and not row["removed"])
            if st["replicas_healthy"] != routable:
                torn.append(("replicas_healthy", st))
                return
            if st["capacity_slots"] != routable * POOL_KW["num_slots"]:
                torn.append(("capacity_slots", st))
                return
            time.sleep(0.0005)

    threads = [threading.Thread(target=scraper, daemon=True)
               for _ in range(3)]
    try:
        for th in threads:
            th.start()
        rng = np.random.default_rng(22)
        for _cycle in range(3):  # eject -> reroute -> respawn, repeatedly
            rr = pool.submit(_prompt(rng, 6), max_new_tokens=6, tenant="s")
            victim = pool._replica_at(rr._replica_idx)
            pool.pump_once()
            _kill_decode(victim)
            pool.result(rr, timeout=60)
            time.sleep(0.05)  # past the respawn backoff
            pool.pump_once()  # respawn happens at the next pump
        assert len(pool.healthy_replicas()) == 2
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=30)
        pool.close()
        paddle.set_flags(keep)
    assert not torn, torn[0]


@pytest.mark.chaos
def test_chaos_trace_timeline_survives_eject_and_reroute(model):
    """ISSUE 17 chaos acceptance: a serving_device eject -> re-route ->
    journal replay keeps ONE trace_id whose span timeline is complete and
    ordered — exactly one SUBMITTED (the gateway mints, everyone
    downstream passes the id along), exactly one FIRST_TOKEN (the
    journal-seeded resubmit must not re-record it), a REROUTED span at
    the fail-over followed by QUEUED/ADMITTED on the survivor, FINISHED
    last, ``seq`` strictly increasing throughout."""
    keep = paddle.get_flags(["serving_max_rebuilds", "serving_telemetry"])
    paddle.set_flags({"serving_max_rebuilds": 1, "serving_telemetry": True})
    telemetry.reset_tracelog()
    pool = ReplicaPool(model, replicas=2, respawn_backoff=600, **POOL_KW)
    try:
        rng = np.random.default_rng(23)
        p = _prompt(rng, 8)
        ref = _ref(model, p, 8)
        rr = pool.submit(p, max_new_tokens=8, tenant="chaos")
        victim = pool._replica_at(rr._replica_idx)
        for _ in range(3):  # a few tokens land before the chip dies
            pool.pump_once()
        assert not rr.finished
        _kill_decode(victim)
        out = pool.result(rr, timeout=60)
        np.testing.assert_array_equal(out, ref)
        assert rr.reroutes == 1

        events = telemetry.trace(rr.trace_id)
        kinds = [e["event"] for e in events]
        assert kinds.count(telemetry.SUBMITTED) == 1
        assert kinds.count(telemetry.FIRST_TOKEN) == 1
        assert kinds.count(telemetry.REROUTED) == 1
        assert kinds.count(telemetry.FINISHED) == 1
        assert kinds[-1] == telemetry.FINISHED
        # the survivor re-admits from the journal AFTER the re-route
        after = kinds[kinds.index(telemetry.REROUTED):]
        assert telemetry.QUEUED in after and telemetry.ADMITTED in after
        # one contiguous, strictly ordered timeline — no interleaved or
        # duplicated sequence numbers across the replica hop
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        assert all(e["trace_id"] == rr.trace_id for e in events)
        # wall clocks are monotone too (same host; ties allowed)
        ts = [e["ts"] for e in events]
        assert all(b >= a for a, b in zip(ts, ts[1:]))
    finally:
        pool.close()
        paddle.set_flags(keep)
        telemetry.reset_tracelog()


# ----------------------------------------------------------- load (slow)


@pytest.mark.slow
def test_tenant_mix_under_overload_completes_accepted(model):
    """Miniature of the gateway bench's acceptance: three tenants, one
    offering well past its rate quota — every ACCEPTED stream completes,
    the noisy tenant's excess is shed at its bucket, and the unmetered
    compliant tenants are never shed. (The weighted fair-share gate — which
    by design also binds compliant tenants once the pool is genuinely
    overloaded — is unit-tested separately; it is off here so the test is
    deterministic about WHO sheds.)"""
    keep = paddle.get_flags(["gateway_fair_share"])
    paddle.set_flags({"gateway_fair_share": False})
    tm = TenantManager()
    tm.configure(TenantConfig("noisy", rate=12.0, burst=12.0, weight=1.0))
    tm.configure(TenantConfig("calm1", weight=1.0))
    tm.configure(TenantConfig("calm2", weight=1.0))
    pool = ReplicaPool(model, replicas=2, tenants=tm, **POOL_KW)
    try:
        rng = np.random.default_rng(10)
        accepted, shed = [], 0
        for i in range(24):
            tenant = ("noisy", "calm1", "calm2")[i % 3]
            try:
                accepted.append(pool.submit(_prompt(rng, 5 + i % 4),
                                            max_new_tokens=6,
                                            tenant=tenant))
            except resilience.QuotaExceededError as e:
                assert e.tenant == "noisy"  # only the hog is shed
                shed += 1
            pool.pump_once()
        assert shed > 0
        pool.run_until_idle()
        assert all(rr.state == RequestState.FINISHED for rr in accepted)
        st = tm.stats()
        assert st["noisy"]["shed"] == shed
        assert st["calm1"]["shed"] == 0 and st["calm2"]["shed"] == 0
        assert st["calm1"]["tokens_out"] > 0
    finally:
        pool.close()
        paddle.set_flags(keep)

"""Crash-safe gateway (ISSUE 20): the write-ahead request log
(``serving.gateway.wal``), restart recovery, and the exactly-once client
stream contract.

Layers, cheapest first: WAL record framing round-trip + torn-tail
truncation and segment rotation/compaction as pure file-format units (no
engine); in-process crash recovery with token parity for greedy /
seeded-sampled / constrained streams (a foreground pool abandoned
WITHOUT close is the crash — same process, fresh incarnation on the same
directory); the HTTP exactly-once surface across a restart (409 on a
WAL-live duplicate id, cached results for terminal ids, ``?offset=``
stream resume); the ``/healthz`` readiness-vs-``/livez`` liveness split
while replay is in flight; the satellite-2 shutdown ordering regression
(final WAL fsync strictly before worker reaping); and the real chaos
e2e — ``wal_harness`` subprocess SIGKILL'd mid-stream, a second
incarnation on the same WAL dir, token-for-token resumption with frozen
compile counters.

The in-process reference pools double as the ``FLAGS_gateway_wal=0``
default-path check: every parity assertion compares a WAL'd stream
against a WAL-less pool's output.
"""
import json
import os
import signal
import struct
import subprocess
import sys
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (
    ReplicaPool,
    RequestState,
    SamplingParams,
    TrieConstraint,
    telemetry,
)
from paddle_tpu.serving import metrics as serving_metrics
from paddle_tpu.serving.gateway import Gateway, GatewayWAL, ProcessReplicaPool

pytestmark = [pytest.mark.serving, pytest.mark.gateway]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MAX_LEN = 64
POOL_KW = dict(num_slots=4, kv_block_size=8, max_model_len=MAX_LEN)
CHOICES = [[5, 6, 7], [5, 9]]


def worker_model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return worker_model()


def _prompt(rng, n):
    return rng.integers(0, 1024, (n,), dtype=np.int32)


def _ref(model, prompt, max_new, stop=None):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=max_new, stop_token_id=stop)
    return np.asarray(out._data)[0]


def _rr(rid, prompt=(1, 2, 3), mnt=8):
    """A minimal stand-in for ``RoutedRequest`` carrying exactly the
    attributes ``GatewayWAL.accepted`` journals."""
    return types.SimpleNamespace(
        request_id=rid, tenant="default", prompt=list(prompt),
        max_new_tokens=mnt, stop_token_id=None, priority=1, adapter=0,
        sampling=None, trace_id=f"trace-{rid}")


def _read_sse(url, timeout=180):
    toks, done = [], None
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        event = None
        for line in resp:
            line = line.decode().strip()
            if line.startswith("event:"):
                event = line.split(":", 1)[1].strip()
            elif line.startswith("data:"):
                d = json.loads(line.split(":", 1)[1])
                if event == "done":
                    done = d
                else:
                    toks.append(d["token"])
                event = None
    return toks, done


def _wait_ready(base, deadline_s=120):
    """Poll ``/healthz`` until it reports ok; returns every status string
    observed on the way (503 bodies included — readiness is data)."""
    seen = []
    deadline = time.time() + deadline_s
    while True:
        try:
            h = json.load(urllib.request.urlopen(base + "/healthz",
                                                 timeout=10))
        except urllib.error.HTTPError as e:
            h = json.load(e)
        seen.append(h["status"])
        if h["status"] == "ok":
            return seen
        assert time.time() < deadline, f"never became ready: {seen[-5:]}"
        time.sleep(0.02)


# --------------------------------------------------------- WAL file format


def test_wal_roundtrip_and_torn_tail(tmp_path):
    """Append → crash (no close) → replay folds records back; a torn tail
    (half-written frame) truncates replay at the last good record and
    bumps the torn-tail counter instead of raising."""
    d = str(tmp_path / "wal")
    w = GatewayWAL(d)
    w.accepted(_rr("r1"), {"choices": CHOICES, "stop_token_id": 3})
    w.emitted("r1", [10, 11])
    w.moved("r1", "HANDOFF")
    w.accepted(_rr("r2"))
    w.emitted("r2", [20])
    w.terminal("r2", "FINISHED", [30], [20, 30])
    w.commit()
    # crash: the process dies here — no close(), no final fsync beyond
    # the committed batch

    w2 = GatewayWAL(d)
    rec = w2.recover()
    assert [e["rid"] for e in rec["live"]] == ["r1"]
    live = rec["live"][0]
    assert live["toks"] == [10, 11]
    assert live["phase"] == "decode"          # the HANDOFF move replayed
    assert live["prompt"] == [1, 2, 3]
    assert live["cspec"] == {"choices": CHOICES, "stop_token_id": 3}
    assert live["tid"] == "trace-r1"
    assert rec["results"]["r2"] == {"state": "FINISHED",
                                    "tokens": [20, 30]}
    # recover() is one-shot: the state was handed to exactly one pool
    assert w2.recover()["live"] == []

    # torn tail: a frame whose header promises more body than was ever
    # written (the classic power-cut shape DiskTier also defends against)
    with open(os.path.join(d, "wal-00000000.log"), "ab") as f:
        f.write(struct.pack("<II", 40, 0) + b"short")
    t0 = serving_metrics.stats().get("wal.torn_tail", 0)
    rec3 = GatewayWAL(d).recover()
    assert [e["rid"] for e in rec3["live"]] == ["r1"]
    assert rec3["live"][0]["toks"] == [10, 11]
    assert serving_metrics.stats().get("wal.torn_tail", 0) == t0 + 1


def test_wal_rotation_and_compaction_carry_forward(tmp_path):
    """With a 1-byte segment budget every commit rotates; a sealed
    segment whose every stream is terminal is deleted with its results
    carried forward, and a segment holding a live stream survives."""
    d = str(tmp_path / "wal")
    m0 = serving_metrics.stats()
    w = GatewayWAL(d, segment_bytes=1, result_cap=8)
    w.accepted(_rr("c1"))
    w.emitted("c1", [1, 2])
    w.terminal("c1", "FINISHED", [], [1, 2])
    w.commit()  # seals segment 0; fully terminal → carried + deleted
    assert not os.path.exists(os.path.join(d, "wal-00000000.log"))

    w.accepted(_rr("c2"))
    w.commit()  # seals the carry segment; c2 is live → it must survive
    assert len([n for n in os.listdir(d) if n.startswith("wal-")]) == 2

    w.terminal("c2", "FINISHED", [7], [7])
    w.commit()  # everything terminal: only the active segment remains
    assert len([n for n in os.listdir(d) if n.startswith("wal-")]) == 1
    m1 = serving_metrics.stats()
    assert m1.get("wal.rotations", 0) > m0.get("wal.rotations", 0)
    assert m1.get("wal.compactions", 0) >= m0.get("wal.compactions", 0) + 2
    assert m1.get("wal.carried", 0) > m0.get("wal.carried", 0)
    assert w.stats()["segments"] == 1
    w.close()

    # the carried summaries replay: no live resurrections, results intact
    rec = GatewayWAL(d).recover()
    assert rec["live"] == []
    assert rec["results"]["c1"]["tokens"] == [1, 2]
    assert rec["results"]["c2"]["tokens"] == [7]


def test_wal_tombstone_replay_and_terminal_gc(tmp_path):
    """A terminal request whose result aged out of the bounded cache
    compacts to a token-free tombstone while later segments still hold
    its records; replaying that tombstone must come up clean (terminal,
    no result, no resurrection), not crash recovery on ``toks: None``.
    And a rid compacted away with NO surviving records drops out of the
    terminal set instead of leaking for the life of the process."""
    d = str(tmp_path / "wal")
    w = GatewayWAL(d, segment_bytes=1, result_cap=1)
    # x spans three segments (A | E | T in seg 0/1/2) so compacting the
    # older ones needs a tombstone; y evicts x's result from the 1-deep
    # cache before compaction runs, forcing the toks-free T form
    w.accepted(_rr("x"))
    w.commit()                    # seals seg0 (x live: survives intact)
    w.emitted("x", [1])
    w.commit()                    # seals seg1 (x still live)
    w.terminal("x", "FINISHED", [2], [1, 2])
    w.accepted(_rr("y"))
    w.terminal("y", "FINISHED", [3], [3])   # cap 1: x's result evicted
    w.commit()   # everything terminal: seg0..2 compact via tombstones
    assert not os.path.exists(os.path.join(d, "wal-00000000.log"))
    w.close()

    rec = GatewayWAL(d).recover()   # must not raise on the tombstone
    assert rec["live"] == []                 # x never resurrects...
    assert "x" not in rec["results"]         # ...and stays forgotten
    assert rec["results"]["y"]["tokens"] == [3]

    # terminal-set GC: z lives and dies entirely inside seg0, its result
    # is evicted before compaction — no carry, no surviving records, so
    # terminal membership has nothing left to guard and is discarded
    d2 = str(tmp_path / "wal2")
    w2 = GatewayWAL(d2, segment_bytes=1, result_cap=1)
    w2.accepted(_rr("z"))
    w2.terminal("z", "FINISHED", [1], [1])
    w2.accepted(_rr("q"))
    w2.terminal("q", "FINISHED", [2], [2])   # evicts z's result
    w2.commit()   # seg0 compacts: q carries forward (R), z drops whole
    assert w2.stats()["terminal"] == 1       # q only; z not leaked
    w2.close()


def test_wal_compaction_carry_durable_before_unlink(tmp_path):
    """Compaction fsyncs its carry-forwards into the active segment
    BEFORE unlinking the compacted one: a crash right after the unlink
    (no close, no further commit) must still replay the carried result —
    an acknowledged ``/v1/result`` can never regress to 404."""
    d = str(tmp_path / "wal")
    w = GatewayWAL(d, segment_bytes=1, result_cap=8)
    w.accepted(_rr("c1"))
    w.terminal("c1", "FINISHED", [1, 2], [1, 2])
    w.commit()   # seals + compacts seg0, carrying c1's result forward
    assert not os.path.exists(os.path.join(d, "wal-00000000.log"))
    # crash here: NO close(), NO later commit — the carry must already
    # be on disk, not sitting in the userspace write buffer
    rec = GatewayWAL(d).recover()
    assert rec["live"] == []
    assert rec["results"]["c1"] == {"state": "FINISHED", "tokens": [1, 2]}


# ------------------------------------------------- in-process recovery


def test_pool_crash_recovery_token_parity(model, tmp_path):
    """The tentpole invariant, in-process: a WAL'd foreground pool
    abandoned mid-decode (no close — the crash) is rebuilt by a fresh
    incarnation on the same directory, and every recovered stream
    (greedy, seeded-sampled, constrained) finishes token-for-token
    identical to a WAL-less reference pool. The journaled trace id keeps
    ONE timeline across the restart, with a RECOVERED span at the seam."""
    keep = paddle.get_flags(["serving_telemetry"])
    paddle.set_flags({"serving_telemetry": True})
    telemetry.reset_tracelog()
    d = str(tmp_path / "wal")
    pool2 = refpool = None
    try:
        rng = np.random.default_rng(11)
        p1, p2, p3 = _prompt(rng, 8), _prompt(rng, 8), _prompt(rng, 5)
        ref1 = _ref(model, p1, 8)

        wal = GatewayWAL(d)
        pool = ReplicaPool(model, replicas=1, wal=wal, **POOL_KW)
        pool.submit(p1, max_new_tokens=8, request_id="r1")
        pool.submit(p2, max_new_tokens=8, request_id="r2",
                    sampling=SamplingParams(temperature=0.8, seed=42))
        pool.submit(p3, max_new_tokens=8, stop_token_id=3, request_id="r3",
                    constraint=TrieConstraint(
                        CHOICES, vocab_size=pool.vocab_size(),
                        stop_token_id=3),
                    constraint_spec={"choices": CHOICES,
                                     "stop_token_id": 3})
        for _ in range(3):
            pool.pump_once()  # partial: every stream is mid-flight
        # crash: abandon the incarnation without close/drain

        # the WAL-off reference (also the FLAGS_gateway_wal=0 default
        # path): same model, same pinned seed, same constraint
        refpool = ReplicaPool(model, replicas=1, **POOL_KW)
        q2 = refpool.submit(p2, max_new_tokens=8,
                            sampling=SamplingParams(temperature=0.8,
                                                    seed=42))
        q3 = refpool.submit(p3, max_new_tokens=8, stop_token_id=3,
                            constraint=TrieConstraint(
                                CHOICES, vocab_size=refpool.vocab_size(),
                                stop_token_id=3))
        refpool.run_until_idle()
        ref2, ref3 = list(q2.tokens()), list(q3.tokens())

        g0 = serving_metrics.stats().get("gateway.recovered", 0)
        pool2 = ReplicaPool(model, replicas=1, wal=GatewayWAL(d), **POOL_KW)
        assert not pool2.recovering  # foreground recovery is inline
        rec = {rr.request_id: rr for rr in pool2.recovered_live()}
        assert set(rec) == {"r1", "r2", "r3"}
        assert serving_metrics.stats().get("gateway.recovered", 0) == g0 + 3
        pool2.run_until_idle()

        assert rec["r1"].state == RequestState.FINISHED
        np.testing.assert_array_equal(
            np.concatenate([p1, rec["r1"].tokens()]), ref1)
        assert list(rec["r2"].tokens()) == ref2
        assert list(rec["r3"].tokens()) == ref3
        assert list(rec["r3"].tokens()) in ([5, 6, 7, 3], [5, 9, 3])

        # one trace, both incarnations: a single SUBMITTED (from the
        # first life), a RECOVERED span at the restart seam, and the
        # journal-seeded resubmit never re-records FIRST_TOKEN
        events = telemetry.trace(rec["r1"].trace_id)
        kinds = [e["event"] for e in events]
        assert kinds.count(telemetry.SUBMITTED) == 1
        assert telemetry.RECOVERED in kinds
        assert kinds.count(telemetry.FIRST_TOKEN) == 1
        assert kinds.index(telemetry.RECOVERED) \
            > kinds.index(telemetry.SUBMITTED)
    finally:
        if refpool is not None:
            refpool.close()
        if pool2 is not None:
            pool2.close()
        paddle.set_flags(keep)
        telemetry.reset_tracelog()


def test_wal_terminal_not_skipped_when_finalized_during_submit(
        model, tmp_path):
    """A stream that finishes — and is swept — in the window between
    routing and the ACCEPTED append must still get its TERMINAL record:
    an A-only log would replay the finished stream as live and re-decode
    it after restart (regression: the sweep's ``_wal_finalize`` checked
    ``_wal_accepted`` before ``submit`` had set it)."""
    d = str(tmp_path / "wal")
    rng = np.random.default_rng(23)
    p = _prompt(rng, 6)
    ref = _ref(model, p, 4)
    pool = ReplicaPool(model, replicas=1, wal=GatewayWAL(d), **POOL_KW)
    orig_route = ReplicaPool._route

    def route_then_sweep(self, rr, journal):
        # deterministic worst case of the race: the stream runs to
        # completion and the sweep finalizes it BEFORE submit's WAL
        # block has appended the ACCEPTED record
        orig_route(self, rr, journal)
        self.run_until_idle()
        assert rr.finished

    ReplicaPool._route = route_then_sweep
    try:
        rr = pool.submit(p, max_new_tokens=4, request_id="early")
    finally:
        ReplicaPool._route = orig_route
    assert rr.state == RequestState.FINISHED
    toks = list(rr.tokens())
    np.testing.assert_array_equal(np.concatenate([p, toks]), ref)
    pool.close()

    rec = GatewayWAL(d).recover()
    assert rec["live"] == []        # the TERMINAL made it into the log
    assert rec["results"]["early"]["state"] == RequestState.FINISHED
    assert rec["results"]["early"]["tokens"] == toks


# ------------------------------------------------ HTTP exactly-once


def test_http_restart_exactly_once(model, tmp_path):
    """The client-visible contract across a restart: a WAL-live id
    resubmitted to the new incarnation is a 409 (never a second decode),
    a terminal id's result is served from the recovered cache with
    ``cached: true``, and ``GET /v1/stream/<id>?offset=N`` resumes the
    recovered stream with no duplicated and no missing token."""
    d = str(tmp_path / "wal")
    rng = np.random.default_rng(17)
    p_done, p_live = _prompt(rng, 6), _prompt(rng, 6)
    ref_done = [int(t) for t in _ref(model, p_done, 6)[6:]]
    ref_live = [int(t) for t in _ref(model, p_live, 48)[6:]]

    pool1 = ReplicaPool(model, replicas=1, wal=GatewayWAL(d), **POOL_KW)
    done_rr = pool1.submit(p_done, max_new_tokens=6, request_id="dup-done")
    pool1.run_until_idle()
    assert done_rr.state == RequestState.FINISHED
    live_rr = pool1.submit(p_live, max_new_tokens=48, request_id="dup-live")
    for _ in range(4):
        pool1.pump_once()
    assert not live_rr.finished
    prefix = [int(t) for t in live_rr.tokens()]
    assert prefix  # the pre-crash client got a real prefix
    # crash: abandon without close

    pool2 = ReplicaPool(model, replicas=1, wal=GatewayWAL(d),
                        background=True, **POOL_KW)
    gw = Gateway(pool2, port=0).start()
    base = f"http://127.0.0.1:{gw.port}"
    try:
        _wait_ready(base)

        # the recovered stream is live again: a duplicate submit is 409
        body = json.dumps({"prompt": p_live.tolist(), "max_new_tokens": 48,
                           "request_id": "dup-live"}).encode()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/submit", data=body, method="POST"), timeout=60)
        assert ei.value.code == 409

        # offset resume: the client skips the prefix it already has and
        # sees exactly the remainder — no dup, no gap
        toks, done = _read_sse(
            base + f"/v1/stream/dup-live?offset={len(prefix)}")
        assert prefix + toks == ref_live
        assert done["state"] == "FINISHED"
        # a full re-read of the finished stream is the whole reference
        toks_all, _ = _read_sse(base + "/v1/stream/dup-live?offset=0")
        assert toks_all == ref_live

        # terminal id from the previous life: the recovered result cache
        res = json.load(urllib.request.urlopen(
            base + "/v1/result/dup-done", timeout=30))
        assert res["cached"] is True
        assert res["state"] == "FINISHED"
        assert res["tokens"] == ref_done
        # resubmitting the terminal id answers from the cache too
        body2 = json.dumps({"prompt": p_done.tolist(),
                            "request_id": "dup-done"}).encode()
        sub = json.load(urllib.request.urlopen(urllib.request.Request(
            base + "/v1/submit", data=body2, method="POST"), timeout=30))
        assert sub["cached"] is True and sub["tokens"] == ref_done
    finally:
        gw.close()


def test_healthz_readiness_split_during_replay(model, tmp_path):
    """Satellite 1: while WAL replay is in flight the gateway is ALIVE
    but not READY — ``/healthz`` 503 with Retry-After and a
    ``recovering`` status, ``/livez`` 200 throughout — and flips to 200
    only once recovery hands routing back."""
    gate = threading.Event()

    class BlockingWAL(GatewayWAL):
        def recover(self):
            gate.wait(30)
            return super().recover()

    pool = ReplicaPool(model, replicas=1, wal=BlockingWAL(
        str(tmp_path / "wal")), background=True, **POOL_KW)
    gw = Gateway(pool, port=0).start()
    base = f"http://127.0.0.1:{gw.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/healthz", timeout=10)
        assert ei.value.code == 503
        assert float(ei.value.headers["Retry-After"]) > 0
        assert json.load(ei.value)["status"] == "recovering"
        # liveness stays green: an orchestrator must NOT restart a
        # gateway that is busy replaying its log
        lv = json.load(urllib.request.urlopen(base + "/livez", timeout=10))
        assert lv["status"] == "alive"

        gate.set()
        seen = _wait_ready(base, 60)
        assert seen[-1] == "ok"
    finally:
        gate.set()
        gw.close()


# -------------------------------------------------- shutdown ordering


def test_close_orders_wal_flush_before_worker_reap(model, tmp_path):
    """Satellite 2 regression: on a clean close the WAL's terminal sweep
    and final fsync land strictly BEFORE the worker processes are
    reaped — a shutdown interleaving the two would journal streams as
    live that the workers already finished. A reopened WAL must replay
    zero live records after a clean close."""
    d = str(tmp_path / "wal")
    wal = GatewayWAL(d)
    pool = ProcessReplicaPool(worker_model, replicas=1, background=True,
                              wal=wal, respawn_backoff=0.5,
                              heartbeat_interval=0.2, heartbeat_misses=5,
                              worker_timeout=10.0, **POOL_KW)
    try:
        rng = np.random.default_rng(19)
        p = _prompt(rng, 6)
        ref = _ref(model, p, 6)
        rr = pool.submit(p, max_new_tokens=6, request_id="w1")
        out = pool.result(rr, timeout=180)
        np.testing.assert_array_equal(out, ref)
    except BaseException:
        pool.close()
        raise

    order = []
    orig_close, orig_reap = wal.close, pool._reap_workers

    def traced_close():
        order.append("wal-close")
        orig_close()

    def traced_reap(*a, **kw):
        order.append("reap")
        return orig_reap(*a, **kw)

    wal.close = traced_close
    pool._reap_workers = traced_reap
    pool.close()
    assert "wal-close" in order and "reap" in order
    assert order.index("wal-close") < order.index("reap")

    rec = GatewayWAL(d).recover()
    assert rec["live"] == []  # a clean shutdown leaves nothing live
    assert rec["results"]["w1"]["state"] == "FINISHED"
    assert rec["results"]["w1"]["tokens"] == [int(t) for t in ref[6:]]


# ------------------------------------------------------- chaos e2e


def _boot_harness(wal_dir):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.serving.gateway.wal_harness",
         "--wal-dir", wal_dir],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        cwd=REPO, env=env, text=True)
    line = proc.stdout.readline()
    assert line, "harness died before announcing its port"
    info = json.loads(line)
    return proc, f"http://127.0.0.1:{info['port']}", info["pid"]


def _kill_proc(proc):
    if proc.poll() is None:
        proc.kill()
    proc.wait(timeout=30)
    if proc.stdout is not None:
        proc.stdout.close()


def test_sigkill_chaos_exactly_once_across_restart(model, tmp_path):
    """THE acceptance chaos: a real gateway process SIGKILL'd mid-stream
    (greedy + seeded-sampled + constrained in flight), a second process
    booted on the same WAL dir, and every accepted stream finishes
    token-for-token identical to an in-process reference — the resumed
    ``?offset=`` client sees no duplicate and no gap, the terminal-id
    retry is served from the cache, and the decode/prefill compile
    counters are FROZEN from the first resumed stream's completion on
    (journal replay reuses every compiled program)."""
    d = str(tmp_path / "wal")
    rng = np.random.default_rng(29)
    pg, ps, pc = _prompt(rng, 6), _prompt(rng, 6), _prompt(rng, 5)

    # references: the harness seeds paddle.seed(0) exactly like
    # worker_model(), so weights (hence streams) match in-process
    ref_g = [int(t) for t in _ref(model, pg, 24)[6:]]
    refpool = ReplicaPool(model, replicas=1, **POOL_KW)
    qs = refpool.submit(ps, max_new_tokens=24,
                        sampling=SamplingParams(temperature=0.9, seed=7))
    qc = refpool.submit(pc, max_new_tokens=8, stop_token_id=3,
                        constraint=TrieConstraint(
                            CHOICES, vocab_size=refpool.vocab_size(),
                            stop_token_id=3))
    refpool.run_until_idle()
    ref_s, ref_c = list(qs.tokens()), list(qc.tokens())
    refpool.close()

    proc1, base1, pid1 = _boot_harness(d)
    seen = []
    try:
        _wait_ready(base1)
        for body in (
                {"prompt": pg.tolist(), "max_new_tokens": 24,
                 "request_id": "cg"},
                {"prompt": ps.tolist(), "max_new_tokens": 24,
                 "temperature": 0.9, "seed": 7, "request_id": "cs"},
                {"prompt": pc.tolist(), "max_new_tokens": 8,
                 "stop_token_id": 3, "choices": CHOICES,
                 "request_id": "cc"}):
            sub = json.load(urllib.request.urlopen(urllib.request.Request(
                base1 + "/v1/submit", data=json.dumps(body).encode(),
                method="POST"), timeout=120))
            assert sub["request_id"] == body["request_id"]
        # stream a few greedy tokens — the pre-crash client's prefix —
        # then pull the plug mid-decode (kill -9: no drain, no atexit)
        try:
            with urllib.request.urlopen(base1 + "/v1/stream/cg",
                                        timeout=120) as resp:
                event = None
                for line in resp:
                    line = line.decode().strip()
                    if line.startswith("event:"):
                        event = line.split(":", 1)[1].strip()
                    elif line.startswith("data:"):
                        dd = json.loads(line.split(":", 1)[1])
                        if event != "done":
                            seen.append(dd["token"])
                        event = None
                    if len(seen) >= 4:
                        break
        except (OSError, urllib.error.URLError):
            pass
        assert len(seen) >= 4 and len(seen) < len(ref_g)
        os.kill(pid1, signal.SIGKILL)
        proc1.wait(timeout=60)
    finally:
        _kill_proc(proc1)

    proc2, base2, pid2 = _boot_harness(d)
    try:
        _wait_ready(base2)
        # resume exactly where the dead connection left the client: the
        # recovered stream replays deterministically, so offset=N is
        # no-dup/no-gap even for tokens that outran the journal's fsync
        toks, done = _read_sse(base2 + f"/v1/stream/cg?offset={len(seen)}")
        assert seen + toks == ref_g
        assert done["state"] == "FINISHED"
        st1 = json.load(urllib.request.urlopen(base2 + "/v1/stats",
                                               timeout=30))

        rs = json.load(urllib.request.urlopen(
            base2 + "/v1/result/cs?timeout=120", timeout=150))
        assert rs["tokens"] == ref_s
        rc = json.load(urllib.request.urlopen(
            base2 + "/v1/result/cc?timeout=120", timeout=150))
        assert rc["tokens"] == ref_c
        assert rc["tokens"] in ([5, 6, 7, 3], [5, 9, 3])

        # compile counters froze once the first resumed stream finished:
        # recovery re-used every compiled program for the rest
        st2 = json.load(urllib.request.urlopen(base2 + "/v1/stats",
                                               timeout=30))
        for key in ("serving.decode_compiles", "serving.prefill_compiles"):
            assert st2["compile"].get(key, 0) == st1["compile"].get(key, 0)
        assert st2["pool"]["wal"]["results_cached"] >= 3

        # let the background sweep commit the terminal records before
        # this incarnation dies too
        time.sleep(0.3)
    finally:
        _kill_proc(proc2)

    # a THIRD incarnation replays only terminal records: the retried id
    # is served from the recovered result cache with ZERO decode work
    proc3, base3, _pid3 = _boot_harness(d)
    try:
        _wait_ready(base3)
        sub = json.load(urllib.request.urlopen(urllib.request.Request(
            base3 + "/v1/submit",
            data=json.dumps({"prompt": pg.tolist(),
                             "request_id": "cg"}).encode(),
            method="POST"), timeout=30))
        assert sub["cached"] is True and sub["tokens"] == ref_g
        res = json.load(urllib.request.urlopen(
            base3 + "/v1/result/cs", timeout=30))
        assert res["cached"] is True and res["tokens"] == ref_s
        st3 = json.load(urllib.request.urlopen(base3 + "/v1/stats",
                                               timeout=30))
        assert st3["compile"].get("serving.decode_compiles", 0) == 0
    finally:
        _kill_proc(proc3)

"""Autoregressive decoding: compiled GPT.generate + nn transformer KV cache
(ref:python/paddle/nn/layer/transformer.py cache contract)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


def test_generate_greedy_matches_stepwise_argmax(model):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 1024, (2, 5), dtype=np.int32)
    out = model.generate(paddle.to_tensor(ids), max_new_tokens=3)
    o = np.asarray(out.numpy())
    assert o.shape == (2, 8)
    np.testing.assert_array_equal(o[:, :5], ids)
    # first generated token == argmax of the model's own next-token logits
    logits = model(paddle.to_tensor(ids)).numpy()
    np.testing.assert_array_equal(o[:, 5], np.argmax(logits[:, -1], -1))
    # deterministic
    o2 = np.asarray(model.generate(paddle.to_tensor(ids),
                                   max_new_tokens=3).numpy())
    np.testing.assert_array_equal(o, o2)


def test_generate_sampling_and_eos(model):
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 1024, (1, 4), dtype=np.int32)
    s1 = np.asarray(model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                                   do_sample=True, top_k=5, seed=3).numpy())
    s2 = np.asarray(model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                                   do_sample=True, top_k=5, seed=3).numpy())
    np.testing.assert_array_equal(s1, s2)  # seeded sampling is reproducible
    # eos forcing: whatever greedy emits first, using it as eos fills the tail
    g = np.asarray(model.generate(paddle.to_tensor(ids),
                                  max_new_tokens=3).numpy())
    eos = int(g[0, 4])
    out = np.asarray(model.generate(paddle.to_tensor(ids), max_new_tokens=3,
                                    eos_token_id=eos).numpy())
    assert (out[0, 4:] == eos).all()


def test_generate_length_guard(model):
    ids = np.zeros((1, 250), np.int32)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        model.generate(paddle.to_tensor(ids), max_new_tokens=100)


def test_mha_incremental_cache_matches_full_forward():
    paddle.seed(0)
    mha = nn.MultiHeadAttention(16, 4)
    mha.eval()
    rng = np.random.default_rng(2)
    x = paddle.to_tensor(rng.standard_normal((2, 6, 16)).astype(np.float32))
    # full causal-free forward over all 6 positions
    full = mha(x).numpy()
    # incremental: feed one position at a time through the Cache path
    cache = mha.gen_cache(x)
    assert cache.k.shape[2] == 0
    outs = []
    for t in range(6):
        step = Tensor(x._data[:, t:t + 1])
        out, cache = mha(step, cache=cache)
        outs.append(out.numpy())
    np.testing.assert_allclose(np.concatenate(outs, 1)[:, -1], full[:, -1],
                               rtol=1e-4, atol=1e-5)
    assert cache.k.shape[2] == 6


def test_mha_static_cache_cross_attention():
    paddle.seed(1)
    mha = nn.MultiHeadAttention(16, 4)
    mha.eval()
    rng = np.random.default_rng(3)
    q = paddle.to_tensor(rng.standard_normal((1, 3, 16)).astype(np.float32))
    mem = paddle.to_tensor(rng.standard_normal((1, 5, 16)).astype(np.float32))
    ref = mha(q, mem, mem).numpy()
    static = mha.gen_cache(mem, mem, type=nn.MultiHeadAttention.StaticCache)
    out, returned = mha(q, mem, mem, cache=static)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)
    assert returned is static


def test_decoder_cache_pipeline():
    paddle.seed(2)
    layer = nn.TransformerDecoderLayer(16, 4, 32, dropout=0.0)
    dec = nn.TransformerDecoder(layer, num_layers=2)
    dec.eval()
    rng = np.random.default_rng(4)
    mem = paddle.to_tensor(rng.standard_normal((1, 4, 16)).astype(np.float32))
    tgt = paddle.to_tensor(rng.standard_normal((1, 5, 16)).astype(np.float32))
    # full forward with causal mask vs incremental decode
    import jax.numpy as jnp
    causal = paddle.to_tensor(
        np.tril(np.ones((1, 1, 5, 5), bool)))
    full = dec(tgt, mem, tgt_mask=causal).numpy()
    caches = dec.gen_cache(mem)
    outs = []
    for t in range(5):
        step = Tensor(tgt._data[:, t:t + 1])
        out, caches = dec(step, mem, cache=caches)
        outs.append(out.numpy())
    np.testing.assert_allclose(outs[-1][:, 0], full[:, -1], rtol=1e-4,
                               atol=1e-5)


def test_cached_decode_matches_padded_full_forward(model):
    """KV-cache incremental decode must produce exactly the padded
    full-forward decode's tokens (greedy, same model)."""
    rng = np.random.default_rng(5)
    ids = rng.integers(0, 1024, (2, 6), dtype=np.int32)
    full = np.asarray(model.generate(paddle.to_tensor(ids), max_new_tokens=5,
                                     use_cache=False).numpy())
    cached = np.asarray(model.generate(paddle.to_tensor(ids),
                                       max_new_tokens=5,
                                       use_cache=True).numpy())
    np.testing.assert_array_equal(full, cached)


def test_cached_decode_with_sampling_and_eos(model):
    rng = np.random.default_rng(6)
    ids = rng.integers(0, 1024, (1, 4), dtype=np.int32)
    s1 = np.asarray(model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                                   do_sample=True, top_k=5, seed=11,
                                   use_cache=True).numpy())
    s2 = np.asarray(model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                                   do_sample=True, top_k=5, seed=11,
                                   use_cache=True).numpy())
    np.testing.assert_array_equal(s1, s2)
    g = np.asarray(model.generate(paddle.to_tensor(ids), max_new_tokens=3,
                                  use_cache=True).numpy())
    eos = int(g[0, 4])
    out = np.asarray(model.generate(paddle.to_tensor(ids), max_new_tokens=3,
                                    eos_token_id=eos, use_cache=True).numpy())
    assert (out[0, 4:] == eos).all()


def test_mha_need_weights_returns_probs():
    paddle.seed(3)
    mha = nn.MultiHeadAttention(16, 4, need_weights=True)
    mha.eval()
    x = paddle.to_tensor(np.random.default_rng(5).standard_normal(
        (2, 6, 16)).astype(np.float32))
    out, weights = mha(x)
    assert out.shape == [2, 6, 16]
    w = np.asarray(weights.numpy())
    assert w.shape == (2, 4, 6, 6)
    np.testing.assert_allclose(w.sum(-1), 1.0, rtol=1e-5)  # softmax rows
    # matches the need_weights=False output
    mha2 = nn.MultiHeadAttention(16, 4)
    mha2.eval()
    mha2.set_state_dict(mha.state_dict())
    np.testing.assert_allclose(out.numpy(), mha2(x).numpy(), rtol=1e-4,
                               atol=1e-5)


def test_encoder_incremental_cache():
    paddle.seed(4)
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, num_layers=2)
    enc.eval()
    x = paddle.to_tensor(np.random.default_rng(6).standard_normal(
        (1, 5, 16)).astype(np.float32))
    causal = paddle.to_tensor(np.tril(np.ones((1, 1, 5, 5), bool)))
    full = enc(x, src_mask=causal).numpy()
    caches = enc.gen_cache(x)
    outs = []
    from paddle_tpu.core.tensor import Tensor
    for t in range(5):
        out, caches = enc(Tensor(x._data[:, t:t + 1]), cache=caches)
        outs.append(out.numpy())
    np.testing.assert_allclose(outs[-1][:, 0], full[:, -1], rtol=1e-4,
                               atol=1e-5)


def test_filter_logits_top_p_unit():
    """Nucleus filter keeps the smallest prefix reaching top_p (top token
    always survives), composes with top_k, and -inf's the rest."""
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.models.gpt import _filter_logits

    # probs ~ [0.6438, 0.2369, 0.0871, 0.0321] for logits [3,2,1,0]
    logits = jnp.asarray([[3.0, 2.0, 1.0, 0.0]])
    out = np.asarray(_filter_logits(logits, 0, 0.7, 4))
    # cum-before: [0, .644, .881, .968] -> keep first two
    assert np.isfinite(out[0, 0]) and np.isfinite(out[0, 1])
    assert np.isinf(out[0, 2]) and np.isinf(out[0, 3])
    # tiny top_p: only the argmax survives
    out = np.asarray(_filter_logits(logits, 0, 1e-6, 4))
    assert np.isfinite(out[0, 0]) and np.isinf(out[0, 1:]).all()
    # top_k composes: k=3 then p=0.95 keeps {0,1,2} ∩ nucleus
    out = np.asarray(_filter_logits(logits, 3, 0.95, 4))
    assert np.isinf(out[0, 3])
    # p>=1 is a no-op
    out = np.asarray(_filter_logits(logits, 0, 1.0, 4))
    assert np.isfinite(out).all()


def test_generate_top_p(model):
    """top_p sampling decodes valid tokens; a vanishing nucleus reduces to
    greedy for both the cached and uncached paths."""
    import numpy as np

    from paddle_tpu.core.tensor import Tensor

    ids = Tensor(np.array([[5, 3, 9]], np.int32))
    greedy = model.generate(ids, max_new_tokens=6, do_sample=False)
    for use_cache in (True, False):
        tiny_p = model.generate(ids, max_new_tokens=6, do_sample=True,
                                top_p=1e-6, seed=7, use_cache=use_cache)
        np.testing.assert_array_equal(tiny_p.numpy(), greedy.numpy())
        sampled = model.generate(ids, max_new_tokens=6, do_sample=True,
                                 top_p=0.9, seed=3, use_cache=use_cache)
        assert sampled.numpy().shape == (1, 9)
        assert (sampled.numpy() >= 0).all()

"""paddle.geometric message passing + sampling (ref:python/paddle/geometric/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import geometric as G


def T(x, dt=np.float32):
    return paddle.to_tensor(np.asarray(x, dt))


def test_send_u_recv_reduces():
    x = T([[1.0], [2.0], [4.0]])
    src = T([0, 1, 2, 0], np.int32)
    dst = T([1, 2, 1, 0], np.int32)
    out = G.send_u_recv(x, src, dst, reduce_op="sum").numpy()
    np.testing.assert_allclose(out, [[1.0], [5.0], [2.0]])
    out = G.send_u_recv(x, src, dst, reduce_op="max").numpy()
    np.testing.assert_allclose(out, [[1.0], [4.0], [2.0]])
    out = G.send_u_recv(x, src, dst, reduce_op="mean").numpy()
    np.testing.assert_allclose(out, [[1.0], [2.5], [2.0]])


def test_send_ue_recv_and_uv():
    x = T([[1.0], [2.0], [4.0]])
    e = T([[10.0], [20.0], [30.0]])
    src = T([0, 1, 2], np.int32)
    dst = T([1, 1, 0], np.int32)
    out = G.send_ue_recv(x, e, src, dst, "add", "sum").numpy()
    np.testing.assert_allclose(out, [[34.0], [33.0], [0.0]])
    uv = G.send_uv(x, x, src, dst, "mul").numpy()
    np.testing.assert_allclose(uv, [[2.0], [4.0], [4.0]])


def test_segment_ops_reexported():
    data = T([[1.0], [2.0], [3.0]])
    ids = T([0, 0, 1], np.int32)
    np.testing.assert_allclose(G.segment_sum(data, ids).numpy(),
                               [[3.0], [3.0]])
    np.testing.assert_allclose(G.segment_mean(data, ids).numpy(),
                               [[1.5], [3.0]])


def test_reindex_graph():
    x = T([10, 20], np.int64)
    neighbors = T([30, 10, 20, 30], np.int64)
    count = T([2, 2], np.int32)
    src, dst, nodes = G.reindex_graph(x, neighbors, count)
    n = nodes.numpy().tolist()
    assert n[:2] == [10, 20] and set(n) == {10, 20, 30}
    assert dst.numpy().tolist() == [0, 0, 1, 1]
    assert src.numpy().tolist() == [n.index(30), 0, 1, n.index(30)]


def test_reindex_heter_graph():
    x = T([10, 20], np.int64)
    srcs, dsts, nodes = G.reindex_heter_graph(
        x, [T([30, 10], np.int64), T([20, 30], np.int64)],
        [T([1, 1], np.int32), T([1, 1], np.int32)])
    assert len(srcs) == 2 and len(dsts) == 2
    assert srcs[0].numpy().shape == (2,)


def test_sample_neighbors_uniform_and_weighted():
    # CSC: node0 -> {1,2,3}, node1 -> {0}
    row = T([1, 2, 3, 0], np.int64)
    colptr = T([0, 3, 4], np.int64)
    nodes = T([0, 1], np.int64)
    neigh, cnt = G.sample_neighbors(row, colptr, nodes, sample_size=2)
    assert cnt.numpy().tolist() == [2, 1]
    assert set(neigh.numpy().tolist()[:2]) <= {1, 2, 3}
    w = T([0.0, 0.0, 1.0, 1.0])
    neigh, cnt, eids = G.weighted_sample_neighbors(
        row, colptr, w, nodes, sample_size=1, return_eids=True)
    assert neigh.numpy().tolist()[0] == 3  # only nonzero-weight edge
    assert eids.numpy().tolist()[0] == 2


def test_send_u_recv_int_empty_segments_zero():
    x = T([[5], [7]], np.int32)
    src = T([0, 1], np.int32)
    dst = T([0, 0], np.int32)  # slot 1 receives nothing
    out = G.send_u_recv(x, src, dst, reduce_op="max", out_size=2).numpy()
    assert out[0, 0] == 7 and out[1, 0] == 0  # not INT32_MIN


def test_weighted_sampling_fewer_nonzero_than_k():
    row = T([1, 2, 3], np.int64)
    colptr = T([0, 3], np.int64)
    w = T([0.0, 0.0, 1.0])
    neigh, cnt = G.weighted_sample_neighbors(
        row, colptr, w, T([0], np.int64), sample_size=2)
    # only one positive-weight edge: degrade to 1 sample, don't crash
    assert cnt.numpy().tolist() == [1] and neigh.numpy().tolist() == [3]


class TestMessagePassingBackward:
    """Scatter-reduce gradients vs torch (index_add / scatter_reduce):
    sum/mean route grads to every contributing edge, max only to the
    argmax edge — the subgradient conventions dense tests can't see."""

    def _setup(self):
        rng = np.random.RandomState(50)
        x = rng.randn(6, 3).astype(np.float32)  # no ties (random floats)
        src = np.array([0, 1, 2, 3, 4, 5, 0, 2], np.int64)
        dst = np.array([1, 0, 3, 2, 5, 4, 2, 0], np.int64)
        w = rng.randn(6, 3).astype(np.float32)
        return x, src, dst, w

    def _torch_grad(self, x, src, dst, w, reduce):
        import torch

        tx = torch.tensor(x, requires_grad=True)
        gathered = tx[torch.tensor(src)]
        if reduce in ("sum", "mean"):
            out = torch.zeros(6, 3).index_add_(0, torch.tensor(dst),
                                               gathered)
            if reduce == "mean":
                cnt = torch.zeros(6).index_add_(
                    0, torch.tensor(dst), torch.ones(len(dst)))
                out = out / cnt.clamp(min=1).unsqueeze(1)
        else:
            out = torch.full((6, 3), -torch.inf).scatter_reduce(
                0, torch.tensor(dst)[:, None].expand(-1, 3), gathered,
                reduce="amax", include_self=False)
            out = torch.where(torch.isinf(out), torch.zeros(()), out)
        (out * torch.tensor(w)).sum().backward()
        return tx.grad.numpy()

    @pytest.mark.parametrize("reduce", ["sum", "mean", "max"])
    def test_send_u_recv_grad(self, reduce):
        x, src, dst, w = self._setup()
        px = paddle.to_tensor(x)
        px.stop_gradient = False
        out = G.send_u_recv(px, paddle.to_tensor(src),
                            paddle.to_tensor(dst), reduce_op=reduce,
                            out_size=6)
        (out * paddle.to_tensor(w)).sum().backward()
        want = self._torch_grad(x, src, dst, w, reduce)
        np.testing.assert_allclose(np.asarray(px.grad._data), want,
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"send_u_recv {reduce} grad")

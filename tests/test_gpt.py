"""Flagship GPT: eager forward, compiled TrainStep convergence, hybrid-mesh
sharded step on the 8-device CPU mesh."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models import GPTForCausalLM, gpt_tiny


def setup_function(_):
    dist.destroy_process_group()
    dist.set_mesh(None)


def _batch(cfg, b=4, s=32, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, size=(b, s + 1)).astype(np.int32)
    return paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:].astype(np.int64))


def test_gpt_forward_shapes():
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    x, y = _batch(cfg)
    logits = model(x)
    assert logits.shape == [4, 32, cfg.vocab_size]
    loss = model(x, y)
    assert loss.shape == [] and np.isfinite(loss.numpy())


def test_gpt_trainstep_loss_decreases():
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = paddle.jit.TrainStep(lambda x, y: model(x, y), opt, layers=model)
    x, y = _batch(cfg, b=2, s=16)
    losses = [float(step(x, y).numpy()) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_gpt_sharded_hybrid_step():
    dist.init_hybrid_mesh(dp=2, mp=2, sep=2)
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = paddle.jit.TrainStep(lambda x, y: model(x, y), opt, layers=model)
    x, y = _batch(cfg, b=4, s=32)
    x, y = dist.shard_batch(x), dist.shard_batch(y)
    l0 = float(step(x, y).numpy())
    l1 = float(step(x, y).numpy())
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0

    # TP weights really live sharded on the model axis
    w = model.gpt.layers[0].attn.qkv.weight
    assert "model" in str(w._data.sharding.spec)


def test_chunked_lm_loss_matches_unchunked():
    """loss_chunk_size fuses head+CE over sequence chunks without changing
    the math (incl. ragged tail padding)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    paddle.seed(11)
    m1 = GPTForCausalLM(gpt_tiny())
    paddle.seed(11)
    cfg = gpt_tiny()
    cfg.loss_chunk_size = 16
    m2 = GPTForCausalLM(cfg)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 1024, (2, 33), dtype=np.int32)
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(np.roll(ids, -1, 1))
    l1 = float(m1(x, y).numpy())
    l2 = float(m2(x, y).numpy())
    assert abs(l1 - l2) < 1e-4


def test_chunked_lm_loss_ignore_index_parity():
    """With -100-padded labels the chunked path must match F.cross_entropy's
    valid-token normalization exactly."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    paddle.seed(21)
    m1 = GPTForCausalLM(gpt_tiny())
    paddle.seed(21)
    cfg = gpt_tiny()
    cfg.loss_chunk_size = 16
    m2 = GPTForCausalLM(cfg)
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 1024, (2, 24), dtype=np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)
    labels[:, -6:] = -100  # padded tail
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(labels)
    l1 = float(m1(x, y).numpy())
    l2 = float(m2(x, y).numpy())
    assert abs(l1 - l2) < 1e-4


def test_gpt_recompute_multi_step_no_tracer_leak():
    """Regression: jax.checkpoint over a PERSISTENT layer caches its jaxpr
    keyed on the layer and replayed stale closure-captured param tracers on
    a re-trace — UnexpectedTracerError on the 2nd+ TrainStep call with
    use_recompute=True (the remat bench/sweep path). The explicit-params
    remat (_remat_layer) must run many steps and still converge."""
    cfg = gpt_tiny(use_recompute=True)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = paddle.jit.TrainStep(lambda x, y: model(x, y), opt, layers=model)
    x, y = _batch(cfg, b=2, s=16)
    losses = [float(step(x, y).numpy()) for _ in range(6)]
    assert losses[-1] < losses[0], losses


def test_gpt_scan_layers_training_parity():
    """use_scan_layers (lax.scan one block over stacked per-layer params —
    the compile-time lever for deep configs) must be a pure execution
    strategy: same seed, same per-step losses as the unrolled stack, with
    and without remat, across multiple optimizer steps."""
    from paddle_tpu.core import rng as prng

    def run(scan, remat):
        prng.seed(7)
        cfg = gpt_tiny(use_scan_layers=scan, use_recompute=remat)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        step = paddle.jit.TrainStep(lambda a, b: model(a, b), opt,
                                    layers=model)
        x, y = _batch(cfg, b=2, s=16, seed=5)
        return [float(step(x, y).numpy()) for _ in range(3)]

    base = run(False, False)
    assert base[-1] < base[0], base
    np.testing.assert_allclose(run(True, False), base, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(run(True, True), base, rtol=2e-5, atol=2e-6)


def test_gpt_scan_o2_chunk_loss_combination():
    """The exact knob combination the on-chip sweep leads with (scan +
    AMP O2 + sequence-chunked fused LM-head loss, remat fallback variant)
    must train consistently with the unrolled equivalent — proven off-chip
    before the chip ever sees it."""
    from paddle_tpu import amp
    from paddle_tpu.core import rng as prng

    def run(scan, remat):
        prng.seed(3)
        cfg = gpt_tiny(use_scan_layers=scan, use_recompute=remat,
                       loss_chunk_size=16)
        m = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters(),
                                     weight_decay=0.01)
        amp.decorate(m, opt, level="O2")

        def loss_fn(a, b):
            with amp.auto_cast(level="O1", dtype="bfloat16"):
                return m(a, b)

        step = paddle.jit.TrainStep(loss_fn, opt, layers=m)
        x, y = _batch(cfg, b=2, s=16, seed=5)
        return [float(step(x, y).numpy()) for _ in range(3)]

    base = run(False, False)
    # bf16 compute: small rounding drift between the two schedules is fine;
    # divergence (wrong grads) is not
    np.testing.assert_allclose(run(True, False), base, rtol=5e-3, atol=1e-3)
    np.testing.assert_allclose(run(True, True), base, rtol=5e-3, atol=1e-3)


def test_gpt_scan_layers_under_tp_mesh():
    """Scan-over-layers must compose with GSPMD tensor parallelism: the
    stacked per-layer params carry the model-axis shardings through
    lax.scan, and per-step losses match the unrolled stack on a
    dp2 x mp4 mesh."""
    import jax

    from paddle_tpu.core import rng as prng
    from paddle_tpu.distributed import mesh as M

    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("needs the 8-device CPU mesh")

    def run(scan):
        prng.seed(4)
        M.set_mesh(M.build_mesh({"data": 2, "model": 4}))
        try:
            cfg = gpt_tiny(use_scan_layers=scan)
            m = GPTForCausalLM(cfg)
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters())
            step = paddle.jit.TrainStep(lambda a, b: m(a, b), opt, layers=m)
            x, y = _batch(cfg, b=4, s=16, seed=5)
            return [float(step(x, y).numpy()) for _ in range(3)]
        finally:
            M.set_mesh(None)

    base = run(False)
    np.testing.assert_allclose(run(True), base, rtol=2e-5, atol=2e-6)


def test_gpt_recompute_matches_plain_forward():
    """Remat must not change the math: same seed, same loss with and
    without use_recompute on the compiled path."""
    from paddle_tpu.core import rng as prng

    vals = []
    for rc in (False, True):
        prng.seed(99)
        cfg = gpt_tiny(use_recompute=rc)
        model = GPTForCausalLM(cfg)
        x, y = _batch(cfg, b=2, s=16, seed=3)
        f = paddle.jit.to_static(lambda a, b: model(a, b))
        vals.append(float(f(x, y).numpy()))
    assert abs(vals[0] - vals[1]) < 1e-5, vals


def test_gpt_recompute_policy_core_attn_parity():
    """recompute_policy="core_attn" (save weight-matmul outputs, recompute
    only attention scores/softmax) is a pure memory/speed strategy: same
    seed -> same per-step losses as full remat and as no remat, in both the
    unrolled and scanned stacks."""
    from paddle_tpu.core import rng as prng

    def run(scan, remat, policy="full"):
        prng.seed(7)
        cfg = gpt_tiny(use_scan_layers=scan, use_recompute=remat,
                       recompute_policy=policy)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        step = paddle.jit.TrainStep(lambda a, b: model(a, b), opt,
                                    layers=model)
        x, y = _batch(cfg, b=2, s=16, seed=5)
        return [float(step(x, y).numpy()) for _ in range(3)]

    base = run(False, False)
    assert base[-1] < base[0], base
    np.testing.assert_allclose(run(False, True, "core_attn"), base,
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(run(True, True, "core_attn"), base,
                               rtol=2e-5, atol=2e-6)


def test_recompute_policy_kwarg_direct():
    """fleet.recompute(policy=...) accepts every registered policy name and
    produces the plain-call value under a trace."""
    import jax.numpy as jnp
    from paddle_tpu.distributed.fleet.recompute import recompute, _POLICIES

    lin = paddle.nn.Linear(4, 4)

    def f(t):
        return paddle.nn.functional.relu(lin(t))

    x = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(2, 4))
    want = f(x).numpy()
    for name in _POLICIES:
        @paddle.jit.to_static
        def g(t, _name=name):
            return recompute(f, t, policy=_name)

        np.testing.assert_allclose(g(x).numpy(), want, rtol=1e-6)


def test_generate_with_bf16_cast_model():
    """Serving mode: model.bfloat16() must decode end-to-end — the KV cache
    follows the weight dtype (a f32 cache would break dynamic_update_slice
    and silently double decode HBM traffic)."""
    import jax.numpy as jnp

    cfg = gpt_tiny()
    paddle.seed(3)
    m32 = GPTForCausalLM(cfg)
    m32.eval()
    x = paddle.to_tensor(np.arange(8, dtype=np.int32).reshape(1, 8) % cfg.vocab_size)
    out32 = m32.generate(x, max_new_tokens=8)
    paddle.seed(3)
    m16 = GPTForCausalLM(cfg)
    m16.eval()
    m16.bfloat16()
    assert m16.gpt.layers[0].attn.qkv.weight._data.dtype == jnp.bfloat16
    out16 = m16.generate(x, max_new_tokens=8)
    assert out16.numpy().shape == out32.numpy().shape
    # same seed, same greedy path at tiny scale: tokens should mostly agree
    agree = (out16.numpy() == out32.numpy()).mean()
    assert agree > 0.5, (agree, out16.numpy(), out32.numpy())

"""GPT pipeline-parallel model: hybrid pp x mp x dp training."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet.meta_parallel import PipelineParallel
from paddle_tpu.models.gpt import GPTForCausalLMPipe, gpt_tiny


def test_gpt_pipe_hybrid_training_converges():
    paddle.seed(0)
    dist.init_hybrid_mesh(pp=2, mp=2, dp=2)
    model = GPTForCausalLMPipe(gpt_tiny(), num_stages=2, num_microbatches=2)
    pp = PipelineParallel(model)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    rng = np.random.default_rng(0)
    x = rng.integers(0, 1024, (4, 32)).astype(np.int32)
    y = np.roll(x, -1, 1).astype(np.int32)
    losses = []
    for _ in range(5):
        loss = pp.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt)
        losses.append(float(loss.numpy()))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_gpt_pipe_tied_embeddings_share_parameter():
    dist.init_hybrid_mesh(pp=2, dp=4)
    model = GPTForCausalLMPipe(gpt_tiny(), num_stages=2)
    names = [n for n, _ in model.named_parameters()]
    # tied head contributes no duplicate weight parameter
    assert sum("wte" in n for n in names) == 1

"""Gradient merge (k-step accumulation) + distributed.passes framework.

Reference semantics: ref:python/paddle/distributed/passes/auto_parallel_gradient_merge.py:26
(accumulate k microbatch grads, apply optimizer once, averaged) and the
pass registration contract ref:python/paddle/distributed/passes/pass_base.py:133.
TPU-native form: the k-microbatch loop is a lax.scan inside ONE compiled
TrainStep program.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW, Momentum


def _data(n=8, din=6, dout=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, din), dtype=np.float32)
    y = rng.standard_normal((n, dout), dtype=np.float32)
    return x, y


def _mlp(seed=0, din=6, dout=3):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(din, 16), nn.ReLU(), nn.Linear(16, dout))


class TestTrainStepAccumulate:
    def test_matches_full_batch_step(self):
        """k microbatches accumulated == one full-batch step (mean loss)."""
        x, y = _data()

        m1 = _mlp()
        o1 = AdamW(learning_rate=1e-2, parameters=m1.parameters())
        s1 = TrainStep(lambda a, b: ((m1(a) - b) ** 2).mean(), o1, layers=m1)

        m2 = _mlp()
        o2 = AdamW(learning_rate=1e-2, parameters=m2.parameters())
        s2 = TrainStep(lambda a, b: ((m2(a) - b) ** 2).mean(), o2, layers=m2,
                       accumulate_steps=4)

        for _ in range(3):
            l1 = s1(Tensor(x), Tensor(y))
            l2 = s2(Tensor(x), Tensor(y))
        np.testing.assert_allclose(float(l1._data), float(l2._data),
                                   rtol=1e-5)
        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(np.asarray(p1._data),
                                       np.asarray(p2._data), atol=1e-6)

    def test_batch_not_divisible_raises(self):
        x, y = _data(n=6)
        m = _mlp()
        o = AdamW(learning_rate=1e-2, parameters=m.parameters())
        s = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), o, layers=m,
                      accumulate_steps=4)
        with pytest.raises(ValueError, match="divisible"):
            s(Tensor(x), Tensor(y))

    def test_bn_stats_chain_across_microbatches(self):
        """Running BN stats must see each microbatch in turn (carry
        threading), matching k sequential eager forward passes."""
        x, _ = _data(n=8, din=4, dout=4)

        paddle.seed(1)
        bn_ref = nn.BatchNorm1D(4, momentum=0.5)
        for chunk in np.split(x, 4):
            bn_ref(Tensor(chunk))  # eager: stats update per microbatch

        paddle.seed(1)
        bn = nn.BatchNorm1D(4, momentum=0.5)
        o = Momentum(learning_rate=0.0, parameters=bn.parameters())
        s = TrainStep(lambda a: bn(a).mean(), o, layers=bn,
                      accumulate_steps=4)
        s(Tensor(x))
        np.testing.assert_allclose(np.asarray(bn._mean._data),
                                   np.asarray(bn_ref._mean._data), atol=1e-6)
        np.testing.assert_allclose(np.asarray(bn._variance._data),
                                   np.asarray(bn_ref._variance._data),
                                   atol=1e-6)

    def test_accumulate_with_master_weights(self):
        """O2 decoration (bf16 params, f32 master) composes with the scan."""
        from paddle_tpu import amp

        x, y = _data()
        m = _mlp(seed=2)
        o = AdamW(learning_rate=1e-2, parameters=m.parameters())
        amp.decorate(m, o, level="O2", dtype="bfloat16")
        s = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), o, layers=m,
                      accumulate_steps=2)
        l0 = float(s(Tensor(x), Tensor(y))._data)
        for _ in range(5):
            l1 = float(s(Tensor(x), Tensor(y))._data)
        assert l1 < l0  # loss decreases through the accumulated steps


class TestEagerGradientMerge:
    def test_step_applies_every_k(self):
        from paddle_tpu.distributed.passes import GradientMergeOptimizer

        x, y = _data()
        m = _mlp(seed=3)
        o = GradientMergeOptimizer(
            Momentum(learning_rate=0.1, parameters=m.parameters()), k_steps=2)
        w0 = np.asarray(m[0].weight._data).copy()

        loss = ((m(Tensor(x)) - Tensor(y)) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()  # boundary not reached: must NOT clear
        np.testing.assert_array_equal(np.asarray(m[0].weight._data), w0)
        assert m[0].weight.grad is not None

        loss = ((m(Tensor(x)) - Tensor(y)) ** 2).mean()
        loss.backward()
        o.step()  # k-th call: applies with grads averaged by k
        o.clear_grad()
        assert not np.array_equal(np.asarray(m[0].weight._data), w0)
        assert m[0].weight.grad is None or \
            not np.any(np.asarray(m[0].weight.grad._data))

    def test_equivalent_to_scaled_single_step(self):
        """Two identical half-batches accumulated == one step on the same
        grad (average of two equal grads == the grad)."""
        from paddle_tpu.distributed.passes import GradientMergeOptimizer

        x, y = _data(n=4)

        m1 = _mlp(seed=4)
        o1 = Momentum(learning_rate=0.1, parameters=m1.parameters())
        loss = ((m1(Tensor(x)) - Tensor(y)) ** 2).mean()
        loss.backward()
        o1.step()

        m2 = _mlp(seed=4)
        o2 = GradientMergeOptimizer(
            Momentum(learning_rate=0.1, parameters=m2.parameters()), k_steps=2)
        for _ in range(2):
            loss = ((m2(Tensor(x)) - Tensor(y)) ** 2).mean()
            loss.backward()
            o2.step()
            o2.clear_grad()
        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(np.asarray(p1._data),
                                       np.asarray(p2._data), atol=1e-6)

    def test_fleet_strategy_wires_wrapper(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.passes import GradientMergeOptimizer

        m = _mlp(seed=5)
        strat = fleet.DistributedStrategy()
        strat.gradient_merge = True
        strat.gradient_merge_configs = {"k_steps": 4, "avg": True}
        opt = fleet.distributed_optimizer(
            Momentum(learning_rate=0.1, parameters=m.parameters()),
            strategy=strat)
        assert isinstance(opt, GradientMergeOptimizer)
        assert opt._k == 4

    def test_trainstep_adopts_fleet_wrapper(self):
        """Passing the fleet gradient_merge wrapper to TrainStep must not
        silently drop the configured k: the step adopts it as
        accumulate_steps and drives the inner optimizer."""
        from paddle_tpu.distributed.passes import GradientMergeOptimizer

        x, y = _data()
        m = _mlp(seed=10)
        inner = AdamW(learning_rate=1e-2, parameters=m.parameters())
        wrapper = GradientMergeOptimizer(inner, k_steps=4)
        ts = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), wrapper,
                       layers=m)
        assert ts._accumulate_steps == 4
        assert ts._opt is inner
        l0 = float(ts(Tensor(x), Tensor(y))._data)
        l1 = float(ts(Tensor(x), Tensor(y))._data)
        assert l1 < l0
        assert inner._step_count == 2  # bookkeeping lands on the inner opt

    def test_non_uniform_leading_dim_raises(self):
        x, _ = _data()
        m = _mlp(seed=11)
        o = AdamW(learning_rate=1e-2, parameters=m.parameters())
        w = np.ones(4, np.float32)  # 4 % k == 0 but NOT the batch dim

        def loss(a, wvec):
            return ((m(a) * wvec.reshape(1, -1)).mean())

        s = TrainStep(loss, o, layers=m, accumulate_steps=4)
        with pytest.raises(ValueError, match="share one leading"):
            s(Tensor(x), Tensor(w))


class TestPassFramework:
    def test_new_pass_and_manager(self):
        from paddle_tpu.distributed.passes import PassManager, new_pass

        x, y = _data()
        m = _mlp(seed=6)
        o = AdamW(learning_rate=1e-2, parameters=m.parameters())
        ts = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), o, layers=m)
        pm = PassManager([new_pass("gradient_merge", {"k_steps": 2}),
                          new_pass("fuse_all_reduce")])
        ts = pm.apply(ts)
        assert ts._accumulate_steps == 2
        assert "fuse_all_reduce" in pm.context.attrs["compiler_performed"]
        l0 = float(ts(Tensor(x), Tensor(y))._data)
        l1 = float(ts(Tensor(x), Tensor(y))._data)
        assert l1 < l0

    def test_gradient_merge_after_build_raises(self):
        from paddle_tpu.distributed.passes import new_pass

        x, y = _data()
        m = _mlp(seed=7)
        o = AdamW(learning_rate=1e-2, parameters=m.parameters())
        ts = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), o, layers=m)
        ts(Tensor(x), Tensor(y))
        with pytest.raises(RuntimeError, match="before"):
            new_pass("gradient_merge", {"k_steps": 2}).apply(ts)

    def test_unknown_pass_raises(self):
        from paddle_tpu.distributed.passes import new_pass

        with pytest.raises(ValueError, match="unknown pass"):
            new_pass("definitely_not_a_pass")

    def test_amp_pass_wraps_autocast(self):
        from paddle_tpu.distributed.passes import new_pass

        x, y = _data()
        m = _mlp(seed=8)
        o = AdamW(learning_rate=1e-2, parameters=m.parameters())
        ts = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), o, layers=m)
        new_pass("auto_parallel_amp", {"dtype": "bfloat16"}).apply(ts)
        l0 = float(ts(Tensor(x), Tensor(y))._data)
        l1 = float(ts(Tensor(x), Tensor(y))._data)
        assert l1 < l0

    def test_recompute_pass_wraps_sublayers(self):
        from paddle_tpu.distributed.passes import PassContext, new_pass

        x, y = _data()
        m = _mlp(seed=9)
        ctx = PassContext()
        new_pass("auto_parallel_recompute", {"checkpoints": ["0"]}).apply(
            m, context=ctx)
        assert ctx.attrs["recompute_wrapped"] == ["0"]
        # still trains (remat is functionally transparent)
        o = AdamW(learning_rate=1e-2, parameters=m.parameters())
        ts = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), o, layers=m)
        l0 = float(ts(Tensor(x), Tensor(y))._data)
        l1 = float(ts(Tensor(x), Tensor(y))._data)
        assert l1 < l0


class TestEngineGradientMerge:
    def test_strategy_gradient_merge_k_reaches_train_step(self):
        """auto_parallel Strategy.gradient_merge_k compiles into the
        Engine's TrainStep (was a declared-but-dead knob)."""
        from paddle_tpu.distributed.auto_parallel import Engine, Strategy

        x, y = _data(n=32, din=8, dout=1)
        m = _mlp(seed=12, din=8, dout=1)
        o = AdamW(learning_rate=5e-3, parameters=m.parameters())
        eng = Engine(m, loss=lambda out, t: ((out - t) ** 2).mean(),
                     optimizer=o,
                     strategy=Strategy(dp_degree=8, gradient_merge_k=2))
        eng.prepare()
        assert eng._step._accumulate_steps == 2
        data = [(Tensor(x), Tensor(y)) for _ in range(2)]
        hist = eng.fit(data, epochs=15, verbose=0)
        assert hist[-1] < 0.5 * hist[0]


class TestDistributedGradientMerge:
    def test_dp_sharded_accumulation_matches_single_device(self):
        """shard_batch over the data axis x accumulate_steps=2 equals
        unsharded k=1 full-batch training (grads all-reduce inside the
        compiled scan; microbatch split composes with the dp sharding)."""
        from paddle_tpu.distributed import shard_batch
        from paddle_tpu.distributed.mesh import init_hybrid_mesh

        x, y = _data(n=32, din=6, dout=3)

        m1 = _mlp(seed=21)
        o1 = AdamW(learning_rate=1e-2, parameters=m1.parameters())
        s1 = TrainStep(lambda a, b: ((m1(a) - b) ** 2).mean(), o1, layers=m1)
        for _ in range(3):
            l1 = s1(Tensor(x), Tensor(y))

        init_hybrid_mesh(dp=8)
        m2 = _mlp(seed=21)
        o2 = AdamW(learning_rate=1e-2, parameters=m2.parameters())
        s2 = TrainStep(lambda a, b: ((m2(a) - b) ** 2).mean(), o2, layers=m2,
                       accumulate_steps=2)
        for _ in range(3):
            l2 = s2(shard_batch(Tensor(x)), shard_batch(Tensor(y)))

        np.testing.assert_allclose(float(l1._data), float(l2._data),
                                   rtol=1e-5)
        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(np.asarray(p1._data),
                                       np.asarray(p2._data), atol=1e-5)


class TestAccumulateCheckpointResume:
    def test_state_dict_resume_matches_uninterrupted(self):
        """Snapshot after 2 accumulated steps, restore into a FRESH model +
        TrainStep(accumulate_steps), continue: trajectories match the
        uninterrupted run (optimizer accumulators stay coherent through
        the compiled scan)."""
        x, y = _data(n=16, seed=7)

        def build():
            m = _mlp(seed=30)
            o = AdamW(learning_rate=1e-2, parameters=m.parameters())
            s = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), o,
                          layers=m, accumulate_steps=2)
            return m, o, s

        m1, o1, s1 = build()
        for _ in range(5):
            l_ref = s1(Tensor(x), Tensor(y))

        m2, o2, s2 = build()
        for _ in range(2):
            s2(Tensor(x), Tensor(y))
        model_sd = {k: np.asarray(v._data) for k, v in
                    m2.state_dict().items()}
        opt_sd = o2.state_dict()

        m3, o3, s3 = build()
        m3.set_state_dict(model_sd)
        o3.set_state_dict(opt_sd)
        for _ in range(3):
            l_res = s3(Tensor(x), Tensor(y))

        np.testing.assert_allclose(float(l_ref._data), float(l_res._data),
                                   rtol=1e-5)
        for p1, p3 in zip(m1.parameters(), m3.parameters()):
            np.testing.assert_allclose(np.asarray(p1._data),
                                       np.asarray(p3._data), atol=1e-6)


def test_set_state_dict_invalidates_stepped_trainstep():
    """Restoring optimizer state into an ALREADY-STEPPED TrainStep must
    take effect: set_state_dict bumps a state version that drops the
    compiled cache, so the trajectory after restore equals a fresh resume
    (previously the stale cached moments kept training silently)."""
    x, y = _data(n=16, seed=9)

    def build():
        m = _mlp(seed=31)
        o = AdamW(learning_rate=1e-2, parameters=m.parameters())
        s = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), o, layers=m)
        return m, o, s

    # reference: 2 steps, snapshot, 3 more steps
    m1, o1, s1 = build()
    for _ in range(2):
        s1(Tensor(x), Tensor(y))
    snap_model = {k: np.asarray(v._data) for k, v in
                  m1.state_dict().items()}
    snap_opt = o1.state_dict()
    for _ in range(3):
        l_ref = s1(Tensor(x), Tensor(y))

    # victim: 2 steps, DIVERGE for 2 steps, then restore the snapshot into
    # the same (stepped) model+optimizer+TrainStep and continue 3 steps
    m2, o2, s2 = build()
    for _ in range(2):
        s2(Tensor(x), Tensor(y))
    for _ in range(2):  # diverge: pollutes the cached compiled opt state
        s2(Tensor(x), Tensor(y))
    m2.set_state_dict(snap_model)
    o2.set_state_dict(snap_opt)
    for _ in range(3):
        l_res = s2(Tensor(x), Tensor(y))

    np.testing.assert_allclose(float(l_ref._data), float(l_res._data),
                               rtol=1e-5)
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(np.asarray(p1._data),
                                   np.asarray(p2._data), atol=1e-6)


def test_step0_snapshot_restore_resets_moments():
    """A snapshot taken BEFORE any optimizer step has no slot entries;
    restoring it must CLEAR leftover accumulators (not overlay stale
    post-training moments under a reset step counter)."""
    x, y = _data(n=16, seed=12)
    m = _mlp(seed=33)
    o = AdamW(learning_rate=1e-2, parameters=m.parameters())
    s = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), o, layers=m)
    snap_model = {k: np.asarray(v._data).copy()
                  for k, v in m.state_dict().items()}
    snap_opt = o.state_dict()  # step 0, no accumulators yet
    losses_fresh = [float(s(Tensor(x), Tensor(y))._data) for _ in range(3)]

    m.set_state_dict(snap_model)
    o.set_state_dict(snap_opt)
    losses_restored = [float(s(Tensor(x), Tensor(y))._data)
                       for _ in range(3)]
    np.testing.assert_allclose(losses_fresh, losses_restored, rtol=1e-5)


def test_trainstep_alternating_batch_shapes():
    """Shape polymorphism: the compiled step retraces per batch shape while
    optimizer state stays coherent (donation must not corrupt state across
    the retrace boundary)."""
    m = _mlp(seed=40)
    o = AdamW(learning_rate=1e-2, parameters=m.parameters())
    s = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), o, layers=m)
    rng = np.random.RandomState(0)
    for i, bsz in enumerate((4, 8, 4, 16, 8)):
        X = Tensor(rng.rand(bsz, 6).astype(np.float32))
        Y = Tensor(rng.rand(bsz, 3).astype(np.float32))
        l = float(s(X, Y)._data)
        assert np.isfinite(l)
    assert int(s._opt_state["step"]) == 5


def test_trainstep_tied_lm_head_trains():
    """Weight tying (embedding table reused as the output head via
    transpose_y matmul): ONE parameter, gradients accumulate from both
    uses, loss decreases."""
    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    emb = nn.Embedding(16, 8)
    o = AdamW(learning_rate=1e-2, parameters=emb.parameters())
    rng = np.random.RandomState(0)
    ids = Tensor(rng.randint(0, 16, (4, 5)).astype(np.int64))
    y = Tensor(rng.randint(0, 16, (4, 5)).astype(np.int64))

    def loss_fn(ids, y):
        h = emb(ids)
        logits = paddle.matmul(h, emb.weight, transpose_y=True)
        return nn.functional.cross_entropy(logits, y).mean()

    s = TrainStep(loss_fn, o, layers=[emb])
    ls = [float(s(ids, y)._data) for _ in range(6)]
    assert ls[-1] < ls[0], ls

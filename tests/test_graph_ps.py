"""PS-hosted graph table: distributed adjacency + server-side neighbor
sampling (ref:paddle/fluid/distributed/ps/table/common_graph_table.cc)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import geometric
from paddle_tpu.distributed import ps


@pytest.fixture(scope="module")
def graph_cluster():
    svc = ps.EmbeddingService(dim=8, num_shards=2)
    yield svc
    svc.stop()


def _ring_graph(n=50, extra=5):
    # ring + a few hubs with high degree
    src = list(range(n)) + [0] * extra
    dst = [(i + 1) % n for i in range(n)] + list(range(100, 100 + extra))
    return np.asarray(src, np.uint64), np.asarray(dst, np.uint64)


def test_graph_add_sample_degree(graph_cluster):
    g = graph_cluster.graph_client()
    src, dst = _ring_graph()
    g.add_edges(src, dst)
    nodes, edges = g.stats()
    assert edges == len(src) and nodes == 50  # 50 distinct sources

    # full neighborhoods in input order
    probe = np.array([0, 1, 49, 777], np.uint64)
    flat, counts = g.sample_neighbors(probe, sample_size=-1)
    assert counts.tolist() == [6, 1, 1, 0]  # node 0: ring edge + 5 hubs
    assert set(flat[:6].tolist()) == {1, 100, 101, 102, 103, 104}
    assert flat[6] == 2 and flat[7] == 0
    assert g.degrees(probe).tolist() == [6, 1, 1, 0]

    # bounded fanout: k-subset of the true neighborhood, deterministic per seed
    f1, c1 = g.sample_neighbors(np.array([0], np.uint64), 3, seed=7)
    f2, c2 = g.sample_neighbors(np.array([0], np.uint64), 3, seed=7)
    assert c1.tolist() == [3] and np.array_equal(f1, f2)
    assert set(f1.tolist()) <= {1, 100, 101, 102, 103, 104}
    assert len(set(f1.tolist())) == 3  # without replacement
    # the seed must actually steer selection: across many seeds the
    # 3-subsets of a 6-neighborhood cannot all coincide
    draws = {tuple(sorted(g.sample_neighbors(
        np.array([0], np.uint64), 3, seed=sd)[0].tolist()))
        for sd in range(12)}
    assert len(draws) > 1, draws


def test_sample_retry_after_undersized_buffer(graph_cluster):
    """An undersized response (rc -3) must leave the connection usable:
    the wire layer drains the body, the client retries bigger, and
    subsequent calls on the same connection stay correct."""
    g = graph_cluster.graph_client()
    src = np.full(20, 7000, np.uint64)
    dst = np.arange(8000, 8020, dtype=np.uint64)
    g.add_edges(src, dst)
    node = np.array([7000], np.uint64)
    lib = g._lib
    import ctypes as ct

    conn = g._conns[int(g._route(node)[0])]
    cnt = np.zeros(1, np.uint32)
    small = np.zeros(2, np.uint64)  # 20 neighbors won't fit
    rc = lib.pt_graph_sample(
        conn, node.ctypes.data_as(ct.POINTER(ct.c_uint64)), 1, -1, 0,
        cnt.ctypes.data_as(ct.POINTER(ct.c_uint32)),
        small.ctypes.data_as(ct.POINTER(ct.c_uint64)), len(small))
    assert rc == -3
    # the SAME connection must still serve correct results afterwards
    flat, counts = g.sample_neighbors(node, -1)
    assert counts.tolist() == [20]
    assert set(flat.tolist()) == set(range(8000, 8020))
    assert g.degrees(node).tolist() == [20]


def test_distributed_sampling_feeds_reindex(graph_cluster):
    g = graph_cluster.graph_client()
    # bipartite block: sources 200..203 each -> {300..303}
    src = np.repeat(np.arange(200, 204, dtype=np.uint64), 4)
    dst = np.tile(np.arange(300, 304, dtype=np.uint64), 4)
    g.add_edges(src, dst)

    x = paddle.to_tensor(np.arange(200, 204, dtype=np.int64))
    nbrs, cnt = geometric.distributed_sample_neighbors(g, x, sample_size=2,
                                                       seed=1)
    assert cnt.numpy().tolist() == [2, 2, 2, 2]
    r_src, r_dst, out_nodes = geometric.reindex_graph(x, nbrs, cnt)
    # reindexed ids are a compact local space covering x + sampled nbrs
    assert out_nodes.shape[0] == len(set(
        x.numpy().tolist() + nbrs.numpy().tolist()))
    assert int(r_src.numpy().max()) < out_nodes.shape[0]
    assert np.array_equal(out_nodes.numpy()[:4], x.numpy())

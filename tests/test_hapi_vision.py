"""hapi Model.fit + vision zoo + metrics: the 'book' MNIST config end-to-end."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall
from paddle_tpu.vision.datasets import FakeData
from paddle_tpu.vision.models import LeNet
from paddle_tpu.vision.transforms import Compose, Normalize, ToTensor


def test_model_fit_lenet_fakedata():
    paddle.seed(0)
    train = FakeData(num_samples=128, seed=0)
    val = FakeData(num_samples=64, seed=1)
    model = paddle.Model(LeNet())
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(), Accuracy())
    hist = model.fit(train, val, batch_size=32, epochs=2, verbose=0)
    assert len(hist["loss"]) == 2
    assert np.isfinite(hist["loss"][-1])
    logs = model.evaluate(val, batch_size=32, verbose=0)
    assert "loss" in logs and "acc" in logs


def test_model_fit_learns_separable():
    paddle.seed(0)

    class DS(paddle.io.Dataset):
        def __init__(self, n=256):
            rng = np.random.default_rng(0)
            self.x = rng.standard_normal((n, 8)).astype(np.float32)
            self.y = (self.x.sum(1) > 0).astype(np.int64).reshape(-1, 1)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    ds = DS()
    model = paddle.Model(nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2)))
    model.prepare(paddle.optimizer.Adam(learning_rate=0.01, parameters=model.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
    model.fit(ds, batch_size=64, epochs=8, verbose=0)
    logs = model.evaluate(ds, batch_size=64, verbose=0)
    assert logs["acc"] > 0.9, logs


def test_model_save_load(tmp_path):
    import os

    model = paddle.Model(LeNet())
    model.prepare(paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters()),
                  nn.CrossEntropyLoss())
    p = os.path.join(str(tmp_path), "ck", "model")
    model.save(p)
    m2 = paddle.Model(LeNet())
    m2.load(p)
    for (k, a), (_, b) in zip(model.network.state_dict().items(),
                              m2.network.state_dict().items()):
        np.testing.assert_allclose(a.numpy(), b.numpy())


def test_summary_counts():
    info = paddle.summary(LeNet())
    assert info["total_params"] > 60000
    assert info["trainable_params"] == info["total_params"]


def test_metrics_precision_recall_auc():
    p = Precision(); r = Recall(); a = Auc()
    preds = np.asarray([0.9, 0.8, 0.2, 0.1, 0.7, 0.3])
    labels = np.asarray([1, 1, 0, 0, 0, 1])
    p.update(preds, labels); r.update(preds, labels); a.update(preds, labels)
    assert p.accumulate() == pytest.approx(2 / 3)
    assert r.accumulate() == pytest.approx(2 / 3)
    assert 0.5 < a.accumulate() <= 1.0


def test_transforms_pipeline():
    t = Compose([ToTensor(), Normalize(mean=[0.5], std=[0.5])])
    img = (np.random.rand(28, 28) * 255).astype(np.uint8)
    out = t(img)
    assert list(out.shape) == [1, 28, 28]  # ToTensor returns a Tensor now
    vals = out.numpy()
    assert vals.min() >= -1.0 - 1e-6 and vals.max() <= 1.0 + 1e-6


def test_vision_models_forward_shapes():
    from paddle_tpu.vision.models import mobilenet_v2, resnet18

    x = paddle.to_tensor(np.random.rand(2, 3, 32, 32).astype(np.float32))
    for ctor in (resnet18, mobilenet_v2):
        m = ctor(num_classes=7)
        m.eval()
        assert m(x).shape == [2, 7]


def test_resize_matches_pil_and_honors_interpolation():
    from PIL import Image

    from paddle_tpu.vision.transforms import Resize

    img = Image.fromarray(
        (np.random.rand(32, 48, 3) * 255).astype(np.uint8))
    out = Resize(16)(img)
    assert isinstance(out, Image.Image) and out.size == (24, 16)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(img.resize((24, 16), Image.BILINEAR)))
    nearest = Resize(16, interpolation="nearest")(img)
    assert np.asarray(nearest).shape == (16, 24, 3)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="interpolation"):
        Resize(16, interpolation="bogus")

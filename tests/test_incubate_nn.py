"""incubate.nn fused layers + incubate.autograd functional transforms
(ref:python/paddle/incubate/nn/, incubate/autograd/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate import autograd as iag
from paddle_tpu.incubate import nn as inn


def T(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


def test_fused_linear_matches_linear():
    rng = np.random.default_rng(0)
    x = T(rng.standard_normal((2, 8)))
    fl = inn.FusedLinear(8, 4)
    ref = nn.Linear(8, 4)
    ref.weight._data = fl.weight._data
    ref.bias._data = fl.bias._data
    np.testing.assert_allclose(fl(x).numpy(), ref(x).numpy(), rtol=1e-5)


def test_fused_dropout_add_eval_is_add():
    m = inn.FusedDropoutAdd(p=0.9)
    m.eval()
    x, y = T(np.ones((3, 3))), T(np.full((3, 3), 2.0))
    np.testing.assert_allclose(m(x, y).numpy(), 3.0)


def test_fused_bias_dropout_residual_ln():
    m = inn.FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.0)
    x = T(np.random.default_rng(1).standard_normal((2, 4, 8)))
    r = T(np.random.default_rng(2).standard_normal((2, 4, 8)))
    out = m(x, r).numpy()
    assert out.shape == (2, 4, 8)
    np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)  # LN output


def test_fused_mha_shapes_and_grad():
    m = inn.FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                    attn_dropout_rate=0.0)
    x = T(np.random.default_rng(3).standard_normal((2, 6, 16)))
    x.stop_gradient = False
    out = m(x)
    assert out.shape == [2, 6, 16]
    out.sum().backward()
    assert m.qkv_weight.grad is not None
    assert float(np.abs(m.qkv_weight.grad.numpy()).sum()) > 0


def test_fused_encoder_layer_and_multi_transformer():
    enc = inn.FusedTransformerEncoderLayer(16, 4, 32, dropout_rate=0.0)
    x = T(np.random.default_rng(4).standard_normal((2, 5, 16)))
    assert enc(x).shape == [2, 5, 16]
    mt = inn.FusedMultiTransformer(16, 4, 32, num_layers=2)
    mt.eval()
    assert mt(x).shape == [2, 5, 16]


def test_fused_ec_moe():
    m = inn.FusedEcMoe(16, 32, num_experts=4)
    x = T(np.random.default_rng(5).standard_normal((2, 6, 16)))
    gate = T(np.random.default_rng(6).standard_normal((2, 6, 4)))
    out = m(x, gate)
    assert out.shape == [2, 6, 16]
    # one-hot gate == that expert alone
    g = np.full((2, 6, 4), -1e9, np.float32)
    g[..., 1] = 0.0
    only1 = m(x, T(g)).numpy()
    assert np.isfinite(only1).all()


def test_incubate_autograd_vjp_jvp():
    f = lambda x: (x * x).sum()
    x = T([1.0, 2.0, 3.0])
    out, g = iag.vjp(f, x)
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0, 6.0])
    out, t = iag.jvp(lambda x: x * x, x, T([1.0, 1.0, 1.0]))
    np.testing.assert_allclose(t.numpy(), [2.0, 4.0, 6.0])
    fg = iag.forward_grad(lambda x: x * 3.0, x, T([1.0, 0.0, 0.0]))
    np.testing.assert_allclose(fg.numpy(), [3.0, 0.0, 0.0])
    g2 = iag.grad(f, x)
    np.testing.assert_allclose(g2.numpy(), [2.0, 4.0, 6.0])
    iag.enable_prim(); assert iag.prim_enabled(); iag.disable_prim()


def test_fused_multi_transformer_cached_decode_matches_full():
    """Incremental cached decode through FusedMultiTransformer equals the
    full-sequence forward position by position."""
    paddle.seed(5)
    mt = inn.FusedMultiTransformer(16, 4, 32, num_layers=2, dropout_rate=0.0)
    mt.eval()
    rng = np.random.default_rng(7)
    x = T(rng.standard_normal((2, 5, 16)))
    # full pass needs a causal mask to be comparable with incremental decode
    causal = paddle.to_tensor(np.tril(np.ones((1, 1, 5, 5), bool)))
    full = mt(x, attn_mask=causal).numpy()
    caches = mt.gen_caches(2, 8)
    outs = []
    from paddle_tpu.core.tensor import Tensor as Tn
    for t in range(5):
        step = Tn(x._data[:, t:t + 1])
        out, caches = mt(step, caches=caches, time_step=t)
        outs.append(out.numpy())
    np.testing.assert_allclose(outs[-1][:, 0], full[:, -1], rtol=1e-4,
                               atol=1e-5)


def test_fused_mha_matches_unfused_forward_and_backward():
    """Fused attention (packed [3,H,Dh,E] qkv, flash core) equals the
    plain nn.MultiHeadAttention with the same weights — outputs AND
    gradients (fusion must be a layout change, never a math change)."""
    from paddle_tpu import nn as pnn

    e, h = 16, 4
    dh = e // h
    rng = np.random.default_rng(7)
    x_np = rng.standard_normal((2, 6, e)).astype(np.float32)
    w_np = rng.standard_normal((2, 6, e)).astype(np.float32)

    fused = inn.FusedMultiHeadAttention(e, h, dropout_rate=0.0,
                                        attn_dropout_rate=0.0)
    plain = pnn.MultiHeadAttention(e, h)
    # fused packs [3, H, Dh, E] (w @ x convention per slice); plain's
    # Linear holds [E, E] with x @ w
    import jax.numpy as jnp

    qkv = np.asarray(fused.qkv_weight._data)  # [3, H, Dh, E]
    for i, proj in enumerate((plain.q_proj, plain.k_proj, plain.v_proj)):
        proj.weight._data = jnp.asarray(qkv[i].reshape(e, e).T)
        proj.bias._data = jnp.asarray(
            np.asarray(fused.qkv_bias._data)[i].reshape(e))
    plain.out_proj.weight._data = fused.linear_weight._data
    plain.out_proj.bias._data = fused.linear_bias._data

    xf = T(x_np); xf.stop_gradient = False
    xp = T(x_np); xp.stop_gradient = False
    of = fused(xf)
    # fused applies residual + post-LN (normalize_before=False): build the
    # same residual+LN around the plain attention with fused's ln params
    op_ = plain(xp, xp, xp)
    op_ = pnn.functional.layer_norm(
        op_ + xp, normalized_shape=[e],
        weight=T(np.asarray(fused.ln_scale._data)),
        bias=T(np.asarray(fused.ln_bias._data)))
    np.testing.assert_allclose(np.asarray(of._data), np.asarray(op_._data),
                               rtol=2e-4, atol=2e-4)

    (of * T(w_np)).sum().backward()
    (op_ * T(w_np)).sum().backward()
    np.testing.assert_allclose(np.asarray(xf.grad._data),
                               np.asarray(xp.grad._data),
                               rtol=2e-3, atol=2e-4,
                               err_msg="fused vs unfused input grad")
    # packed qkv grad slices equal the plain projections' grads
    qg = np.asarray(fused.qkv_weight.grad._data)
    for i, proj in enumerate((plain.q_proj, plain.k_proj, plain.v_proj)):
        np.testing.assert_allclose(qg[i].reshape(e, e),
                                   np.asarray(proj.weight.grad._data).T,
                                   rtol=2e-3, atol=2e-4,
                                   err_msg=f"qkv slice {i} grad")

"""Paddle-specific index/scatter semantics (these diverge from torch/numpy
in overwrite behavior and axis conventions — ref:python/paddle/tensor/
manipulation.py docstring contracts)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def T(x, dt=None):
    return paddle.to_tensor(np.asarray(x, dt) if dt else np.asarray(x))


def test_scatter_overwrite_true():
    x = np.ones((3, 2), np.float32)
    index = np.array([2, 1, 0, 1], np.int64)
    updates = np.arange(8, dtype=np.float32).reshape(4, 2)
    out = paddle.scatter(T(x), T(index), T(updates), overwrite=True).numpy()
    # duplicate index 1: last write wins (paddle contract)
    np.testing.assert_array_equal(out[2], updates[0])
    np.testing.assert_array_equal(out[1], updates[3])
    np.testing.assert_array_equal(out[0], updates[2])


def test_scatter_overwrite_false_accumulates():
    x = np.zeros((3, 2), np.float32)
    index = np.array([1, 1, 0], np.int64)
    updates = np.ones((3, 2), np.float32)
    out = paddle.scatter(T(x), T(index), T(updates), overwrite=False).numpy()
    np.testing.assert_array_equal(out[1], [2.0, 2.0])
    np.testing.assert_array_equal(out[0], [1.0, 1.0])
    np.testing.assert_array_equal(out[2], [0.0, 0.0])


def test_scatter_nd_add():
    x = np.zeros((4,), np.float32)
    index = np.array([[1], [1], [3]], np.int64)
    updates = np.array([1.0, 2.0, 5.0], np.float32)
    out = paddle.scatter_nd_add(T(x), T(index), T(updates)).numpy()
    np.testing.assert_array_equal(out, [0.0, 3.0, 0.0, 5.0])


def test_put_along_axis_modes():
    x = np.zeros((2, 3), np.float32)
    idx = np.array([[0, 1, 2], [2, 1, 0]], np.int64)
    val = np.ones((2, 3), np.float32)
    out = paddle.put_along_axis(T(x), T(idx), T(val), axis=1).numpy()
    np.testing.assert_array_equal(out, np.ones((2, 3)))
    out = paddle.put_along_axis(T(np.ones((2, 3), np.float32)), T(idx),
                                T(val), axis=1, reduce="add").numpy()
    np.testing.assert_array_equal(out, np.full((2, 3), 2.0))


def test_index_sample():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    index = np.array([[0, 2], [1, 3], [0, 0]], np.int64)
    out = paddle.index_sample(T(x), T(index)).numpy()
    np.testing.assert_array_equal(out, [[0, 2], [5, 7], [8, 8]])


def test_index_select_and_index_add():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = paddle.index_select(T(x), T(np.array([2, 0], np.int64)),
                              axis=0).numpy()
    np.testing.assert_array_equal(out, x[[2, 0]])
    added = paddle.index_add(T(x), T(np.array([0, 0], np.int64)), 0,
                             T(np.ones((2, 4), np.float32))).numpy()
    np.testing.assert_array_equal(added[0], x[0] + 2.0)
    np.testing.assert_array_equal(added[1:], x[1:])


def test_gather_nd_and_take_along_axis():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    index = np.array([[0, 2], [1, 0]], np.int64)
    out = paddle.gather_nd(T(x), T(index)).numpy()
    np.testing.assert_array_equal(out, np.stack([x[0, 2], x[1, 0]]))
    idx = np.array([[[1], [0], [3]]], np.int64)
    out = paddle.take_along_axis(T(x[:1]), T(idx), axis=2).numpy()
    np.testing.assert_array_equal(out[0, :, 0], [x[0, 0, 1], x[0, 1, 0],
                                                 x[0, 2, 3]])


def test_masked_select_and_fill():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    mask = x > 2
    out = paddle.masked_select(T(x), T(mask)).numpy()
    np.testing.assert_array_equal(out, [3, 4, 5])
    filled = paddle.masked_fill(T(x), T(mask), -1.0).numpy()
    np.testing.assert_array_equal(filled, np.where(mask, -1.0, x))


def test_index_put_absent_matches_reference_surface():
    # the reference snapshot predates paddle.index_put; we track its surface
    assert not hasattr(paddle, "index_put")

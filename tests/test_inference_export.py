"""jit.save/load StableHLO export + inference predictor."""
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import inference, nn
from paddle_tpu.jit import InputSpec


def _model():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))


def test_export_and_load_runs_without_model_code(tmp_path):
    m = _model()
    m.eval()
    x = np.random.rand(3, 4).astype(np.float32)
    expected = m(paddle.to_tensor(x)).numpy()
    prefix = os.path.join(str(tmp_path), "deploy", "model")
    paddle.jit.save(m, prefix, input_spec=[InputSpec([3, 4], "float32")])
    assert os.path.exists(prefix + ".pdmodel")
    loaded = paddle.jit.load(prefix)
    out = loaded(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), expected, atol=1e-5)


def test_static_save_load_inference_model(tmp_path):
    m = _model()
    m.eval()
    prefix = os.path.join(str(tmp_path), "infer")
    paddle.static.save_inference_model(prefix, m, [InputSpec([2, 4])])
    loaded = paddle.static.load_inference_model(prefix)
    x = np.random.rand(2, 4).astype(np.float32)
    np.testing.assert_allclose(loaded(x).numpy(), m(paddle.to_tensor(x)).numpy(),
                               atol=1e-5)


def test_predictor_api(tmp_path):
    m = _model()
    m.eval()
    prefix = os.path.join(str(tmp_path), "pred")
    paddle.jit.save(m, prefix, input_spec=[InputSpec([2, 4])])
    cfg = inference.Config(prefix + ".pdmodel")
    predictor = inference.create_predictor(cfg)
    x = np.random.rand(2, 4).astype(np.float32)
    h = predictor.get_input_handle("input_0")
    h.copy_from_cpu(x)
    predictor.run()
    out = predictor.get_output_handle("output_0").copy_to_cpu()
    np.testing.assert_allclose(out, m(paddle.to_tensor(x)).numpy(), atol=1e-5)


def test_static_program_apis_are_real():
    # Program/data/Executor are real capture machinery now (round 4) — the
    # legacy *serialization* path stays a redirect (StableHLO export is the
    # deployment story)
    import pytest

    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data("x", [1])
        assert getattr(x, "_sym_id", None) is not None
    with pytest.raises(NotImplementedError):
        paddle.static.serialize_program()

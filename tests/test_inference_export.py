"""jit.save/load StableHLO export + inference predictor."""
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import inference, nn
from paddle_tpu.jit import InputSpec


def _model():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))


def test_export_and_load_runs_without_model_code(tmp_path):
    m = _model()
    m.eval()
    x = np.random.rand(3, 4).astype(np.float32)
    expected = m(paddle.to_tensor(x)).numpy()
    prefix = os.path.join(str(tmp_path), "deploy", "model")
    paddle.jit.save(m, prefix, input_spec=[InputSpec([3, 4], "float32")])
    assert os.path.exists(prefix + ".pdmodel")
    loaded = paddle.jit.load(prefix)
    out = loaded(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), expected, atol=1e-5)


def test_static_save_load_inference_model(tmp_path):
    m = _model()
    m.eval()
    prefix = os.path.join(str(tmp_path), "infer")
    paddle.static.save_inference_model(prefix, m, [InputSpec([2, 4])])
    loaded = paddle.static.load_inference_model(prefix)
    x = np.random.rand(2, 4).astype(np.float32)
    np.testing.assert_allclose(loaded(x).numpy(), m(paddle.to_tensor(x)).numpy(),
                               atol=1e-5)


def test_predictor_api(tmp_path):
    m = _model()
    m.eval()
    prefix = os.path.join(str(tmp_path), "pred")
    paddle.jit.save(m, prefix, input_spec=[InputSpec([2, 4])])
    cfg = inference.Config(prefix + ".pdmodel")
    predictor = inference.create_predictor(cfg)
    x = np.random.rand(2, 4).astype(np.float32)
    h = predictor.get_input_handle("input_0")
    h.copy_from_cpu(x)
    predictor.run()
    out = predictor.get_output_handle("output_0").copy_to_cpu()
    np.testing.assert_allclose(out, m(paddle.to_tensor(x)).numpy(), atol=1e-5)


def test_static_program_apis_are_real():
    # Program/data/Executor are real capture machinery now (round 4) — the
    # legacy *serialization* path stays a redirect (StableHLO export is the
    # deployment story)
    import pytest

    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data("x", [1])
        assert getattr(x, "_sym_id", None) is not None
    with pytest.raises(NotImplementedError):
        paddle.static.serialize_program()


def test_scanned_model_exports_and_roundtrips(tmp_path):
    """A use_scan_layers model exports to StableHLO (the program contains
    while ops from lax.scan) and loads back bit-exact — the deploy story
    must not depend on the execution strategy chosen at training time."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(21)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=3,
                    num_heads=4, max_position_embeddings=64,
                    use_scan_layers=True)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = np.random.default_rng(8).integers(0, 256, (2, 16), dtype=np.int32)
    x = paddle.to_tensor(ids)
    ref = m(x).numpy()
    prefix = os.path.join(str(tmp_path), "scan_gpt")
    paddle.jit.save(m, prefix, input_spec=[InputSpec([2, 16], "int32")])
    out = paddle.jit.load(prefix)(x).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-5)

"""Inplace op variants: value semantics, identity return, version bumps,
and tape safety (ref:python/paddle/tensor `*_` ops + inplace_version)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def T(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


def test_inplace_math_family():
    x = T(np.ones(4))
    assert x.add_(T(np.full(4, 2.0))) is x
    np.testing.assert_allclose(x.numpy(), 3.0)
    x.subtract_(T(np.ones(4)))
    x.multiply_(T(np.full(4, 3.0)))
    np.testing.assert_allclose(x.numpy(), 6.0)
    x.sqrt_()
    np.testing.assert_allclose(x.numpy(), np.sqrt(6.0), rtol=1e-6)
    x.fill_(0.25)
    x.rsqrt_()
    np.testing.assert_allclose(x.numpy(), 2.0, rtol=1e-6)


def test_inplace_shape_family():
    t = T(np.arange(6).reshape(2, 3))
    assert t.reshape_([3, 2]) is t and t.shape == [3, 2]
    t.flatten_()
    assert t.shape == [6]
    t.unsqueeze_(0)
    assert t.shape == [1, 6]
    t.squeeze_()
    assert t.shape == [6]


def test_inplace_bumps_version():
    x = T(np.ones(3))
    v0 = x._version
    x.add_(T(np.ones(3)))
    x.zero_()
    assert x._version == v0 + 2


def test_inplace_after_save_for_backward_raises():
    leaf = T(np.ones(3))
    leaf.stop_gradient = False
    x = leaf * 1.0  # non-leaf (leaf mutation is rejected upfront)
    y = (x * x).sum()  # saves x for the backward
    x.add_(T(np.ones(3)))  # mutates after save
    with pytest.raises(RuntimeError, match="[Ii]n-place|version"):
        y.backward()


def test_inplace_on_grad_leaf_rejected():
    x = T(np.ones(3))
    x.stop_gradient = False
    with pytest.raises(RuntimeError, match="[Ll]eaf"):
        x.add_(T(np.ones(3)))
    with paddle.no_grad():
        x.add_(T(np.ones(3)))  # allowed without grad tracking
    np.testing.assert_allclose(x.numpy(), 2.0)


def test_scatter_and_index_add_inplace():
    x = T(np.zeros((3, 2)))
    x.scatter_(paddle.to_tensor(np.array([1], np.int64)),
               T(np.ones((1, 2))))
    np.testing.assert_allclose(x.numpy()[1], 1.0)
    x.index_add_(paddle.to_tensor(np.array([0], np.int64)), 0,
                 T(np.full((1, 2), 5.0)))
    np.testing.assert_allclose(x.numpy()[0], 5.0)


def test_uniform_and_fill_diagonal():
    paddle.seed(3)
    t = T(np.zeros((4, 4)))
    t.uniform_(0.0, 2.0)
    assert 0.0 <= t.numpy().min() and t.numpy().max() <= 2.0
    t.zero_()
    t.fill_diagonal_(1.0)
    np.testing.assert_allclose(t.numpy(), np.eye(4))


def test_tensor_T_property():
    t = T(np.arange(6).reshape(2, 3))
    assert t.T.shape == [3, 2]
    np.testing.assert_array_equal(t.T.numpy(), t.numpy().T)
    u = T(np.arange(24).reshape(2, 3, 4))
    assert u.T.shape == [4, 3, 2]
    v = T(np.arange(3))
    assert v.T.shape == [3]  # <2-D: unchanged (paddle contract)


def test_mask_assignment_and_grad():
    t = T(np.arange(4))
    t[paddle.to_tensor(np.array([True, False, True, False]))] = -1.0
    np.testing.assert_allclose(t.numpy(), [-1, 1, -1, 3])
    x = T(np.ones(4))
    x.stop_gradient = False
    y = x * 2.0
    y[paddle.to_tensor(np.array([True, True, False, False]))] = 0.0
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0, 0, 2, 2])


def test_fill_diagonal_offsets_and_wrap():
    m = T(np.zeros((2, 5)))
    m.fill_diagonal_(1.0, offset=2)
    np.testing.assert_allclose(m.numpy(),
                               [[0, 0, 1, 0, 0], [0, 0, 0, 1, 0]])
    tall = T(np.zeros((5, 2)))
    tall.fill_diagonal_(1.0, wrap=True)
    ref = np.zeros((5, 2))
    np.fill_diagonal(ref, 1.0, wrap=True)
    np.testing.assert_allclose(tall.numpy(), ref)
    cube = T(np.zeros((3, 3, 3)))
    cube.fill_diagonal_(7.0)
    assert cube.numpy().sum() == 21.0


def test_uniform_preserves_trainability():
    p = T(np.zeros(4))
    p.stop_gradient = False
    with paddle.no_grad():
        p.uniform_()
    assert not p.stop_gradient

"""Launcher CLI + spawn: env contract, failure handling, elastic restart."""
import os
import subprocess
import sys
import textwrap

import pytest


def _run_launch(tmp_path, script_body, extra_args=None, nproc=2):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(script_body))
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           f"--nproc_per_node={nproc}", f"--log_dir={tmp_path}/log"]
    cmd += (extra_args or [])
    cmd += [str(script)]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")
    return subprocess.run(cmd, capture_output=True, text=True, timeout=120,
                          env=env, cwd=str(tmp_path))


def test_launch_sets_env_contract(tmp_path):
    r = _run_launch(tmp_path, """
        import os
        rank = os.environ["PADDLE_TRAINER_ID"]
        n = os.environ["PADDLE_TRAINERS_NUM"]
        eps = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
        cur = os.environ["PADDLE_CURRENT_ENDPOINT"]
        assert len(eps) == int(n) == 2
        assert cur == eps[int(rank)]
        print("WORKER_OK", rank)
    """)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "WORKER_OK 0" in r.stdout
    log1 = (tmp_path / "log" / "workerlog.1").read_text()
    assert "WORKER_OK 1" in log1


def test_launch_propagates_failure(tmp_path):
    r = _run_launch(tmp_path, """
        import os, sys
        sys.exit(3 if os.environ["PADDLE_TRAINER_ID"] == "1" else 0)
    """)
    assert r.returncode == 3


def test_launch_elastic_restarts(tmp_path):
    # worker fails once (flag file), succeeds after restart
    r = _run_launch(tmp_path, """
        import os, sys
        flag = "restarted.flag"
        if not os.path.exists(flag):
            open(flag, "w").close()
            sys.exit(1)
        print("RECOVERED")
    """, extra_args=["--elastic_level=1", "--max_restart=2"], nproc=1)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RECOVERED" in r.stdout


def test_spawn_runs_function_per_rank():
    from paddle_tpu.distributed.spawn import spawn

    results = spawn(_rank_fn, nprocs=2)
    assert sorted(results) == [0, 1]


def _rank_fn():
    import os

    return int(os.environ["PADDLE_TRAINER_ID"])


def test_spawn_tcpstore_cross_process():
    from paddle_tpu.distributed.spawn import spawn

    results = spawn(_store_fn, nprocs=2)
    assert sorted(results) == [b"from_rank_0", b"from_rank_1"]


def _store_fn():
    import os

    from paddle_tpu.distributed.store import TCPStore

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    host, port = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")[0].rsplit(":", 1)
    store = TCPStore(host, int(port), is_master=(rank == 0), world_size=2)
    store.set(f"msg/{rank}", f"from_rank_{rank}")
    store.barrier("x")
    other = store.wait(f"msg/{1 - rank}")
    store.barrier("y")
    store.close()
    return other


def test_launch_ps_mode_servers_and_trainers(tmp_path):
    """--server_num spawns PSERVER-role processes (TRAINING_ROLE contract)
    that serve tables until every trainer exits; the one script runs both
    roles via fleet.is_server() — the reference PS launch shape."""
    r = _run_launch(tmp_path, """
        import os
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        from paddle_tpu.distributed import fleet, ps

        if fleet.is_server():
            os.environ.setdefault("PADDLE_PS_DIM", "8")
            fleet.run_server()           # blocks until the launcher retires us
        else:
            assert fleet.is_worker()
            client = ps.init_from_env(dim=8)
            rank = int(os.environ["PADDLE_TRAINER_ID"])
            ids = np.arange(16, dtype=np.uint64)
            client.pull(ids)
            client.push(ids, np.ones((16, 8), np.float32), lr=0.1)
            rows = client.pull(ids)
            assert np.isfinite(rows).all()
            print("PS_WORKER_OK", rank)
    """, extra_args=["--server_num=2", "--trainer_num=2"], nproc=1)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PS_WORKER_OK 0" in r.stdout
    log1 = (tmp_path / "log" / "workerlog.1").read_text()
    assert "PS_WORKER_OK 1" in log1

"""Linalg correctness: reconstruction/identity properties (sign/ordering
conventions vary across backends, so tests verify the defining equations —
ref:python/paddle/tensor/linalg.py contracts)."""
import numpy as np
import pytest

import paddle_tpu as paddle

RNG = np.random.default_rng(1)


def T(x):
    return paddle.to_tensor(np.asarray(x))


def _spd(n):
    a = RNG.standard_normal((n, n))
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


def test_svd_reconstructs():
    a = RNG.standard_normal((5, 3)).astype(np.float32)
    u, s, vh = paddle.linalg.svd(T(a))
    rec = u.numpy()[:, :3] @ np.diag(s.numpy()) @ vh.numpy()
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-5)
    assert (np.diff(s.numpy()) <= 1e-6).all()  # descending singular values


def test_qr_reconstructs_orthonormal():
    a = RNG.standard_normal((6, 4)).astype(np.float32)
    q, r = paddle.linalg.qr(T(a))
    np.testing.assert_allclose(q.numpy() @ r.numpy(), a, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(q.numpy().T @ q.numpy(), np.eye(4), atol=1e-5)
    np.testing.assert_allclose(np.tril(r.numpy(), -1), 0, atol=1e-6)


def test_eigh_spd():
    a = _spd(4)
    w, v = paddle.linalg.eigh(T(a))
    rec = v.numpy() @ np.diag(w.numpy()) @ v.numpy().T
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-4)
    assert (w.numpy() > 0).all()


def test_cholesky_and_solve():
    a = _spd(4)
    L = paddle.linalg.cholesky(T(a)).numpy()
    np.testing.assert_allclose(L @ L.T, a, rtol=1e-4, atol=1e-4)
    b = RNG.standard_normal((4, 2)).astype(np.float32)
    x = paddle.linalg.solve(T(a), T(b)).numpy()
    np.testing.assert_allclose(a @ x, b, rtol=1e-3, atol=1e-3)
    xc = paddle.linalg.cholesky_solve(T(b), T(L), upper=False).numpy()
    np.testing.assert_allclose(a @ xc, b, rtol=1e-3, atol=1e-3)


def test_triangular_solve():
    a = np.triu(_spd(4))
    b = RNG.standard_normal((4, 1)).astype(np.float32)
    x = paddle.linalg.triangular_solve(T(a), T(b), upper=True).numpy()
    np.testing.assert_allclose(a @ x, b, rtol=1e-3, atol=1e-3)


def test_lu_unpack_reconstructs():
    a = RNG.standard_normal((4, 4)).astype(np.float32)
    lu, piv = paddle.linalg.lu(T(a))
    p, L, U = paddle.linalg.lu_unpack(lu, piv)
    rec = p.numpy() @ L.numpy() @ U.numpy()
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-4)


def test_inv_pinv_det():
    a = _spd(3)
    inv = paddle.linalg.inv(T(a)).numpy()
    np.testing.assert_allclose(a @ inv, np.eye(3), atol=1e-4)
    r = RNG.standard_normal((5, 3)).astype(np.float32)
    pinv = paddle.linalg.pinv(T(r)).numpy()
    np.testing.assert_allclose(r @ pinv @ r, r, rtol=1e-3, atol=1e-3)
    det = float(paddle.linalg.det(T(a)).numpy())
    np.testing.assert_allclose(det, np.linalg.det(a.astype(np.float64)),
                               rtol=1e-4)
    sign, logd = paddle.linalg.slogdet(T(a))
    np.testing.assert_allclose(float(sign.numpy()) * np.exp(float(logd.numpy())),
                               det, rtol=1e-4)


def test_lstsq():
    a = RNG.standard_normal((6, 3)).astype(np.float32)
    b = RNG.standard_normal((6, 2)).astype(np.float32)
    sol = paddle.linalg.lstsq(T(a), T(b))[0].numpy()
    want = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(sol, want, rtol=1e-3, atol=1e-3)


def test_matrix_rank_power_cond():
    a = np.zeros((4, 4), np.float32)
    a[:2, :2] = _spd(2)
    assert int(paddle.linalg.matrix_rank(T(a)).numpy()) == 2
    m = _spd(3)
    p3 = paddle.linalg.matrix_power(T(m), 3).numpy()
    np.testing.assert_allclose(p3, m @ m @ m, rtol=1e-3)
    c = float(paddle.linalg.cond(T(m)).numpy())
    np.testing.assert_allclose(c, np.linalg.cond(m.astype(np.float64)),
                               rtol=1e-3)


def test_norms():
    x = RNG.standard_normal((3, 4)).astype(np.float32)
    np.testing.assert_allclose(float(paddle.linalg.norm(T(x)).numpy()),
                               np.linalg.norm(x), rtol=1e-5)
    np.testing.assert_allclose(
        float(paddle.linalg.norm(T(x), p="fro").numpy()),
        np.linalg.norm(x, "fro"), rtol=1e-5)
    np.testing.assert_allclose(
        paddle.linalg.norm(T(x), p=1, axis=1).numpy(),
        np.abs(x).sum(1), rtol=1e-5)


def test_multi_dot_cov_corrcoef():
    a = RNG.standard_normal((3, 4)).astype(np.float32)
    b = RNG.standard_normal((4, 5)).astype(np.float32)
    c = RNG.standard_normal((5, 2)).astype(np.float32)
    got = paddle.linalg.multi_dot([T(a), T(b), T(c)]).numpy()
    np.testing.assert_allclose(got, a @ b @ c, rtol=1e-4, atol=1e-4)
    x = RNG.standard_normal((4, 10)).astype(np.float32)
    np.testing.assert_allclose(paddle.linalg.cov(T(x)).numpy(), np.cov(x),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(paddle.linalg.corrcoef(T(x)).numpy(),
                               np.corrcoef(x), rtol=1e-4, atol=1e-5)

"""LR scheduler value sequences vs hand-computed reference formulas
(ref:python/paddle/optimizer/lr.py docstring math)."""
import math

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.optimizer import lr as L


def _values(sched, n):
    out = []
    for _ in range(n):
        out.append(float(sched()))
        sched.step()
    return out


def test_step_decay():
    s = L.StepDecay(learning_rate=1.0, step_size=3, gamma=0.5)
    vals = _values(s, 8)
    np.testing.assert_allclose(vals, [1, 1, 1, .5, .5, .5, .25, .25])


def test_multistep_decay():
    s = L.MultiStepDecay(learning_rate=1.0, milestones=[2, 5], gamma=0.1)
    vals = _values(s, 7)
    np.testing.assert_allclose(vals, [1, 1, .1, .1, .1, .01, .01])


def test_exponential_decay():
    s = L.ExponentialDecay(learning_rate=2.0, gamma=0.9)
    vals = _values(s, 4)
    np.testing.assert_allclose(vals, [2 * 0.9 ** i for i in range(4)],
                               rtol=1e-6)


def test_natural_exp_decay():
    s = L.NaturalExpDecay(learning_rate=1.0, gamma=0.5)
    vals = _values(s, 3)
    np.testing.assert_allclose(vals, [math.exp(-0.5 * i) for i in range(3)],
                               rtol=1e-6)


def test_inverse_time_decay():
    s = L.InverseTimeDecay(learning_rate=1.0, gamma=0.5)
    vals = _values(s, 3)
    np.testing.assert_allclose(vals, [1 / (1 + 0.5 * i) for i in range(3)],
                               rtol=1e-6)


def test_polynomial_decay():
    s = L.PolynomialDecay(learning_rate=1.0, decay_steps=4, end_lr=0.1,
                          power=1.0)
    vals = _values(s, 6)
    expect = [(1.0 - 0.1) * (1 - min(i, 4) / 4) ** 1.0 + 0.1
              for i in range(6)]
    np.testing.assert_allclose(vals, expect, rtol=1e-6)


def test_cosine_annealing():
    s = L.CosineAnnealingDecay(learning_rate=1.0, T_max=10, eta_min=0.0)
    vals = _values(s, 11)
    assert abs(vals[0] - 1.0) < 1e-6
    # the reference's recursive formulation hits ~eta_min at T_max
    assert vals[10] < 0.01
    assert all(vals[i + 1] <= vals[i] + 1e-6 for i in range(10))


def test_linear_warmup():
    s = L.LinearWarmup(learning_rate=1.0, warmup_steps=4, start_lr=0.0,
                       end_lr=1.0)
    vals = _values(s, 6)
    np.testing.assert_allclose(vals[:4], [0.0, 0.25, 0.5, 0.75], rtol=1e-6)
    np.testing.assert_allclose(vals[4:], 1.0, rtol=1e-6)


def test_noam_decay():
    d, warm = 64, 10
    s = L.NoamDecay(d_model=d, warmup_steps=warm, learning_rate=1.0)
    vals = _values(s, 12)
    expect = [d ** -0.5 * min((i or 1) ** -0.5, (i or 1) * warm ** -1.5)
              for i in range(12)]
    np.testing.assert_allclose(vals[1:], expect[1:], rtol=1e-5)


def test_piecewise_decay():
    s = L.PiecewiseDecay(boundaries=[2, 4], values=[1.0, 0.5, 0.1])
    vals = _values(s, 6)
    np.testing.assert_allclose(vals, [1, 1, .5, .5, .1, .1])


def test_lambda_and_multiplicative():
    s = L.LambdaDecay(learning_rate=2.0, lr_lambda=lambda e: 0.9 ** e)
    np.testing.assert_allclose(_values(s, 3), [2 * 0.9 ** i
                                               for i in range(3)], rtol=1e-6)
    s = L.MultiplicativeDecay(learning_rate=1.0, lr_lambda=lambda e: 0.5)
    np.testing.assert_allclose(_values(s, 3), [1.0, 0.5, 0.25], rtol=1e-6)


def test_one_cycle():
    s = L.OneCycleLR(max_learning_rate=1.0, total_steps=10, phase_pct=0.3)
    vals = _values(s, 10)
    peak = np.argmax(vals)
    assert 2 <= peak <= 4  # peak near phase_pct * total_steps
    assert vals[0] < vals[peak] and vals[-1] < vals[peak] / 10


def test_reduce_on_plateau_scheduler():
    s = L.ReduceOnPlateau(learning_rate=1.0, factor=0.5, patience=1,
                          cooldown=0)
    assert float(s()) == 1.0
    s.step(metrics=1.0)
    s.step(metrics=1.0)
    s.step(metrics=1.0)
    assert float(s()) <= 0.5

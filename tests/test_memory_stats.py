"""Memory stat registry + device properties + stream/event surface.

The reference's registry contract (ref:paddle/fluid/memory/stats.h:50):
thread-local current aggregated on read, monotone global peak, string-keyed
update. Host side is ours to track (shm transport, PS tables); device side
is read-only from PJRT.
"""
import threading

import numpy as np
import pytest

from paddle_tpu.core import memory_stats as ms


def test_stat_current_and_peak():
    s = ms.Stat()
    s.update(100)
    s.update(50)
    assert s.current_value() == 150
    assert s.peak_value() == 150
    s.update(-120)
    assert s.current_value() == 30
    assert s.peak_value() == 150  # peak is monotone
    s.reset_peak()
    assert s.peak_value() == 30


def test_stat_aggregates_across_threads():
    s = ms.Stat()

    def work(n):
        for _ in range(n):
            s.update(10)

    ts = [threading.Thread(target=work, args=(100,)) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert s.current_value() == 4 * 100 * 10
    assert s.peak_value() == s.current_value()


def test_string_keyed_host_registry():
    ms.host_memory_stat_update("UnitTestStat", 3, 4096)
    assert ms.host_memory_stat_current_value("UnitTestStat", 3) == 4096
    assert ms.host_memory_stat_peak_value("UnitTestStat", 3) == 4096
    ms.host_memory_stat_update("UnitTestStat", 3, -4096)
    assert ms.host_memory_stat_current_value("UnitTestStat", 3) == 0
    assert ms.host_memory_stat_peak_value("UnitTestStat", 3) == 4096
    # other (type, dev) keys are independent
    assert ms.host_memory_stat_current_value("UnitTestStat", 0) == 0


def test_provider_gauge_in_stats_and_summary():
    ms.register_stat_provider("unittest_gauge", lambda: 12345)
    try:
        stats = ms.memory_stats()
        assert stats["provider.unittest_gauge"] == 12345
        summary = ms.memory_summary()
        assert "unittest_gauge" in summary
        assert "paddle_tpu memory summary" in summary
    finally:
        ms.unregister_stat_provider("unittest_gauge")
    assert "provider.unittest_gauge" not in ms.memory_stats()


def test_shm_transport_accounted():
    """DataLoader shm transport: attach/unlink in the consuming process
    updates the ShmTransport host stat (current returns to 0, peak records
    the segment size)."""
    from paddle_tpu.io import worker as w

    before_peak = ms.host_memory_stat_peak_value("ShmTransport", 0)
    arr = np.arange(8192, dtype=np.float32)  # 32 KiB > shm threshold
    packed = w._pack_leaf(arr, use_shm=True)
    assert packed[0] == "shm"
    out = w._unpack_leaf(packed)
    np.testing.assert_array_equal(out, arr)
    assert ms.host_memory_stat_current_value("ShmTransport", 0) == 0
    assert ms.host_memory_stat_peak_value("ShmTransport", 0) >= max(
        before_peak, arr.nbytes)


def test_ps_table_provider_registered():
    native = pytest.importorskip("paddle_tpu.native")
    try:
        native.load()
    except Exception:
        pytest.skip("native lib unavailable")
    from paddle_tpu.distributed.ps import EmbeddingServer

    srv = EmbeddingServer(dim=8, rule="sgd")
    name = f"provider.ps_table:{srv.port}"
    try:
        assert name in ms.memory_stats()
    finally:
        srv.stop()
    assert name not in ms.memory_stats()


def test_device_namespace_surface():
    import paddle_tpu.device as D

    stats = D.memory_stats()
    assert isinstance(stats, dict)
    assert isinstance(D.memory_summary(), str)
    D.reset_max_memory_allocated()
    # CPU test backend: PJRT reports no stats; the calls still work
    assert D.memory_allocated() >= 0

    props = D.get_device_properties(0)
    assert props.name
    assert "_DeviceProperties" in repr(props)
    assert D.get_device_name() == props.name
    major, minor = D.get_device_capability()
    assert (major, minor) == (props.major, props.minor)
    with pytest.raises(ValueError):
        D.get_device_properties(999)


def test_stream_event_ordering_api():
    import jax.numpy as jnp

    import paddle_tpu.device as D

    s = D.current_stream()
    assert D.current_stream() is s  # stable handle
    e1 = D.Event(enable_timing=True)
    e2 = D.Event(enable_timing=True)
    e1.record()
    e1.synchronize()  # observe completions in record order
    _ = (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
    e2.record()
    e2.synchronize()
    assert e1.query() and e2.query()
    assert e1.elapsed_time(e2) >= 0.0
    ev = s.record_event()
    ev.synchronize()
    assert ev.query()
    with D.stream_guard(D.Stream()):
        assert D.current_stream() is not s
    assert D.current_stream() is s
    with pytest.raises(ValueError):
        D.Event(interprocess=True)
    with pytest.raises(ValueError):
        D.Event().elapsed_time(D.Event())

"""Mesh-sharded execution core (ISSUE 14): tensor-parallel serving and
data-parallel training over the ("data", "model") mesh, on the 8 virtual
CPU devices conftest forces.

The contract under test is the one the engine sells on a single chip,
extended to a mesh:

* sharded-vs-single-device GREEDY TOKEN PARITY — decode, prefix-cache
  hits, speculative decode, quantized serving, and LoRA adapters each
  reproduce the no-mesh engine token-for-token (GSPMD resharding may
  reassociate float reductions, so parity is asserted on emitted tokens,
  the serving observable);
* ZERO RECOMPILES under admit/retire churn with the mesh live
  (trace-counter asserted — block tables/positions stay runtime data,
  committed shardings never change between steps);
* the supervisor's rebuild/replay path re-commits the SAME pool
  shardings (``_arena_args`` carry the mesh), so recovery is
  zero-recompile and token-identical on a mesh too;
* a 1-DEVICE mesh is bit-identical to no mesh at all (same programs,
  same tokens) while keying differently (``mesh_axes_key`` joins the
  program keys like quant/donation);
* the acceptance shape: a model whose bf16-scale weights+arena would
  exceed one device's equal share actually serves with every device
  holding strictly less than the logical total (tensor parallelism is
  real, not annotation theater).
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import compile_cache, resilience
from paddle_tpu.distributed.mesh import get_mesh, serving_mesh
from paddle_tpu.distributed.sharding_util import mesh_axes_key
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (
    LoraAdapter,
    RequestState,
    SamplingParams,
    ServingAPI,
    ServingConfig,
)

MAX_LEN = 128


def _model(seed=0):
    paddle.seed(seed)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


def _workload(rng, n=5, max_new=8):
    lens = [8, 12, 20, 7, 16]
    return [(rng.integers(0, 1024, (lens[i % len(lens)],), dtype=np.int32),
             max_new) for i in range(n)]


def _serve(model, workload, submit_kw=None, **cfg_kw):
    cfg = ServingConfig(num_slots=4, kv_block_size=16, max_model_len=MAX_LEN,
                        **cfg_kw)
    api = ServingAPI(model, cfg)
    try:
        kws = submit_kw or [{}] * len(workload)
        reqs = [api.submit(p, max_new_tokens=n, **kw)
                for (p, n), kw in zip(workload, kws)]
        api.run_until_idle()
        assert all(r.state == RequestState.FINISHED for r in reqs)
        outs = [np.asarray(r.output_ids()) for r in reqs]
        stats = api.engine.stats()
        engine = api.engine
    finally:
        api.close()
    return outs, stats, engine


def _device0_bytes(arrays):
    """Bytes the first mesh device actually holds for ``arrays`` (the
    per-chip HBM share the sharding buys)."""
    total = 0
    for a in arrays:
        sh = getattr(a, "addressable_shards", None)
        total += int(sh[0].data.nbytes) if sh else int(a.nbytes)
    return total


def _model_arrays(model):
    params, buffers = model.functional_state()
    return [p._data for p in list(params.values()) + list(buffers.values())]


def _pool_arrays(arena):
    out = []
    for pools in [arena.pools] + [arena.ns_pools(n)
                                  for n in arena.namespaces()]:
        for entry in pools:
            out.extend(entry)
    return out


# --------------------------------------------------------------- parity


def test_tp_decode_token_parity_and_per_chip_share():
    """The headline gate: a (data=2, model=4) mesh engine reproduces the
    single-device engine token-for-token on a mixed workload, while every
    device holds strictly less than the logical weights+arena bytes —
    the config serves even where one device's equal share could not."""
    assert get_mesh() is None  # conftest reset: the reference is mesh-free
    w = _workload(np.random.default_rng(0))
    ref_outs, _, _ = _serve(_model(), w)

    serving_mesh(4, data=2)
    model = _model()
    outs, stats, engine = _serve(model, w)
    assert stats["mesh.key"] == (("data", 2), ("model", 4))
    for a, b in zip(ref_outs, outs):
        np.testing.assert_array_equal(a, b)

    arrays = _model_arrays(model) + _pool_arrays(engine.arena)
    logical = sum(int(a.nbytes) for a in arrays)
    per_chip = _device0_bytes(arrays)
    # tensor parallelism is real: the big arrays (attention/MLP weights,
    # vocab embedding, KV pools) are 4-way sharded; only the small
    # replicated remainder (LayerNorms, positions, biases) keeps this
    # above logical/4
    assert per_chip <= 0.55 * logical, (per_chip, logical)
    kp = engine.arena.pools[0][0]
    assert kp.addressable_shards[0].data.shape[2] \
        == kp.shape[2] // 4  # heads dim model-sharded


def test_zero_recompile_churn_on_live_mesh():
    """Admit/retire churn on a live mesh is runtime data only: ONE decode
    trace, one prefill trace per bucket, arena clean at the end."""
    serving_mesh(4, data=2)
    rng = np.random.default_rng(1)
    w = _workload(rng, n=8, max_new=6)
    outs, stats, engine = _serve(_model(), w)
    assert stats["decode_traces"] == 1
    assert all(v == 1 for v in stats["prefill_traces"].values())
    assert stats["arena.blocks_in_use"] == 0
    assert stats["arena.blocks_reserved"] == 0
    assert stats["arena.mesh"] == (("data", 2), ("model", 4))


def test_prefix_hit_parity_on_mesh():
    """Radix-cache hits attach host-side block ids — layout-agnostic by
    construction: hit-path tokens equal the no-mesh hit-path tokens."""
    rng = np.random.default_rng(2)
    sys_p = rng.integers(0, 1024, (32,), dtype=np.int32)
    w = [(np.concatenate([sys_p,
                          rng.integers(0, 1024, (6,), dtype=np.int32)]), 8)
         for _ in range(4)]
    ref_outs, ref_stats, _ = _serve(_model(), w, prefix_cache=True)
    assert ref_stats["prefix.hits"] >= 3

    serving_mesh(4, data=2)
    outs, stats, _ = _serve(_model(), w, prefix_cache=True)
    assert stats["prefix.hits"] >= 3
    for a, b in zip(ref_outs, outs):
        np.testing.assert_array_equal(a, b)


def test_spec_decode_parity_on_mesh():
    """Lockstep speculative decode (fused multi-token sub-steps) over
    sharded pools: tokens equal the plain no-mesh engine's."""
    w = _workload(np.random.default_rng(3), n=4)
    ref_outs, _, _ = _serve(_model(), w)

    serving_mesh(4, data=2)
    outs, stats, _ = _serve(_model(), w, spec_k=2)
    assert stats["spec.mode"] == "lockstep"
    assert stats["spec.emitted"] > 0
    for a, b in zip(ref_outs, outs):
        np.testing.assert_array_equal(a, b)


def test_quant_serving_parity_on_mesh():
    """int8 weight-only decode + int8 KV arena on the mesh: tokens equal
    the quantized no-mesh engine's; the int8 payload pools shard over the
    model axis while the per-block scale pools replicate (the 4-tuple
    placement rule of sharding_util.shard_kv_entry)."""
    w = _workload(np.random.default_rng(4), n=4)
    ref_outs, _, _ = _serve(_model(), w, quant_weights=True, quant_kv=True)

    serving_mesh(4, data=2)
    outs, stats, engine = _serve(_model(), w, quant_weights=True,
                                 quant_kv=True)
    assert stats["quant.weights"] == 1 and stats["quant.kv"] == 1
    for a, b in zip(ref_outs, outs):
        np.testing.assert_array_equal(a, b)
    entry = engine.arena.pools[0]
    assert len(entry) == 4
    assert entry[0].addressable_shards[0].data.shape[2] \
        == entry[0].shape[2] // 4        # int8 payload: heads sharded
    assert entry[2].addressable_shards[0].data.shape \
        == entry[2].shape                # scale pool: replicated


def test_lora_adapter_parity_on_mesh():
    """Per-slot LoRA over sharded base weights: the adapter pools
    replicate, the base matmuls stay model-sharded, tokens match the
    no-mesh adapter engine (adapter-0 lanes stay base-identical)."""
    w = _workload(np.random.default_rng(5), n=3)

    def run(model):
        cfg = ServingConfig(num_slots=4, kv_block_size=16,
                            max_model_len=MAX_LEN, lora_rank=4)
        api = ServingAPI(model, cfg)
        try:
            aid = api.register_adapter(
                LoraAdapter.random(model.cfg, rank=4, seed=7, scale=0.25,
                                   name="m"))
            kws = [{"adapter": aid}, {}, {"adapter": aid}]
            reqs = [api.submit(p, max_new_tokens=n, **kw)
                    for (p, n), kw in zip(w, kws)]
            api.run_until_idle()
            assert all(r.state == RequestState.FINISHED for r in reqs)
            return [np.asarray(r.output_ids()) for r in reqs]
        finally:
            api.close()

    ref = run(_model())
    serving_mesh(4, data=2)
    outs = run(_model())
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, b)


def test_sampling_parity_on_mesh():
    """Seeded per-slot sampling is positional-PRNG runtime data — the
    sampled stream is reproduced exactly on the mesh."""
    w = _workload(np.random.default_rng(6), n=3)
    sp = SamplingParams(temperature=0.8, top_k=40, seed=123)
    kws = [{"sampling": sp}, {}, {"sampling": sp}]
    ref, _, _ = _serve(_model(), w, submit_kw=kws)
    serving_mesh(4, data=2)
    outs, _, _ = _serve(_model(), w, submit_kw=kws)
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, b)


# ----------------------------------------------------- recovery / identity


def test_supervisor_rebuild_replay_on_mesh():
    """A transient device failure mid-run on the mesh: the supervisor
    rebuilds (same shapes AND same committed shardings via _arena_args)
    and replays every journal — tokens identical to the undisturbed
    no-mesh run, pools sharded again afterwards."""
    w = _workload(np.random.default_rng(8), n=3)
    ref, _, _ = _serve(_model(), w)

    serving_mesh(4, data=2)
    keep = paddle.get_flags("fault_injection")["fault_injection"]
    paddle.set_flags({"fault_injection": 1})
    try:
        cfg = ServingConfig(num_slots=4, kv_block_size=16,
                            max_model_len=MAX_LEN)
        api = ServingAPI(_model(), cfg)
        try:
            resilience.inject_fault("serving_device", times=1, after=6)
            reqs = [api.submit(p, max_new_tokens=n) for p, n in w]
            api.run_until_idle()
            assert all(r.state == RequestState.FINISHED for r in reqs)
            assert api.supervisor.rebuild_count == 1
            assert api.supervisor.replay_count >= 1
            assert api.engine.decode_traces == 1  # rebuild never recompiles
            outs = [np.asarray(r.output_ids()) for r in reqs]
            kp = api.engine.arena.pools[0][0]
            assert kp.addressable_shards[0].data.shape[2] \
                == kp.shape[2] // 4
        finally:
            api.close()
    finally:
        resilience.clear_faults()
        paddle.set_flags({"fault_injection": keep})
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, b)


def test_one_device_mesh_bitwise_identity():
    """A 1-device mesh runs the same ops on the same chip: tokens are
    identical to the flag-off (no-mesh) engine, while the mesh key still
    distinguishes the builds (committed shardings differ)."""
    w = _workload(np.random.default_rng(9), n=4)
    ref, ref_stats, _ = _serve(_model(), w)
    assert ref_stats["mesh.key"] is None

    serving_mesh(1, data=1)
    outs, stats, _ = _serve(_model(), w)
    assert stats["mesh.key"] == (("data", 1),)
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, b)


def test_generate_runner_cache_is_mesh_keyed():
    """generate()'s memoized runner keys on the mesh fingerprint like the
    quant/donation tags: installing a mesh between calls rebuilds instead
    of replaying a runner traced against the old placement."""
    model = _model()
    ids = paddle.to_tensor(
        np.random.default_rng(10).integers(0, 1024, (1, 8)).astype(np.int32))
    before = compile_cache.stats().get("decode.builds", 0)
    model.generate(ids, max_new_tokens=4)
    model.generate(ids, max_new_tokens=4)  # warm: same key, cache hit
    mid = compile_cache.stats()
    assert mid.get("decode.builds", 0) == before + 1
    assert mid.get("decode.cache_hits", 0) >= 1

    serving_mesh(1, data=1)
    model.generate(ids, max_new_tokens=4)
    assert compile_cache.stats().get("decode.builds", 0) == before + 2
    assert mesh_axes_key() == (("data", 1),)


def test_explicit_config_mesh_threads_everywhere():
    """An explicit ServingConfig.mesh (equal to the installed mesh the
    model was built under) reaches every engine-placed buffer: int8
    weight payloads+scales, KV pools, adapter pools — no piece silently
    follows a different global."""
    mesh = serving_mesh(4, data=2)
    model = _model()
    cfg = ServingConfig(num_slots=4, kv_block_size=16, max_model_len=MAX_LEN,
                        quant_weights=True, quant_kv=True, lora_rank=4,
                        mesh=mesh)
    api = ServingAPI(model, cfg)
    try:
        eng = api.engine
        assert eng.mesh is mesh
        qkv = model.gpt.layers[0].attn.qkv
        assert qkv.weight._data.sharding.spec == (None, "model")
        assert qkv.weight_scale._data.sharding.spec[-1] == "model"
        a_pool, _ = eng.lora.device_pools()[0]
        assert a_pool.sharding.mesh.devices.size == 8  # replicated on-mesh
        p = api.submit(np.arange(8, dtype=np.int32) + 1, max_new_tokens=4)
        api.run_until_idle()
        assert p.state == RequestState.FINISHED
    finally:
        api.close()


def test_paged_kernel_serves_on_data_only_mesh():
    """ISSUE 16 closed the kernels-on-mesh gap: a data-only mesh (no
    model axis to split heads over) serves the KERNEL with every operand
    replicated inside the shard_map wrapper — no warning, no gather
    fallback, token parity with the mesh-gather engine. (The full
    model-sharded route is tests/test_paged_kernel.py's mesh family.)"""
    serving_mesh(1, data=2)  # drops the size-1 model axis: ("data", 2)
    w = _workload(np.random.default_rng(11), n=2)
    model = _model()
    off, _, _ = _serve(model, w, paged_kernel=False)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        outs, stats, _ = _serve(model, w, paged_kernel=True)
    assert stats["kernel.paged"] == 1
    assert stats["kernel.mesh"] == "kernel@data2"
    assert stats["mesh.key"] == (("data", 2),)
    for a, b in zip(off, outs):
        np.testing.assert_array_equal(a, b)


# -------------------------------------------------------------- training


def test_trainstep_data_parallel_on_mesh():
    """TrainStep over the mesh: batch on the data axis, weights on the
    model axis — losses track the single-device run (float reassociation
    across shards bounds this to close, not bitwise) and decrease."""
    from paddle_tpu.jit import TrainStep

    def run(mesh_on):
        if mesh_on:
            serving_mesh(4, data=2)
        paddle.seed(0)
        model = GPTForCausalLM(gpt_tiny())
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        step = TrainStep(lambda x, y: model(x, y), opt, layers=model)
        rng = np.random.default_rng(0)
        x = rng.integers(0, 1024, (8, 64)).astype(np.int32)
        y = np.roll(x, -1, 1).astype(np.int32)
        xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
        if mesh_on:
            from paddle_tpu.distributed import shard_batch

            xt, yt = shard_batch(xt), shard_batch(yt)
        return [float(step(xt, yt).numpy()) for _ in range(4)]

    ref = run(False)
    from paddle_tpu.distributed import mesh as mesh_mod

    mesh_mod._global_mesh = None  # fresh reference run done; now the mesh
    losses = run(True)
    assert losses[-1] < losses[0]
    np.testing.assert_allclose(losses, ref, rtol=2e-3, atol=2e-4)

"""paddle.regularizer / reader / callbacks / version / sysconfig parity
(ref:python/paddle/regularizer.py, reader/decorator.py, callbacks.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.regularizer import L1Decay, L2Decay


def _step_sgd(init, wd, lr=1.0):
    q = paddle.to_tensor(np.full(4, init, np.float32))
    q.stop_gradient = False
    opt = paddle.optimizer.SGD(learning_rate=lr, parameters=[q],
                               weight_decay=wd)
    (q * 0.0).sum().backward()
    opt.step()
    return q.numpy()


def test_l2_decay_pulls_toward_zero():
    np.testing.assert_allclose(_step_sgd(-2.0, L2Decay(0.5)), -1.0)
    np.testing.assert_allclose(_step_sgd(-2.0, 0.5), -1.0)  # float == L2


def test_l1_decay_steps_by_sign():
    np.testing.assert_allclose(_step_sgd(-2.0, L1Decay(0.5)), -1.5)
    np.testing.assert_allclose(_step_sgd(2.0, L1Decay(0.5)), 1.5)


def test_adamw_accepts_regularizer():
    q = paddle.to_tensor(np.full(4, 2.0, np.float32))
    q.stop_gradient = False
    opt = paddle.optimizer.AdamW(learning_rate=0.1, parameters=[q],
                                 weight_decay=L2Decay(0.01))
    (q * 0.0).sum().backward()
    opt.step()
    assert float(q.numpy()[0]) < 2.0  # decoupled decay shrank the weight


def test_reader_combinators():
    import paddle_tpu.reader as reader

    r = lambda: iter(range(5))
    assert list(reader.firstn(r, 3)()) == [0, 1, 2]
    assert sorted(reader.shuffle(r, 2)()) == [0, 1, 2, 3, 4]
    assert list(reader.chain(r, r)()) == list(range(5)) * 2
    assert list(reader.compose(r, r)()) == [(i, i) for i in range(5)]
    assert list(reader.map_readers(lambda a, b: a + b, r, r)()) == [
        0, 2, 4, 6, 8]
    assert sorted(reader.buffered(r, 2)()) == [0, 1, 2, 3, 4]
    assert sorted(reader.xmap_readers(lambda v: v * 2, r, 2, 4)()) == [
        0, 2, 4, 6, 8]
    cached = reader.cache(r)
    assert list(cached()) == list(cached()) == [0, 1, 2, 3, 4]


def test_reader_compose_misalignment_raises():
    import paddle_tpu.reader as reader

    r5 = lambda: iter(range(5))
    r3 = lambda: iter(range(3))
    with pytest.raises(ValueError, match="different lengths"):
        list(reader.compose(r5, r3)())


def test_callbacks_version_sysconfig():
    import os

    assert paddle.callbacks.EarlyStopping is paddle.hapi.callbacks.EarlyStopping
    assert paddle.version.full_version == paddle.__version__
    paddle.version.show()  # must not raise
    assert os.path.isdir(paddle.sysconfig.get_include())


def test_reader_error_propagates_not_deadlocks():
    import paddle_tpu.reader as reader

    def bad():
        yield 1
        raise IOError("boom")

    with pytest.raises(IOError, match="boom"):
        list(reader.buffered(lambda: bad(), 2)())
    with pytest.raises(IOError, match="boom"):
        list(reader.multiprocess_reader([lambda: bad()])())


def test_local_fs_mv_no_clobber(tmp_path):
    from paddle_tpu.distributed.fleet.utils import LocalFS

    fs = LocalFS()
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    fs.touch(a)
    fs.touch(b)
    with pytest.raises(FileExistsError):
        fs.mv(a, b, overwrite=False)
    fs.mv(a, b, overwrite=True)
    assert not fs.is_exist(a) and fs.is_exist(b)

"""paddle.regularizer / reader / callbacks / version / sysconfig parity
(ref:python/paddle/regularizer.py, reader/decorator.py, callbacks.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.regularizer import L1Decay, L2Decay


def _step_sgd(init, wd, lr=1.0):
    q = paddle.to_tensor(np.full(4, init, np.float32))
    q.stop_gradient = False
    opt = paddle.optimizer.SGD(learning_rate=lr, parameters=[q],
                               weight_decay=wd)
    (q * 0.0).sum().backward()
    opt.step()
    return q.numpy()


def test_l2_decay_pulls_toward_zero():
    np.testing.assert_allclose(_step_sgd(-2.0, L2Decay(0.5)), -1.0)
    np.testing.assert_allclose(_step_sgd(-2.0, 0.5), -1.0)  # float == L2


def test_l1_decay_steps_by_sign():
    np.testing.assert_allclose(_step_sgd(-2.0, L1Decay(0.5)), -1.5)
    np.testing.assert_allclose(_step_sgd(2.0, L1Decay(0.5)), 1.5)


def test_adamw_accepts_regularizer():
    q = paddle.to_tensor(np.full(4, 2.0, np.float32))
    q.stop_gradient = False
    opt = paddle.optimizer.AdamW(learning_rate=0.1, parameters=[q],
                                 weight_decay=L2Decay(0.01))
    (q * 0.0).sum().backward()
    opt.step()
    assert float(q.numpy()[0]) < 2.0  # decoupled decay shrank the weight


def test_reader_combinators():
    import paddle_tpu.reader as reader

    r = lambda: iter(range(5))
    assert list(reader.firstn(r, 3)()) == [0, 1, 2]
    assert sorted(reader.shuffle(r, 2)()) == [0, 1, 2, 3, 4]
    assert list(reader.chain(r, r)()) == list(range(5)) * 2
    assert list(reader.compose(r, r)()) == [(i, i) for i in range(5)]
    assert list(reader.map_readers(lambda a, b: a + b, r, r)()) == [
        0, 2, 4, 6, 8]
    assert sorted(reader.buffered(r, 2)()) == [0, 1, 2, 3, 4]
    assert sorted(reader.xmap_readers(lambda v: v * 2, r, 2, 4)()) == [
        0, 2, 4, 6, 8]
    cached = reader.cache(r)
    assert list(cached()) == list(cached()) == [0, 1, 2, 3, 4]


def test_reader_compose_misalignment_raises():
    import paddle_tpu.reader as reader

    r5 = lambda: iter(range(5))
    r3 = lambda: iter(range(3))
    with pytest.raises(ValueError, match="different lengths"):
        list(reader.compose(r5, r3)())


def test_callbacks_version_sysconfig():
    import os

    assert paddle.callbacks.EarlyStopping is paddle.hapi.callbacks.EarlyStopping
    assert paddle.version.full_version == paddle.__version__
    paddle.version.show()  # must not raise
    assert os.path.isdir(paddle.sysconfig.get_include())


def test_reader_error_propagates_not_deadlocks():
    import paddle_tpu.reader as reader

    def bad():
        yield 1
        raise IOError("boom")

    with pytest.raises(IOError, match="boom"):
        list(reader.buffered(lambda: bad(), 2)())
    with pytest.raises(IOError, match="boom"):
        list(reader.multiprocess_reader([lambda: bad()])())


def test_local_fs_mv_no_clobber(tmp_path):
    from paddle_tpu.distributed.fleet.utils import LocalFS

    fs = LocalFS()
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    fs.touch(a)
    fs.touch(b)
    with pytest.raises(FileExistsError):
        fs.mv(a, b, overwrite=False)
    fs.mv(a, b, overwrite=True)
    assert not fs.is_exist(a) and fs.is_exist(b)


def test_tensor_namespace_resolves():
    import paddle_tpu.tensor as t

    x = paddle.to_tensor(np.eye(3, dtype=np.float32))
    np.testing.assert_allclose(t.matmul(x, x).numpy(), np.eye(3))
    assert hasattr(t, "math") and hasattr(t, "manipulation")


def test_cost_model_measures_time():
    from paddle_tpu.cost_model import CostModel

    cm = CostModel()
    x = paddle.to_tensor(np.ones((64, 64), np.float32))
    r = cm.profile_measure(lambda a: paddle.matmul(a, a), (x,),
                           warmup=1, iters=3)
    assert r["time"] > 0


def test_legacy_dataset_reader_creators(tmp_path):
    import paddle_tpu.dataset as dataset

    rows = np.arange(20 * 14, dtype=np.float64).reshape(20, 14) / 3.0
    f = tmp_path / "housing.data"
    with open(f, "w") as fh:
        for r in rows:
            fh.write(" ".join(f"{v:.4f}" for v in r) + "\n")
    reader = dataset.uci_housing.train(data_file=str(f))
    samples = list(reader())
    assert len(samples) == 16
    x, y = samples[0]
    assert x.shape == (13,) and y.shape == (1,)
    assert dataset.common.md5file(str(f))


def test_reduce_lr_on_plateau_callback():
    from paddle_tpu.callbacks import ReduceLROnPlateau

    class FakeOpt:
        lr = 1.0
        def get_lr(self): return self.lr
        def set_lr(self, v): self.lr = v

    class FakeModel:
        _optimizer = FakeOpt()

    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2, verbose=0)
    cb.set_model(FakeModel())
    cb.on_eval_end({"loss": 1.0})
    for _ in range(2):
        cb.on_eval_end({"loss": 1.0})  # no improvement
    assert abs(FakeModel._optimizer.lr - 0.5) < 1e-9


def test_visualdl_callback_writes_scalars(tmp_path):
    import json

    from paddle_tpu.callbacks import VisualDL

    cb = VisualDL(log_dir=str(tmp_path))
    cb.on_train_batch_end(0, {"loss": 0.5})
    cb.on_eval_end({"acc": [0.9]})
    cb.on_train_end()
    lines = [json.loads(ln) for ln in
             (tmp_path / "scalars.jsonl").read_text().splitlines()]
    assert {r["tag"] for r in lines} == {"train/loss", "eval/acc"}


def test_wandb_callback_requires_package():
    from paddle_tpu.callbacks import WandbCallback

    with pytest.raises(ImportError, match="wandb"):
        WandbCallback(project="x")


def test_reduce_lr_cooldown_suppresses_repeat_cuts():
    from paddle_tpu.callbacks import ReduceLROnPlateau

    class FakeOpt:
        lr = 1.0
        def get_lr(self): return self.lr
        def set_lr(self, v): self.lr = v

    class FakeModel:
        _optimizer = FakeOpt()

    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                           cooldown=3, verbose=0)
    cb.set_model(FakeModel())
    cb.on_eval_end({"loss": 1.0})
    for _ in range(4):  # plateaued evals: one cut, then cooldown holds
        cb.on_eval_end({"loss": 1.0})
    assert abs(FakeModel._optimizer.lr - 0.5) < 1e-9


def test_reduce_lr_auto_mode_maximizes_accuracy():
    from paddle_tpu.callbacks import ReduceLROnPlateau

    class FakeOpt:
        lr = 1.0
        def get_lr(self): return self.lr
        def set_lr(self, v): self.lr = v

    class FakeModel:
        _optimizer = FakeOpt()

    cb = ReduceLROnPlateau(monitor="acc", patience=2, verbose=0)
    cb.set_model(FakeModel())
    for a in (0.5, 0.6, 0.7, 0.8):  # steadily improving accuracy
        cb.on_eval_end({"acc": a})
    assert FakeModel._optimizer.lr == 1.0  # never reduced


def test_utils_deprecated_and_require_version():
    import warnings

    from paddle_tpu import utils

    @utils.deprecated(update_to="paddle.new_api", since="2.0")
    def old():
        return 42

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert old() == 42
        assert any("deprecated" in str(x.message) for x in w)
    assert utils.require_version("0.1.0")
    with pytest.raises(Exception, match="<"):
        utils.require_version("99.0.0")

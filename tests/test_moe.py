"""MoE layer: routing correctness, expert parallelism, training."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.incubate.distributed.models.moe import MoELayer, NaiveGate


class Expert(nn.Layer):
    def __init__(self, d, hidden=None):
        super().__init__()
        h = hidden or 2 * d
        self.fc1 = nn.Linear(d, h)
        self.fc2 = nn.Linear(h, d)

    def forward(self, x):
        return self.fc2(nn.functional.gelu(self.fc1(x)))


def test_moe_forward_shapes():
    paddle.seed(0)
    d = 8
    moe = MoELayer(d, lambda i: Expert(d), num_experts=4, gate="gshard")
    x = paddle.to_tensor(np.random.rand(2, 6, d).astype(np.float32))
    y = moe(x)
    assert y.shape == [2, 6, d]
    assert moe.l_aux is not None


def test_moe_single_expert_equals_dense():
    """1 expert, top-1, generous capacity: MoE == the dense expert."""
    paddle.seed(0)
    d = 8
    moe = MoELayer(d, lambda i: Expert(d), num_experts=1, gate="naive",
                   top_k=1, capacity_factor=8.0)
    x = paddle.to_tensor(np.random.rand(16, d).astype(np.float32))
    y = moe(x)
    # rebuild the dense expert from stacked params
    dense = Expert(d)
    sd = {}
    for n in moe._t_names:
        key = "experts__" + n.replace(".", "__")
        sd[n] = paddle.to_tensor(np.asarray(dict(moe.named_parameters())[key]._data)[0])
    dense.set_state_dict(sd)
    np.testing.assert_allclose(y.numpy(), dense(x).numpy(), atol=1e-5)


def test_moe_trains_eager():
    paddle.seed(0)
    d = 8
    moe = MoELayer(d, lambda i: Expert(d), num_experts=4, gate="switch", top_k=1)
    head = nn.Linear(d, 1)
    opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                parameters=moe.parameters() + head.parameters())
    X = np.random.rand(64, d).astype(np.float32)
    Y = (X.mean(1, keepdims=True) > 0.5).astype(np.float32)
    first = None
    for _ in range(40):
        out = head(moe(paddle.to_tensor(X)))
        loss = ((out - paddle.to_tensor(Y)) ** 2).mean() + 0.01 * moe.l_aux
        if first is None:
            first = float(loss.numpy())
        loss.backward()
        opt.step(); opt.clear_grad()
    assert float(loss.numpy()) < first


def test_moe_expert_parallel_mesh():
    """Experts sharded over the expert axis; step compiles and runs."""
    paddle.seed(0)
    dist.init_hybrid_mesh(expert=4, dp=2)
    d = 8
    moe = MoELayer(d, lambda i: Expert(d), num_experts=4, gate="gshard")
    head = nn.Linear(d, 1)
    from paddle_tpu.jit import TrainStep

    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=moe.parameters() + head.parameters())

    def loss_fn(x, y):
        out = head(moe(x))
        return ((out - y) ** 2).mean() + 0.01 * moe.l_aux

    step = TrainStep(loss_fn, opt, layers=[moe, head])
    X = paddle.to_tensor(np.random.rand(32, d).astype(np.float32))
    Y = paddle.to_tensor(np.random.rand(32, 1).astype(np.float32))
    losses = [float(step(X, Y).numpy()) for _ in range(3)]
    assert all(np.isfinite(l) for l in losses)
    # stacked expert params are sharded over the expert axis
    p = dict(moe.named_parameters())["experts__fc1.weight".replace(".", "__") if False else "experts__fc1__weight"]
    assert "expert" in str(p._data.sharding.spec)


def test_gate_capacity_drops_overflow():
    paddle.seed(0)
    d = 4
    g = NaiveGate(d, 2, top_k=1, capacity_factor=0.1)
    x = jnp.asarray(np.random.rand(64, d).astype(np.float32))
    dispatch, combine, _ = g.route(x, 2)  # capacity 2
    # per-expert routed count never exceeds capacity
    per_expert = np.asarray(dispatch.sum(axis=(0, 2)))
    assert (np.asarray(dispatch.sum(axis=2)) <= 1.0 + 1e-6).all()
    assert (np.asarray(dispatch.sum(axis=(0,))) <= 1.0 + 1e-6).all()

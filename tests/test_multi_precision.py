"""multi_precision (master-weight) optimizer path — AMP O2.

The reference's multi-precision kernels keep an f32 master param alongside a
low-precision model param (ref:paddle/phi/kernels/gpu/adamw_kernel.cu master
path; python knob ``multi_precision=`` on the optimizer ctors, auto-enabled
by ``amp.decorate`` at O2). Contract tested here: updates smaller than a
bf16 ulp must still accumulate (they vanish without a master copy), the
eager and compiled paths agree, and master state survives checkpointing.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import amp, nn, optimizer
from paddle_tpu.core.tensor import Tensor


def _bf16_param(value=1.0, n=64):
    p = Tensor(jnp.full((n,), value, jnp.bfloat16), stop_gradient=False)
    p.name = "w"
    return p


def test_sub_ulp_updates_accumulate_with_master():
    # bf16 ulp at 1.0 is 2^-8 ≈ 3.9e-3; each SGD step moves 1e-4 — invisible
    # to bf16, visible to the f32 master
    steps, lr = 50, 1e-4
    p_master = _bf16_param()
    opt_m = optimizer.SGD(learning_rate=lr, parameters=[p_master],
                          multi_precision=True)
    p_plain = _bf16_param()
    opt_p = optimizer.SGD(learning_rate=lr, parameters=[p_plain])
    g = jnp.ones((64,), jnp.bfloat16)
    for _ in range(steps):
        for p, opt in ((p_master, opt_m), (p_plain, opt_p)):
            p.grad = Tensor(g)
            opt.step()
    # plain bf16: every update rounds away; master: they accumulate
    assert float(jnp.max(jnp.abs(p_plain._data.astype(jnp.float32) - 1.0))) == 0.0
    master = opt_m._accumulators[id(p_master)]["master_weight"]
    np.testing.assert_allclose(np.asarray(master), 1.0 - steps * lr, rtol=1e-5)
    # the bf16 param is the cast of the master (one visible notch after 50
    # sub-ulp steps would appear once accumulation crosses the ulp; at 5e-3
    # past 1.0 the cast has moved)
    assert float(p_master._data[0]) != 1.0


def test_adamw_master_matches_f32_reference():
    """bf16+master AdamW fed f32 grads must track the all-f32 trajectory to
    within ONE bf16 cast (the only rounding left is the final param emit);
    the plain-bf16 path rounds grads AND params every step and drifts
    further."""
    rng = np.random.RandomState(0)
    init = rng.standard_normal(128).astype(np.float32)
    grads = [rng.standard_normal(128).astype(np.float32) * 0.1
             for _ in range(30)]

    def run(dtype, multi_precision):
        p = Tensor(jnp.asarray(init, dtype), stop_gradient=False)
        p.name = "w"
        opt = optimizer.AdamW(learning_rate=1e-3, parameters=[p],
                              weight_decay=0.01,
                              multi_precision=multi_precision)
        for g in grads:
            p.grad = Tensor(jnp.asarray(g))  # f32 grads for both runs
            opt.step()
        if multi_precision:
            return np.asarray(opt._accumulators[id(p)]["master_weight"])
        return np.asarray(p._data.astype(jnp.float32))

    ref = run(jnp.float32, False)
    with_master = run(jnp.bfloat16, True)
    plain = run(jnp.bfloat16, False)
    err_master = np.abs(with_master - ref).max()
    err_plain = np.abs(plain - ref).max()
    assert err_master < err_plain
    # the master trajectory IS the f32 trajectory (init cast aside)
    assert err_master <= np.abs(init).max() * 2**-8 + 1e-6


def test_decorate_o2_enables_master_and_trainstep_converges():
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 1))
    opt = optimizer.AdamW(learning_rate=5e-3,
                          parameters=model.parameters())
    amp.decorate(model, opt, level="O2", dtype="bfloat16")
    assert opt._multi_precision
    assert model.parameters()[0]._data.dtype == jnp.bfloat16

    from paddle_tpu.jit import TrainStep

    x = Tensor(np.random.RandomState(1).standard_normal((64, 16)).astype(np.float32))
    y = Tensor((np.asarray(x._data)[:, :4].sum(axis=1, keepdims=True)).astype(np.float32))

    def loss_fn(x, y):
        with amp.auto_cast(level="O1", dtype="bfloat16"):
            pred = model(x)
        return ((pred.astype("float32") - y) ** 2).mean()

    step = TrainStep(loss_fn, opt, layers=model)
    losses = [float(step(x, y)) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    # master slots exist in the compiled-path optimizer state
    assert any("master_weight" in s for s in step._opt_state["slots"])


def test_decorate_master_weight_false_opts_out():
    model = nn.Linear(4, 4)
    opt = optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    amp.decorate(model, opt, level="O2", master_weight=False)
    assert not opt._multi_precision


def test_moment_dtype_stable_under_master():
    """Moments must be f32 from step 0 under multi_precision — a bf16→f32
    flip after the first update would change the opt_state pytree dtype and
    retrigger a full XLA compile of the donated TrainStep."""
    p = _bf16_param()
    opt = optimizer.Momentum(learning_rate=1e-3, momentum=0.9,
                             parameters=[p], multi_precision=True)
    slots0 = opt._init_slot(p._data)
    assert slots0["velocity"].dtype == jnp.float32
    p.grad = Tensor(jnp.ones((64,), jnp.bfloat16))
    opt.step()
    assert opt._accumulators[id(p)]["velocity"].dtype == jnp.float32


def test_trainstep_resumes_restored_optimizer_state():
    from paddle_tpu.jit import TrainStep

    def make():
        model = nn.Linear(8, 1)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        return model, opt

    x = Tensor(np.random.RandomState(0).standard_normal((16, 8)).astype(np.float32))
    y = Tensor(np.ones((16, 1), np.float32))

    model, opt = make()
    step = TrainStep(lambda a, b: ((model(a) - b) ** 2).mean(), opt,
                     layers=model)
    for _ in range(5):
        step(x, y)
    sd_w = {k: v for k, v in model.state_dict().items()}
    sd_o = opt.state_dict()

    model2, opt2 = make()
    model2.set_state_dict(sd_w)
    opt2.set_state_dict(sd_o)
    step2 = TrainStep(lambda a, b: ((model2(a) - b) ** 2).mean(), opt2,
                      layers=model2)
    step2(x, y)
    # resumed: step continues from 5 (not restarting bias correction), and
    # the seeded moments came from the checkpoint (non-zero)
    assert int(step2._opt_state["step"]) == 6
    m1 = np.asarray(step2._opt_state["slots"][0]["moment1"])
    assert np.abs(m1).max() > 0


def test_state_dict_snapshot_survives_next_step():
    """opt.state_dict() after TrainStep training must be a copy — the live
    opt_state buffers are donated to the next compiled call."""
    from paddle_tpu.jit import TrainStep

    model = nn.Linear(8, 1)
    opt = optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
    x = Tensor(np.ones((4, 8), np.float32))
    y = Tensor(np.ones((4, 1), np.float32))
    step = TrainStep(lambda a, b: ((model(a) - b) ** 2).mean(), opt,
                     layers=model)
    step(x, y)
    sd = opt.state_dict()
    step(x, y)  # donates the buffers sd would alias without the copy
    for k, v in sd.items():
        if isinstance(v, Tensor):
            np.asarray(v._data)  # must not raise "Array has been deleted"


def test_master_weight_survives_state_dict_roundtrip():
    p = _bf16_param(2.0)
    opt = optimizer.Adam(learning_rate=1e-3, parameters=[p],
                         multi_precision=True)
    p.grad = Tensor(jnp.ones((64,), jnp.bfloat16))
    opt.step()
    sd = opt.state_dict()
    assert any(k.endswith("master_weight") for k in sd)

    p2 = _bf16_param(2.0)
    opt2 = optimizer.Adam(learning_rate=1e-3, parameters=[p2],
                          multi_precision=True)
    opt2.set_state_dict(sd)
    m1 = opt._accumulators[id(p)]["master_weight"]
    m2 = opt2._accumulators[id(p2)]["master_weight"]
    np.testing.assert_array_equal(np.asarray(m1), np.asarray(m2))

"""Multi-process mesh: 2 jax processes form ONE global mesh and run real
cross-process collectives + a DP train step.

The reference proves its distributed stack by spawning trainers and
comparing losses against a single-process run
(ref:python/paddle/fluid/tests/unittests/test_dist_base.py:926). Same
pattern here, at the layer the reference never exercises this way: the
compiled-collective path itself. Each worker calls
``jax.distributed.initialize`` (CPU backend, gloo collectives), builds the
global mesh through ``init_parallel_env``, and the parent checks

- allreduce/allgather/broadcast values are exact across processes, and
- a 2-step DP train over the assembled global batch matches the
  single-process run on the concatenated batch elementwise.
"""
import numpy as np
import pytest

from paddle_tpu.distributed.spawn import spawn

WORLD = 2
STEPS = 3


def _make_data():
    rng = np.random.RandomState(7)
    x = rng.randn(8, 4).astype(np.float32)
    y = (x @ rng.randn(4, 1) + 0.3).astype(np.float32)
    return x, y


def _build_model_and_opt():
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer

    paddle.seed(0)
    model = nn.Linear(4, 1)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    return model, opt


def _train(model, opt, x_t, y_t, steps=STEPS):
    from paddle_tpu import nn

    losses = []
    for _ in range(steps):
        loss = nn.functional.mse_loss(model(x_t), y_t)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def _mp_worker():
    # one XLA device per process: the mesh must span PROCESSES, so that the
    # collectives cross a real process boundary (gloo), not just threads
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    import jax

    assert jax.process_count() == WORLD, jax.process_count()
    assert len(jax.devices()) == WORLD  # ONE global mesh, not per-proc
    rank = dist.get_rank()
    out = {"ndev": len(jax.devices())}

    t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
    dist.all_reduce(t)
    out["allreduce"] = t.numpy().tolist()

    tp = paddle.to_tensor(np.array([float(rank + 2)], np.float32))
    dist.all_reduce(tp, op=dist.ReduceOp.PROD)
    out["prod"] = tp.numpy().tolist()

    gathered = []
    dist.all_gather(gathered, paddle.to_tensor(
        np.array([float(rank), float(rank) + 0.5], np.float32)))
    out["allgather"] = [g.numpy().tolist() for g in gathered]

    b = paddle.to_tensor(np.full((2,), float(rank * 10 + 5), np.float32))
    dist.broadcast(b, src=0)
    out["broadcast"] = b.numpy().tolist()

    # DP train: each process loads ITS OWN half of the batch (the per-rank
    # loading contract); shard_batch assembles the global array
    x, y = _make_data()
    lo, hi = rank * 4, (rank + 1) * 4
    model, opt = _build_model_and_opt()
    model = paddle.DataParallel(model)
    x_t = dist.shard_batch(paddle.to_tensor(x[lo:hi]))
    y_t = dist.shard_batch(paddle.to_tensor(y[lo:hi]))
    out["losses"] = _train(model, opt, x_t, y_t)
    out["w"] = np.asarray(
        model.state_dict()["weight"].numpy()).ravel().tolist()
    return out


def test_two_process_global_mesh_matches_single_process():
    results = spawn(_mp_worker, nprocs=WORLD)

    # every process saw the same global mesh and identical collective values
    for r in results:
        assert r["ndev"] == WORLD
        assert r["allreduce"] == [3.0] * 4  # (rank0+1) + (rank1+1)
        assert r["prod"] == [6.0]  # (rank0+2) * (rank1+2)
        assert r["allgather"] == [[0.0, 0.5], [1.0, 1.5]]
        assert r["broadcast"] == [5.0, 5.0]  # rank 0's value

    # DP losses/weights match a single-process run on the full batch
    import paddle_tpu as paddle

    x, y = _make_data()
    model, opt = _build_model_and_opt()
    ref_losses = _train(model, opt, paddle.to_tensor(x), paddle.to_tensor(y))
    ref_w = model.state_dict()["weight"].numpy().ravel()
    for r in results:
        np.testing.assert_allclose(r["losses"], ref_losses, rtol=2e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(r["w"], ref_w, rtol=2e-5, atol=1e-6)
    # and both ranks agree bit-for-bit with each other
    assert results[0]["losses"] == results[1]["losses"]


def _hybrid_worker():
    """2 processes x 2 local devices = ONE 4-device dp2 x mp2 mesh: the
    dp axis crosses the process boundary while mp stays process-local —
    GSPMD must insert cross-process collectives for the grad reduction."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    import jax

    assert len(jax.devices()) == 4
    from paddle_tpu.distributed.mesh import init_hybrid_mesh

    init_hybrid_mesh(dp=2, mp=2)
    rank = dist.get_rank()

    from paddle_tpu.distributed.fleet.meta_parallel import ColumnParallelLinear
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    layer = ColumnParallelLinear(8, 8, gather_output=True)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=layer.parameters())
    x, y = _make_data()
    x8 = np.concatenate([x, x], axis=1).reshape(8, 1, 8)  # [B, S, H]
    step = TrainStep(lambda a, b: ((layer(a) - b) ** 2).mean(), opt,
                     layers=layer)
    lo, hi = rank * 4, (rank + 1) * 4
    xb = dist.shard_batch(paddle.to_tensor(x8[lo:hi]))
    yb = dist.shard_batch(paddle.to_tensor(x8[lo:hi] * 0.5))
    losses = [float(np.asarray(step(xb, yb)._data)) for _ in range(2)]
    return losses


def test_hybrid_dp_mp_mesh_across_processes():
    """dp crosses processes, mp is local; compiled TrainStep loss parity
    vs the single-process run on the full batch."""
    results = spawn(_hybrid_worker, nprocs=WORLD)

    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.meta_parallel import ColumnParallelLinear
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    layer = ColumnParallelLinear(8, 8, gather_output=True)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=layer.parameters())
    x, _ = _make_data()
    x8 = np.concatenate([x, x], axis=1).reshape(8, 1, 8)
    step = TrainStep(lambda a, b: ((layer(a) - b) ** 2).mean(), opt,
                     layers=layer)
    ref = [float(np.asarray(step(paddle.to_tensor(x8),
                                 paddle.to_tensor(x8 * 0.5))._data))
           for _ in range(2)]
    for r in results:
        np.testing.assert_allclose(r, ref, rtol=2e-5, atol=1e-6)
    assert results[0] == results[1]


def _ckpt_worker(workdir):
    """Both ranks save the shared replicated state to ONE path repeatedly
    with overwrite (the multi-host checkpoint pattern): the keep-aside
    rename must be primary-only or the ranks race on shared storage."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)

    dist.init_parallel_env()
    import jax

    path = os.path.join(workdir, "shared_ckpt")
    for step in range(3):
        sd = {"w": paddle.to_tensor(
            np.full((4,), float(step), np.float32)), "step": step}
        save_state_dict(sd, path, overwrite=True, blocking=True)
    restored = load_state_dict(path)
    # targeted restore must come back HOST-USABLE (localized), not as a
    # global array spanning non-addressable devices
    target = {"w": paddle.to_tensor(np.zeros(4, np.float32)), "step": 0}
    restored_t = load_state_dict(path, target=target)
    return {"rank": jax.process_index(),
            "w": np.asarray(restored["w"]).tolist(),
            "w_t": np.asarray(restored_t["w"]).tolist(),
            "step": int(restored["step"])}


@pytest.mark.slow  # TRACKING: hangs tier-1 in sandboxed runs — the orbax
# multi-process save path deadlocks inside save_state_dict(blocking=True)
# (reproduced on the clean pre-PR-10 tree, orphan-free; see CHANGES.md PR 9
# note). Marked slow so the unattended tier-1 suite completes; the case
# still runs in full/slow CI. Remove the mark once the orbax barrier hang
# is root-caused.
def test_multiprocess_checkpoint_overwrite_primary_only(tmp_path):
    results = spawn(_ckpt_worker, args=(str(tmp_path),), nprocs=WORLD)
    for r in results:
        assert r["step"] == 2, results
        assert r["w"] == [2.0, 2.0, 2.0, 2.0], results
        assert r["w_t"] == [2.0, 2.0, 2.0, 2.0], results

"""Native C++ runtime: TCPStore KV/barrier and host trace recorder."""
import json
import os
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.profiler import Profiler, ProfilerTarget, RecordEvent


def test_store_set_get_add():
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=1)
    try:
        client = TCPStore("127.0.0.1", master.port, is_master=False, world_size=1)
        client.set("k1", b"hello")
        assert master.get("k1") == b"hello"
        assert client.get("missing") is None
        assert client.add("cnt", 3) == 3
        assert master.add("cnt", 2) == 5
        client.close()
    finally:
        master.close()


def test_store_wait_blocks_until_set():
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=1)
    try:
        client = TCPStore("127.0.0.1", master.port)
        result = {}

        def waiter():
            result["v"] = client.wait("late_key")

        t = threading.Thread(target=waiter)
        t.start()
        import time

        time.sleep(0.2)
        assert "v" not in result
        master.set("late_key", b"now")
        t.join(timeout=5)
        assert result.get("v") == b"now"
        client.close()
    finally:
        master.close()


def test_store_barrier_world2():
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=2)
    try:
        c2 = TCPStore("127.0.0.1", master.port, world_size=2)
        hits = []

        def hit(store, i):
            store.barrier("b")
            hits.append(i)

        t1 = threading.Thread(target=hit, args=(master, 1))
        t1.start()
        import time

        time.sleep(0.2)
        assert not hits  # first arriver blocks
        hit(c2, 2)
        t1.join(timeout=5)
        assert sorted(hits) == [1, 2]
        c2.close()
    finally:
        master.close()


def test_trace_records_ops_and_exports(tmp_path):
    prof = Profiler(targets=[ProfilerTarget.CPU])
    prof.start()
    with RecordEvent("user_scope"):
        x = paddle.to_tensor(np.random.rand(8, 8).astype(np.float32))
        y = (x @ x).sum()
    prof.stop()
    path = prof.export_chrome_tracing(str(tmp_path))
    with open(path) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "user_scope" in names
    assert any("matmul" in n or "sum" in n for n in names), names
    table = prof.summary()
    assert "user_scope" in table


def test_trace_disabled_is_cheap_and_empty(tmp_path):
    prof = Profiler()
    prof.start()
    prof.stop()
    # after stop, new ops are NOT recorded
    x = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
    _ = (x + x).numpy()
    path = prof.export_chrome_tracing(str(tmp_path))
    with open(path) as f:
        trace = json.load(f)
    assert all("add" not in e["name"] for e in trace["traceEvents"])

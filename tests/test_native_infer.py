"""Native C++ deploy path: .pdnative artifact + PJRT runner.

Covers the runner plumbing with a fake PJRT plugin (the reference's
fake-device test pattern) on CPU, and end-to-end numerics on TPU when a real
plugin + device are reachable (ref:paddle/fluid/inference/api/
analysis_predictor_tester.cc is the parity model)."""
import ctypes
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit import InputSpec
from paddle_tpu.native import pdnative


class _AddW(nn.Layer):
    """y = x + w: output shape == input shape == weight shape, so the fake
    plugin's echo semantics (output := first argument) are well-typed."""

    def __init__(self):
        super().__init__()
        self.w = self.create_parameter([2, 8])

    def forward(self, x):
        return x + self.w


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    d = tmp_path_factory.mktemp("native")
    path = str(d / "addw")
    m = _AddW()
    paddle.jit.save(m, path, input_spec=[InputSpec([2, 8], "float32")])
    w = np.asarray(m.w._data)
    return path, w


def test_pdnative_container_roundtrip(artifact):
    path, w = artifact
    assert os.path.exists(path + ".pdnative")
    art = pdnative.read(path + ".pdnative")
    assert art["stablehlo"][:4] in (b"ML\xefR",)  # MLIR bytecode magic
    assert len(art["compile_options"]) > 0
    kinds = [a.is_weight for a in art["args"]]
    assert kinds.count(True) == 1 and kinds.count(False) == 1
    wspec = next(a for a in art["args"] if a.is_weight)
    assert wspec.shape == (2, 8) and wspec.dtype == np.float32
    np.testing.assert_array_equal(
        np.frombuffer(wspec.data, np.float32).reshape(2, 8), w)
    (out,) = art["outputs"]
    assert out.shape == (2, 8) and out.dtype == np.float32


def test_native_predictor_fake_plugin(artifact):
    path, w = artifact
    plugin = pdnative.build_fake_plugin()
    pred = pdnative.NativePredictor(path + ".pdnative", plugin)
    try:
        assert pred.input_specs == [((2, 8), np.dtype(np.float32))]
        assert pred.output_specs == [((2, 8), np.dtype(np.float32))]
        x = np.random.default_rng(0).standard_normal((2, 8)).astype(np.float32)
        (y,) = pred.run(x)
        # fake plugin echoes argument 0 of the exported main = the weight
        np.testing.assert_array_equal(y, w)
    finally:
        pred.close()


def test_native_predictor_input_validation(artifact):
    path, _ = artifact
    pred = pdnative.NativePredictor(path + ".pdnative",
                                    pdnative.build_fake_plugin())
    try:
        with pytest.raises(ValueError, match="expected 1 inputs"):
            pred.run()
        with pytest.raises(ValueError, match="shape"):
            pred.run(np.zeros((3, 8), np.float32))
    finally:
        pred.close()


def test_create_errors_are_reported(tmp_path, artifact):
    path, _ = artifact
    lib = pdnative._lib()
    # bad artifact
    bad = tmp_path / "bad.pdnative"
    bad.write_bytes(b"NOTMAGIC" + b"\0" * 16)
    h = lib.pt_infer_create(b"/nonexistent.so", str(bad).encode())
    assert not h
    assert b"magic" in lib.pt_infer_last_error()
    # good artifact, bad plugin
    h = lib.pt_infer_create(b"/nonexistent.so",
                            (path + ".pdnative").encode())
    assert not h
    assert b"dlopen" in lib.pt_infer_last_error()


def test_dynamic_spec_skips_pdnative(tmp_path):
    m = nn.Linear(8, 4)
    path = str(tmp_path / "dyn")
    paddle.jit.save(m, path, input_spec=[InputSpec([None, 8], "float32")])
    assert os.path.exists(path + ".pdmodel")
    assert not os.path.exists(path + ".pdnative")
    # an EXPLICIT native request with dynamic dims must fail loudly
    with pytest.raises(ValueError, match="fully-static"):
        paddle.jit.save(m, str(tmp_path / "dyn2"),
                        input_spec=[InputSpec([None, 8], "float32")],
                        native=True)


def _tpu_plugin():
    p = pdnative.default_plugin_path()
    if p is None or not os.path.exists(p):
        return None
    if os.environ.get("PADDLE_TPU_NATIVE_TPU_TEST") != "1":
        return None  # needs a live chip; opt-in (tunnel may be down)
    return p


@pytest.mark.skipif(_tpu_plugin() is None,
                    reason="real PJRT plugin test is opt-in "
                           "(PADDLE_TPU_NATIVE_TPU_TEST=1)")
def test_native_predictor_real_plugin(artifact):
    path, w = artifact
    plugin = _tpu_plugin()
    opts = (pdnative.axon_client_create_options()
            if "axon" in os.path.basename(plugin) else None)
    pred = pdnative.NativePredictor(path + ".pdnative", plugin,
                                    create_options=opts)
    try:
        x = np.random.default_rng(1).standard_normal((2, 8)).astype(np.float32)
        (y,) = pred.run(x)
        np.testing.assert_allclose(y, x + w, rtol=1e-5, atol=1e-5)
    finally:
        pred.close()


def test_cpp_demo_app(artifact, tmp_path):
    """Compile the C++ demo against libpaddle_tpu_native.so and run it with
    the fake plugin — the full C/C++ deploy recipe, end to end."""
    import subprocess

    from paddle_tpu import native

    path, _ = artifact
    so = native.load()._name  # the exact .so this session built/loaded
    here = os.path.dirname(os.path.abspath(native.__file__))
    demo_src = os.path.join(here, "csrc", "testing", "pt_infer_demo.cc")
    demo = str(tmp_path / "demo")
    subprocess.run(["g++", "-std=c++17", demo_src, so, "-o", demo],
                   check=True, capture_output=True)
    r = subprocess.run([demo, pdnative.build_fake_plugin(),
                        path + ".pdnative"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "ok" in r.stdout and "output 0" in r.stdout


def test_gpt_exports_tpu_pdnative(tmp_path):
    """The flagship model cross-lowers to a TPU-platform deploy artifact
    from a CPU host (jax.export platforms=['tpu'])."""
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    path = str(tmp_path / "gpt")
    paddle.jit.save(m, path, input_spec=[InputSpec([2, 16], "int32")])
    art = pdnative.read(path + ".pdnative")
    assert art["platform"] == "tpu"
    assert sum(1 for a in art["args"] if a.is_weight) == len(
        m.state_dict())
    (out,) = art["outputs"]
    assert out.shape == (2, 16, m.cfg.vocab_size)


def test_create_options_reach_plugin(artifact, tmp_path, monkeypatch):
    """create_options must arrive at PJRT_Client_Create as typed
    NamedValues, with the PYTHON type deciding the NamedValue type — a
    digit-only string option must stay kString (the axon plugin rejects
    mistyped values)."""
    path, _ = artifact
    dump = tmp_path / "opts.txt"
    monkeypatch.setenv("FAKE_PJRT_DUMP_OPTIONS", str(dump))
    pred = pdnative.NativePredictor(
        path + ".pdnative", pdnative.build_fake_plugin(),
        create_options={"remote_compile": True, "topology": "v5e:1x1x1",
                        "rank": 0xFFFF_FFFF, "session_id": "12345"})
    pred.close()
    got = dict(l.split("=", 1) for l in dump.read_text().splitlines())
    assert got["remote_compile"] == "i:1"
    assert got["topology"] == "s:v5e:1x1x1"
    assert got["rank"] == f"i:{0xFFFF_FFFF}"
    assert got["session_id"] == "s:12345"  # digits, but typed str in Python


def test_create_options_env_fallback_and_overflow(artifact, tmp_path,
                                                  monkeypatch):
    """pt_infer_create (no explicit options) honors the env var with
    guess-typing; an out-of-range integer fails loudly instead of being
    silently clamped."""
    path, _ = artifact
    dump = tmp_path / "opts.txt"
    monkeypatch.setenv("FAKE_PJRT_DUMP_OPTIONS", str(dump))
    monkeypatch.setenv("PADDLE_TPU_PJRT_CREATE_OPTIONS",
                       "priority=3;name=svc")
    pred = pdnative.NativePredictor(path + ".pdnative",
                                    pdnative.build_fake_plugin())
    pred.close()
    got = dict(l.split("=", 1) for l in dump.read_text().splitlines())
    assert got["priority"] == "i:3"
    assert got["name"] == "s:svc"
    monkeypatch.setenv("PADDLE_TPU_PJRT_CREATE_OPTIONS",
                       "rank=99999999999999999999999")
    with pytest.raises(RuntimeError, match="out-of-range"):
        pdnative.NativePredictor(path + ".pdnative",
                                 pdnative.build_fake_plugin())

"""Golden tests for the nn surface completion (losses, unpool, vision ops).

Torch (CPU) is the reference oracle where it implements the same op —
mirroring the reference's OpTest numpy/torch-golden pattern (SURVEY.md §4.1).
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F

RNG = np.random.RandomState(11)


def _t(a):
    return paddle.to_tensor(a)


# ------------------------------------------------------------------ losses


def test_ctc_loss_matches_torch():
    T, B, V, L = 12, 3, 6, 4
    logits = RNG.randn(T, B, V).astype(np.float32)
    log_probs = torch.log_softmax(torch.tensor(logits), dim=-1)
    labels = RNG.randint(1, V, (B, L)).astype(np.int32)
    in_len = np.array([12, 10, 8], np.int64)
    lab_len = np.array([4, 3, 2], np.int64)

    exp = TF.ctc_loss(log_probs, torch.tensor(labels.astype(np.int64)),
                      torch.tensor(in_len), torch.tensor(lab_len),
                      blank=0, reduction="none").numpy()
    got = F.ctc_loss(_t(log_probs.numpy()), _t(labels), _t(in_len.astype(np.int32)),
                     _t(lab_len.astype(np.int32)), blank=0,
                     reduction="none").numpy()
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-4)


def test_ctc_loss_gradient_flows():
    T, B, V, L = 8, 2, 5, 3
    x = paddle.to_tensor(RNG.randn(T, B, V).astype(np.float32),
                         stop_gradient=False)
    lp = F.log_softmax(x, axis=-1)
    labels = _t(RNG.randint(1, V, (B, L)).astype(np.int32))
    loss = F.ctc_loss(lp, labels, _t(np.array([8, 8], np.int32)),
                      _t(np.array([3, 2], np.int32)))
    loss.backward()
    assert np.isfinite(x.grad.numpy()).all()


def _rnnt_brute(lp, lab, T, U, blank):
    """Enumerate all monotone paths (tiny sizes only)."""
    import itertools

    best = []
    # path = sequence of T blanks and U emits interleaved; prob summed
    total = -np.inf
    for positions in itertools.combinations(range(T + U), U):
        t = u = 0
        logp = 0.0
        ok = True
        for step in range(T + U):
            if step in positions:  # emit label u at (t, u)
                if u >= U or t >= T:
                    ok = False
                    break
                logp += lp[t, u, lab[u]]
                u += 1
            else:  # blank at (t, u)
                if t >= T:
                    ok = False
                    break
                logp += lp[t, u, blank]
                t += 1
        if ok and u == U and t == T:
            total = np.logaddexp(total, logp)
    return -total


def test_rnnt_loss_matches_bruteforce():
    B, T, U, V = 2, 3, 2, 4
    lp = np.log(np.random.RandomState(3).dirichlet(np.ones(V), (B, T, U + 1))
                ).astype(np.float32)
    lab = np.array([[1, 2], [3, 1]], np.int32)
    got = F.rnnt_loss(_t(lp), _t(lab), _t(np.array([T, T], np.int32)),
                      _t(np.array([U, U], np.int32)), blank=0,
                      reduction="none").numpy()
    for b in range(B):
        exp = _rnnt_brute(lp[b], lab[b], T, U, 0)
        np.testing.assert_allclose(got[b], exp, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("red", ["none", "mean", "sum"])
def test_margin_losses_match_torch(red):
    x = RNG.randn(8, 5).astype(np.float32)
    y = RNG.randint(0, 5, 8)
    np.testing.assert_allclose(
        F.multi_margin_loss(_t(x), _t(y.astype(np.int32)), reduction=red).numpy(),
        TF.multi_margin_loss(torch.tensor(x), torch.tensor(y), reduction=red).numpy(),
        rtol=1e-5, atol=1e-6)

    xs = RNG.randn(10).astype(np.float32)
    ys = np.sign(RNG.randn(10)).astype(np.float32)
    np.testing.assert_allclose(
        F.soft_margin_loss(_t(xs), _t(ys), reduction=red).numpy(),
        TF.soft_margin_loss(torch.tensor(xs), torch.tensor(ys), reduction=red).numpy(),
        rtol=1e-5, atol=1e-6)

    yl = (RNG.rand(8, 5) > 0.5).astype(np.float32)
    np.testing.assert_allclose(
        F.multi_label_soft_margin_loss(_t(x), _t(yl), reduction=red).numpy(),
        TF.multilabel_soft_margin_loss(torch.tensor(x), torch.tensor(yl),
                                       reduction=red).numpy(),
        rtol=1e-5, atol=1e-6)


def test_poisson_gaussian_nll_match_torch():
    x = RNG.rand(10).astype(np.float32) + 0.1
    y = RNG.poisson(2.0, 10).astype(np.float32)
    np.testing.assert_allclose(
        F.poisson_nll_loss(_t(x), _t(y)).numpy(),
        TF.poisson_nll_loss(torch.tensor(x), torch.tensor(y)).numpy(),
        rtol=1e-5)
    mu = RNG.randn(10).astype(np.float32)
    var = RNG.rand(10).astype(np.float32) + 0.1
    tgt = RNG.randn(10).astype(np.float32)
    np.testing.assert_allclose(
        F.gaussian_nll_loss(_t(mu), _t(tgt), _t(var)).numpy(),
        TF.gaussian_nll_loss(torch.tensor(mu), torch.tensor(tgt),
                             torch.tensor(var)).numpy(),
        rtol=1e-5, atol=1e-6)


def test_pairwise_distance_matches_torch():
    a = RNG.randn(6, 8).astype(np.float32)
    b = RNG.randn(6, 8).astype(np.float32)
    np.testing.assert_allclose(
        F.pairwise_distance(_t(a), _t(b)).numpy(),
        TF.pairwise_distance(torch.tensor(a), torch.tensor(b)).numpy(),
        rtol=1e-4)


def test_hsigmoid_loss_runs_and_trains():
    feat, C = 8, 10
    layer = nn.HSigmoidLoss(feat, C)
    x = paddle.to_tensor(RNG.randn(16, feat).astype(np.float32))
    y = _t(RNG.randint(0, C, 16).astype(np.int32))
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=layer.parameters())
    first = None
    for _ in range(20):
        loss = layer(x, y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss.numpy())
    assert float(loss.numpy()) < first * 0.7


# ---------------------------------------------------------- pooling/unpool


def test_max_pool_mask_and_unpool_match_torch():
    x = RNG.randn(2, 3, 8, 8).astype(np.float32)
    out, mask = F.max_pool2d(_t(x), 2, 2, return_mask=True)
    tout, tmask = TF.max_pool2d(torch.tensor(x), 2, 2, return_indices=True)
    np.testing.assert_allclose(out.numpy(), tout.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(mask.numpy(), tmask.numpy())

    un = F.max_unpool2d(out, mask, 2, 2)
    tun = TF.max_unpool2d(tout, tmask, 2, 2)
    np.testing.assert_allclose(un.numpy(), tun.numpy(), rtol=1e-6)


def test_max_unpool1d_3d():
    x1 = RNG.randn(2, 3, 10).astype(np.float32)
    o, m = F.max_pool1d(_t(x1), 2, 2, return_mask=True)
    u = F.max_unpool1d(o, m, 2, 2)
    to, tm = TF.max_pool1d(torch.tensor(x1), 2, 2, return_indices=True)
    tu = TF.max_unpool1d(to, tm, 2, 2)
    np.testing.assert_allclose(u.numpy(), tu.numpy(), rtol=1e-6)

    x3 = RNG.randn(1, 2, 4, 4, 4).astype(np.float32)
    o3, m3 = F.max_pool3d(_t(x3), 2, 2, return_mask=True)
    u3 = F.max_unpool3d(o3, m3, 2, 2)
    to3, tm3 = TF.max_pool3d(torch.tensor(x3), 2, 2, return_indices=True)
    tu3 = TF.max_unpool3d(to3, tm3, 2, 2)
    np.testing.assert_allclose(u3.numpy(), tu3.numpy(), rtol=1e-6)


# ------------------------------------------------------------- vision ops


def test_grid_sample_and_affine_grid_match_torch():
    x = RNG.randn(2, 3, 6, 6).astype(np.float32)
    theta = np.tile(np.array([[[0.8, 0.1, 0.1], [-0.1, 0.9, -0.2]]],
                             np.float32), (2, 1, 1))
    grid = F.affine_grid(_t(theta), [2, 3, 5, 5], align_corners=True)
    tgrid = TF.affine_grid(torch.tensor(theta), [2, 3, 5, 5],
                           align_corners=True)
    np.testing.assert_allclose(grid.numpy(), tgrid.numpy(), rtol=1e-4,
                               atol=1e-5)
    out = F.grid_sample(_t(x), grid, align_corners=True)
    texp = TF.grid_sample(torch.tensor(x), tgrid, align_corners=True)
    np.testing.assert_allclose(out.numpy(), texp.numpy(), rtol=1e-4, atol=1e-5)


def test_channel_shuffle_matches_torch():
    x = RNG.randn(2, 6, 4, 4).astype(np.float32)
    np.testing.assert_array_equal(
        F.channel_shuffle(_t(x), 3).numpy(),
        TF.channel_shuffle(torch.tensor(x), 3).numpy())


def test_local_response_norm_matches_torch():
    x = RNG.randn(2, 7, 5, 5).astype(np.float32)
    layer = nn.LocalResponseNorm(size=3, alpha=1e-4, beta=0.75, k=1.0)
    exp = TF.local_response_norm(torch.tensor(x), 3, alpha=1e-4, beta=0.75,
                                 k=1.0).numpy()
    np.testing.assert_allclose(layer(_t(x)).numpy(), exp, rtol=1e-4, atol=1e-6)


def test_bilinear_matches_torch():
    m = nn.Bilinear(4, 5, 3)
    x1 = RNG.randn(6, 4).astype(np.float32)
    x2 = RNG.randn(6, 5).astype(np.float32)
    w = np.asarray(m.weight._data)
    b = np.asarray(m.bias._data)
    exp = TF.bilinear(torch.tensor(x1), torch.tensor(x2), torch.tensor(w),
                      torch.tensor(b[0]))
    np.testing.assert_allclose(m(_t(x1), _t(x2)).numpy(), exp.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_sequence_mask():
    lens = _t(np.array([1, 3, 5], np.int32))
    m = F.sequence_mask(lens, maxlen=5, dtype="int32").numpy()
    exp = np.array([[1, 0, 0, 0, 0], [1, 1, 1, 0, 0], [1, 1, 1, 1, 1]])
    np.testing.assert_array_equal(m, exp)


def test_temporal_shift_shapes_and_content():
    x = np.arange(2 * 2 * 4 * 2 * 2, dtype=np.float32).reshape(4, 4, 2, 2)
    out = F.temporal_shift(_t(x), seg_num=2, shift_ratio=0.25).numpy()
    assert out.shape == x.shape
    x5 = x.reshape(2, 2, 4, 2, 2)
    np.testing.assert_array_equal(out.reshape(2, 2, 4, 2, 2)[:, 0, 0],
                                  x5[:, 1, 0])  # fwd-shifted slice


def test_spectral_norm_normalizes():
    w = RNG.randn(8, 6).astype(np.float32) * 5
    sn = nn.SpectralNorm([8, 6], dim=0, power_iters=20)
    out = sn(_t(w)).numpy()
    s = np.linalg.svd(out, compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=1e-2)


def test_layers_smoke():
    """Every new layer constructs and runs on a plausible input."""
    x4 = _t(RNG.randn(2, 4, 8, 8).astype(np.float32))
    x3 = _t(RNG.randn(2, 4, 8).astype(np.float32))
    x5 = _t(RNG.randn(2, 4, 4, 8, 8).astype(np.float32))
    assert nn.Identity()(x4).shape == [2, 4, 8, 8]
    assert nn.Softmax2D()(x4).shape == [2, 4, 8, 8]
    assert nn.MaxPool3D(2)(x5).shape == [2, 4, 2, 4, 4]
    assert nn.AvgPool3D(2)(x5).shape == [2, 4, 2, 4, 4]
    assert nn.AdaptiveAvgPool3D(2)(x5).shape == [2, 4, 2, 2, 2]
    assert nn.AdaptiveMaxPool1D(4)(x3).shape == [2, 4, 4]
    assert nn.Pad1D([1, 2])(x3).shape == [2, 4, 11]
    assert nn.Pad3D([1, 1, 1, 1, 1, 1])(x5).shape == [2, 4, 6, 10, 10]
    assert nn.ZeroPad2D([1, 1, 2, 2])(x4).shape == [2, 4, 12, 10]
    assert nn.PixelUnshuffle(2)(x4).shape == [2, 16, 4, 4]
    assert nn.ChannelShuffle(2)(x4).shape == [2, 4, 8, 8]
    assert nn.UpsamplingNearest2D(scale_factor=2)(x4).shape == [2, 4, 16, 16]
    assert nn.UpsamplingBilinear2D(size=[16, 16])(x4).shape == [2, 4, 16, 16]
    assert nn.InstanceNorm1D(4)(x3).shape == [2, 4, 8]
    assert nn.InstanceNorm3D(4)(x5).shape == [2, 4, 4, 8, 8]
    assert nn.CosineSimilarity()(x4, x4).shape == [2, 8, 8]
    assert nn.Dropout3D(0.5)(x5).shape == [2, 4, 4, 8, 8]
    assert nn.AlphaDropout(0.5)(x3).shape == [2, 4, 8]
    assert nn.RReLU()(x3).shape == [2, 4, 8]
    d = nn.LayerDict({"a": nn.Linear(3, 4)})
    assert "a" in d and len(d) == 1
    assert nn.Conv1DTranspose(4, 6, 3)(x3).shape[1] == 6
    assert nn.Conv3DTranspose(4, 6, 3)(x5).shape[1] == 6
    # loss layers
    a = _t(RNG.randn(5, 3).astype(np.float32))
    b = _t(RNG.randn(5, 3).astype(np.float32))
    yv = _t(np.sign(RNG.randn(5)).astype(np.float32))
    for layer, args in [
        (nn.MarginRankingLoss(), (a[:, 0], b[:, 0], yv)),
        (nn.HingeEmbeddingLoss(), (a, _t(np.sign(RNG.randn(5, 3)).astype(np.float32)))),
        (nn.CosineEmbeddingLoss(), (a, b, yv)),
        (nn.TripletMarginLoss(), (a, b, _t(RNG.randn(5, 3).astype(np.float32)))),
        (nn.TripletMarginWithDistanceLoss(), (a, b, _t(RNG.randn(5, 3).astype(np.float32)))),
        (nn.SoftMarginLoss(), (a, _t(np.sign(RNG.randn(5, 3)).astype(np.float32)))),
        (nn.MultiMarginLoss(), (a, _t(RNG.randint(0, 3, 5).astype(np.int32)))),
        (nn.MultiLabelSoftMarginLoss(), (a, _t((RNG.rand(5, 3) > 0.5).astype(np.float32)))),
        (nn.PoissonNLLLoss(), (_t(RNG.rand(5).astype(np.float32)), _t(RNG.poisson(1.0, 5).astype(np.float32)))),
        (nn.GaussianNLLLoss(), (a, b, _t(RNG.rand(5, 3).astype(np.float32) + 0.1))),
    ]:
        out = layer(*args)
        assert np.isfinite(out.numpy()).all(), type(layer).__name__


@pytest.mark.parametrize("kw", [{}, {"stride": 2, "padding": 1},
                                {"stride": 2, "padding": 1, "output_padding": 1},
                                {"dilation": 2}])
def test_conv_transpose_matches_torch(kw):
    """Regression: the convT path double-swapped the kernel IO axes and
    mis-mapped padding (every output was wrong before this fix)."""
    x = RNG.rand(1, 4, 8, 8).astype(np.float32)
    w = RNG.rand(4, 6, 3, 3).astype(np.float32)
    got = F.conv2d_transpose(_t(x), _t(w), **kw).numpy()
    exp = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w), **kw).numpy()
    np.testing.assert_allclose(got, exp, rtol=1e-3, atol=1e-4)


def test_conv_transpose_grouped():
    x = RNG.rand(1, 4, 8, 8).astype(np.float32)
    w = RNG.rand(4, 3, 3, 3).astype(np.float32)
    got = F.conv2d_transpose(_t(x), _t(w), groups=2).numpy()
    exp = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w), groups=2).numpy()
    np.testing.assert_allclose(got, exp, rtol=1e-3, atol=1e-4)


def test_rnn_birnn_wrappers():
    cell = nn.SimpleRNNCell(8, 16)
    rnn = nn.RNN(cell)
    x = _t(RNG.randn(2, 5, 8).astype(np.float32))
    y, s = rnn(x)
    assert y.shape == [2, 5, 16]
    bi = nn.BiRNN(nn.SimpleRNNCell(8, 16), nn.SimpleRNNCell(8, 16))
    yb, _ = bi(x)
    assert yb.shape == [2, 5, 32]


def test_cross_entropy_ignore_index_with_weight_finite():
    """Regression: the label gather must clamp ignore_index rows BEFORE the
    lookup — an out-of-range fill-mode gather yields NaN, and NaN*0 survives
    the mask into the weighted mean."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.nn import functional as F

    rng = np.random.default_rng(0)
    logits = paddle.to_tensor(rng.standard_normal((6, 5)).astype(np.float32))
    labels = paddle.to_tensor(np.array([0, 1, -100, 2, -100, 4]))
    w = paddle.to_tensor(np.ones(5, np.float32))
    lw = float(F.cross_entropy(logits, labels, weight=w).numpy())
    l = float(F.cross_entropy(logits, labels).numpy())
    assert np.isfinite(lw) and np.isfinite(l)
    assert abs(lw - l) < 1e-5  # all-ones weights == unweighted


def test_mha_need_weights_dropout():
    # the explicit-weights path applies probability dropout in training
    # (ref MultiHeadAttention applies F.dropout to the weights)
    paddle.seed(7)
    mha = nn.MultiHeadAttention(16, 4, dropout=0.5, need_weights=True)
    x = paddle.randn([2, 5, 16])
    mha.train()
    _, w_train = mha(x, x, x)
    assert (w_train.numpy() == 0).any()
    mha.eval()
    _, w_eval = mha(x, x, x)
    assert np.allclose(w_eval.numpy().sum(-1), 1.0, atol=1e-4)


def test_rnnt_fastemit_rescales_emission_grads():
    """FastEmit leaves the loss value unchanged and adds exactly
    lambda * (emission-path gradient): grad(l) = grad(0) + l*(grad(1)-grad(0))."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.nn import functional as F

    rng = np.random.RandomState(0)
    B, T, U, V = 2, 4, 3, 5
    logits = rng.randn(B, T, U + 1, V).astype(np.float32)
    lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    labels = rng.randint(1, V, (B, U)).astype(np.int32)
    in_len = np.array([T, T - 1], np.int32)
    lab_len = np.array([U, U - 1], np.int32)

    def run(lam):
        x = paddle.to_tensor(lp)
        x.stop_gradient = False
        loss = F.rnnt_loss(x, paddle.to_tensor(labels),
                           paddle.to_tensor(in_len),
                           paddle.to_tensor(lab_len),
                           fastemit_lambda=lam, reduction="sum")
        loss.backward()
        return float(loss), x.grad.numpy().copy()

    v0, g0 = run(0.0)
    v1, g1 = run(1.0)
    vh, gh = run(0.5)
    assert abs(v0 - v1) < 1e-5 and abs(v0 - vh) < 1e-5  # value unchanged
    assert not np.allclose(g0, g1)  # gradient IS regularized
    np.testing.assert_allclose(gh, g0 + 0.5 * (g1 - g0), rtol=1e-4,
                               atol=1e-6)

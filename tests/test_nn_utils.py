"""paddle.nn.utils: clipping helpers, parameter vectorization, weight/
spectral norm hooks (ref:python/paddle/nn/utils/)."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


def _net():
    paddle.seed(0)
    return nn.Linear(4, 3)


def test_clip_grad_norm_matches_torch():
    net = _net()
    x = np.random.randn(8, 4).astype(np.float32) * 10
    loss = (net(Tensor(x)) ** 2).sum()
    loss.backward()
    grads_before = [p.grad.numpy().copy() for p in net.parameters()]

    tp = [torch.nn.Parameter(torch.tensor(g)) for g in grads_before]
    for t, g in zip(tp, grads_before):
        t.grad = torch.tensor(g)
    tnorm = torch.nn.utils.clip_grad_norm_(tp, 1.0)

    total = nn.utils.clip_grad_norm_(net.parameters(), 1.0)
    assert float(total) == pytest.approx(float(tnorm), rel=1e-5)
    for p, t in zip(net.parameters(), tp):
        np.testing.assert_allclose(p.grad.numpy(), t.grad.numpy(), rtol=1e-4)


def test_clip_grad_norm_inf_and_value():
    net = _net()
    loss = (net(Tensor(np.ones((2, 4), np.float32))) ** 2).sum()
    loss.backward()
    total = nn.utils.clip_grad_norm_(net.parameters(), 0.5,
                                     norm_type=float("inf"))
    assert float(total) >= 0
    for p in net.parameters():
        assert float(np.abs(p.grad.numpy()).max()) <= 0.5 + 1e-6
    nn.utils.clip_grad_value_(net.parameters(), 0.1)
    for p in net.parameters():
        assert float(np.abs(p.grad.numpy()).max()) <= 0.1 + 1e-7


def test_parameters_vector_round_trip():
    net = _net()
    vec = nn.utils.parameters_to_vector(net.parameters())
    assert vec.shape == [4 * 3 + 3]
    new = Tensor(np.arange(15, dtype=np.float32))
    nn.utils.vector_to_parameters(new, net.parameters())
    np.testing.assert_allclose(
        nn.utils.parameters_to_vector(net.parameters()).numpy(),
        np.arange(15, dtype=np.float32))
    with pytest.raises(ValueError, match="elements"):
        nn.utils.vector_to_parameters(Tensor(np.zeros(7, np.float32)),
                                      net.parameters())


def test_weight_norm_forward_and_training():
    paddle.seed(1)
    lin = nn.Linear(4, 3)
    w0 = lin.weight.numpy().copy()
    out_ref = lin(Tensor(np.ones((2, 4), np.float32))).numpy()
    nn.utils.weight_norm(lin, "weight", dim=0)
    names = dict(lin.named_parameters())
    assert "weight_g" in names and "weight_v" in names and "weight" not in names
    # reparameterized forward equals the original at init
    out = lin(Tensor(np.ones((2, 4), np.float32))).numpy()
    np.testing.assert_allclose(out, out_ref, atol=1e-5)
    # trains: grads reach g and v
    loss = (lin(Tensor(np.random.randn(4, 4).astype(np.float32))) ** 2).mean()
    loss.backward()
    assert lin.weight_g.grad is not None and lin.weight_v.grad is not None
    # remove folds back to a single parameter with the same effective value
    nn.utils.remove_weight_norm(lin, "weight")
    assert "weight" in dict(lin.named_parameters())
    np.testing.assert_allclose(lin.weight.numpy(), w0, atol=1e-5)


def test_weight_norm_compiled_step():
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import SGD

    paddle.seed(2)
    lin = nn.Linear(4, 2)
    nn.utils.weight_norm(lin, "weight")
    opt = SGD(learning_rate=0.05, parameters=lin.parameters())
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randn(8, 2).astype(np.float32)
    step = TrainStep(lambda a, b: ((lin(a) - b) ** 2).mean(), opt, layers=lin)
    l0 = float(step(Tensor(x), Tensor(y))._data)
    for _ in range(20):
        l1 = float(step(Tensor(x), Tensor(y))._data)
    assert l1 < 0.5 * l0


def test_spectral_norm_hook():
    paddle.seed(3)
    lin = nn.Linear(6, 5)
    nn.utils.spectral_norm(lin, "weight", n_power_iterations=3)
    out = lin(Tensor(np.ones((2, 6), np.float32)))
    assert out.shape == [2, 5]
    # effective weight has unit spectral norm (power iteration converged)
    w = lin.weight.numpy()
    assert np.linalg.svd(w, compute_uv=False)[0] == pytest.approx(1.0,
                                                                  rel=1e-2)
    # trains through the reparameterization
    loss = (lin(Tensor(np.random.randn(3, 6).astype(np.float32))) ** 2).sum()
    loss.backward()
    assert lin.weight_orig.grad is not None


def test_vector_to_parameters_accepts_iterator():
    net = _net()
    vec = Tensor(np.arange(15, dtype=np.float32))
    nn.utils.vector_to_parameters(vec, iter(list(net.parameters())))
    np.testing.assert_allclose(
        nn.utils.parameters_to_vector(net.parameters()).numpy(),
        np.arange(15, dtype=np.float32))


def test_spectral_norm_dim_none_and_eval_stability():
    paddle.seed(4)
    lin = nn.Linear(6, 5)
    nn.utils.spectral_norm(lin)  # dim=None -> 1 for Linear (reference)
    lin.eval()
    x = Tensor(np.ones((2, 6), np.float32))
    a = lin(x).numpy()
    b = lin(x).numpy()
    np.testing.assert_array_equal(a, b)  # eval: no iteration, no drift
    u_before = lin.weight_u.numpy().copy()
    lin(x)
    np.testing.assert_array_equal(lin.weight_u.numpy(), u_before)


def test_clip_alias_routes_to_utils():
    from paddle_tpu.nn.clip import clip_grad_norm_ as alias

    net = _net()
    loss = (net(Tensor(np.ones((2, 4), np.float32))) ** 2).sum()
    loss.backward()
    t1 = float(alias(net.parameters(), 1.0))
    assert t1 > 0

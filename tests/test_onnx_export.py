"""paddle.onnx.export emits real, numerically-correct ONNX
(ref:python/paddle/onnx/export.py). Since onnxruntime isn't in this
environment, a minimal numpy interpreter of the emitted op set executes
the graph and the result is compared against the framework forward."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit import InputSpec
from paddle_tpu.onnx import onnx_ir_pb2 as P

_NP_DTYPES = {
    P.TensorProto.FLOAT: np.float32, P.TensorProto.DOUBLE: np.float64,
    P.TensorProto.INT32: np.int32, P.TensorProto.INT64: np.int64,
    P.TensorProto.BOOL: np.bool_, P.TensorProto.INT8: np.int8,
    P.TensorProto.UINT8: np.uint8,
}


def _tensor_to_np(t):
    dt = _NP_DTYPES[t.data_type]
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, np.uint8 if dt == np.bool_ else dt)
        if dt == np.bool_:
            arr = arr.astype(np.bool_)
        return arr.reshape(list(t.dims)).copy()
    raise AssertionError("only raw_data initializers are emitted")


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == P.AttributeProto.INT:
            out[a.name] = a.i
        elif a.type == P.AttributeProto.FLOAT:
            out[a.name] = a.f
        elif a.type == P.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == P.AttributeProto.INTS:
            out[a.name] = list(a.ints)
        elif a.type == P.AttributeProto.FLOATS:
            out[a.name] = list(a.floats)
    return out


def _conv(x, w, at):
    import jax.lax as lax

    pads = at.get("pads", [0] * (2 * (x.ndim - 2)))
    nd = x.ndim - 2
    pad_pairs = list(zip(pads[:nd], pads[nd:]))
    return np.asarray(lax.conv_general_dilated(
        x, w, window_strides=at.get("strides", [1] * nd),
        padding=pad_pairs, rhs_dilation=at.get("dilations", [1] * nd),
        feature_group_count=at.get("group", 1)))


def _pool(x, at, reduce_max=True):
    import jax.lax as lax

    k = at["kernel_shape"]
    s = at.get("strides", [1] * len(k))
    nd = len(k)
    pads = at.get("pads", [0] * (2 * nd))
    pad_pairs = [(0, 0), (0, 0)] + list(zip(pads[:nd], pads[nd:]))
    wd = (1, 1) + tuple(k)
    ws = (1, 1) + tuple(s)
    if reduce_max:
        return np.asarray(lax.reduce_window(
            x, -np.inf, lax.max, wd, ws, pad_pairs))
    total = np.asarray(lax.reduce_window(x, 0.0, lax.add, wd, ws, pad_pairs))
    return total / float(np.prod(k))


def run_onnx(model: "P.ModelProto", feeds: dict):
    env = dict(feeds)
    for init in model.graph.initializer:
        env[init.name] = _tensor_to_np(init)
    for node in model.graph.node:
        i = [env[n] for n in node.input]
        at = _attrs(node)
        op = node.op_type
        if op == "Add":
            out = i[0] + i[1]
        elif op == "Sub":
            out = i[0] - i[1]
        elif op == "Mul":
            out = i[0] * i[1]
        elif op == "Div":
            out = i[0] / i[1]
        elif op == "Max":
            out = np.maximum(i[0], i[1])
        elif op == "Min":
            out = np.minimum(i[0], i[1])
        elif op == "Pow":
            out = np.power(i[0], i[1])
        elif op == "Exp":
            out = np.exp(i[0])
        elif op == "Log":
            out = np.log(i[0])
        elif op == "Tanh":
            out = np.tanh(i[0])
        elif op == "Sigmoid":
            out = 1.0 / (1.0 + np.exp(-i[0]))
        elif op == "Sqrt":
            out = np.sqrt(i[0])
        elif op == "Erf":
            from scipy.special import erf

            out = erf(i[0]).astype(i[0].dtype)
        elif op == "Neg":
            out = -i[0]
        elif op == "Reciprocal":
            out = 1.0 / i[0]
        elif op == "Where":
            out = np.where(i[0], i[1], i[2])
        elif op == "Greater":
            out = i[0] > i[1]
        elif op == "GreaterOrEqual":
            out = i[0] >= i[1]
        elif op == "Less":
            out = i[0] < i[1]
        elif op == "LessOrEqual":
            out = i[0] <= i[1]
        elif op == "Equal":
            out = i[0] == i[1]
        elif op == "Cast":
            out = i[0].astype(_NP_DTYPES[at["to"]])
        elif op == "Reshape":
            out = i[0].reshape(list(i[1]))
        elif op == "Expand":
            out = np.broadcast_to(i[0], list(i[1])).copy()
        elif op == "Transpose":
            out = np.transpose(i[0], at["perm"])
        elif op == "Squeeze":
            out = np.squeeze(i[0], tuple(int(a) for a in i[1]))
        elif op == "Unsqueeze":
            out = np.expand_dims(i[0], tuple(int(a) for a in i[1]))
        elif op == "Concat":
            out = np.concatenate(i, axis=at["axis"])
        elif op == "Slice":
            starts, ends, axes = i[1], i[2], i[3]
            steps = i[4] if len(i) > 4 else np.ones_like(starts)
            sl = [slice(None)] * i[0].ndim
            for st, en, ax, sp in zip(starts, ends, axes, steps):
                sl[int(ax)] = slice(int(st), None if en < -2**62 else int(en),
                                    int(sp))
            out = i[0][tuple(sl)]
        elif op == "Gather":
            out = np.take(i[0], i[1].astype(np.int64), axis=at.get("axis", 0))
        elif op == "Einsum":
            out = np.einsum(at["equation"], *i)
        elif op == "Conv":
            out = _conv(i[0], i[1], at)
        elif op == "MaxPool":
            out = _pool(i[0], at, reduce_max=True)
        elif op == "AveragePool":
            out = _pool(i[0], at, reduce_max=False)
        elif op in ("ReduceSum", "ReduceMax", "ReduceMin", "ReduceProd"):
            fn = {"ReduceSum": np.sum, "ReduceMax": np.max,
                  "ReduceMin": np.min, "ReduceProd": np.prod}[op]
            # ReduceSum takes axes as input[1] from opset 13; the rest of
            # the Reduce family switches to the input form at opset 18 —
            # enforce the form the model's DECLARED opset requires
            opset = model.opset_import[0].version
            if op == "ReduceSum" or opset >= 18:
                assert len(i) == 2, \
                    f"{op} must carry axes as an input at opset {opset}"
                axes = tuple(int(a) for a in i[1])
            else:
                assert len(i) == 1, f"{op} axes-as-input needs opset 18"
                axes = tuple(int(a) for a in at["axes"])
            out = fn(i[0], axis=axes, keepdims=bool(at.get("keepdims", 1)))
        else:
            raise AssertionError(f"test interpreter: unknown op {op}")
        env[node.output[0]] = np.asarray(out)
    return [env[o.name] for o in model.graph.output]


def _export_and_check(layer, specs, feeds, atol=1e-5, opset_version=17):
    import tempfile

    layer.eval()
    ref = layer(*[paddle.to_tensor(f) for f in feeds])
    with tempfile.TemporaryDirectory() as td:
        path = paddle.onnx.export(layer, f"{td}/m", input_spec=specs,
                                  opset_version=opset_version)
        m = P.ModelProto()
        m.ParseFromString(open(path, "rb").read())
    assert m.ir_version == 8 and m.opset_import[0].version == opset_version
    outs = run_onnx(m, {v.name: f for v, f in zip(m.graph.input, feeds)})
    np.testing.assert_allclose(outs[0], ref.numpy(), atol=atol, rtol=1e-4)
    return m


def test_onnx_mlp_numerics():
    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 32)
            self.fc2 = nn.Linear(32, 4)
            self.bn = nn.BatchNorm1D(32)

        def forward(self, x):
            h = paddle.nn.functional.relu(self.bn(self.fc1(x)))
            return paddle.nn.functional.softmax(self.fc2(h))

    x = np.random.default_rng(0).standard_normal((4, 16)).astype(np.float32)
    m = _export_and_check(MLP(), [InputSpec([4, 16])], [x])
    assert any(n.op_type == "Einsum" for n in m.graph.node)


def test_onnx_lenet_numerics():
    from paddle_tpu.vision.models import LeNet

    x = np.random.default_rng(1).standard_normal(
        (2, 1, 28, 28)).astype(np.float32)
    m = _export_and_check(LeNet(), [InputSpec([2, 1, 28, 28])], [x],
                          atol=1e-4)
    ops = {n.op_type for n in m.graph.node}
    assert "Conv" in ops and "MaxPool" in ops


def test_onnx_gpt_numerics():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    model = GPTForCausalLM(gpt_tiny())
    ids = np.random.default_rng(2).integers(0, 1024, (1, 8)).astype(np.int32)
    m = _export_and_check(model, [InputSpec([1, 8], dtype="int32")], [ids],
                          atol=2e-4)
    ops = {n.op_type for n in m.graph.node}
    assert "Gather" in ops and "Tanh" in ops  # embedding + gelu


def test_onnx_export_validations(tmp_path):
    lin = nn.Linear(4, 2)
    with pytest.raises(ValueError, match="input_spec"):
        paddle.onnx.export(lin, str(tmp_path / "x"))
    with pytest.raises(ValueError, match="opset"):
        paddle.onnx.export(lin, str(tmp_path / "x"),
                           input_spec=[InputSpec([1, 4])], opset_version=9)
    # unsupported primitives must raise, not write a broken file
    from paddle_tpu.onnx.exporter import UnsupportedOp, to_onnx_model
    import jax.numpy as jnp

    with pytest.raises(UnsupportedOp, match="sort"):
        to_onnx_model(lambda a: jnp.sort(a),
                      (np.zeros((4,), np.float32),))


def test_onnx_opset18_reduce_axes_as_input():
    """Opset 18+ export emits the whole Reduce family with axes as an
    INPUT (the 13-17 attribute form is invalid ONNX there); numerics
    verified by the opset-aware interpreter."""

    class Reducer(nn.Layer):
        def forward(self, x):
            return (paddle.max(x, axis=1) + paddle.min(x, axis=1)
                    + paddle.sum(x, axis=1))

    feeds = [np.random.rand(3, 5).astype(np.float32)]
    m = _export_and_check(Reducer(), [InputSpec([3, 5])], feeds,
                          opset_version=18)
    forms = {n.op_type: len(n.input) for n in m.graph.node
             if n.op_type.startswith("Reduce")}
    assert forms and all(v == 2 for v in forms.values()), forms


def test_onnx_opset_20_rejected():
    with pytest.raises(ValueError, match=r"\[13, 19\]"):
        paddle.onnx.export(nn.Linear(4, 2), "/tmp/never",
                           input_spec=[InputSpec([1, 4])], opset_version=20)

"""Property-style fuzz battery: random shapes (incl. rank-0, zero-size,
broadcast pairs), NaN/Inf propagation, and dtype promotion across the
elementwise/reduction/comparison op surface, checked against torch CPU.

Complements the fixed-case golden batteries (SURVEY.md §4): those pin known
contracts; this sweeps the shape/value space where silent divergences hide
(reduction over empty axes, -0.0, inf-inf, broadcasting against size-1 and
size-0 dims). Seeded — failures reproduce.
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor

RNG = np.random.default_rng(20260731)

# shape pool: scalars, vectors, matrices, zero-size, higher-rank
SHAPES = [(), (1,), (7,), (0,), (3, 4), (1, 5), (2, 0, 3), (2, 3, 4),
          (1, 1, 6)]


def _rand(shape, with_specials=False):
    x = RNG.standard_normal(shape).astype(np.float32)
    if with_specials and x.size >= 4:
        flat = x.reshape(-1)
        flat[0] = np.nan
        flat[1] = np.inf
        flat[2] = -np.inf
        flat[3] = -0.0
        x = flat.reshape(shape)
    return x


UNARY = [
    ("abs", paddle.abs, torch.abs),
    ("exp", paddle.exp, torch.exp),
    ("log", paddle.log, torch.log),
    ("sqrt", paddle.sqrt, torch.sqrt),
    ("tanh", paddle.tanh, torch.tanh),
    ("sin", paddle.sin, torch.sin),
    ("floor", paddle.floor, torch.floor),
    ("ceil", paddle.ceil, torch.ceil),
    ("round", paddle.round, torch.round),
    ("sign", paddle.sign, torch.sign),
    ("expm1", paddle.expm1, torch.expm1),
    ("log1p", paddle.log1p, torch.log1p),
    ("rsqrt", paddle.rsqrt, torch.rsqrt),
    ("sigmoid", paddle.nn.functional.sigmoid, torch.sigmoid),
    ("erf", paddle.erf, torch.erf),
]


@pytest.mark.parametrize("name,pfn,tfn", UNARY, ids=[u[0] for u in UNARY])
def test_unary_fuzz(name, pfn, tfn):
    for shape in SHAPES:
        for specials in (False, True):
            x = _rand(shape, with_specials=specials)
            got = np.asarray(pfn(Tensor(x))._data)
            want = tfn(torch.from_numpy(x.copy())).numpy()
            np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6,
                                       equal_nan=True,
                                       err_msg=f"{name} shape={shape} "
                                               f"specials={specials}")


BINARY = [
    ("add", paddle.add, torch.add),
    ("subtract", paddle.subtract, torch.subtract),
    ("multiply", paddle.multiply, torch.multiply),
    ("divide", paddle.divide, torch.divide),
    ("maximum", paddle.maximum, torch.maximum),
    ("minimum", paddle.minimum, torch.minimum),
    ("pow", paddle.pow, torch.pow),
    ("atan2", paddle.atan2, torch.atan2),
    ("fmax", paddle.fmax, torch.fmax),
    ("fmin", paddle.fmin, torch.fmin),
]

# broadcastable shape pairs, incl. zero-size and size-1 interplay
PAIRS = [((3, 4), (3, 4)), ((3, 4), (1, 4)), ((3, 4), (4,)), ((3, 1), (1, 4)),
         ((), (3, 2)), ((2, 0, 3), (1, 3)), ((5,), ())]


@pytest.mark.parametrize("name,pfn,tfn", BINARY, ids=[b[0] for b in BINARY])
def test_binary_fuzz(name, pfn, tfn):
    for sa, sb in PAIRS:
        for specials in (False, True):
            a = _rand(sa, with_specials=specials)
            b = _rand(sb)
            got = np.asarray(pfn(Tensor(a), Tensor(b))._data)
            want = tfn(torch.from_numpy(a.copy()),
                       torch.from_numpy(b.copy())).numpy()
            np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6,
                                       equal_nan=True,
                                       err_msg=f"{name} {sa}x{sb} "
                                               f"specials={specials}")


REDUCTIONS = [
    ("sum", paddle.sum, torch.sum),
    ("mean", paddle.mean, torch.mean),
    ("max", paddle.max, torch.amax),
    ("min", paddle.min, torch.amin),
    ("prod", paddle.prod, torch.prod),
]


@pytest.mark.parametrize("name,pfn,tfn", REDUCTIONS,
                         ids=[r[0] for r in REDUCTIONS])
def test_reduction_fuzz(name, pfn, tfn):
    for shape in [(3, 4), (2, 3, 4), (1, 5), (4,)]:
        x = _rand(shape, with_specials=True)
        for axis in [None] + list(range(len(shape))):
            for keepdim in (False, True):
                if axis is None:
                    if keepdim:
                        continue
                    got = np.asarray(pfn(Tensor(x))._data)
                    want = tfn(torch.from_numpy(x.copy())).numpy()
                else:
                    got = np.asarray(pfn(Tensor(x), axis=axis,
                                         keepdim=keepdim)._data)
                    want = tfn(torch.from_numpy(x.copy()), dim=axis,
                               keepdim=keepdim).numpy()
                np.testing.assert_allclose(
                    got, want, rtol=2e-5, atol=1e-5, equal_nan=True,
                    err_msg=f"{name} shape={shape} axis={axis} "
                            f"keepdim={keepdim}")


def test_reduction_empty_semantics():
    """Reductions over zero-size inputs follow the identity-element
    contract (sum->0, prod->1, mean->nan), matching torch."""
    x = np.zeros((0, 3), np.float32)
    assert float(paddle.sum(Tensor(x))) == 0.0
    assert float(paddle.prod(Tensor(x))) == 1.0
    assert np.isnan(float(paddle.mean(Tensor(x))))
    np.testing.assert_array_equal(
        np.asarray(paddle.sum(Tensor(x), axis=0)._data),
        torch.sum(torch.from_numpy(x.copy()), dim=0).numpy())


COMPARISONS = [
    ("equal", paddle.equal, torch.eq),
    ("less_than", paddle.less_than, torch.lt),
    ("greater_than", paddle.greater_than, torch.gt),
    ("not_equal", paddle.not_equal, torch.ne),
]


@pytest.mark.parametrize("name,pfn,tfn", COMPARISONS,
                         ids=[c[0] for c in COMPARISONS])
def test_comparison_fuzz_with_nan(name, pfn, tfn):
    a = _rand((4, 4), with_specials=True)
    b = a.copy()
    b[0, 0] = 1.0  # break one equality; NaN rows keep IEEE semantics
    got = np.asarray(pfn(Tensor(a), Tensor(b))._data)
    want = tfn(torch.from_numpy(a.copy()), torch.from_numpy(b.copy())).numpy()
    np.testing.assert_array_equal(got, want)


def test_division_special_values():
    """x/0 -> ±inf, 0/0 -> nan, matching IEEE + torch."""
    a = np.array([1.0, -1.0, 0.0, np.inf], np.float32)
    b = np.array([0.0, 0.0, 0.0, np.inf], np.float32)
    got = np.asarray(paddle.divide(Tensor(a), Tensor(b))._data)
    want = torch.divide(torch.from_numpy(a.copy()),
                        torch.from_numpy(b.copy())).numpy()
    np.testing.assert_allclose(got, want, equal_nan=True)


def test_integer_division_reference_semantics():
    """The reference's FloorDivideFunctor is C integer division (TRUNC
    toward zero — ref:paddle/phi/kernels/funcs/elementwise_functor.h:594),
    and RemainderFunctor (:527) is floor-mod (divisor's sign). Negative
    operands separate the two conventions."""
    a = np.array([7, -7, 7, -7, 9, -9], np.int32)
    b = np.array([2, 2, -2, -2, 4, 4], np.int32)
    got = np.asarray(paddle.floor_divide(Tensor(a), Tensor(b))._data)
    np.testing.assert_array_equal(got, [3, -3, -3, 3, 2, -2])  # trunc
    got = np.asarray(paddle.mod(Tensor(a), Tensor(b))._data)
    np.testing.assert_array_equal(got, [1, 1, -1, -1, 1, 3])  # floor-mod
    # operator forms route the same way
    got = np.asarray((Tensor(a) // Tensor(b))._data)
    np.testing.assert_array_equal(got, [3, -3, -3, 3, 2, -2])
    # floats keep pythonic floor (the reference registers ints only)
    fa = np.array([-7.0, 7.0], np.float32)
    fb = np.array([2.0, -2.0], np.float32)
    got = np.asarray(paddle.floor_divide(Tensor(fa), Tensor(fb))._data)
    np.testing.assert_array_equal(got, [-4.0, -4.0])
    # float mod matches torch.remainder (divisor-sign contract)
    fm = np.asarray(paddle.mod(Tensor(fa), Tensor(fb))._data)
    np.testing.assert_allclose(
        fm, torch.remainder(torch.from_numpy(fa), torch.from_numpy(fb)).numpy())


ACTIVATIONS = [
    # (name, paddle fn, torch fn) — defaults must agree
    ("relu", paddle.nn.functional.relu, torch.nn.functional.relu),
    ("relu6", paddle.nn.functional.relu6, torch.nn.functional.relu6),
    ("gelu_exact", lambda x: paddle.nn.functional.gelu(x),
     lambda x: torch.nn.functional.gelu(x)),
    ("gelu_tanh", lambda x: paddle.nn.functional.gelu(x, approximate=True),
     lambda x: torch.nn.functional.gelu(x, approximate="tanh")),
    ("silu", paddle.nn.functional.silu, torch.nn.functional.silu),
    ("mish", paddle.nn.functional.mish, torch.nn.functional.mish),
    ("softplus", paddle.nn.functional.softplus,
     torch.nn.functional.softplus),
    ("hardswish", paddle.nn.functional.hardswish,
     torch.nn.functional.hardswish),
    ("hardsigmoid", paddle.nn.functional.hardsigmoid,
     lambda x: torch.clamp(x / 6 + 0.5, 0, 1)),  # paddle slope=1/6 offset=.5
    ("elu", paddle.nn.functional.elu, torch.nn.functional.elu),
    ("selu", paddle.nn.functional.selu, torch.nn.functional.selu),
    ("leaky_relu", lambda x: paddle.nn.functional.leaky_relu(x, 0.01),
     lambda x: torch.nn.functional.leaky_relu(x, 0.01)),
    ("log_sigmoid", paddle.nn.functional.log_sigmoid,
     torch.nn.functional.logsigmoid),
    ("tanhshrink", paddle.nn.functional.tanhshrink,
     torch.nn.functional.tanhshrink),
    ("softsign", paddle.nn.functional.softsign,
     torch.nn.functional.softsign),
]


@pytest.mark.parametrize("name,pfn,tfn", ACTIVATIONS,
                         ids=[a[0] for a in ACTIVATIONS])
def test_activation_fuzz(name, pfn, tfn):
    """Default-parameter activations match torch over wide magnitudes
    (large |x| exposes approximate-vs-exact formulations and overflow
    handling in softplus/mish)."""
    for scale in (1.0, 10.0, 100.0):
        x = (_rand((64,)) * scale).astype(np.float32)
        got = np.asarray(pfn(Tensor(x))._data)
        want = tfn(torch.from_numpy(x.copy())).numpy()
        np.testing.assert_allclose(got, want, rtol=3e-5, atol=1e-5,
                                   err_msg=f"{name} scale={scale}")


def test_softmax_edge_rows():
    """Softmax rows of -inf (fully masked) and mixed inf behave like
    torch: all -inf -> nan row (0/0), one finite -> one-hot."""
    x = np.array([[-np.inf, -np.inf, -np.inf],
                  [1.0, -np.inf, -np.inf],
                  [1000.0, 999.0, -1000.0]], np.float32)
    got = np.asarray(paddle.nn.functional.softmax(Tensor(x), axis=-1)._data)
    want = torch.softmax(torch.from_numpy(x.copy()), dim=-1).numpy()
    np.testing.assert_allclose(got, want, atol=1e-6, equal_nan=True)


def test_cumsum_cumprod_with_nan():
    x = _rand((3, 5), with_specials=True)
    np.testing.assert_allclose(
        np.asarray(paddle.cumsum(Tensor(x), axis=1)._data),
        torch.cumsum(torch.from_numpy(x.copy()), dim=1).numpy(),
        rtol=1e-5, equal_nan=True)
    np.testing.assert_allclose(
        np.asarray(paddle.cumprod(Tensor(x), dim=1)._data),
        torch.cumprod(torch.from_numpy(x.copy()), dim=1).numpy(),
        rtol=1e-5, equal_nan=True)


def test_clip_with_nan_and_reversed_bounds():
    x = _rand((8,), with_specials=True)
    got = np.asarray(paddle.clip(Tensor(x), -0.5, 0.5)._data)
    want = torch.clamp(torch.from_numpy(x.copy()), -0.5, 0.5).numpy()
    np.testing.assert_allclose(got, want, equal_nan=True)
    # min > max: torch/paddle contract clamps to max
    got = np.asarray(paddle.clip(Tensor(x), 1.0, -1.0)._data)
    want = torch.clamp(torch.from_numpy(x.copy()), 1.0, -1.0).numpy()
    np.testing.assert_allclose(got, want, equal_nan=True)


def test_logsumexp_extremes():
    x = np.array([[-np.inf, -np.inf], [1000.0, 1000.0], [0.0, -np.inf]],
                 np.float32)
    got = np.asarray(paddle.logsumexp(Tensor(x), axis=1)._data)
    want = torch.logsumexp(torch.from_numpy(x.copy()), dim=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)


class TestIndexingFuzz:
    """Gather/scatter family vs torch analogs: negative indices, duplicate
    scatter targets (paddle overwrite=False ACCUMULATES), axis variants."""

    def test_gather_and_index_select(self):
        x = _rand((5, 4))
        idx = np.array([3, 0, 3, 1], np.int64)
        np.testing.assert_allclose(
            np.asarray(paddle.gather(Tensor(x), Tensor(idx))._data),
            torch.index_select(torch.from_numpy(x.copy()), 0,
                               torch.from_numpy(idx)).numpy())
        np.testing.assert_allclose(
            np.asarray(paddle.index_select(Tensor(x), Tensor(idx),
                                           axis=1)._data),
            torch.index_select(torch.from_numpy(x.copy()), 1,
                               torch.from_numpy(idx)).numpy())

    def test_scatter_overwrite_and_accumulate(self):
        x = np.zeros((5, 3), np.float32)
        idx = np.array([1, 3, 1], np.int64)  # duplicate target row 1
        upd = np.arange(9, dtype=np.float32).reshape(3, 3) + 1
        # overwrite=False: duplicates ACCUMULATE onto x (paddle contract)
        got = np.asarray(paddle.scatter(Tensor(x), Tensor(idx), Tensor(upd),
                                        overwrite=False)._data)
        want = x.copy()
        np.add.at(want, idx, upd)
        np.testing.assert_allclose(got, want)
        # overwrite=True with unique indices == torch index_copy
        idx_u = np.array([4, 0, 2], np.int64)
        got = np.asarray(paddle.scatter(Tensor(x), Tensor(idx_u), Tensor(upd),
                                        overwrite=True)._data)
        want = torch.zeros(5, 3).index_copy_(
            0, torch.from_numpy(idx_u), torch.from_numpy(upd)).numpy()
        np.testing.assert_allclose(got, want)

    def test_take_along_and_put_along_axis(self):
        x = _rand((4, 6))
        idx = RNG.integers(0, 6, (4, 3)).astype(np.int64)
        np.testing.assert_allclose(
            np.asarray(paddle.take_along_axis(Tensor(x), Tensor(idx),
                                              axis=1)._data),
            torch.gather(torch.from_numpy(x.copy()), 1,
                         torch.from_numpy(idx)).numpy())
        v = _rand((4, 3))
        got = np.asarray(paddle.put_along_axis(
            Tensor(x), Tensor(idx), Tensor(v), axis=1, reduce="add")._data)
        want = torch.from_numpy(x.copy()).scatter_add(
            1, torch.from_numpy(idx), torch.from_numpy(v)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_index_add_and_index_put(self):
        x = _rand((5, 3))
        idx = np.array([0, 2, 0], np.int64)
        v = _rand((3, 3))
        got = np.asarray(paddle.index_add(Tensor(x), Tensor(idx), 0,
                                          Tensor(v))._data)
        want = torch.from_numpy(x.copy()).index_add(
            0, torch.from_numpy(idx), torch.from_numpy(v)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_masked_select_and_where(self):
        x = _rand((4, 4), with_specials=True)
        m = x > 0
        np.testing.assert_allclose(
            np.asarray(paddle.masked_select(Tensor(x), Tensor(m))._data),
            torch.masked_select(torch.from_numpy(x.copy()),
                                torch.from_numpy(m)).numpy(), equal_nan=True)
        np.testing.assert_allclose(
            np.asarray(paddle.where(Tensor(m), Tensor(x),
                                    Tensor(np.zeros_like(x)))._data),
            torch.where(torch.from_numpy(m), torch.from_numpy(x.copy()),
                        torch.zeros(4, 4)).numpy(), equal_nan=True)

    def test_negative_gather_indices(self):
        """paddle.gather follows numpy-style negative indexing on this
        stack (jnp contract); pin it so it can't silently change."""
        x = _rand((5, 2))
        got = np.asarray(paddle.gather(Tensor(x),
                                       Tensor(np.array([-1], np.int64)))._data)
        np.testing.assert_allclose(got, x[[-1]])

    def test_put_along_axis_mul_and_include_self(self):
        x = np.full((2, 4), 2.0, np.float32)
        idx = np.array([[1, 1], [0, 3]], np.int64)
        v = np.full((2, 2), 3.0, np.float32)
        # mul with duplicate targets multiplies BOTH updates in
        got = np.asarray(paddle.put_along_axis(
            Tensor(x), Tensor(idx), Tensor(v), axis=1, reduce="mul")._data)
        np.testing.assert_allclose(got, [[2, 18, 2, 2], [6, 2, 2, 6]])
        # include_self=False: only the updates at touched positions
        got = np.asarray(paddle.put_along_axis(
            Tensor(x), Tensor(idx), Tensor(v), axis=1, reduce="add",
            include_self=False)._data)
        np.testing.assert_allclose(got, [[2, 6, 2, 2], [3, 2, 2, 3]])


class TestLossFuzz:
    """Loss functionals vs torch: ignore_index bookkeeping, extreme-logit
    stability, reduction semantics, pos_weight broadcasting."""

    def test_cross_entropy_ignore_index(self):
        logits = _rand((6, 5))
        labels = np.array([0, 4, -100, 2, -100, 1], np.int64)
        got = float(paddle.nn.functional.cross_entropy(
            Tensor(logits), Tensor(labels), ignore_index=-100))
        want = float(torch.nn.functional.cross_entropy(
            torch.from_numpy(logits.copy()), torch.from_numpy(labels),
            ignore_index=-100))
        assert got == pytest.approx(want, rel=1e-5)
        # all-ignored: the REFERENCE guards the zero count to 0.0
        # (ref:python/paddle/nn/functional/loss.py:2860
        # `out_sum / (count + (count == 0.0))`) where torch yields NaN —
        # pin the reference convention
        labels_all = np.full((6,), -100, np.int64)
        got = float(paddle.nn.functional.cross_entropy(
            Tensor(logits), Tensor(labels_all), ignore_index=-100))
        assert got == 0.0

    def test_cross_entropy_weight_and_none_reduction(self):
        logits = _rand((4, 3))
        labels = np.array([2, 0, 1, 2], np.int64)
        w = np.array([0.2, 1.0, 3.0], np.float32)
        got = np.asarray(paddle.nn.functional.cross_entropy(
            Tensor(logits), Tensor(labels), weight=Tensor(w),
            reduction="none")._data)
        want = torch.nn.functional.cross_entropy(
            torch.from_numpy(logits.copy()), torch.from_numpy(labels),
            weight=torch.from_numpy(w), reduction="none").numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)
        # weighted mean divides by the sum of selected weights
        got = float(paddle.nn.functional.cross_entropy(
            Tensor(logits), Tensor(labels), weight=Tensor(w)))
        want = float(torch.nn.functional.cross_entropy(
            torch.from_numpy(logits.copy()), torch.from_numpy(labels),
            weight=torch.from_numpy(w)))
        assert got == pytest.approx(want, rel=1e-5)

    def test_bce_with_logits_extremes(self):
        logits = np.array([[-100.0, 100.0, 0.0, 30.0]], np.float32)
        target = np.array([[0.0, 1.0, 0.5, 0.0]], np.float32)
        pw = np.array([2.0, 0.5, 1.0, 3.0], np.float32)
        got = np.asarray(paddle.nn.functional.binary_cross_entropy_with_logits(
            Tensor(logits), Tensor(target), pos_weight=Tensor(pw),
            reduction="none")._data)
        want = torch.nn.functional.binary_cross_entropy_with_logits(
            torch.from_numpy(logits.copy()), torch.from_numpy(target),
            pos_weight=torch.from_numpy(pw), reduction="none").numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        assert np.isfinite(got).all()  # the log-sum-exp form must not overflow

    def test_smooth_l1_and_huber_deltas(self):
        x = _rand((16,)) * 3
        y = _rand((16,)) * 3
        # paddle smooth_l1_loss(delta): torch huber_loss/delta relation
        for delta in (0.5, 1.0, 2.0):
            got = float(paddle.nn.functional.smooth_l1_loss(
                Tensor(x), Tensor(y), delta=delta))
            want = float(torch.nn.functional.smooth_l1_loss(
                torch.from_numpy(x.copy()), torch.from_numpy(y.copy()),
                beta=delta))
            # paddle's smooth_l1 is huber (delta-scaled), torch's is beta-
            # normalized: huber = beta * smooth_l1_torch
            assert got == pytest.approx(want * delta, rel=1e-4), delta

    def test_kl_div_reductions(self):
        p_log = np.log(np.array([[0.2, 0.3, 0.5], [0.6, 0.3, 0.1]],
                                np.float32))
        q = np.array([[0.1, 0.4, 0.5], [0.3, 0.3, 0.4]], np.float32)
        for red in ("none", "sum", "mean", "batchmean"):
            got = paddle.nn.functional.kl_div(Tensor(p_log), Tensor(q),
                                              reduction=red)
            want = torch.nn.functional.kl_div(
                torch.from_numpy(p_log.copy()), torch.from_numpy(q),
                reduction=red)
            np.testing.assert_allclose(
                np.asarray(got._data), want.numpy(), rtol=1e-5,
                err_msg=f"reduction={red}")

    def test_nll_and_log_softmax_chain(self):
        logits = _rand((5, 7))
        labels = RNG.integers(0, 7, (5,)).astype(np.int64)
        lp = paddle.nn.functional.log_softmax(Tensor(logits), axis=-1)
        got = float(paddle.nn.functional.nll_loss(lp, Tensor(labels)))
        want = float(torch.nn.functional.nll_loss(
            torch.log_softmax(torch.from_numpy(logits.copy()), -1),
            torch.from_numpy(labels)))
        assert got == pytest.approx(want, rel=1e-5)

    def test_mse_l1_reduction_matrix(self):
        a, b = _rand((3, 4)), _rand((3, 4))
        for red in ("none", "mean", "sum"):
            got = paddle.nn.functional.mse_loss(Tensor(a), Tensor(b),
                                                reduction=red)
            want = torch.nn.functional.mse_loss(
                torch.from_numpy(a.copy()), torch.from_numpy(b.copy()),
                reduction=red)
            np.testing.assert_allclose(np.asarray(got._data), want.numpy(),
                                       rtol=1e-5)
            got = paddle.nn.functional.l1_loss(Tensor(a), Tensor(b),
                                               reduction=red)
            want = torch.nn.functional.l1_loss(
                torch.from_numpy(a.copy()), torch.from_numpy(b.copy()),
                reduction=red)
            np.testing.assert_allclose(np.asarray(got._data), want.numpy(),
                                       rtol=1e-5)


class TestLinalgDegenerate:
    """Degenerate/rank-deficient inputs across paddle.linalg vs numpy/torch
    (reconstruction goldens don't exercise these)."""

    def test_pinv_rank_deficient(self):
        # the reference's rcond default (1e-15) is float64-tuned: f32
        # round-off singular values get inverted (documented footgun, same
        # as reference/old torch) — a dtype-appropriate rcond recovers the
        # Moore-Penrose inverse of the rank-1 matrix
        a = np.outer(np.arange(1, 5), np.arange(1, 4)).astype(np.float32)
        got = np.asarray(paddle.linalg.pinv(Tensor(a), rcond=1e-6)._data)
        want = np.linalg.pinv(a, rcond=1e-6)
        np.testing.assert_allclose(got, want, atol=1e-5)
        # Moore-Penrose identities hold for the deficient case
        np.testing.assert_allclose(a @ got @ a, a, atol=1e-4)

    def test_matrix_rank_with_tolerance(self):
        a = np.diag([1.0, 0.5, 1e-9, 0.0]).astype(np.float32)
        assert int(paddle.linalg.matrix_rank(Tensor(a))) == 2
        assert int(paddle.linalg.matrix_rank(Tensor(a), tol=1e-10)) == 3

    def test_lstsq_overdetermined_and_deficient(self):
        a = RNG.standard_normal((6, 3)).astype(np.float32)
        b = RNG.standard_normal((6, 2)).astype(np.float32)
        sol = paddle.linalg.lstsq(Tensor(a), Tensor(b))[0]
        want = np.linalg.lstsq(a, b, rcond=None)[0]
        np.testing.assert_allclose(np.asarray(sol._data), want, atol=1e-4)

    def test_eigh_ascending_and_reconstruction(self):
        m = RNG.standard_normal((5, 5)).astype(np.float32)
        s = (m + m.T) / 2
        w, v = paddle.linalg.eigh(Tensor(s))
        w_np = np.asarray(w._data)
        assert (np.diff(w_np) >= -1e-5).all()  # ascending (reference order)
        rec = np.asarray(v._data) @ np.diag(w_np) @ np.asarray(v._data).T
        np.testing.assert_allclose(rec, s, atol=1e-4)

    def test_qr_modes(self):
        a = RNG.standard_normal((6, 4)).astype(np.float32)
        q, r = paddle.linalg.qr(Tensor(a), mode="reduced")
        assert list(q.shape) == [6, 4] and list(r.shape) == [4, 4]
        np.testing.assert_allclose(np.asarray(q._data) @ np.asarray(r._data),
                                   a, atol=1e-4)
        q2, r2 = paddle.linalg.qr(Tensor(a), mode="complete")
        assert list(q2.shape) == [6, 6] and list(r2.shape) == [6, 4]
        np.testing.assert_allclose(np.tril(np.asarray(r._data), -1), 0,
                                   atol=1e-6)

    def test_cond_and_norm_orders(self):
        a = np.diag([4.0, 2.0, 1.0]).astype(np.float32)
        assert float(paddle.linalg.cond(Tensor(a))) == pytest.approx(4.0,
                                                                     rel=1e-4)
        v = np.array([3.0, -4.0], np.float32)
        assert float(paddle.linalg.norm(Tensor(v))) == pytest.approx(5.0)
        assert float(paddle.linalg.norm(Tensor(v), p=1)) == pytest.approx(7.0)
        assert float(paddle.linalg.norm(Tensor(v),
                                        p=np.inf)) == pytest.approx(4.0)

    def test_solve_singular_raises_or_inf(self):
        """Singular solve: jnp yields inf/nan rather than raising — pin the
        behavior so it can't silently change."""
        a = np.zeros((2, 2), np.float32)
        b = np.ones((2,), np.float32)
        out = np.asarray(paddle.linalg.solve(Tensor(a), Tensor(b))._data)
        assert not np.isfinite(out).all()


def test_einsum_equation_zoo():
    """Representative einsum equations vs torch: contraction, batch,
    trace, outer, ellipsis, repeated-index diagonal."""
    cases = [
        ("ij,jk->ik", [(3, 4), (4, 5)]),
        ("bij,bjk->bik", [(2, 3, 4), (2, 4, 5)]),
        ("ii->", [(5, 5)]),               # trace
        ("ii->i", [(5, 5)]),              # diagonal
        ("i,j->ij", [(3,), (4,)]),        # outer
        ("...ij->...ji", [(2, 3, 4)]),    # ellipsis transpose
        ("bhqd,bhkd->bhqk", [(2, 2, 3, 8), (2, 2, 5, 8)]),  # attention
        ("ij->", [(3, 4)]),               # full reduce
    ]
    for eq, shapes in cases:
        ops = [RNG.standard_normal(s).astype(np.float32) for s in shapes]
        got = np.asarray(paddle.einsum(eq, *[Tensor(o) for o in ops])._data)
        want = torch.einsum(eq, *[torch.from_numpy(o.copy()) for o in ops])
        np.testing.assert_allclose(got, want.numpy(), rtol=1e-5, atol=1e-5,
                                   err_msg=eq)


def test_getitem_numpy_equivalence():
    """Indexing zoo vs numpy: ellipsis, None-newaxis, negative steps, bool
    masks, integer arrays, mixed forms."""
    x = _rand((4, 5, 6))
    t = Tensor(x)
    cases = [
        np.s_[...],
        np.s_[1],
        np.s_[-1],
        np.s_[::2],
        np.s_[::-1],
        np.s_[1:4:2, ::-1],
        np.s_[..., 0],
        np.s_[None, 1, ...],
        np.s_[:, None, 2:],
        np.s_[[2, 0, 3]],
        np.s_[[1, 2], [0, 4]],
        np.s_[x[:, 0, 0] > 0],
    ]
    for c in cases:
        got = np.asarray(t[c]._data)
        np.testing.assert_allclose(got, x[c], err_msg=str(c))


def test_getitem_bool_list_mask():
    """Python bool lists are masks (numpy/reference contract), alone and
    inside tuples."""
    x = _rand((4, 6))
    t = Tensor(x)
    m = [True, False, True, False]
    np.testing.assert_allclose(np.asarray(t[m]._data), x[m])
    np.testing.assert_allclose(np.asarray(t[m, 2]._data), x[m, 2])

"""Edge-case torch-golden battery for geometry-sensitive ops: conv
(groups/dilation/same-padding), interpolate modes, pad modes, adaptive
pools, pixel shuffle, grid_sample (ref test/legacy_test op tests)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F


def _t(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


RNG = np.random.default_rng(0)


@pytest.mark.parametrize("groups,dilation,padding,stride", [
    (1, 1, 0, 1),
    (1, 1, 2, 2),
    (2, 1, 1, 1),
    (4, 1, 0, 1),
    (1, 2, 2, 1),
    (2, 2, 3, 2),
])
def test_conv2d_variants(groups, dilation, padding, stride):
    x = RNG.standard_normal((2, 4, 12, 12)).astype(np.float32)
    w = RNG.standard_normal((8, 4 // groups, 3, 3)).astype(np.float32)
    got = F.conv2d(_t(x), _t(w), stride=stride, padding=padding,
                   dilation=dilation, groups=groups).numpy()
    want = TF.conv2d(torch.tensor(x), torch.tensor(w), stride=stride,
                     padding=padding, dilation=dilation,
                     groups=groups).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_conv2d_same_padding():
    x = RNG.standard_normal((1, 3, 11, 11)).astype(np.float32)
    w = RNG.standard_normal((5, 3, 3, 3)).astype(np.float32)
    got = F.conv2d(_t(x), _t(w), padding="SAME").numpy()
    want = TF.conv2d(torch.tensor(x), torch.tensor(w),
                     padding="same").numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("mode,align", [
    ("nearest", False),
    ("bilinear", False),
    ("bilinear", True),
    ("bicubic", False),
    ("bicubic", True),
])
def test_interpolate_modes(mode, align):
    x = RNG.standard_normal((1, 2, 6, 6)).astype(np.float32)
    kwargs = {} if mode == "nearest" else {"align_corners": align}
    got = F.interpolate(_t(x), size=[11, 9], mode=mode, **kwargs).numpy()
    want = TF.interpolate(torch.tensor(x), size=[11, 9], mode=mode,
                          **({} if mode == "nearest"
                             else {"align_corners": align})).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("mode", ["constant", "reflect", "replicate",
                                  "circular"])
def test_pad_modes(mode):
    x = RNG.standard_normal((1, 2, 5, 5)).astype(np.float32)
    got = F.pad(_t(x), [1, 2, 2, 1], mode=mode).numpy()
    want = TF.pad(torch.tensor(x), (1, 2, 2, 1), mode=mode).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("out_size", [1, 3, 5])
def test_adaptive_pools(out_size):
    x = RNG.standard_normal((2, 3, 7, 9)).astype(np.float32)
    got = F.adaptive_avg_pool2d(_t(x), out_size).numpy()
    want = TF.adaptive_avg_pool2d(torch.tensor(x), out_size).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    got = F.adaptive_max_pool2d(_t(x), out_size).numpy()
    want = TF.adaptive_max_pool2d(torch.tensor(x), out_size).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("ceil_mode", [False, True])
def test_avg_pool_ceil_and_pad(ceil_mode):
    x = RNG.standard_normal((1, 2, 7, 7)).astype(np.float32)
    got = F.avg_pool2d(_t(x), 3, 2, padding=1, ceil_mode=ceil_mode,
                       exclusive=False).numpy()
    want = TF.avg_pool2d(torch.tensor(x), 3, 2, padding=1,
                         ceil_mode=ceil_mode,
                         count_include_pad=True).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_pixel_shuffle_and_unshuffle():
    x = RNG.standard_normal((1, 8, 4, 4)).astype(np.float32)
    got = F.pixel_shuffle(_t(x), 2).numpy()
    want = TF.pixel_shuffle(torch.tensor(x), 2).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)
    back = F.pixel_unshuffle(_t(got), 2).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-6)


@pytest.mark.parametrize("mode,align", [("bilinear", True),
                                        ("bilinear", False),
                                        ("nearest", True)])
def test_grid_sample(mode, align):
    x = RNG.standard_normal((1, 2, 5, 5)).astype(np.float32)
    grid = (RNG.random((1, 4, 4, 2)) * 2 - 1).astype(np.float32)
    got = F.grid_sample(_t(x), _t(grid), mode=mode,
                        align_corners=align).numpy()
    want = TF.grid_sample(torch.tensor(x), torch.tensor(grid), mode=mode,
                          align_corners=align).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_local_response_norm():
    x = RNG.standard_normal((2, 6, 5, 5)).astype(np.float32)
    got = F.local_response_norm(_t(x), size=3, alpha=1e-4, beta=0.75,
                                k=1.0).numpy()
    want = TF.local_response_norm(torch.tensor(x), size=3, alpha=1e-4,
                                  beta=0.75, k=1.0).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_unfold_matches_torch():
    x = RNG.standard_normal((1, 3, 8, 8)).astype(np.float32)
    got = F.unfold(_t(x), 3, strides=2, paddings=1).numpy()
    want = TF.unfold(torch.tensor(x), 3, stride=2, padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

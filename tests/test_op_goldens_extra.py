"""Edge-case torch-golden battery for geometry-sensitive ops: conv
(groups/dilation/same-padding), interpolate modes, pad modes, adaptive
pools, pixel shuffle, grid_sample (ref test/legacy_test op tests)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F


def _t(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


RNG = np.random.default_rng(0)


@pytest.mark.parametrize("groups,dilation,padding,stride", [
    (1, 1, 0, 1),
    (1, 1, 2, 2),
    (2, 1, 1, 1),
    (4, 1, 0, 1),
    (1, 2, 2, 1),
    (2, 2, 3, 2),
])
def test_conv2d_variants(groups, dilation, padding, stride):
    x = RNG.standard_normal((2, 4, 12, 12)).astype(np.float32)
    w = RNG.standard_normal((8, 4 // groups, 3, 3)).astype(np.float32)
    got = F.conv2d(_t(x), _t(w), stride=stride, padding=padding,
                   dilation=dilation, groups=groups).numpy()
    want = TF.conv2d(torch.tensor(x), torch.tensor(w), stride=stride,
                     padding=padding, dilation=dilation,
                     groups=groups).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_conv2d_same_padding():
    x = RNG.standard_normal((1, 3, 11, 11)).astype(np.float32)
    w = RNG.standard_normal((5, 3, 3, 3)).astype(np.float32)
    got = F.conv2d(_t(x), _t(w), padding="SAME").numpy()
    want = TF.conv2d(torch.tensor(x), torch.tensor(w),
                     padding="same").numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("mode,align", [
    ("nearest", False),
    ("bilinear", False),
    ("bilinear", True),
    ("bicubic", False),
    ("bicubic", True),
])
def test_interpolate_modes(mode, align):
    x = RNG.standard_normal((1, 2, 6, 6)).astype(np.float32)
    kwargs = {} if mode == "nearest" else {"align_corners": align}
    got = F.interpolate(_t(x), size=[11, 9], mode=mode, **kwargs).numpy()
    want = TF.interpolate(torch.tensor(x), size=[11, 9], mode=mode,
                          **({} if mode == "nearest"
                             else {"align_corners": align})).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("mode", ["constant", "reflect", "replicate",
                                  "circular"])
def test_pad_modes(mode):
    x = RNG.standard_normal((1, 2, 5, 5)).astype(np.float32)
    got = F.pad(_t(x), [1, 2, 2, 1], mode=mode).numpy()
    want = TF.pad(torch.tensor(x), (1, 2, 2, 1), mode=mode).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("out_size", [1, 3, 5])
def test_adaptive_pools(out_size):
    x = RNG.standard_normal((2, 3, 7, 9)).astype(np.float32)
    got = F.adaptive_avg_pool2d(_t(x), out_size).numpy()
    want = TF.adaptive_avg_pool2d(torch.tensor(x), out_size).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    got = F.adaptive_max_pool2d(_t(x), out_size).numpy()
    want = TF.adaptive_max_pool2d(torch.tensor(x), out_size).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("ceil_mode", [False, True])
def test_avg_pool_ceil_and_pad(ceil_mode):
    x = RNG.standard_normal((1, 2, 7, 7)).astype(np.float32)
    got = F.avg_pool2d(_t(x), 3, 2, padding=1, ceil_mode=ceil_mode,
                       exclusive=False).numpy()
    want = TF.avg_pool2d(torch.tensor(x), 3, 2, padding=1,
                         ceil_mode=ceil_mode,
                         count_include_pad=True).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_pixel_shuffle_and_unshuffle():
    x = RNG.standard_normal((1, 8, 4, 4)).astype(np.float32)
    got = F.pixel_shuffle(_t(x), 2).numpy()
    want = TF.pixel_shuffle(torch.tensor(x), 2).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)
    back = F.pixel_unshuffle(_t(got), 2).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-6)


@pytest.mark.parametrize("mode,align", [("bilinear", True),
                                        ("bilinear", False),
                                        ("nearest", True)])
def test_grid_sample(mode, align):
    x = RNG.standard_normal((1, 2, 5, 5)).astype(np.float32)
    grid = (RNG.random((1, 4, 4, 2)) * 2 - 1).astype(np.float32)
    got = F.grid_sample(_t(x), _t(grid), mode=mode,
                        align_corners=align).numpy()
    want = TF.grid_sample(torch.tensor(x), torch.tensor(grid), mode=mode,
                          align_corners=align).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_local_response_norm():
    x = RNG.standard_normal((2, 6, 5, 5)).astype(np.float32)
    got = F.local_response_norm(_t(x), size=3, alpha=1e-4, beta=0.75,
                                k=1.0).numpy()
    want = TF.local_response_norm(torch.tensor(x), size=3, alpha=1e-4,
                                  beta=0.75, k=1.0).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_unfold_matches_torch():
    x = RNG.standard_normal((1, 3, 8, 8)).astype(np.float32)
    got = F.unfold(_t(x), 3, strides=2, paddings=1).numpy()
    want = TF.unfold(torch.tensor(x), 3, stride=2, padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("groups", [1, 2, 3])
def test_group_norm_matches_torch(groups):
    x = RNG.standard_normal((2, 6, 5, 5)).astype(np.float32)
    w = RNG.standard_normal(6).astype(np.float32)
    b = RNG.standard_normal(6).astype(np.float32)
    got = F.group_norm(_t(x), groups, weight=_t(w), bias=_t(b),
                       epsilon=1e-5).numpy()
    want = TF.group_norm(torch.tensor(x), groups, torch.tensor(w),
                         torch.tensor(b), eps=1e-5).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_instance_norm_matches_torch():
    x = RNG.standard_normal((2, 3, 6, 6)).astype(np.float32)
    got = F.instance_norm(_t(x), eps=1e-5).numpy()
    want = TF.instance_norm(torch.tensor(x), eps=1e-5).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_conv1d_conv3d():
    x1 = RNG.standard_normal((2, 3, 12)).astype(np.float32)
    w1 = RNG.standard_normal((4, 3, 3)).astype(np.float32)
    got = F.conv1d(_t(x1), _t(w1), stride=2, padding=1).numpy()
    want = TF.conv1d(torch.tensor(x1), torch.tensor(w1), stride=2,
                     padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    x3 = RNG.standard_normal((1, 2, 6, 6, 6)).astype(np.float32)
    w3 = RNG.standard_normal((3, 2, 3, 3, 3)).astype(np.float32)
    got = F.conv3d(_t(x3), _t(w3), padding=1).numpy()
    want = TF.conv3d(torch.tensor(x3), torch.tensor(w3), padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_kl_div_and_smooth_l1_conventions():
    p = RNG.random((4, 5)).astype(np.float32) + 0.1
    logq = np.log(RNG.random((4, 5)).astype(np.float32) + 0.1)
    # paddle kl_div(input=log-prob, label=prob), batchmean default? use 'mean'
    got = F.kl_div(_t(logq), _t(p), reduction="mean").numpy()
    want = TF.kl_div(torch.tensor(logq), torch.tensor(p),
                     reduction="mean").numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    x = RNG.standard_normal((6,)).astype(np.float32) * 3
    y = RNG.standard_normal((6,)).astype(np.float32)
    # the reference's smooth_l1_loss lowers to huber_loss (NOT torch's
    # smooth_l1 beta parameterization)
    got = F.smooth_l1_loss(_t(x), _t(y), delta=2.0).numpy()
    want = TF.huber_loss(torch.tensor(x), torch.tensor(y),
                         delta=2.0).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("act,targs", [
    ("hardsigmoid", {}),
    ("hardswish", {}),
    ("mish", {}),
    ("softsign", {}),
    ("tanhshrink", {}),
    ("hardshrink", {}),
    ("softshrink", {}),
    ("celu", {}),
    ("selu", {}),
    ("relu6", {}),
    ("silu", {}),
    ("log_sigmoid", {}),
])
def test_activations_match_torch(act, targs):
    x = (RNG.standard_normal((3, 7)).astype(np.float32) * 3)
    ours = getattr(F, act)
    torch_name = {"log_sigmoid": "logsigmoid"}.get(act, act)
    theirs = getattr(TF, torch_name)
    np.testing.assert_allclose(ours(_t(x)).numpy(),
                               theirs(torch.tensor(x)).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_dropout_downscale_in_infer_mode():
    x = _t(np.ones((1000,)))
    # inference: output scales by keep prob (legacy paddle contract)
    out = F.dropout(x, p=0.4, training=False, mode="downscale_in_infer")
    np.testing.assert_allclose(out.numpy(), 0.6, rtol=1e-6)
    # train: kept values stay raw (no 1/(1-p) upscale)
    paddle.seed(0)
    tr = F.dropout(x, p=0.4, training=True, mode="downscale_in_infer").numpy()
    kept = tr[tr != 0]
    np.testing.assert_allclose(kept, 1.0, rtol=1e-6)
    with pytest.raises(ValueError, match="dropout mode"):
        F.dropout(x, p=0.4, mode="bogus")


def test_einsum_cases_match_numpy():
    a = RNG.standard_normal((3, 4)).astype(np.float32)
    b = RNG.standard_normal((4, 5)).astype(np.float32)
    c = RNG.standard_normal((2, 3, 4)).astype(np.float32)
    cases = [
        ("ij,jk->ik", (a, b)),
        ("ij->ji", (a,)),
        ("ij->", (a,)),
        ("bij,jk->bik", (c, b)),
        ("ij,ij->i", (a, a)),
        ("bij->bj", (c,)),
    ]
    for eq, ops_ in cases:
        got = paddle.einsum(eq, *[_t(o) for o in ops_]).numpy()
        want = np.einsum(eq, *ops_)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5,
                                   err_msg=eq)


def test_broadcast_semantics():
    a = RNG.standard_normal((3, 1, 5)).astype(np.float32)
    b = RNG.standard_normal((4, 1)).astype(np.float32)
    np.testing.assert_allclose((_t(a) + _t(b)).numpy(), a + b, rtol=1e-6)
    out = paddle.broadcast_to(_t(b), [3, 4, 5]).numpy()
    np.testing.assert_array_equal(out, np.broadcast_to(b, (3, 4, 5)))
    shapes = paddle.broadcast_shape([3, 1, 5], [4, 1])
    assert list(shapes) == [3, 4, 5]
    x1, x2 = paddle.broadcast_tensors([_t(a), _t(b)])
    assert x1.shape == [3, 4, 5] and x2.shape == [3, 4, 5]


def test_stft_istft_match_torch_roundtrip():
    x = RNG.standard_normal(256).astype(np.float32)
    win = np.hanning(65)[:-1].astype(np.float32)
    got = paddle.signal.stft(_t(x[None]), n_fft=64, hop_length=16,
                             window=_t(win), center=True).numpy()
    want = torch.stft(torch.tensor(x[None]), n_fft=64, hop_length=16,
                      window=torch.tensor(win), center=True,
                      return_complex=True).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    rec = paddle.signal.istft(paddle.to_tensor(got), n_fft=64, hop_length=16,
                              window=_t(win), center=True).numpy()
    np.testing.assert_allclose(rec[0, :200], x[:200], rtol=1e-4, atol=1e-5)

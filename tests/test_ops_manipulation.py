import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_output

RNG = np.random.RandomState(1)


def test_reshape_flatten_transpose():
    x = RNG.rand(2, 3, 4).astype(np.float32)
    check_output(paddle.reshape, lambda a, shape: a.reshape(shape), [x], kwargs=dict(shape=[4, 6]))
    check_output(paddle.flatten, lambda a, start_axis=0, stop_axis=-1: a.reshape(2, 12), [x], kwargs=dict(start_axis=1))
    check_output(paddle.transpose, lambda a, perm: a.transpose(perm), [x], kwargs=dict(perm=[2, 0, 1]))


def test_concat_stack_split():
    xs = [RNG.rand(2, 3).astype(np.float32) for _ in range(3)]
    out = paddle.concat([paddle.to_tensor(a) for a in xs], axis=1)
    np.testing.assert_allclose(out.numpy(), np.concatenate(xs, axis=1))
    out = paddle.stack([paddle.to_tensor(a) for a in xs], axis=0)
    np.testing.assert_allclose(out.numpy(), np.stack(xs, axis=0))
    parts = paddle.split(paddle.to_tensor(xs[0]), 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1]
    parts = paddle.split(paddle.to_tensor(xs[0]), [1, -1], axis=1)
    assert parts[1].shape == [2, 2]


def test_squeeze_unsqueeze_expand_tile():
    x = RNG.rand(1, 3, 1).astype(np.float32)
    assert paddle.squeeze(paddle.to_tensor(x)).shape == [3]
    assert paddle.squeeze(paddle.to_tensor(x), axis=0).shape == [3, 1]
    assert paddle.unsqueeze(paddle.to_tensor(x), [0, 2]).shape == [1, 1, 1, 3, 1]
    assert paddle.expand(paddle.to_tensor(x), [2, 3, 4]).shape == [2, 3, 4]
    np.testing.assert_allclose(paddle.tile(paddle.to_tensor(x), [2, 1, 2]).numpy(), np.tile(x, [2, 1, 2]))


def test_gather_scatter():
    x = RNG.rand(5, 3).astype(np.float32)
    idx = np.array([0, 2, 4])
    np.testing.assert_allclose(paddle.gather(paddle.to_tensor(x), paddle.to_tensor(idx)).numpy(), x[idx])
    upd = RNG.rand(2, 3).astype(np.float32)
    out = paddle.scatter(paddle.to_tensor(x), paddle.to_tensor(np.array([1, 3])), paddle.to_tensor(upd))
    ref = x.copy()
    ref[[1, 3]] = upd
    np.testing.assert_allclose(out.numpy(), ref)


def test_gather_nd():
    x = RNG.rand(3, 4, 5).astype(np.float32)
    idx = np.array([[0, 1], [2, 3]])
    out = paddle.gather_nd(paddle.to_tensor(x), paddle.to_tensor(idx))
    np.testing.assert_allclose(out.numpy(), x[[0, 2], [1, 3]])


def test_where_masked():
    x = RNG.rand(3, 4).astype(np.float32)
    y = RNG.rand(3, 4).astype(np.float32)
    c = x > 0.5
    out = paddle.where(paddle.to_tensor(c), paddle.to_tensor(x), paddle.to_tensor(y))
    np.testing.assert_allclose(out.numpy(), np.where(c, x, y))
    ms = paddle.masked_select(paddle.to_tensor(x), paddle.to_tensor(c))
    np.testing.assert_allclose(ms.numpy(), x[c])


def test_argmax_sort_topk():
    x = RNG.rand(3, 5).astype(np.float32)
    np.testing.assert_array_equal(paddle.argmax(paddle.to_tensor(x), axis=1).numpy(), np.argmax(x, axis=1))
    np.testing.assert_allclose(paddle.sort(paddle.to_tensor(x), axis=1).numpy(), np.sort(x, axis=1))
    vals, idx = paddle.topk(paddle.to_tensor(x), 2, axis=1)
    ref = np.sort(x, axis=1)[:, ::-1][:, :2]
    np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)


def test_nonzero_unique():
    x = np.array([[1, 0], [0, 3]], np.int64)
    nz = paddle.nonzero(paddle.to_tensor(x))
    np.testing.assert_array_equal(nz.numpy(), np.stack(np.nonzero(x), axis=1))
    u = paddle.unique(paddle.to_tensor(np.array([3, 1, 1, 2])))
    np.testing.assert_array_equal(u.numpy(), [1, 2, 3])


def test_one_hot_pad_roll_flip():
    x = np.array([0, 2, 1])
    oh = paddle.manipulation.one_hot(paddle.to_tensor(x), 3)
    np.testing.assert_array_equal(oh.numpy(), np.eye(3)[x])
    y = RNG.rand(2, 2).astype(np.float32)
    np.testing.assert_allclose(paddle.roll(paddle.to_tensor(y), 1, axis=0).numpy(), np.roll(y, 1, axis=0))
    np.testing.assert_allclose(paddle.flip(paddle.to_tensor(y), axis=[0]).numpy(), np.flip(y, axis=0))
    p = paddle.manipulation.pad(paddle.to_tensor(y), [1, 1, 2, 2], mode="constant", value=0.0, data_format=None)
    assert p.shape == [4, 6]  # full-spec per-dim (lo,hi) pad


def test_grad_manipulation():
    x = RNG.rand(2, 3).astype(np.float32)
    check_grad(paddle.reshape, [x], kwargs=dict(shape=[3, 2]))
    check_grad(paddle.transpose, [x], kwargs=dict(perm=[1, 0]))
    idx = np.array([0, 1])
    check_grad(lambda t: paddle.gather(t, paddle.to_tensor(idx)), [x])


def test_take_put_along_axis():
    x = RNG.rand(3, 4).astype(np.float32)
    idx = np.array([[0, 1, 2, 3], [3, 2, 1, 0], [0, 0, 0, 0]])
    out = paddle.take_along_axis(paddle.to_tensor(x), paddle.to_tensor(idx), axis=1)
    np.testing.assert_allclose(out.numpy(), np.take_along_axis(x, idx, axis=1))

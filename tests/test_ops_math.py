import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_output


RNG = np.random.RandomState(0)


@pytest.mark.parametrize(
    "name",
    ["abs", "exp", "log", "sqrt", "square", "sin", "cos", "tanh", "floor", "ceil", "sign", "sigmoid"],
)
def test_unary_golden(name):
    x = RNG.rand(3, 4).astype(np.float32) + 0.5
    np_fn = {
        "sigmoid": lambda a: 1 / (1 + np.exp(-a)),
    }.get(name, getattr(np, name, None))
    # XLA's transcendental approximations differ from libm at ~1e-4
    check_output(getattr(paddle, name), np_fn, [x], rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("name,np_name", [
    ("add", "add"), ("subtract", "subtract"), ("multiply", "multiply"), ("divide", "divide"),
    ("maximum", "maximum"), ("minimum", "minimum"), ("pow", "power"), ("atan2", "arctan2"),
])
def test_binary_golden(name, np_name):
    x = RNG.rand(3, 4).astype(np.float32) + 0.5
    y = RNG.rand(3, 4).astype(np.float32) + 0.5
    check_output(getattr(paddle, name), getattr(np, np_name), [x, y])


def test_broadcasting():
    x = RNG.rand(3, 1, 4).astype(np.float32)
    y = RNG.rand(2, 4).astype(np.float32)
    check_output(paddle.add, np.add, [x, y])


@pytest.mark.parametrize("name", ["sum", "mean", "max", "min", "prod"])
@pytest.mark.parametrize("axis,keepdim", [(None, False), (0, False), (1, True), ((0, 1), False)])
def test_reductions(name, axis, keepdim):
    x = RNG.rand(3, 4, 5).astype(np.float32)
    def np_fn(a, axis=None, keepdim=False):
        return getattr(np, name if name != "prod" else "prod")(a, axis=axis, keepdims=keepdim)
    check_output(getattr(paddle, name), np_fn, [x], kwargs=dict(axis=axis, keepdim=keepdim))


def test_logsumexp():
    from scipy.special import logsumexp as np_lse  # noqa

    x = RNG.rand(3, 4).astype(np.float32)
    out = paddle.logsumexp(paddle.to_tensor(x), axis=1)
    ref = np.log(np.sum(np.exp(x), axis=1))
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4)


def test_cumsum_clip_scale():
    x = RNG.rand(3, 4).astype(np.float32)
    np.testing.assert_allclose(paddle.cumsum(paddle.to_tensor(x), axis=1).numpy(), np.cumsum(x, axis=1), rtol=1e-5)
    np.testing.assert_allclose(paddle.clip(paddle.to_tensor(x), 0.2, 0.8).numpy(), np.clip(x, 0.2, 0.8))
    np.testing.assert_allclose(paddle.scale(paddle.to_tensor(x), scale=2.0, bias=1.0).numpy(), x * 2 + 1, rtol=1e-6)


def test_comparisons_and_logical():
    x = np.array([1.0, 2.0, 3.0], np.float32)
    y = np.array([2.0, 2.0, 2.0], np.float32)
    assert (paddle.equal(paddle.to_tensor(x), paddle.to_tensor(y)).numpy() == (x == y)).all()
    assert (paddle.less_than(paddle.to_tensor(x), paddle.to_tensor(y)).numpy() == (x < y)).all()
    a = np.array([True, False])
    b = np.array([True, True])
    assert (paddle.logical_and(paddle.to_tensor(a), paddle.to_tensor(b)).numpy() == (a & b)).all()


def test_add_n_assign_lerp():
    xs = [RNG.rand(2, 2).astype(np.float32) for _ in range(3)]
    out = paddle.add_n([paddle.to_tensor(x) for x in xs])
    np.testing.assert_allclose(out.numpy(), sum(xs), rtol=1e-6)
    t = paddle.to_tensor(xs[0])
    np.testing.assert_allclose(paddle.assign(t).numpy(), xs[0])
    l = paddle.lerp(paddle.to_tensor(xs[0]), paddle.to_tensor(xs[1]), 0.5)
    np.testing.assert_allclose(l.numpy(), xs[0] + 0.5 * (xs[1] - xs[0]), rtol=1e-6)


def test_grad_unary():
    x = RNG.rand(2, 3).astype(np.float32) + 0.5
    check_grad(paddle.exp, [x])
    check_grad(paddle.log, [x])
    check_grad(paddle.tanh, [x])


def test_grad_binary_broadcast():
    x = RNG.rand(2, 3).astype(np.float32)
    y = RNG.rand(3).astype(np.float32) + 0.5
    check_grad(paddle.multiply, [x, y], wrt=(0, 1))
    check_grad(paddle.divide, [x, y], wrt=(0, 1))


def test_grad_reduction():
    x = RNG.rand(2, 3).astype(np.float32)
    check_grad(paddle.sum, [x], kwargs=dict(axis=1))
    check_grad(paddle.mean, [x])


def test_isnan_isinf():
    x = np.array([1.0, np.nan, np.inf], np.float32)
    assert (paddle.isnan(paddle.to_tensor(x)).numpy() == np.isnan(x)).all()
    assert (paddle.isinf(paddle.to_tensor(x)).numpy() == np.isinf(x)).all()

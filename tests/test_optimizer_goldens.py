"""Optimizer update math vs torch single/multi-step goldens (bias
correction, momentum accumulation, centered RMSProp, decoupled AdamW —
ref:python/paddle/optimizer/*.py formulas)."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle


def _run_ours(opt_cls, steps=3, lr=0.1, grads=None, **kw):
    p = paddle.to_tensor(np.arange(1.0, 5.0, dtype=np.float32))
    p.stop_gradient = False
    opt = opt_cls(learning_rate=lr, parameters=[p], **kw)
    for g in grads:
        loss = (p * paddle.to_tensor(g)).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return p.numpy()


def _run_torch(topt_cls, steps=3, lr=0.1, grads=None, **kw):
    p = torch.arange(1.0, 5.0, requires_grad=True)
    opt = topt_cls([p], lr=lr, **kw)
    for g in grads:
        opt.zero_grad()
        (p * torch.tensor(g)).sum().backward()
        opt.step()
    return p.detach().numpy()


GRADS = [np.random.default_rng(s).standard_normal(4).astype(np.float32)
         for s in range(3)]


def test_sgd_matches_torch():
    ours = _run_ours(paddle.optimizer.SGD, grads=GRADS)
    torchs = _run_torch(torch.optim.SGD, grads=GRADS)
    np.testing.assert_allclose(ours, torchs, rtol=1e-5, atol=1e-6)


def test_momentum_matches_torch():
    ours = _run_ours(paddle.optimizer.Momentum, grads=GRADS, momentum=0.9)
    torchs = _run_torch(torch.optim.SGD, grads=GRADS, momentum=0.9)
    np.testing.assert_allclose(ours, torchs, rtol=1e-5, atol=1e-6)


def test_adam_bias_correction_matches_torch():
    ours = _run_ours(paddle.optimizer.Adam, grads=GRADS, beta1=0.9,
                     beta2=0.999, epsilon=1e-8)
    torchs = _run_torch(torch.optim.Adam, grads=GRADS, betas=(0.9, 0.999),
                        eps=1e-8)
    np.testing.assert_allclose(ours, torchs, rtol=1e-5, atol=1e-6)


def test_adamw_decoupled_matches_torch():
    ours = _run_ours(paddle.optimizer.AdamW, grads=GRADS, weight_decay=0.05)
    torchs = _run_torch(torch.optim.AdamW, grads=GRADS, weight_decay=0.05)
    np.testing.assert_allclose(ours, torchs, rtol=1e-4, atol=1e-5)


def test_adagrad_matches_torch():
    ours = _run_ours(paddle.optimizer.Adagrad, grads=GRADS,
                     initial_accumulator_value=0.1, epsilon=1e-10)
    torchs = _run_torch(torch.optim.Adagrad, grads=GRADS,
                        initial_accumulator_value=0.1, eps=1e-10)
    np.testing.assert_allclose(ours, torchs, rtol=1e-4, atol=1e-5)


def test_adamax_matches_torch():
    ours = _run_ours(paddle.optimizer.Adamax, grads=GRADS, beta1=0.9,
                     beta2=0.999, epsilon=1e-8)
    torchs = _run_torch(torch.optim.Adamax, grads=GRADS, betas=(0.9, 0.999),
                        eps=1e-8)
    np.testing.assert_allclose(ours, torchs, rtol=1e-4, atol=1e-5)

"""Optimizer update math vs torch single/multi-step goldens (bias
correction, momentum accumulation, centered RMSProp, decoupled AdamW —
ref:python/paddle/optimizer/*.py formulas)."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle


def _run_ours(opt_cls, steps=3, lr=0.1, grads=None, **kw):
    p = paddle.to_tensor(np.arange(1.0, 5.0, dtype=np.float32))
    p.stop_gradient = False
    opt = opt_cls(learning_rate=lr, parameters=[p], **kw)
    for g in grads:
        loss = (p * paddle.to_tensor(g)).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return p.numpy()


def _run_torch(topt_cls, steps=3, lr=0.1, grads=None, **kw):
    p = torch.arange(1.0, 5.0, requires_grad=True)
    opt = topt_cls([p], lr=lr, **kw)
    for g in grads:
        opt.zero_grad()
        (p * torch.tensor(g)).sum().backward()
        opt.step()
    return p.detach().numpy()


GRADS = [np.random.default_rng(s).standard_normal(4).astype(np.float32)
         for s in range(3)]


def test_sgd_matches_torch():
    ours = _run_ours(paddle.optimizer.SGD, grads=GRADS)
    torchs = _run_torch(torch.optim.SGD, grads=GRADS)
    np.testing.assert_allclose(ours, torchs, rtol=1e-5, atol=1e-6)


def test_momentum_matches_torch():
    ours = _run_ours(paddle.optimizer.Momentum, grads=GRADS, momentum=0.9)
    torchs = _run_torch(torch.optim.SGD, grads=GRADS, momentum=0.9)
    np.testing.assert_allclose(ours, torchs, rtol=1e-5, atol=1e-6)


def test_adam_bias_correction_matches_torch():
    ours = _run_ours(paddle.optimizer.Adam, grads=GRADS, beta1=0.9,
                     beta2=0.999, epsilon=1e-8)
    torchs = _run_torch(torch.optim.Adam, grads=GRADS, betas=(0.9, 0.999),
                        eps=1e-8)
    np.testing.assert_allclose(ours, torchs, rtol=1e-5, atol=1e-6)


def test_adamw_decoupled_matches_torch():
    ours = _run_ours(paddle.optimizer.AdamW, grads=GRADS, weight_decay=0.05)
    torchs = _run_torch(torch.optim.AdamW, grads=GRADS, weight_decay=0.05)
    np.testing.assert_allclose(ours, torchs, rtol=1e-4, atol=1e-5)


def test_adagrad_matches_torch():
    ours = _run_ours(paddle.optimizer.Adagrad, grads=GRADS,
                     initial_accumulator_value=0.1, epsilon=1e-10)
    torchs = _run_torch(torch.optim.Adagrad, grads=GRADS,
                        initial_accumulator_value=0.1, eps=1e-10)
    np.testing.assert_allclose(ours, torchs, rtol=1e-4, atol=1e-5)


def test_adamax_matches_torch():
    ours = _run_ours(paddle.optimizer.Adamax, grads=GRADS, beta1=0.9,
                     beta2=0.999, epsilon=1e-8)
    torchs = _run_torch(torch.optim.Adamax, grads=GRADS, betas=(0.9, 0.999),
                        eps=1e-8)
    np.testing.assert_allclose(ours, torchs, rtol=1e-4, atol=1e-5)


def _lars_numpy(p0, grads, lr=0.1, mu=0.9, coeff=0.001, wd=0.0005,
                eps=0.0, rescale=1.0):
    """Reference formula, mirrored from
    ref:paddle/fluid/operators/optimizers/lars_momentum_op.h (float64)."""
    p = p0.astype(np.float64).copy()
    v = np.zeros_like(p)
    for g in grads:
        g = g.astype(np.float64) * rescale
        p_norm = np.linalg.norm(p)
        g_norm = np.linalg.norm(g)
        local_lr = lr
        if wd > 0 and p_norm > 0 and g_norm > 0:
            local_lr = lr * coeff * p_norm / (g_norm + wd * p_norm + eps)
        v = mu * v + local_lr * (g + wd * p)
        p = p - v
    return p


def test_lars_matches_reference_formula():
    p0 = np.arange(1.0, 5.0, dtype=np.float32)
    ours = _run_ours(paddle.optimizer.LarsMomentum, grads=GRADS,
                     momentum=0.9, lars_coeff=0.001, lars_weight_decay=0.0005)
    ref = _lars_numpy(p0, GRADS)
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-7)


def test_lars_zero_wd_is_plain_momentum():
    ours = _run_ours(paddle.optimizer.LarsMomentum, grads=GRADS,
                     momentum=0.9, lars_weight_decay=0.0)
    torchs = _run_torch(torch.optim.SGD, grads=GRADS, momentum=0.9)
    np.testing.assert_allclose(ours, torchs, rtol=1e-5, atol=1e-6)


def test_lars_exclude_from_weight_decay():
    """Excluded params (name substring) update with wd=0 => plain momentum."""
    p1 = paddle.to_tensor(np.arange(1.0, 5.0, dtype=np.float32))
    p1.stop_gradient = False
    p1.name = "fc.weight"
    p2 = paddle.to_tensor(np.arange(1.0, 5.0, dtype=np.float32))
    p2.stop_gradient = False
    p2.name = "bn.scale"
    opt = paddle.optimizer.LarsMomentum(
        learning_rate=0.1, momentum=0.9, parameters=[p1, p2],
        exclude_from_weight_decay=["bn"])
    for g in GRADS:
        loss = (p1 * paddle.to_tensor(g)).sum() + (p2 * paddle.to_tensor(g)).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    ref_lars = _lars_numpy(np.arange(1.0, 5.0, dtype=np.float32), GRADS)
    torchs = _run_torch(torch.optim.SGD, grads=GRADS, momentum=0.9)
    np.testing.assert_allclose(p1.numpy(), ref_lars, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(p2.numpy(), torchs, rtol=1e-5, atol=1e-6)
    assert opt._step_count == len(GRADS)  # split update counts steps once


def test_fleet_lars_strategy_upgrades_momentum():
    from paddle_tpu.distributed import fleet

    p = paddle.to_tensor(np.ones(4, np.float32))
    p.stop_gradient = False
    s = fleet.DistributedStrategy()
    s.lars = True
    s.lars_configs["lars_coeff"] = 0.002
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.8,
                                    parameters=[p])
    wrapped = fleet.distributed_optimizer(opt, s)
    assert isinstance(wrapped, paddle.optimizer.LarsMomentum)
    assert wrapped._lars_coeff == 0.002
    assert wrapped._momentum == 0.8


def test_lars_exclusion_applies_in_compiled_trainstep():
    """The wd=0 exclusion must reach jit.TrainStep's direct _update calls
    (trace-time name dispatch), not just eager step()."""
    from paddle_tpu.jit import TrainStep

    class _Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(4, 4)
            self.bn = paddle.nn.BatchNorm1D(4)

        def forward(self, x):
            return self.bn(self.fc(x))

    def run(exclude):
        paddle.seed(7)
        m = _Net()
        opt = paddle.optimizer.LarsMomentum(
            learning_rate=0.1, momentum=0.9, lars_weight_decay=0.05,
            parameters=m.parameters(), exclude_from_weight_decay=exclude)
        step = TrainStep(lambda x: (m(x) ** 2).mean(), opt, layers=m)
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4)
                             .astype(np.float32))
        step(x)  # one step: identical grads, so only the wd term differs
        return {k: v.numpy().copy() for k, v in m.state_dict().items()}

    with_excl = run(["bn"])
    without = run([])
    bn_keys = [k for k in with_excl if k.startswith("bn.") and
               not k.endswith(("_mean", "_variance"))]
    lin_keys = [k for k in with_excl if k.startswith("fc.")]
    assert bn_keys and lin_keys, list(with_excl)
    # linear params identical either way; bn params differ (wd dropped)
    for k in lin_keys:
        np.testing.assert_allclose(with_excl[k], without[k], rtol=1e-6)
    assert any(not np.allclose(with_excl[k], without[k]) for k in bn_keys), \
        bn_keys


def test_param_names_converge_to_qualified_path():
    """A sub-layer traversal stamping short names must not pin them: the
    root-model traversal upgrades to the qualified path, so optimizer slot
    keys and LARS exclusion match regardless of traversal order."""
    class _Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(2, 2)
            self.bn = paddle.nn.BatchNorm1D(2)

        def forward(self, x):
            return self.bn(self.fc(x))

    m = _Net()
    short = [p.name for p in m.fc.parameters()]  # stamps "weight"/"bias"
    assert short == ["weight", "bias"]
    full = [n for n, _ in m.named_parameters()]
    assert [p.name for p in m.parameters()] == full  # upgraded
    assert full[0] == "fc.weight"
    # optimizer slot keys are the qualified names -> no collisions
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=m.parameters())
    assert len(set(opt._slot_keys())) == len(opt._parameter_list)


# ---------------------------------------------------------------- bf16 moments
# moment_dtype (TPU knob): moments stored bf16, update math in f32 — the
# optimizer-state memory lever that fits large-h configs on a 16 GB chip.

def test_adamw_bf16_moments_storage_and_math():
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    grads = [rng.normal(size=4).astype(np.float32) for _ in range(5)]
    ref = _run_ours(paddle.optimizer.AdamW, grads=grads, weight_decay=0.01)
    p = paddle.to_tensor(np.arange(1.0, 5.0, dtype=np.float32))
    p.stop_gradient = False
    opt = paddle.optimizer.AdamW(learning_rate=0.1, parameters=[p],
                                 weight_decay=0.01, moment_dtype="bfloat16")
    for g in grads:
        (p * paddle.to_tensor(g)).sum().backward()
        opt.step()
        opt.clear_grad()
    slots = opt._accumulators[id(p)]
    assert slots["moment1"].dtype == jnp.bfloat16
    assert slots["moment2"].dtype == jnp.bfloat16
    # bf16 has ~3 decimal digits: the trajectory stays close to the f32 one
    np.testing.assert_allclose(p.numpy(), ref, rtol=2e-2, atol=2e-2)


def test_adamw_bf16_moments_with_master_weights():
    """multi_precision bf16 params + bf16 moments: master stays f32."""
    import jax.numpy as jnp
    p = paddle.to_tensor(np.arange(1.0, 5.0, dtype=np.float32))
    p._data = p._data.astype(jnp.bfloat16)
    p.stop_gradient = False
    opt = paddle.optimizer.AdamW(learning_rate=0.1, parameters=[p],
                                 multi_precision=True, moment_dtype="bfloat16")
    (p * paddle.to_tensor(np.ones(4, np.float32))).sum().backward()
    opt.step()
    slots = opt._accumulators[id(p)]
    assert slots["master_weight"].dtype == jnp.float32
    assert slots["moment1"].dtype == jnp.bfloat16
    assert p._data.dtype == jnp.bfloat16


def test_adamw_bf16_moments_compiled_trainstep_converges():
    from paddle_tpu import nn
    from paddle_tpu.jit import TrainStep
    net = nn.Linear(8, 1)
    opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                 parameters=net.parameters(),
                                 moment_dtype="bfloat16")
    lossf = nn.MSELoss()
    rng = np.random.default_rng(1)
    X = rng.normal(size=(64, 8)).astype(np.float32)
    yv = (X @ rng.normal(size=(8, 1))).astype(np.float32)
    x, y = paddle.to_tensor(X), paddle.to_tensor(yv)
    step = TrainStep(lambda a, b: lossf(net(a), b), opt, layers=net)
    losses = [float(step(x, y)) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.25, losses[::10]


def test_lamb_bf16_moments():
    import jax.numpy as jnp
    p = paddle.to_tensor(np.arange(1.0, 5.0, dtype=np.float32))
    p.stop_gradient = False
    opt = paddle.optimizer.Lamb(learning_rate=0.01, parameters=[p],
                                moment_dtype="bfloat16")
    for _ in range(3):
        (p * paddle.to_tensor(np.ones(4, np.float32))).sum().backward()
        opt.step()
        opt.clear_grad()
    slots = opt._accumulators[id(p)]
    assert slots["moment1"].dtype == jnp.bfloat16
    assert slots["moment2"].dtype == jnp.bfloat16
    assert np.all(np.isfinite(p.numpy()))


def test_lamb_exclude_from_weight_decay_fn():
    """Excluded params (reference: exclude_from_weight_decay_fn(param) ->
    True) must train with wd=0 in BOTH the eager and compiled paths: with a
    zero gradient, a decayed param moves (trust-ratio * wd * p) while an
    excluded one must stay exactly put."""
    def build():
        a = paddle.to_tensor(np.full(4, 2.0, np.float32)); a.stop_gradient = False
        b = paddle.to_tensor(np.full(4, 2.0, np.float32)); b.stop_gradient = False
        a.name, b.name = "decayed", "no_decay"
        return a, b

    # eager
    a, b = build()
    opt = paddle.optimizer.Lamb(learning_rate=0.1, lamb_weight_decay=0.1,
                                parameters=[a, b],
                                exclude_from_weight_decay_fn=lambda p: "no_decay" in p.name)
    z = paddle.to_tensor(np.zeros(4, np.float32))
    ((a * z).sum() + (b * z).sum()).backward()
    opt.step()
    assert not np.allclose(a.numpy(), 2.0), a.numpy()   # wd moved it
    np.testing.assert_allclose(b.numpy(), 2.0)          # excluded: untouched

    # compiled (functional path through apply_gradients/_update_for)
    a2, b2 = build()
    opt2 = paddle.optimizer.Lamb(learning_rate=0.1, lamb_weight_decay=0.1,
                                 parameters=[a2, b2],
                                 exclude_from_weight_decay_fn=lambda p: "no_decay" in p.name)
    params = {"decayed": a2, "no_decay": b2}
    state = opt2.init_state(params)
    grads = {"decayed": z, "no_decay": z}
    new_params, _ = opt2.apply_gradients(params, grads, state)
    assert not np.allclose(np.asarray(new_params["decayed"]._data
                                      if hasattr(new_params["decayed"], "_data")
                                      else new_params["decayed"]), 2.0)
    np.testing.assert_allclose(np.asarray(new_params["no_decay"]._data
                                          if hasattr(new_params["no_decay"], "_data")
                                          else new_params["no_decay"]), 2.0)

"""Pallas paged-attention serving kernels (ISSUE 13): interpreter-mode
parity of :mod:`paddle_tpu.ops.paged_attention` against the XLA gather
baseline (``engine._gather_ctx`` + ``gpt.masked_attention``), the shared
kernel-tuning store (:mod:`paddle_tpu.ops.tuning`), and the engine
integration behind ``FLAGS_serving_paged_kernel``.

Parity policy (docs/performance.md "Paged attention kernels"): the
kernels' online softmax associates differently from the gather path's
full-width softmax, so raw attention output is compared under a small
f32 tolerance — while greedy DECODED TOKENS must match exactly, which the
engine-level tests assert across cache hits, chunked prefill, int8
arenas and speculative verify. Everything here runs the real kernel
bodies through the Pallas interpreter on the CPU mesh."""
import json
import math
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import compile_cache
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.ops import paged_attention as pk
from paddle_tpu.ops import tuning
from paddle_tpu.serving import ServingAPI, ServingConfig
from paddle_tpu.serving import metrics as serving_metrics

pytestmark = pytest.mark.serving

pytest.importorskip("jax.experimental.pallas")
if not pk.available():  # pragma: no cover - environment guard
    pytest.skip("Pallas scalar-prefetch unavailable", allow_module_level=True)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.quantization import quantize_kv  # noqa: E402
from paddle_tpu.serving.engine import _gather_ctx  # noqa: E402
from paddle_tpu.models.gpt import masked_attention  # noqa: E402


# ------------------------------------------------------------- references


def _decode_ref(q, entry, bt, pos):
    """The XLA gather baseline, op-for-op what _PagedCacheView does after
    the scatter: gather the whole logical context, mask to <= pos."""
    t_len = bt.shape[1] * entry[0].shape[1]
    k_all, v_all = _gather_ctx(entry, bt, q.dtype)
    mask = (jnp.arange(t_len)[None, :] <= pos[:, None])[:, None, None, :]
    return masked_attention(q[:, None], k_all, v_all, mask)[:, 0]


def _prefill_ref(q, entry, bt_row, prefix_len):
    """The _PrefixPrefillView baseline: one slot's suffix queries at
    global positions prefix_len + i over the gathered table."""
    t_len = bt_row.shape[0] * entry[0].shape[1]
    k_all, v_all = _gather_ctx(entry, bt_row, q.dtype)
    gpos = prefix_len + jnp.arange(q.shape[0])
    mask = (jnp.arange(t_len)[None, :] <= gpos[:, None])[None, None]
    return masked_attention(q[None], k_all[None], v_all[None], mask)[0]


def _pools(rng, nb, bs, h, d, dtype="float32", quantized=False):
    kf = jnp.asarray(rng.standard_normal((nb, bs, h, d)), dtype)
    vf = jnp.asarray(rng.standard_normal((nb, bs, h, d)), dtype)
    if not quantized:
        return (kf, vf)
    kq, ks = quantize_kv(kf)
    vq, vs = quantize_kv(vf)
    return (kq, vq, ks, vs)


def _tol(dtype):
    # online vs full-width softmax association; bf16 rounds the operands
    return dict(atol=5e-6, rtol=5e-6) if dtype == "float32" \
        else dict(atol=2e-2, rtol=2e-2)


# ---------------------------------------------------------- kernel parity


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("quantized", [False, True],
                         ids=["full", "int8"])
def test_decode_parity_permuted_partial_tables(dtype, quantized):
    """Kernel vs gather+masked_attention over permuted, partially-filled
    tables and mixed per-lane positions — bf16 and int8 entries."""
    rng = np.random.default_rng(0)
    S, H, D, NB, bs, MB = 5, 4, 32, 23, 8, 4
    entry = _pools(rng, NB, bs, H, D, dtype, quantized)
    q = jnp.asarray(rng.standard_normal((S, H, D)), dtype)
    # permuted physical blocks; lanes 3/4 share a "partial" look: table
    # tails still point at arbitrary blocks but positions mask them off
    bt = jnp.asarray(rng.permutation(np.arange(1, NB))[: S * MB].reshape(
        S, MB), jnp.int32)
    pos = jnp.asarray([0, 3, 17, 25, 31], jnp.int32)
    out = pk.paged_decode_attention(q, entry, bt, pos)
    ref = _decode_ref(q, entry, bt, pos)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        **_tol(dtype))


def test_decode_parity_every_head_grouping():
    """block_h is a pure launch parameter: every legal grouping computes
    the same attention (the autotuner can never change results)."""
    rng = np.random.default_rng(1)
    S, H, D, NB, bs, MB = 3, 4, 16, 11, 4, 3
    entry = _pools(rng, NB, bs, H, D)
    q = jnp.asarray(rng.standard_normal((S, H, D)), jnp.float32)
    bt = jnp.asarray(rng.integers(1, NB, (S, MB)), jnp.int32)
    pos = jnp.asarray([2, 7, 11], jnp.int32)
    ref = _decode_ref(q, entry, bt, pos)
    for g in (1, 2, 4):
        out = pk.paged_decode_attention(q, entry, bt, pos, block_h=g)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   **_tol("float32"))


def test_decode_shared_block_between_lanes():
    """Two lanes whose tables alias the same physical block (a radix-
    cache shared prefix) read identical context through the kernel."""
    rng = np.random.default_rng(2)
    S, H, D, NB, bs, MB = 2, 2, 16, 9, 4, 2
    entry = _pools(rng, NB, bs, H, D)
    q0 = rng.standard_normal((1, H, D))
    q = jnp.asarray(np.concatenate([q0, q0]), jnp.float32)  # same query
    bt = jnp.asarray([[5, 3], [5, 7]], jnp.int32)  # block 5 shared
    pos = jnp.asarray([3, 3], jnp.int32)  # both inside the shared block
    out = np.asarray(pk.paged_decode_attention(q, entry, bt, pos))
    np.testing.assert_array_equal(out[0], out[1])


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("quantized", [False, True],
                         ids=["full", "int8"])
def test_prefill_parity_mixed_prefix(dtype, quantized):
    """Chunked-prefill kernel vs the suffix-prefill baseline at several
    runtime prefix lengths (cache hits of different depths / successive
    chunks) — one compiled shape serves them all."""
    rng = np.random.default_rng(3)
    sq, H, D, NB, bs, MB = 16, 4, 32, 19, 8, 6
    entry = _pools(rng, NB, bs, H, D, dtype, quantized)
    q = jnp.asarray(rng.standard_normal((sq, H, D)), dtype)
    bt_row = jnp.asarray(rng.permutation(np.arange(1, MB + 1)), jnp.int32)
    for prefix in (0, 5, 11, 31):
        out = pk.paged_prefill_attention(q, entry, bt_row, prefix)
        ref = _prefill_ref(q, entry, bt_row, prefix)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            err_msg=f"prefix={prefix}", **_tol(dtype))


def test_prefill_parity_every_tile():
    rng = np.random.default_rng(4)
    sq, H, D, NB, bs, MB = 8, 2, 16, 9, 4, 3
    entry = _pools(rng, NB, bs, H, D)
    q = jnp.asarray(rng.standard_normal((sq, H, D)), jnp.float32)
    bt_row = jnp.asarray([4, 1, 7], jnp.int32)
    ref = _prefill_ref(q, entry, bt_row, 2)
    for blk_q in (1, 2, 4, 8):
        for blk_h in (1, 2):
            out = pk.paged_prefill_attention(q, entry, bt_row, 2,
                                             block_q=blk_q, block_h=blk_h)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       **_tol("float32"))


@pytest.mark.parametrize("sq", [8, 12, 16])  # 12: non-divisible pad path
def test_full_prefill_pseudo_table_parity(sq):
    """The no-table entry (PR 13 open item): contiguous K/V through an
    arange pseudo-table with prefix 0 equals plain causal attention —
    including when sq doesn't divide the block size (pad keys sit above
    every query row and are masked off)."""
    rng = np.random.default_rng(6)
    H, D, bs = 4, 32, 8
    q = jnp.asarray(rng.standard_normal((sq, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((sq, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((sq, H, D)), jnp.float32)
    out = pk.paged_full_prefill_attention(q, k, v, bs)
    mask = (jnp.arange(sq)[None, :] <= jnp.arange(sq)[:, None])[None, None]
    ref = masked_attention(q[None], k[None], v[None], mask)[0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               **_tol("float32"))


def test_kernel_runtime_data_one_trace():
    """Tables, positions and prefix lengths are runtime data: one jit
    trace serves arbitrary churn of all three."""
    rng = np.random.default_rng(5)
    S, H, D, NB, bs, MB = 3, 2, 16, 9, 4, 3
    entry = _pools(rng, NB, bs, H, D)
    q = jnp.asarray(rng.standard_normal((S, H, D)), jnp.float32)
    traces = {"n": 0}

    @jax.jit
    def step(q, entry, bt, pos):
        traces["n"] += 1
        return pk.paged_decode_attention(q, entry, bt, pos)

    for i in range(3):
        bt = jnp.asarray(rng.integers(1, NB, (S, MB)), jnp.int32)
        pos = jnp.asarray(rng.integers(0, MB * bs, (S,)), jnp.int32)
        out = step(q, entry, bt, pos)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_decode_ref(q, entry, bt, pos)),
                                   **_tol("float32"))
    assert traces["n"] == 1


# ------------------------------------------------------------ tuning store


def test_tuning_store_roundtrip(tmp_path):
    tuning.set_store_path(str(tmp_path / "TUNED_KERNELS.json"))
    try:
        key = tuning.bucket_key(h=4, d=32, bs=16, mb=7)
        assert tuning.lookup("paged_decode", key) is None
        tuning.adopt("paged_decode", key, {"block_h": 2}, 12.5,
                     baseline_us=20.0)
        tuning.reset()  # force a re-read from disk
        assert tuning.lookup("paged_decode", key) == {"block_h": 2}
        assert tuning.entries() == 1
        assert tuning.entries("paged_decode") == 1
        assert tuning.entries("paged_prefill") == 0
        # persisted under THIS device kind only
        with open(tuning.store_path()) as f:
            data = json.load(f)
        assert list(data["records"]) == [tuning.device_kind()]
    finally:
        tuning.set_store_path(None)


def test_tuning_adopt_merges_fresh_disk_state(tmp_path):
    """adopt() merges into what's on disk NOW, not the per-process
    snapshot — a concurrent tuner's records (flash_tune racing the
    serving bench) must survive this process's adoption."""
    tuning.set_store_path(str(tmp_path / "TUNED_KERNELS.json"))
    try:
        assert tuning.lookup("paged_decode", "k1") is None  # snapshot: {}
        # another process adopts while our snapshot is live
        (tmp_path / "TUNED_KERNELS.json").write_text(json.dumps(
            {"records": {tuning.device_kind(): {"flash_fwd": {
                "s=2048": {"params": {"blk_q": 256, "blk_k": 512},
                           "measured_us": 1.0}}}}}))
        assert tuning.adopt("paged_decode", "k1", {"block_h": 2}, 5.0)
        tuning.reset()
        assert tuning.lookup("flash_fwd", "s=2048") == {
            "blk_q": 256, "blk_k": 512}  # the other tuner's record lives
        assert tuning.lookup("paged_decode", "k1") == {"block_h": 2}
    finally:
        tuning.set_store_path(None)


def test_tuning_adopt_reports_persist_failure(tmp_path):
    """A failed persist (unwritable path) returns False so callers never
    report an unpublished tune as adopted."""
    tuning.set_store_path(str(tmp_path / "no_such_dir" / "T.json"))
    try:
        assert tuning.adopt("paged_decode", "k", {"block_h": 1}, 1.0) \
            is False
    finally:
        tuning.set_store_path(None)


def test_tuning_store_device_kind_gated(tmp_path):
    """A record measured on another chip generation is never served."""
    path = tmp_path / "TUNED_KERNELS.json"
    key = tuning.bucket_key(h=4, d=32)
    path.write_text(json.dumps({"records": {"TPU v9000": {
        "paged_decode": {key: {"params": {"block_h": 1},
                               "measured_us": 1.0}}}}}))
    tuning.set_store_path(str(path))
    try:
        assert tuning.lookup("paged_decode", key) is None
    finally:
        tuning.set_store_path(None)


def test_tuning_store_malformed_never_blocks(tmp_path):
    path = tmp_path / "TUNED_KERNELS.json"
    path.write_text("{not json")
    tuning.set_store_path(str(path))
    try:
        assert tuning.lookup("paged_decode", "h=4") is None
        assert tuning.entries() == 0
    finally:
        tuning.set_store_path(None)


def test_tuning_bucket_key_buckets_like_compile_cache():
    """Tuning keys ride the compile cache's bucket ladder: shapes that
    share a compiled program share a tuning record."""
    assert tuning.bucket_key(s=100) == tuning.bucket_key(s=128)
    assert tuning.bucket_key(s=100) == f"s={compile_cache.bucket_dim(100, 1)}"
    assert tuning.bucket_key(d=64, h=12) == "d=64,h=12"


def test_flash_tuned_blocks_reads_shared_store(tmp_path):
    """_tuned_blocks consults the shared store first (kernel
    "flash_fwd"), keeping FLASH_TUNED.json as the legacy fallback."""
    from paddle_tpu.ops import pallas_ops

    tuning.set_store_path(str(tmp_path / "TUNED_KERNELS.json"))
    try:
        tuning.adopt("flash_fwd", tuning.bucket_key(s=2048),
                     {"blk_q": 256, "blk_k": 512}, 10.0)
        tuning.reset()
        assert pallas_ops._tuned_blocks(2048) == (256, 512)
    finally:
        tuning.set_store_path(None)


def test_use_interpret_memoized():
    """Satellite: the backend probe resolves once per process, at module
    level — not once per pallas_call trace."""
    from paddle_tpu.ops import pallas_ops

    assert pallas_ops._use_interpret() is True  # CPU test mesh
    assert pallas_ops._INTERPRET_MEMO  # resolved and memoized
    memo = dict(pallas_ops._INTERPRET_MEMO)
    assert pallas_ops._use_interpret() is True
    assert pallas_ops._INTERPRET_MEMO == memo  # no re-probe growth


def test_gather_ctx_per_block_dequant_bitwise():
    """Satellite: the bf16 fallback dequant chunks per block (lax.map)
    but stays bitwise identical to the whole-context expression."""
    from paddle_tpu.quantization import dequantize_kv

    rng = np.random.default_rng(6)
    NB, bs, H, D, S, MB = 9, 4, 2, 16, 3, 3
    entry = _pools(rng, NB, bs, H, D, quantized=True)
    table = jnp.asarray(rng.integers(0, NB, (S, MB)), jnp.int32)
    k_all, v_all = _gather_ctx(entry, table, "bfloat16")
    k_ref = dequantize_kv(entry[0][table], entry[2][table],
                          "bfloat16").reshape(S, MB * bs, H, D)
    v_ref = dequantize_kv(entry[1][table], entry[3][table],
                          "bfloat16").reshape(S, MB * bs, H, D)
    np.testing.assert_array_equal(np.asarray(k_all), np.asarray(k_ref))
    np.testing.assert_array_equal(np.asarray(v_all), np.asarray(v_ref))


# ------------------------------------------------------- engine integration


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


def _serve(model, rng, workload, **cfg_kw):
    cfg = ServingConfig(num_slots=4, kv_block_size=16, max_model_len=128,
                        **cfg_kw)
    api = ServingAPI(model, cfg)
    try:
        reqs = [api.submit(p, max_new_tokens=n) for p, n in workload]
        api.run_until_idle()
        outs = [np.asarray(r.output_ids()) for r in reqs]
        stats = api.engine.stats()
    finally:
        api.close()
    return outs, stats


def _workload(rng, n=6):
    lens = [8, 12, 20, 7, 16, 9]
    return [(rng.integers(0, 1024, (lens[i % len(lens)],), dtype=np.int32),
             8) for i in range(n)]


def test_engine_token_parity_and_zero_recompile_churn(model):
    """The headline gate: a paged-kernel engine reproduces the gather
    engine token-for-token across admit/retire churn, with decode traced
    exactly ONCE (kernel.decode_traces mirrors it) — block-table and
    position churn never re-lowers the kernel."""
    off, _ = _serve(model, None, _workload(np.random.default_rng(0)),
                    paged_kernel=False)
    before = serving_metrics.stats()
    on, st = _serve(model, None, _workload(np.random.default_rng(0)),
                    paged_kernel=True)
    after = serving_metrics.stats()
    assert st["kernel.paged"] == 1
    assert st["decode_traces"] == 1
    assert after.get("kernel.decode_traces", 0) \
        - before.get("kernel.decode_traces", 0) == 1
    for a, b in zip(off, on):
        np.testing.assert_array_equal(a, b)


def test_engine_parity_int8_arena(model):
    """Fused in-kernel dequant: int8 arena + kernel reproduces the int8
    gather engine exactly (quantized serving never materializes f32
    context on the kernel path)."""
    w = _workload(np.random.default_rng(1))
    off, _ = _serve(model, None, w, paged_kernel=False, quant_kv=True)
    on, st = _serve(model, None, w, paged_kernel=True, quant_kv=True)
    assert st["arena.quantized"] is True
    for a, b in zip(off, on):
        np.testing.assert_array_equal(a, b)


def test_engine_parity_prefix_cache_suffix_prefill(model):
    """Cache-hit admissions route the suffix prefill through the paged
    prefill kernel (prefix_len runtime data — one program per bucket)."""
    rng = np.random.default_rng(2)
    sys_p = rng.integers(0, 1024, (32,), dtype=np.int32)
    w = [(np.concatenate([sys_p,
                          rng.integers(0, 1024, (6,), dtype=np.int32)]), 8)
         for _ in range(4)]
    off, _ = _serve(model, None, w, paged_kernel=False, prefix_cache=True)
    on, st = _serve(model, None, w, paged_kernel=True, prefix_cache=True)
    assert st["prefix.hits"] >= 3
    assert sum(st["prefix_prefill_traces"].values()) == 1
    for a, b in zip(off, on):
        np.testing.assert_array_equal(a, b)


def test_engine_parity_chunked_prefill(model):
    """Chunked admissions drive every chunk through the prefill kernel —
    same tokens, chunks actually taken."""
    rng = np.random.default_rng(7)
    w = [(rng.integers(0, 1024, (40,), dtype=np.int32), 6)
         for _ in range(3)]
    off, _ = _serve(model, None, w, paged_kernel=False, chunked_prefill=8)
    before = serving_metrics.stats()
    on, st = _serve(model, None, w, paged_kernel=True, chunked_prefill=8)
    after = serving_metrics.stats()
    assert after.get("chunk.chunks", 0) > before.get("chunk.chunks", 0)
    for a, b in zip(off, on):
        np.testing.assert_array_equal(a, b)


def test_engine_parity_spec_verify(model):
    """Speculative decoding's draft/verify sub-steps read through the
    kernel too (the _PagedCacheView route inside _spec_step): lockstep
    spec + kernel == plain greedy, acceptance structurally 1.0."""
    w = _workload(np.random.default_rng(3), n=4)
    off, _ = _serve(model, None, w, paged_kernel=False)
    on, st = _serve(model, None, w, paged_kernel=True, spec_k=2)
    assert st["spec.mode"] == "lockstep"
    for a, b in zip(off, on):
        np.testing.assert_array_equal(a, b)


@pytest.mark.chaos
def test_engine_kernel_supervisor_replay_parity(model):
    """Standing invariant: supervisor rebuild/replay is unchanged under
    the kernel — a mid-decode device fault recovers with token-identical
    output, one rebuild, and the decode step never re-traced (the
    rebuilt arena has the same shapes, so the kernel programs are
    reused)."""
    keep = paddle.get_flags("fault_injection")["fault_injection"]
    paddle.set_flags({"fault_injection": 1})
    from paddle_tpu.core import resilience

    cfg = ServingConfig(num_slots=4, kv_block_size=16, max_model_len=128,
                        paged_kernel=True)
    api = ServingAPI(model, cfg)
    try:
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, 1024, (n,), dtype=np.int32)
                   for n in (5, 9, 12)]
        reqs = [api.submit(p, max_new_tokens=8) for p in prompts]
        api.run_until_idle()
        refs = [r.output_ids() for r in reqs]
        d0 = api.engine.decode_traces
        reqs2 = [api.submit(p, max_new_tokens=8) for p in prompts]
        for _ in range(3):
            api._pump_once()
        resilience.inject_fault("serving_device", times=1)
        api.run_until_idle()
        for ref, r in zip(refs, reqs2):
            np.testing.assert_array_equal(ref, r.output_ids())
        assert api.engine.decode_traces == d0 == 1
        assert api.engine.stats()["kernel.paged"] == 1
    finally:
        api.close()
        paddle.set_flags({"fault_injection": keep})


def test_arena_kernel_layout_contract(model):
    """KVArena.kernel_layout() states the facts the kernels and the
    --paged-attention bench size launches from — it must match the live
    pool arrays exactly, quantized and not."""
    for quant in (False, True):
        cfg = ServingConfig(num_slots=2, kv_block_size=16,
                            max_model_len=64, paged_kernel=True,
                            quant_kv=quant)
        api = ServingAPI(model, cfg)
        try:
            arena = api.engine.arena
            lay = arena.kernel_layout()
            entry = arena.pools[0]
            assert lay["num_blocks"] == entry[0].shape[0]
            assert lay["block_size"] == entry[0].shape[1]
            assert lay["quantized"] == (len(entry) == 4)
            assert lay["scratch_block"] == 0
            if quant:
                assert tuple(entry[2].shape) == (lay["num_blocks"],
                                                 lay["block_size"])
        finally:
            api.close()


def test_engine_kernel_off_is_default(model):
    """Flag-off (the default): the gather path, kernel gauge 0 — the
    bit-preserved baseline every parity test above compares against."""
    _, st = _serve(model, None, _workload(np.random.default_rng(4), n=2))
    assert st["kernel.paged"] == 0
    assert st["kernel.mesh"] == "gather@single"


# ------------------------------------------------- SPMD partitioning (mesh)
#
# ISSUE 16: on a multi-device mesh the kernels run per model-shard
# through headwise_shard_map — head-sharded q/K/V pool operands,
# replicated block tables/positions/scales, the row-parallel output
# psum closing the attention output. Everything below runs on the 8
# virtual CPU devices conftest forces.

from paddle_tpu.distributed.mesh import serving_mesh  # noqa: E402
from paddle_tpu.distributed.sharding_util import mesh_axes_key  # noqa: E402


def _fresh():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("quantized", [False, True], ids=["full", "int8"])
def test_sharded_decode_parity(dtype, quantized):
    """The sharded decode kernel (4-way model split of 8 heads — each
    device runs its 2 local heads against replicated tables) matches the
    unsharded kernel: per-head attention is independent, so splitting
    the head dim changes nothing but placement."""
    mesh = serving_mesh(4, install=False)
    rng = np.random.default_rng(9)
    S, H, D, NB, bs, MB = 4, 8, 32, 17, 8, 4
    entry = _pools(rng, NB, bs, H, D, dtype, quantized)
    q = jnp.asarray(rng.standard_normal((S, H, D)), dtype)
    bt = jnp.asarray(rng.integers(1, NB, (S, MB)), jnp.int32)
    pos = jnp.asarray([0, 7, 19, 31], jnp.int32)
    ref = pk.paged_decode_attention(q, entry, bt, pos)
    out = pk.paged_decode_attention(q, entry, bt, pos, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), **_tol(dtype))


@pytest.mark.parametrize("quantized", [False, True], ids=["full", "int8"])
def test_sharded_prefill_parity(quantized):
    """The sharded suffix-prefill kernel at several runtime prefix
    lengths — one shard_map'd program serves them all."""
    mesh = serving_mesh(4, install=False)
    rng = np.random.default_rng(10)
    sq, H, D, NB, bs, MB = 16, 8, 32, 19, 8, 6
    entry = _pools(rng, NB, bs, H, D, "float32", quantized)
    q = jnp.asarray(rng.standard_normal((sq, H, D)), jnp.float32)
    bt_row = jnp.asarray(rng.permutation(np.arange(1, MB + 1)), jnp.int32)
    for prefix in (0, 5, 31):
        out = pk.paged_prefill_attention(q, entry, bt_row, prefix,
                                         mesh=mesh)
        ref = _prefill_ref(q, entry, bt_row, prefix)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            err_msg=f"prefix={prefix}", **_tol("float32"))


def test_sharded_nondivisible_heads_replicate():
    """Heads not divisible by the model degree degrade to replicated
    specs inside the wrapper — correct output, never a crash or a
    gather fallback."""
    mesh = serving_mesh(4, install=False)
    rng = np.random.default_rng(11)
    S, H, D, NB, bs, MB = 3, 6, 16, 9, 4, 3  # 6 % 4 != 0
    entry = _pools(rng, NB, bs, H, D)
    q = jnp.asarray(rng.standard_normal((S, H, D)), jnp.float32)
    bt = jnp.asarray(rng.integers(1, NB, (S, MB)), jnp.int32)
    pos = jnp.asarray([2, 7, 11], jnp.int32)
    out = pk.paged_decode_attention(q, entry, bt, pos, mesh=mesh)
    ref = _decode_ref(q, entry, bt, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               **_tol("float32"))


def test_mesh_engine_kernel_vs_gather_parity_one_trace():
    """The ISSUE 16 headline gate: on a live (model=4) mesh the kernel
    engine reproduces the mesh-gather engine token-for-token, decode is
    traced exactly ONCE (kernel.decode_traces mirrors it), and the
    route gauge reports kernel@model4 — admit/retire churn on the mesh
    re-lowers nothing."""
    serving_mesh(4)
    model = _fresh()
    w = _workload(np.random.default_rng(12))
    off, st0 = _serve(model, None, w, paged_kernel=False)
    assert st0["kernel.mesh"] == "gather@model4"
    before = serving_metrics.stats()
    on, st = _serve(model, None, w, paged_kernel=True)
    after = serving_metrics.stats()
    assert st["kernel.paged"] == 1
    assert st["kernel.mesh"] == "kernel@model4"
    assert st["decode_traces"] == 1
    assert after.get("kernel.decode_traces", 0) \
        - before.get("kernel.decode_traces", 0) == 1
    for a, b in zip(off, on):
        np.testing.assert_array_equal(a, b)


def test_mesh_engine_parity_int8_arena():
    """Fused in-kernel dequant per model-shard: int8 arena + kernel on
    the mesh reproduces the int8 mesh-gather engine exactly (the scale
    pools ride replicated next to the head-sharded payloads)."""
    serving_mesh(4)
    model = _fresh()
    w = _workload(np.random.default_rng(13), n=4)
    off, _ = _serve(model, None, w, paged_kernel=False, quant_kv=True)
    on, st = _serve(model, None, w, paged_kernel=True, quant_kv=True)
    assert st["arena.quantized"] is True
    assert st["kernel.mesh"] == "kernel@model4"
    for a, b in zip(off, on):
        np.testing.assert_array_equal(a, b)


def test_mesh_engine_parity_spec_verify():
    """Speculative draft/verify sub-steps ride the sharded kernel too:
    lockstep spec + kernel + mesh == plain mesh greedy decode."""
    serving_mesh(4)
    model = _fresh()
    w = _workload(np.random.default_rng(14), n=3)
    off, _ = _serve(model, None, w, paged_kernel=False)
    on, st = _serve(model, None, w, paged_kernel=True, spec_k=2)
    assert st["spec.mode"] == "lockstep"
    assert st["kernel.mesh"] == "kernel@model4"
    for a, b in zip(off, on):
        np.testing.assert_array_equal(a, b)


def test_mesh_mp1_kernel_bit_identity():
    """A 1-device mesh never takes the shard_map route (`_kernel_mesh`
    stays None): same tokens as no mesh at all — the PR 13 kernel path
    is bit-preserved, while the program key still differs (mesh_axes_key
    joins it)."""
    w = _workload(np.random.default_rng(15), n=3)
    ref, st0 = _serve(_fresh(), None, w, paged_kernel=True)
    assert st0["kernel.mesh"] == "kernel@single"
    serving_mesh(1)
    on, st = _serve(_fresh(), None, w, paged_kernel=True)
    assert st["kernel.paged"] == 1
    assert st["kernel.mesh"].startswith("kernel@")
    assert st["kernel.mesh"] != "kernel@single"  # keyed differently
    for a, b in zip(ref, on):
        np.testing.assert_array_equal(a, b)


def test_tuning_mesh_key_roundtrip(tmp_path):
    """Mesh-keyed records: adopted under the topology suffix, resolved
    only at that topology — never off-mesh, never at another degree."""
    tuning.set_store_path(str(tmp_path / "TUNED_KERNELS.json"))
    try:
        key = tuning.bucket_key(h=2, d=32, bs=16, mb=8)
        topo = (("data", 1), ("model", 4))
        assert tuning.mesh_suffix(topo) == "mesh=data1.model4"
        tuning.adopt("paged_decode", key, {"block_h": 2}, 9.0, mesh=topo)
        tuning.reset()
        assert tuning.lookup("paged_decode", key, mesh=topo) \
            == {"block_h": 2}
        assert tuning.lookup("paged_decode", key) is None
        assert tuning.lookup("paged_decode", key,
                             mesh=(("data", 1), ("model", 2))) is None
    finally:
        tuning.set_store_path(None)


def test_tuning_mesh_legacy_migration(tmp_path):
    """Pre-ISSUE-16 stores (no mesh suffix) keep resolving on 1-device
    topologies; a multi-device topology never borrows a single-chip
    tune; a suffixed 1-device record wins over the legacy fallback."""
    tuning.set_store_path(str(tmp_path / "TUNED_KERNELS.json"))
    try:
        key = tuning.bucket_key(h=4, d=32)
        tuning.adopt("paged_decode", key, {"block_h": 4}, 7.0)  # legacy
        tuning.reset()
        one = (("data", 1), ("model", 1))
        assert tuning.lookup("paged_decode", key, mesh=one) \
            == {"block_h": 4}
        assert tuning.lookup("paged_decode", key,
                             mesh=(("model", 4),)) is None
        tuning.adopt("paged_decode", key, {"block_h": 2}, 5.0, mesh=one)
        tuning.reset()
        assert tuning.lookup("paged_decode", key, mesh=one) \
            == {"block_h": 2}
    finally:
        tuning.set_store_path(None)


def test_sharded_tuned_block_h_applies(tmp_path):
    """A mesh-keyed tune actually reaches the sharded launch: the
    record's block_h (legal for the LOCAL head count, 8//4 = 2) changes
    nothing numerically — block_h stays a pure launch parameter under
    shard_map."""
    mesh = serving_mesh(4, install=False)
    rng = np.random.default_rng(16)
    S, H, D, NB, bs, MB = 3, 8, 16, 11, 4, 3
    entry = _pools(rng, NB, bs, H, D)
    q = jnp.asarray(rng.standard_normal((S, H, D)), jnp.float32)
    bt = jnp.asarray(rng.integers(1, NB, (S, MB)), jnp.int32)
    pos = jnp.asarray([2, 7, 11], jnp.int32)
    ref = _decode_ref(q, entry, bt, pos)
    tuning.set_store_path(str(tmp_path / "TUNED_KERNELS.json"))
    try:
        key = tuning.bucket_key(h=H // 4, d=D, bs=bs, mb=MB)
        tuning.adopt("paged_decode", key, {"block_h": 2}, 3.0,
                     mesh=mesh_axes_key(mesh))
        tuning.reset()
        out = pk.paged_decode_attention(q, entry, bt, pos, mesh=mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   **_tol("float32"))
    finally:
        tuning.set_store_path(None)


@pytest.mark.chaos
def test_mesh_kernel_supervisor_replay_parity():
    """Supervisor rebuild/replay with mesh AND kernel on: a mid-decode
    device fault recovers token-identically, the rebuilt arena
    re-commits the same shardings, and the sharded decode program is
    reused (decode never re-traced)."""
    keep = paddle.get_flags("fault_injection")["fault_injection"]
    paddle.set_flags({"fault_injection": 1})
    from paddle_tpu.core import resilience

    serving_mesh(4)
    model = _fresh()
    cfg = ServingConfig(num_slots=4, kv_block_size=16, max_model_len=128,
                        paged_kernel=True)
    api = ServingAPI(model, cfg)
    try:
        rng = np.random.default_rng(17)
        prompts = [rng.integers(0, 1024, (n,), dtype=np.int32)
                   for n in (5, 9, 12)]
        reqs = [api.submit(p, max_new_tokens=8) for p in prompts]
        api.run_until_idle()
        refs = [r.output_ids() for r in reqs]
        d0 = api.engine.decode_traces
        reqs2 = [api.submit(p, max_new_tokens=8) for p in prompts]
        for _ in range(3):
            api._pump_once()
        resilience.inject_fault("serving_device", times=1)
        api.run_until_idle()
        for ref, r in zip(refs, reqs2):
            np.testing.assert_array_equal(ref, r.output_ids())
        assert api.engine.decode_traces == d0 == 1
        assert api.engine.stats()["kernel.mesh"] == "kernel@model4"
    finally:
        api.close()
        paddle.set_flags({"fault_injection": keep})

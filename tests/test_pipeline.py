"""Pipeline transform: pipelined == sequential, forward and backward."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu.distributed.pipeline import pipeline_apply, stack_stage_params


def _stage_fn(p, h):
    # one stage = two chained linear+tanh layers
    def layer(h, wb):
        w, b = wb
        return jnp.tanh(h @ w + b)

    return jax.lax.scan(lambda c, wb: (layer(c, wb), None), h, p)[0]


def _make(S, L_per, d, key):
    ks = jax.random.split(key, S * L_per * 2).reshape(S, L_per, 2, 2)
    stages = []
    for s in range(S):
        ws = jnp.stack([jax.random.normal(jax.random.fold_in(key, s * 100 + l), (d, d)) * 0.3
                        for l in range(L_per)])
        bs = jnp.stack([jax.random.normal(jax.random.fold_in(key, s * 100 + 50 + l), (d,)) * 0.1
                        for l in range(L_per)])
        stages.append((ws, bs))
    return stages


def _sequential(stages, x):
    h = x
    for p in stages:
        h = _stage_fn(p, h)
    return h


@pytest.mark.parametrize("pp,M", [(4, 4), (4, 8), (2, 4)])
def test_pipeline_matches_sequential(pp, M):
    mesh = dist.init_hybrid_mesh(pp=pp, dp=8 // pp)
    d, B = 8, 16
    key = jax.random.PRNGKey(0)
    stages = _make(pp, 2, d, key)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, d))

    ref = _sequential(stages, x)
    stacked = stack_stage_params(stages, pp, mesh=mesh)
    out = pipeline_apply(_stage_fn, stacked, x, num_microbatches=M, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_grads_match_sequential():
    mesh = dist.init_hybrid_mesh(pp=4, dp=2)
    d, B, M = 8, 16, 4
    key = jax.random.PRNGKey(0)
    stages = _make(4, 2, d, key)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, d))
    y = jax.random.normal(jax.random.PRNGKey(2), (B, d))

    def loss_seq(params):
        return jnp.mean((_sequential(params, x) - y) ** 2)

    stacked = stack_stage_params(stages, 4, mesh=mesh)

    def loss_pipe(params):
        out = pipeline_apply(_stage_fn, params, x, num_microbatches=M, mesh=mesh)
        return jnp.mean((out - y) ** 2)

    g_ref = jax.grad(loss_seq)(stages)
    # autodiff through shard_map requires jit (the TrainStep always jits)
    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    # re-stack reference per-stage grads for comparison
    g_ref_stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *g_ref)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_ref_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pipeline_single_stage_fallback():
    mesh = dist.init_hybrid_mesh(dp=8)
    d, B = 4, 8
    stages = _make(1, 2, d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, d))
    ref = _sequential(stages, x)
    stacked = stack_stage_params(stages, 1, mesh=mesh)
    out = pipeline_apply(_stage_fn, stacked, x, num_microbatches=4, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_under_jit_compiles_once():
    mesh = dist.init_hybrid_mesh(pp=4, dp=2)
    d, B, M = 8, 16, 8
    stages = _make(4, 2, d, jax.random.PRNGKey(0))
    stacked = stack_stage_params(stages, 4, mesh=mesh)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, d))

    @jax.jit
    def f(p, xx):
        return pipeline_apply(_stage_fn, p, xx, num_microbatches=M, mesh=mesh)

    out = f(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_sequential(stages, x)), atol=1e-5)

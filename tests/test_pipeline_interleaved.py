"""Interleaved virtual-stage pipeline schedule
(ref:python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:514
PipelineParallelWithInterleave)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import rng as prng
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.mesh import init_hybrid_mesh
from paddle_tpu.distributed.pipeline import (pipeline_apply,
                                             pipeline_apply_interleaved,
                                             pipeline_tick_cost,
                                             stack_chunk_params,
                                             stack_stage_params)


def test_interleaved_forward_matches_sequential():
    mesh = init_hybrid_mesh(pp=4, dp=2)
    S, V = 4, 2
    rng = np.random.default_rng(0)
    Ws = [{"w": jnp.asarray(rng.standard_normal((16, 16), np.float32) * 0.3)}
          for _ in range(S * V)]
    x = jnp.asarray(rng.standard_normal((12, 16), np.float32))

    def chunk_fn(p, h, v):
        return jnp.tanh(h @ p["w"])

    ref = np.asarray(x)
    for wj in Ws:
        ref = np.tanh(ref @ np.asarray(wj["w"]))

    cp = stack_chunk_params(Ws, S, V, mesh=mesh)
    out = pipeline_apply_interleaved(chunk_fn, cp, x, num_microbatches=6,
                                     num_chunks=V, mesh=mesh)
    assert np.allclose(np.asarray(out), ref, atol=1e-5)

    # microbatch count NOT a multiple of S exercises group padding
    out2 = pipeline_apply_interleaved(chunk_fn, cp, x, num_microbatches=3,
                                      num_chunks=V, mesh=mesh)
    assert np.allclose(np.asarray(out2), ref, atol=1e-5)


def test_interleaved_gradients_match_sequential():
    mesh = init_hybrid_mesh(pp=4)
    S, V = 4, 2
    rng = np.random.default_rng(1)
    Ws = [jnp.asarray(rng.standard_normal((8, 8), np.float32) * 0.3)
          for _ in range(S * V)]
    x = jnp.asarray(rng.standard_normal((8, 8), np.float32))

    def seq_loss(ws):
        h = x
        for w in ws:
            h = jnp.tanh(h @ w)
        return (h ** 2).mean()

    ref_grads = jax.grad(seq_loss)(Ws)

    def pipe_loss(ws):
        cp = stack_chunk_params([{"w": w} for w in ws], S, V, mesh=mesh)
        out = pipeline_apply_interleaved(
            lambda p, h, v: jnp.tanh(h @ p["w"]), cp, x,
            num_microbatches=4, num_chunks=V, mesh=mesh, remat=True)
        return (out ** 2).mean()

    got = jax.grad(pipe_loss)(Ws)
    for g, r in zip(got, ref_grads):
        assert np.allclose(np.asarray(g), np.asarray(r), atol=1e-4)


def test_interleaved_bubble_smaller_than_gpipe():
    # equal microbatches: the virtual-stage schedule has strictly fewer
    # idle stage-units whenever S > 1 and V > 1
    for S in (2, 4, 8):
        for M in (S, 2 * S, 4 * S):
            gpipe = pipeline_tick_cost(M, S, 1)
            for V in (2, 4):
                inter = pipeline_tick_cost(M, S, V)
                assert inter < gpipe, (S, M, V)
                # closed form: bubble (S-1)/V vs (S-1) stage-units
                assert inter == pytest.approx(M + (S - 1) / V)


def test_gpt_pipe_interleaved_loss_parity():
    """2 training steps of the interleaved GPT pipe match a single-device
    run from identical init (the dryrun's parity bar)."""
    from paddle_tpu.distributed.fleet.meta_parallel import PipelineParallel
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe
    from paddle_tpu.optimizer import AdamW

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 1024, (8, 32), dtype=np.int32)
    lbl = np.roll(ids, -1, axis=1)
    devices = jax.devices()[:4]

    def run(n_dev, stages, virtual):
        prng.seed(777)
        init_hybrid_mesh(pp=stages if n_dev > 1 else 1,
                         dp=1, devices=devices[:n_dev])
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4,
                        num_heads=4, max_position_embeddings=256)
        m = GPTForCausalLMPipe(cfg, num_stages=stages,
                               num_microbatches=2,
                               num_virtual_pipeline_stages=virtual)
        w = PipelineParallel(m)
        o = AdamW(learning_rate=1e-3, parameters=m.parameters())
        out = []
        for _ in range(2):
            l = w.train_batch((Tensor(ids), Tensor(lbl)), o)
            out.append(float(np.asarray(l._data)))
        return out

    ref = run(1, 1, None)
    inter = run(4, 2, 2)  # 2 devices' worth of stages x 2 virtual chunks
    assert np.allclose(ref, inter, rtol=5e-3, atol=5e-3), (ref, inter)


def test_interleaved_degenerate_paths():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((8, 8), np.float32))

    def chunk_fn(p, h, v):
        return jnp.tanh(h @ p["w"])

    # S == 1 (no pipe axis): all chunks run sequentially per microbatch
    mesh1 = init_hybrid_mesh(dp=8)
    V = 3
    Ws = [{"w": jnp.asarray(rng.standard_normal((8, 8), np.float32) * 0.3)}
          for _ in range(V)]
    ref = np.asarray(x)
    for wj in Ws:
        ref = np.tanh(ref @ np.asarray(wj["w"]))
    cp = stack_chunk_params(Ws, 1, V, mesh=mesh1)
    out = pipeline_apply_interleaved(chunk_fn, cp, x, num_microbatches=2,
                                     num_chunks=V, mesh=mesh1)
    assert np.allclose(np.asarray(out), ref, atol=1e-5)

    # V == 1 on a real pipe mesh: falls back to the GPipe schedule
    mesh2 = init_hybrid_mesh(pp=4, dp=2)
    Ws4 = [{"w": jnp.asarray(rng.standard_normal((8, 8), np.float32) * 0.3)}
           for _ in range(4)]
    ref2 = np.asarray(x)
    for wj in Ws4:
        ref2 = np.tanh(ref2 @ np.asarray(wj["w"]))
    cp2 = stack_chunk_params(Ws4, 4, 1, mesh=mesh2)
    out2 = pipeline_apply_interleaved(chunk_fn, cp2, x, num_microbatches=4,
                                      num_chunks=1, mesh=mesh2)
    assert np.allclose(np.asarray(out2), ref2, atol=1e-5)


def test_interleaved_beats_gpipe_wall_clock(tmp_path):
    """VERDICT r3 weak-4: the formula's win must show on a clock, not just
    in closed form. Runs the recorded bench (subprocess: it needs its own
    8-device env) at M=4 — the largest predicted gain (1.27x) — and
    accepts any measured win to stay robust to CPU noise; full M sweep
    numbers live in benches/BASELINE_RESULTS.jsonl. d=1024: below that,
    per-tick dispatch overhead on the emulated CPU mesh (the interleaved
    schedule runs ~1.6x the ticks at 1/V the compute each) cancels the
    bubble win and the ratio is pure noise."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ, PYTHONPATH="/root/repo")
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, '/root/repo/benches'); "
         "sys.path.insert(0, '/root/repo'); "
         "import pipeline_bench as b, json; "
         "print('ROW ' + json.dumps(b.measure(4, d=1024, iters=4)))"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd="/root/repo")
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    row = json.loads(r.stdout.split("ROW ", 1)[1])
    assert row["predicted_speedup"] > 1.2
    assert row["speedup"] > 1.0, row  # measured win, noise-tolerant bar

"""PipelineLayer / PipelineParallel: segmentation, parity with non-pipe, training."""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
)


class Emb(nn.Layer):
    def __init__(self, v, d):
        super().__init__()
        self.e = nn.Embedding(v, d)

    def forward(self, x):
        return self.e(x)


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc1 = nn.Linear(d, 2 * d)
        self.fc2 = nn.Linear(2 * d, d)

    def forward(self, x):
        return x + self.fc2(paddle.tanh(self.fc1(x)))


class Head(nn.Layer):
    def __init__(self, d, v):
        super().__init__()
        self.fc = nn.Linear(d, v)

    def forward(self, x):
        return self.fc(x)


def _descs(v, d, L):
    return ([LayerDesc(Emb, v, d)]
            + [LayerDesc(Block, d) for _ in range(L)]
            + [LayerDesc(Head, d, v)])


def _loss(logits, y):
    return nn.functional.cross_entropy(
        logits.reshape([-1, logits.shape[-1]]), y.reshape([-1]), reduction="mean")


def test_segmentation():
    dist.init_hybrid_mesh(dp=8)
    m = PipelineLayer(_descs(32, 8, 4), num_stages=2, num_microbatches=2)
    assert m.blocks.num_layers == 4
    assert len(m._pre) == 1 and len(m._post) == 1


def test_indivisible_raises():
    dist.init_hybrid_mesh(dp=8)
    with pytest.raises(ValueError):
        PipelineLayer(_descs(32, 8, 3), num_stages=2)


def test_pipe_forward_matches_nopipe():
    paddle.seed(0)
    # build once on a pipe mesh; compare pipe vs single-device execution
    mesh = dist.init_hybrid_mesh(pp=4, dp=2)
    m = PipelineLayer(_descs(64, 8, 4), num_stages=4, num_microbatches=4, loss_fn=_loss)
    x = paddle.to_tensor(np.random.randint(0, 64, (8, 6)).astype(np.int32))
    out_pipe = m(x)

    # same weights, no pipe axis: sequential path
    dist.mesh.set_mesh(dist.build_mesh({"data": 8}))
    out_seq = m(x)
    np.testing.assert_allclose(out_pipe.numpy(), out_seq.numpy(), atol=1e-4)
    dist.mesh.set_mesh(mesh)


def test_pipeline_parallel_train_batch_converges():
    paddle.seed(0)
    dist.init_hybrid_mesh(pp=4, dp=2)
    strat = dist.fleet.DistributedStrategy()
    strat.hybrid_configs = {"dp_degree": 2, "pp_degree": 4}
    strat.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}
    dist.fleet.init(strategy=strat)

    model = PipelineLayer(_descs(16, 8, 4), num_stages=4, loss_fn=_loss)
    model = dist.fleet.distributed_model(model)
    assert isinstance(model, PipelineParallel)
    opt = paddle.optimizer.AdamW(learning_rate=3e-3, parameters=model._layers.parameters())

    rng_ = np.random.default_rng(0)
    x = rng_.integers(0, 16, (8, 4)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    losses = []
    for _ in range(30):
        loss = model.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt)
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]


def test_stage_mesh_mismatch_raises():
    dist.init_hybrid_mesh(pp=2, dp=4)
    m = PipelineLayer(_descs(32, 8, 4), num_stages=4, num_microbatches=2, loss_fn=_loss)
    x = paddle.to_tensor(np.random.randint(0, 32, (4, 4)).astype(np.int32))
    with pytest.raises(ValueError):
        m(x)

"""Radix prefix cache (ISSUE 6): content-addressed KV block sharing with
refcounted copy-on-write in the serving arena.

Unit half: the radix tree (content hashing, left-context keying, LRU leaf
eviction) and the arena's refcount layer (deref-to-free, cache residency,
eviction under reserve pressure, the flag-gated invariant audit) — pure
host-side, no compiles. Engine half: the tier-1 acceptance regressions —
a two-request shared-prefix admit does exactly ONE suffix-bucket prefill
and zero extra decode compiles, copy-on-write on a fully-cached
block-aligned prompt, eviction under arena pressure, bounded cache-affinity
admission, and token-for-token parity with ``generate()`` throughout.

Engine tests pin the cache per-instance (``prefix_cache=True`` engine
kwarg) rather than flipping the global flag, so the rest of the suite —
which must pass byte-identically with ``FLAGS_serving_prefix_cache=0`` —
is never affected by ordering. The refcount audit flag is enabled for the
whole module: every retire path in these tests self-checks.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import compile_cache
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (
    ArenaExhaustedError,
    KVArena,
    PrefixCache,
    RequestState,
    ServingAPI,
)
from paddle_tpu.serving import metrics as serving_metrics

pytestmark = pytest.mark.serving

MAX_LEN = 64


@pytest.fixture(scope="module", autouse=True)
def _invariants_on():
    keep = paddle.get_flags(
        "serving_arena_invariants")["serving_arena_invariants"]
    paddle.set_flags({"serving_arena_invariants": 1})
    yield
    paddle.set_flags({"serving_arena_invariants": keep})


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def api(model):
    a = ServingAPI(model, num_slots=4, kv_block_size=8, max_model_len=MAX_LEN,
                   prefix_cache=True)
    yield a
    a.close()


def _prompt(rng, n):
    return rng.integers(0, 1024, (n,), dtype=np.int32)


def _ref(model, prompt, max_new, stop=None):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=max_new, stop_token_id=stop)
    return np.asarray(out._data)[0]


# ------------------------------------------------------------- tree units


def _arena(num_blocks=12, block_size=4):
    return KVArena(num_layers=1, num_heads=2, head_dim=4,
                   num_blocks=num_blocks, block_size=block_size)


def _take(arena, n):
    res = arena.reserve(n)
    return res, [res.take() for _ in range(n)]


def test_radix_content_hash_keys_on_left_context():
    """Equal chunks under different prefixes never alias: block 1 of
    prompt A is a different node than the same tokens as block 1 of B."""
    arena = _arena()
    cache = PrefixCache(arena)
    rng = np.random.default_rng(0)
    a = rng.integers(0, 100, (12,), dtype=np.int32)   # 3 full chunks of 4
    res, blocks = _take(arena, 3)
    assert cache.insert(a, blocks, 3) == 3
    assert cache.lookup(a) == 12
    assert cache.lookup(a[:9]) == 8    # partial trailing chunk not matched
    assert cache.lookup(a[:4]) == 4
    # same middle chunk under a different first chunk: no match at all
    b = np.concatenate([a[:4] + 1, a[4:8]])
    assert cache.lookup(b) == 0
    # re-inserting resident chunks is a no-op (existing stays authoritative)
    res2, blocks2 = _take(arena, 3)
    assert cache.insert(a, blocks2, 3) == 0
    assert cache.resident_blocks() == 3
    res2.release()
    res.release()


def test_refcounted_release_keeps_cached_blocks_resident():
    """deref at refcount zero frees — unless the prefix cache holds the
    block, in which case it stays allocated (reclaimable, not leaked)."""
    arena = _arena(num_blocks=6)
    cache = PrefixCache(arena)
    res, (blk,) = _take(arena, 1)
    assert arena.refcount(blk) == 1
    arena.mark_cached(blk)
    res.release()
    assert arena.refcount(blk) == 0
    assert blk not in arena._free          # resident, NOT freed
    assert arena.blocks_cached() == 1
    # a sharer can re-reference a cached block; the free path waits for it
    arena.ref(blk)
    arena.uncache(blk)
    assert blk not in arena._free          # still referenced
    arena.deref(blk)
    assert blk in arena._free              # last ref gone -> free list
    # double-free and ref-of-free are loud bugs, not silent corruption
    with pytest.raises(RuntimeError, match="refcount 0"):
        arena.deref(blk)
    with pytest.raises(RuntimeError, match="neither live nor cached"):
        arena.ref(blk)
    del cache


def test_reserve_pressure_evicts_lru_leaves():
    """reserve() beyond the free list evicts refcount-zero LRU leaves —
    cached prefixes extend the free list; pinned blocks never move."""
    arena = _arena(num_blocks=7, block_size=4)  # 6 allocatable
    cache = PrefixCache(arena)
    rng = np.random.default_rng(1)
    old = rng.integers(0, 100, (8,), dtype=np.int32)
    new = rng.integers(100, 200, (8,), dtype=np.int32)
    res_a, blocks_a = _take(arena, 2)
    cache.insert(old, blocks_a, 2)
    res_a.release()
    res_b, blocks_b = _take(arena, 2)
    cache.insert(new, blocks_b, 2)      # touched later -> more recent
    res_b.release()
    assert arena.blocks_free() == 2 and arena.blocks_cached() == 4
    assert arena.grantable() == 6       # evictable counts as grantable
    res = arena.reserve(4)              # needs 2 evictions
    assert cache.evictions == 2
    # LRU: the OLD chain went first, leaf (chunk 1) before its parent
    assert cache.lookup(old) == 0
    assert cache.lookup(new) == 8
    res.release()
    # pinned blocks are not evictable even at the leaf
    chain = cache.match(new)
    arena.ref(chain[-1].block)
    assert cache.evictable_blocks() == 0  # leaf pinned -> parent blocked too
    with pytest.raises(ArenaExhaustedError):
        arena.reserve(5)
    arena.deref(chain[-1].block)
    assert cache.evictable_blocks() == 2


def test_invariant_checker_catches_corruption():
    arena = _arena(num_blocks=6)
    res, blocks = _take(arena, 2)
    arena.check_invariants([list(blocks)])
    # a block in two tables with refcount 1 is a sharing-accounting bug
    with pytest.raises(RuntimeError, match="appears in 2"):
        arena.check_invariants([[blocks[0]], [blocks[0]]])
    arena.ref(blocks[0])
    arena.check_invariants([[blocks[0]], [blocks[0], blocks[1]]])
    arena.deref(blocks[0])
    res.release()
    arena.check_invariants([])
    # a freed block with a nonzero refcount is a double-accounting bug
    arena._refs[blocks[0]] = 1
    with pytest.raises(RuntimeError, match="free block"):
        arena.check_invariants([])


# -------------------------------------------------- engine: tier-1 gates


def test_shared_prefix_single_suffix_prefill_no_new_decode_compiles(
        api, model):
    """ISSUE 6 tier-1 regression: the second of two requests sharing a
    full-block prefix admits with exactly ONE suffix-bucket prefill and
    zero extra decode compiles — and both outputs are token-for-token
    identical to generate()."""
    rng = np.random.default_rng(10)
    shared = _prompt(rng, 24)  # 3 full blocks at kv_block_size=8
    p1 = np.concatenate([shared, _prompt(rng, 5)])
    p2 = np.concatenate([shared, _prompt(rng, 7)])
    r1 = api.submit(p1, max_new_tokens=6)
    api.run_until_idle()
    d0 = api.engine.decode_traces
    cc0 = compile_cache.stats().get("serving.decode_compiles", 0)
    sp0 = serving_metrics.stats().get("prefix.suffix_prefills", 0)
    av0 = serving_metrics.stats().get("tokens.prefill_avoided", 0)
    r2 = api.submit(p2, max_new_tokens=6)
    api.run_until_idle()
    for p, r in ((p1, r1), (p2, r2)):
        assert r.state == RequestState.FINISHED
        np.testing.assert_array_equal(r.output_ids(), _ref(model, p, 6))
    # exactly one suffix-bucket prefill ran for the whole second admission
    assert serving_metrics.stats().get("prefix.suffix_prefills", 0) \
        == sp0 + 1
    # the 3 shared blocks' 24 tokens never touched a prefill program
    assert serving_metrics.stats().get("tokens.prefill_avoided", 0) \
        == av0 + 24
    # and nothing recompiled: block tables are runtime data
    assert api.engine.decode_traces == d0
    assert compile_cache.stats().get("serving.decode_compiles", 0) == cc0
    assert all(v == 1 for v in api.engine.prefix_prefill_traces.values())
    api.engine.check_invariants()


def test_cow_on_fully_cached_aligned_prompt(api, model):
    """A block-aligned prompt whose every block is resident admits by
    copying its last matched block (COW) and recomputing only the final
    token — shared blocks are never written, output parity holds, and
    repeating the hit reuses the one compiled COW program."""
    rng = np.random.default_rng(11)
    p = _prompt(rng, 16)  # exactly 2 blocks
    r1 = api.submit(p, max_new_tokens=4)  # cold: inserts both blocks
    api.run_until_idle()
    cow0 = serving_metrics.stats().get("prefix.cow_copies", 0)
    ct0 = api.engine.cow_traces
    r2 = api.submit(p, max_new_tokens=4)  # fully cached -> COW path
    api.run_until_idle()
    ref = _ref(model, p, 4)
    np.testing.assert_array_equal(r1.output_ids(), ref)
    np.testing.assert_array_equal(r2.output_ids(), ref)
    assert serving_metrics.stats().get("prefix.cow_copies", 0) == cow0 + 1
    r3 = api.submit(p, max_new_tokens=4)  # hit again: no recompile
    api.run_until_idle()
    np.testing.assert_array_equal(r3.output_ids(), ref)
    assert serving_metrics.stats().get("prefix.cow_copies", 0) == cow0 + 2
    assert api.engine.cow_traces == max(ct0, 1)  # traced at most once ever
    api.engine.check_invariants()


def test_eviction_under_arena_pressure_end_to_end(model):
    """Resident prefixes never block live traffic: when an admission's
    reservation exceeds the free list, cold cached blocks are evicted
    (LRU) and the request completes with full parity."""
    a = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=48,
                   num_blocks=7, prefix_cache=True)  # 6 allocatable
    try:
        rng = np.random.default_rng(12)
        pa = _prompt(rng, 16)
        ra = a.submit(pa, max_new_tokens=8)  # 3 blocks; inserts 2
        a.run_until_idle()
        assert a.engine.arena.blocks_cached() == 2
        ev0 = serving_metrics.stats().get("prefix.evictions", 0)
        pb = _prompt(rng, 24)
        rb = a.submit(pb, max_new_tokens=16)  # needs 5 of 6 blocks
        a.run_until_idle()
        assert rb.state == RequestState.FINISHED
        np.testing.assert_array_equal(rb.output_ids(), _ref(model, pb, 16))
        assert serving_metrics.stats().get("prefix.evictions", 0) > ev0
        np.testing.assert_array_equal(ra.output_ids(), _ref(model, pa, 8))
        a.engine.check_invariants()
    finally:
        a.close()


def test_can_admit_never_spends_own_matched_blocks_as_eviction_headroom(
        model):
    """A request whose matched prefix is resident at refcount zero pins
    those blocks (ref before reserve) when admitted — so can_admit() must
    not count them as evictable headroom. Double-counting made can_admit
    say yes, admit() raise ArenaExhaustedError, and the scheduler FAIL a
    request that should simply have waited for capacity."""
    a = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=48,
                   num_blocks=7, prefix_cache=True)  # 6 allocatable
    try:
        rng = np.random.default_rng(21)
        pa = _prompt(rng, 24)
        ra = a.submit(pa, max_new_tokens=8)   # 4 blocks; caches 3
        a.run_until_idle()
        assert a.engine.arena.blocks_cached() == 3
        pb = _prompt(rng, 8)
        rb = a.submit(pb, max_new_tokens=16)  # reserves the other 3
        a._pump_once()
        assert rb.state == RequestState.RUNNING
        pc = np.concatenate([pa, _prompt(rng, 8)])  # matched prefix = 3
        eng = a.engine
        need = eng.admit_blocks_needed(32, 8, prompt=pc)
        # grantable alone (free + evictable) would cover the suffix need —
        # exactly the double-count: the 3 evictable blocks ARE the match
        assert eng.arena.grantable() >= need
        assert eng.admit_sizing(32, 8, prompt=pc)[1] == 3  # pinned-on-admit
        assert not eng.can_admit(32, 8, prompt=pc)
        rc = a.submit(pc, max_new_tokens=8)
        a.run_until_idle()  # admits only once rb retires — never FAILs
        for r in (ra, rb, rc):
            assert r.state == RequestState.FINISHED
        np.testing.assert_array_equal(rc.output_ids(), _ref(model, pc, 8))
        eng.check_invariants()
    finally:
        a.close()


def test_cache_affinity_bounded_head_of_line_skips(model):
    """Cache-preferred admission: a same-priority cache-warm waiter may be
    admitted ahead of a cache-cold head, but only
    FLAGS_serving_cache_affinity times — the cold head is then served
    before any further warm traffic (no starvation)."""
    keep = paddle.get_flags(
        "serving_cache_affinity")["serving_cache_affinity"]
    paddle.set_flags({"serving_cache_affinity": 1})
    a = ServingAPI(model, num_slots=1, kv_block_size=8, max_model_len=MAX_LEN,
                   prefix_cache=True)
    try:
        rng = np.random.default_rng(13)
        warm_prefix = _prompt(rng, 16)
        seed_req = a.submit(warm_prefix, max_new_tokens=4)  # makes it warm
        a.run_until_idle()
        assert seed_req.state == RequestState.FINISHED
        blocker = a.submit(_prompt(rng, 8), max_new_tokens=8)
        a._pump_once()
        assert blocker.state == RequestState.RUNNING
        cold = a.submit(_prompt(rng, 8), max_new_tokens=4)
        w1 = a.submit(np.concatenate([warm_prefix, _prompt(rng, 4)]),
                      max_new_tokens=4)
        w2 = a.submit(np.concatenate([warm_prefix, _prompt(rng, 4)]),
                      max_new_tokens=4)
        a.run_until_idle()
        for r in (blocker, cold, w1, w2):
            assert r.state == RequestState.FINISHED
        # w1 jumped the cold head once; the spent window then forces the
        # cold head in before w2, despite w2 being warm too
        assert cold._cache_skips == 1
        assert blocker._admit_seq < w1._admit_seq < cold._admit_seq \
            < w2._admit_seq
    finally:
        a.close()
        paddle.set_flags({"serving_cache_affinity": keep})


def test_flag_off_keeps_engine_cache_free(model):
    """FLAGS_serving_prefix_cache=0 (the default here): no tree, no
    refs, worst-case reservations — the exact pre-cache engine."""
    a = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=MAX_LEN)
    try:
        eng = a.engine
        assert eng.prefix_cache is None
        p = np.arange(12, dtype=np.int32)
        assert eng.admit_sizing(12, 8, prompt=p) \
            == (eng.blocks_needed(12, 8), 0)
        assert eng.admit_blocks_needed(12, 8, prompt=p) \
            == eng.blocks_needed(12, 8)
    finally:
        a.close()

"""Process-isolated replica fleet (ISSUE 18): RPC framing + error
taxonomy round-trip, framing fuzz → classified ``WorkerProtocolError``
ejects (never a hung handle), heartbeat supervision, ``worker_kill`` /
``worker_hang`` chaos recovery with token parity + contiguous span
timelines + zero leaked tenant slots, and orphan reaping on close.

The worker model is a MODULE-LEVEL factory: spawn ships it by reference
(module + qualname), so each worker process rebuilds its own instance —
``paddle.seed(0)`` inside the factory keeps every process's weights (and
therefore greedy decodes) identical, which is what makes cross-process
re-route parity a meaningful assertion.
"""
import os
import socket
import struct
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flags as core_flags
from paddle_tpu.core import resilience
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import telemetry
from paddle_tpu.serving import metrics as serving_metrics
from paddle_tpu.serving.gateway import (
    ProcessReplicaPool,
    WorkerDiedError,
    WorkerHandle,
    WorkerProtocolError,
)
from paddle_tpu.serving.gateway import worker as worker_mod
from paddle_tpu.serving.scheduler import RequestState

pytestmark = [pytest.mark.serving, pytest.mark.gateway]

MAX_LEN = 64
POOL_KW = dict(num_slots=4, kv_block_size=8, max_model_len=MAX_LEN)


def worker_model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return worker_model()


@pytest.fixture
def flag_guard():
    snap = core_flags.all_flags()
    yield
    core_flags.set_flags(snap)
    resilience.clear_faults()


def _mk_pool(**kw):
    base = dict(replicas=2, background=True, respawn_backoff=0.5,
                heartbeat_interval=0.2, heartbeat_misses=5,
                worker_timeout=10.0, **POOL_KW)
    base.update(kw)
    return ProcessReplicaPool(worker_model, **base)


def _prompt(rng, n=8):
    return rng.integers(0, 1024, (n,), dtype=np.int32)


def _ref(model, prompt, max_new, stop=None):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=max_new, stop_token_id=stop)
    return np.asarray(out._data)[0]


# ------------------------------------------------------------- framing unit


def test_frame_roundtrip():
    a, b = socket.socketpair()
    try:
        msg = {"op": "poll", "id": 7, "reqs": {"0.1": 3},
               "text": "héllo"}
        worker_mod.send_frame(a, msg)
        assert worker_mod.recv_frame(b) == msg
        # clean EOF at a frame boundary is None, not an error
        a.close()
        assert worker_mod.recv_frame(b) is None
    finally:
        b.close()


def test_send_frame_rejects_oversized():
    a, b = socket.socketpair()
    try:
        with pytest.raises(worker_mod.FrameError):
            worker_mod.send_frame(
                a, {"blob": "x" * (worker_mod._MAX_FRAME + 1)})
    finally:
        a.close()
        b.close()


def test_error_taxonomy_roundtrip():
    for exc in (resilience.QueueOverloadError("full"),
                resilience.RequestDrainedError("drained"),
                resilience.DeadlineExceededError("late"),
                resilience.ServingDeviceError("chip pulled"),
                resilience.ArenaCorruptError("bad arena"),
                ValueError("bad journal")):
        back = worker_mod.decode_error(worker_mod.encode_error(exc))
        assert type(back) is type(exc)
        assert str(exc) in str(back)
    # unknown types decode as RuntimeError: NOT re-routable, so a novel
    # worker failure fails the stream loudly instead of bouncing forever
    weird = worker_mod.decode_error({"type": "SegfaultGremlin",
                                     "message": "boom"})
    assert type(weird) is RuntimeError
    assert "boom" in str(weird)


# ------------------------------------------------------------ framing fuzz


def _fuzz_handle():
    """A WorkerHandle over a socketpair with no real worker behind it —
    the reader thread and RPC plumbing are real, the peer is the fuzzer."""
    ours, theirs = socket.socketpair()
    handle = WorkerHandle(idx=0, conn=ours, proc=None, pid=0,
                          num_slots=4, vocab=1024,
                          call_timeout=5.0, hb_interval=0.2)
    return handle, theirs


@pytest.mark.parametrize("junk", [
    struct.pack(">I", 100) + b"abc",            # truncated mid-frame
    struct.pack(">I", worker_mod._MAX_FRAME + 1),   # oversized prefix
    struct.pack(">I", 0),                       # zero-length frame
    struct.pack(">I", 5) + b"\xff\xfe\xfd\xfc\xfb",  # not JSON
    struct.pack(">I", 4) + b"[1]\n",            # JSON but not an object
], ids=["truncated", "oversized", "zero", "garbage", "non-object"])
def test_framing_fuzz_classifies_protocol_error(junk):
    before = resilience._counts.get("worker.protocol_errors", 0)
    handle, peer = _fuzz_handle()
    try:
        peer.sendall(junk)
        peer.close()
        deadline = time.monotonic() + 5.0
        while handle._dead is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert isinstance(handle._dead, WorkerProtocolError), handle._dead
        assert resilience._counts.get("worker.protocol_errors", 0) > before
        # the reader thread exits — a corrupt stream never leaves a
        # spinning/hung pump behind
        handle._thread.join(2.0)
        assert not handle._thread.is_alive()
        # and the dead handle refuses instantly instead of hanging
        with pytest.raises(WorkerProtocolError):
            handle._call("stats", {})
    finally:
        handle.mark_dead(WorkerDiedError("test cleanup"))


def test_fuzz_fails_pending_call_and_requests_fast():
    handle, peer = _fuzz_handle()
    try:
        # a live request that must NOT leak when the stream corrupts
        req = None
        with handle._lock:
            from paddle_tpu.serving.gateway.procpool import RemoteRequest
            req = RemoteRequest(handle, "0.1", "r1", "t1", None)
            handle._reqs["0.1"] = req
        results = []

        def call():
            try:
                handle._call("stats", {}, timeout=30.0)
                results.append("returned")
            except BaseException as e:
                results.append(e)

        t = threading.Thread(target=call, daemon=True)
        t.start()
        worker_mod.recv_frame(peer)  # drain the call (no RST on close)
        peer.sendall(struct.pack(">I", 64) + b"short")
        peer.shutdown(socket.SHUT_WR)  # FIN: EOF mid-frame, not reset
        t.join(5.0)  # must fail FAR before the 30s call budget
        assert not t.is_alive()
        assert len(results) == 1
        assert isinstance(results[0], WorkerProtocolError)
        # the registered request was failed re-routably, not stranded
        assert req.finished
        assert req.state == RequestState.FAILED
        assert isinstance(req.error, WorkerProtocolError)
        assert handle.outstanding() == 0
    finally:
        handle.mark_dead(WorkerDiedError("test cleanup"))


def test_rpc_deadline_classifies_silent_worker():
    handle, peer = _fuzz_handle()
    try:
        t0 = time.monotonic()
        with pytest.raises(WorkerDiedError):
            handle._call("stats", {}, timeout=0.3)  # peer never answers
        assert time.monotonic() - t0 < 3.0
        assert isinstance(handle._dead, WorkerDiedError)
    finally:
        peer.close()
        handle.mark_dead(WorkerDiedError("test cleanup"))


def test_busy_poll_tolerated_while_heartbeating():
    """A poll that blows its deadline on a live, fresh-heartbeating
    worker is BUSY, not hung: tolerated and retried, no eject — until
    hb_misses consecutive busy cycles prove the main loop is wedged."""
    import types

    from paddle_tpu.serving.gateway.procpool import RemoteRequest

    ours, theirs = socket.socketpair()
    handle = WorkerHandle(idx=0, conn=ours,
                          proc=types.SimpleNamespace(
                              is_alive=lambda: True, pid=12345,
                              exitcode=None, join=lambda t=None: None,
                              kill=lambda: None),
                          pid=12345, num_slots=4, vocab=1024,
                          call_timeout=5.0, hb_interval=0.05, hb_misses=3)
    req = RemoteRequest(handle, "0.1", "r1", "", None)
    with handle._lock:
        handle._reqs["0.1"] = req
    busy0 = resilience._counts.get("worker.busy_polls", 0)
    hangs0 = resilience._counts.get("worker.hangs", 0)
    wl = threading.Lock()  # feeder + responder share the peer socket
    stop = threading.Event()

    def feed_heartbeats():
        # a busy worker's heartbeat THREAD keeps running while the main
        # loop is stuck — that's the condition under test
        while not stop.is_set():
            try:
                worker_mod.send_frame(theirs, {
                    "hb": True, "ts": time.time(), "outstanding": 1,
                    "breaker_open": False, "spans": []}, wl)
            except (worker_mod.FrameError, OSError):
                return
            stop.wait(0.03)

    feeder = threading.Thread(target=feed_heartbeats, daemon=True)
    feeder.start()
    try:
        # two busy cycles: deadline blown, heartbeats fresh -> no eject
        for expect in (1, 2):
            handle.poll()  # peer never answers: returns, doesn't raise
            assert handle._dead is None
            assert handle._busy_polls == expect
        assert resilience._counts.get("worker.busy_polls", 0) == busy0 + 2

        # one answered poll resets the consecutive count
        def respond():
            theirs.settimeout(3.0)
            while True:
                try:
                    msg = worker_mod.recv_frame(theirs)
                except (worker_mod.FrameError, OSError):
                    return
                if msg is None:
                    return
                worker_mod.send_frame(theirs, {
                    "id": msg["id"], "ok": True, "reqs": {},
                    "spans": [], "breaker_open": False,
                    "outstanding": 1}, wl)

        responder = threading.Thread(target=respond, daemon=True)
        responder.start()
        handle.poll()
        assert handle._busy_polls == 0
        responder.join(5.0)

        # wedged for real: hb_misses consecutive busy cycles (heartbeats
        # STILL fresh the whole time) -> eject
        for _ in range(2):
            handle.poll()
        with pytest.raises(WorkerDiedError, match="wedged"):
            handle.poll()
        assert isinstance(handle._dead, WorkerDiedError)
        assert resilience._counts.get("worker.hangs", 0) == hangs0 + 1
        # the stranded request was failed, not leaked
        assert req.state == RequestState.FAILED
    finally:
        stop.set()
        feeder.join(2.0)
        theirs.close()
        handle.mark_dead(WorkerDiedError("test cleanup"))


def test_heartbeat_frame_updates_liveness():
    handle, peer = _fuzz_handle()
    try:
        handle._last_hb = time.monotonic() - 60.0
        assert handle.heartbeat_age() > 59.0
        worker_mod.send_frame(peer, {"hb": True, "ts": time.time(),
                                     "outstanding": 0,
                                     "breaker_open": True, "spans": []})
        deadline = time.monotonic() + 2.0
        while handle.heartbeat_age() > 1.0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert handle.heartbeat_age() < 1.0
        assert handle.supervisor.breaker_open is True
    finally:
        peer.close()
        handle.mark_dead(WorkerDiedError("test cleanup"))


# --------------------------------------------------------- live worker pool


def test_process_pool_token_parity_and_reaping(model):
    rng = np.random.default_rng(0)
    pool = _mk_pool()
    try:
        prompts = [_prompt(rng) for _ in range(4)]
        rrs = [pool.submit(p, max_new_tokens=16) for p in prompts]
        outs = [pool.result(rr, timeout=120.0) for rr in rrs]
        for p, o in zip(prompts, outs):
            assert np.array_equal(o, _ref(model, p, 16))

        st = pool.stats()
        assert st["process_replicas"] is True
        assert len(st["replicas"]) == 2
        for row in st["replicas"]:
            assert row["pid"] > 0
            assert row["restarts"] == 0
            assert row["heartbeat_age_ms"] >= 0.0

        # per-worker remote scrapes carry the worker PROCESS's counters
        ws = pool.worker_stats()
        assert set(ws) == {0, 1}
        for idx, snap in ws.items():
            assert snap["pid"] == st["replicas"][idx]["pid"]
            assert any(k.startswith("engine.")
                       for k in snap["metrics"]), snap["metrics"].keys()
    finally:
        procs = [r.api.proc for r in pool.replicas()]
        pool.close()
    # satellite 2: close() REAPS — no orphan worker survives to hold the
    # compile-cache dir lock
    for proc in procs:
        assert not proc.is_alive()


def test_worker_kill_chaos_recovery(model, flag_guard):
    core_flags.set_flags({"fault_injection": True,
                          "serving_telemetry": True})
    kills0 = resilience._counts.get("worker.kills", 0)
    ejected0 = serving_metrics.stats().get("gateway.ejected", 0)
    rng = np.random.default_rng(1)
    pool = _mk_pool()
    try:
        # warm both workers: compiles land before the chaos window, so the
        # zero-recompile invariant holds across the re-route
        warm = [pool.submit(_prompt(rng), max_new_tokens=4)
                for _ in range(2)]
        for rr in warm:
            pool.result(rr, timeout=120.0)

        prompts = [_prompt(rng) for _ in range(6)]
        rrs = [pool.submit(p, max_new_tokens=40) for p in prompts]
        # chaos: the watchdog's next sweep SIGKILLs a live worker
        resilience.inject_fault("worker_kill", times=1)

        outs = [pool.result(rr, timeout=180.0) for rr in rrs]

        # token parity: journaled streams resumed token-for-token on the
        # survivor — byte-identical to the single-model reference
        for p, o in zip(prompts, outs):
            assert np.array_equal(o, _ref(model, p, 40))

        assert resilience._counts.get("fault.worker_kill", 0) >= 1
        assert resilience._counts.get("worker.kills", 0) > kills0
        assert serving_metrics.stats().get("gateway.ejected", 0) > ejected0

        # one contiguous span timeline per trace_id: SUBMITTED first,
        # FINISHED last, and the killed worker's streams show REROUTED
        # with survivor spans after it
        rerouted = 0
        for rr in rrs:
            kinds = [ev["event"] for ev in telemetry.trace(rr.trace_id)]
            assert kinds[0] == telemetry.SUBMITTED
            assert kinds.count(telemetry.SUBMITTED) == 1
            assert kinds[-1] == telemetry.FINISHED
            if telemetry.REROUTED in kinds:
                rerouted += 1
                assert kinds.index(telemetry.REROUTED) < len(kinds) - 1
        assert rerouted >= 1

        # zero leaked tenant concurrency slots after recovery
        assert pool.stats()["tenants"]["default"]["inflight"] == 0

        # the dead worker respawns (doubled backoff ran its course)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            rows = pool.stats()["replicas"]
            if (all(r["healthy"] for r in rows)
                    and any(r["restarts"] >= 1 for r in rows)):
                break
            time.sleep(0.2)
        rows = pool.stats()["replicas"]
        assert all(r["healthy"] for r in rows), rows
        assert any(r["restarts"] >= 1 for r in rows), rows
    finally:
        pool.close()


def test_worker_hang_chaos_recovery(model, flag_guard):
    core_flags.set_flags({"fault_injection": True})
    hangs0 = resilience._counts.get("worker.hangs", 0)
    rng = np.random.default_rng(2)
    # tight heartbeat budget: 0.1s x 8 misses -> ~0.8s to classify
    pool = _mk_pool(heartbeat_interval=0.1, heartbeat_misses=8,
                    worker_timeout=3.0)
    try:
        warm = [pool.submit(_prompt(rng), max_new_tokens=4)
                for _ in range(2)]
        for rr in warm:
            pool.result(rr, timeout=120.0)

        prompts = [_prompt(rng) for _ in range(4)]
        rrs = [pool.submit(p, max_new_tokens=32) for p in prompts]
        # chaos: a worker stops heartbeating but HOLDS its socket — only
        # heartbeat age (not ECONNRESET) can classify this
        resilience.inject_fault("worker_hang", times=1)

        outs = [pool.result(rr, timeout=180.0) for rr in rrs]
        for p, o in zip(prompts, outs):
            assert np.array_equal(o, _ref(model, p, 32))

        assert resilience._counts.get("fault.worker_hang", 0) >= 1
        assert resilience._counts.get("worker.hangs", 0) > hangs0
        assert pool.stats()["tenants"]["default"]["inflight"] == 0
    finally:
        pool.close()


def test_serve_flag_switches_to_process_pool(flag_guard):
    core_flags.set_flags({"gateway_process_replicas": True})
    from paddle_tpu.serving.gateway import serve

    gw = serve(worker_model, replicas=1, guard=False, **POOL_KW)
    try:
        assert isinstance(gw.pool, ProcessReplicaPool)
        base = f"http://127.0.0.1:{gw.port}"
        stats = urllib.request.urlopen(base + "/v1/stats",
                                       timeout=10).read().decode()
        assert '"process_replicas": true' in stats
        metrics_text = urllib.request.urlopen(base + "/v1/metrics",
                                              timeout=10).read().decode()
        assert "paddle_gateway_worker_pid" in metrics_text
        assert "paddle_gateway_worker_heartbeat_age_ms" in metrics_text
        procs = [r.api.proc for r in gw.pool.replicas()]
    finally:
        gw.close()
    # Gateway.close() -> pool.close() -> reap: no orphans
    for proc in procs:
        assert not proc.is_alive()


def test_default_flag_keeps_thread_pool():
    assert core_flags.flag("gateway_process_replicas") is False
    # the worker fault kinds are registered probes
    assert "worker_kill" in resilience.KNOWN_FAULTS
    assert "worker_hang" in resilience.KNOWN_FAULTS

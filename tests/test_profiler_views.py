"""Profiler summary views + scheduler + trace reload
(ref:python/paddle/profiler/profiler_statistic.py:46 SummaryView,
ref:python/paddle/profiler/profiler.py make_scheduler)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, profiler
from paddle_tpu.profiler import (ProfilerState, RecordEvent, SortedKeys,
                                 SummaryView, load_profiler_result,
                                 make_scheduler)


def _profiled_run(prof):
    m = nn.Linear(8, 8)
    opt = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    x = paddle.randn([4, 8])
    with prof:
        for _ in range(3):
            with RecordEvent("forward"):
                loss = (m(x) ** 2).mean()
            with RecordEvent("backward"):
                loss.backward()
            with RecordEvent("optimizer"):
                opt.step()
                opt.clear_grad()
            prof.step()


def test_summary_views_print_all_sections(capsys):
    prof = profiler.Profiler(profile_memory=True)
    _profiled_run(prof)
    out = prof.summary()
    for section in ("[ Overview", "[ Model", "[ Distributed", "[ Operator",
                    "[ Memory", "[ Scheduling"):
        assert section in out, section
    # stage rows present in the Model view
    assert "Forward" in out and "Backward" in out and "Optimizer" in out
    # memory snapshots recorded per step
    assert len(prof._memory_steps) == 3
    # view selection narrows output
    only_ops = prof.summary(views=SummaryView.OperatorView,
                            sorted_by=SortedKeys.CPUMax)
    assert "[ Operator" in only_ops and "[ Overview" not in only_ops


def test_export_protobuf_roundtrip(tmp_path):
    prof = profiler.Profiler(profile_memory=True)
    _profiled_run(prof)
    path = prof.export_protobuf(str(tmp_path))
    res = load_profiler_result(path)
    assert len(res.events) > 0
    out = res.summary(views=[SummaryView.OverView, SummaryView.MemoryView])
    assert "[ Overview" in out and "[ Memory" in out
    with pytest.raises(ValueError):
        bad = tmp_path / "bad.pt_trace"
        bad.write_bytes(b"nope")
        load_profiler_result(str(bad))


def test_make_scheduler_state_machine():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=2,
                           skip_first=1)
    states = [sched(i) for i in range(10)]
    assert states[0] == ProfilerState.CLOSED          # skip_first
    assert states[1] == ProfilerState.CLOSED          # closed
    assert states[2] == ProfilerState.READY
    assert states[3] == ProfilerState.RECORD
    assert states[4] == ProfilerState.RECORD_AND_RETURN
    assert states[5] == ProfilerState.CLOSED          # cycle 2
    assert states[8] == ProfilerState.RECORD_AND_RETURN
    assert states[9] == ProfilerState.CLOSED          # repeat exhausted


def test_scheduler_gates_recording_and_keeps_step_marks():
    # closed=1, ready=0, record=2: iterations 0 CLOSED, 1-2 RECORD, cycle
    sched = make_scheduler(closed=1, ready=0, record=2)
    prof = profiler.Profiler(scheduler=sched)
    m = nn.Linear(4, 4)
    x = paddle.randn([2, 4])
    with prof:
        for _ in range(6):
            with RecordEvent("forward"):
                m(x)
            prof.step()
    events = prof._events()
    # step boundary markers survive CLOSED windows
    marks = [e for e in events if e["name"].startswith("profiler_step")]
    assert len(marks) == 6
    # iteration 0, 3 are CLOSED -> only 4 of 6 forward scopes recorded
    fwd = [e for e in events if e["name"] == "forward"]
    assert len(fwd) == 4


"""Async / geo-async PS communicators
(ref:paddle/fluid/distributed/ps/service/communicator/communicator.h:427
AsyncCommunicator, :597 GeoCommunicator).

Covers: exact merge math (same-lr linearity), flush barriers, strategy
knob mapping, error surfacing, geo local-replica semantics, multi-worker
geo convergence, and async-vs-sync convergence on the Wide&Deep-tiny head
(the verdict's convergence-within-tolerance requirement).
"""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import ps
from paddle_tpu.distributed.ps import (AsyncCommunicator, GeoCommunicator,
                                       create_communicator)


@pytest.fixture
def cluster():
    svc = ps.start_local_cluster(dim=4, num_shards=2, rule="sgd")
    yield svc
    svc.stop()


def test_async_push_matches_sync_after_flush(cluster):
    """Merged background pushes land the exact same table state as the same
    pushes applied synchronously (SGD is linear in the summed grads)."""
    ids = np.arange(40, dtype=np.uint64)
    sync = cluster.client()
    comm = AsyncCommunicator(cluster.client(), max_merge_var_num=4)
    base = sync.pull(ids).copy()  # materialize rows once

    rng = np.random.RandomState(0)
    expected = base.copy()
    for _ in range(10):
        sel = rng.choice(40, size=16)  # duplicate ids on purpose
        g = rng.randn(16, 4).astype(np.float32)
        comm.push(ids[sel], g, lr=0.1)
        merged = np.zeros((40, 4), np.float32)
        np.add.at(merged, sel, g)
        expected -= 0.1 * merged
    comm.flush()
    np.testing.assert_allclose(sync.pull(ids), expected, rtol=1e-5, atol=1e-6)
    assert comm._sent_batches < 10  # merging actually batched the wire pushes
    comm.stop()
    sync.close()


def test_async_distinct_lrs_not_merged(cluster):
    ids = np.array([5], np.uint64)
    sync = cluster.client()
    base = sync.pull(ids).copy()
    comm = AsyncCommunicator(cluster.client(), max_merge_var_num=8)
    g = np.ones((1, 4), np.float32)
    comm.push(ids, g, lr=0.1)
    comm.push(ids, g, lr=0.3)
    comm.flush()
    np.testing.assert_allclose(sync.pull(ids), base - 0.4, rtol=1e-5)
    comm.stop()
    sync.close()


def test_async_error_surfaces_on_flush():
    svc = ps.start_local_cluster(dim=4, num_shards=1, rule="sgd")
    comm = AsyncCommunicator(svc.client(), max_merge_var_num=1)
    comm.pull(np.array([1], np.uint64))
    svc.stop()  # kill the server under the sender
    comm.push(np.array([1], np.uint64), np.ones((1, 4), np.float32), 0.1)
    with pytest.raises(RuntimeError, match="send failed"):
        comm.flush()


def test_create_communicator_strategy_mapping(cluster):
    from paddle_tpu.distributed import fleet

    s = fleet.DistributedStrategy()
    assert create_communicator(cluster.client(), s) .__class__.__name__ \
        == "SparseTableClient"
    s.a_sync = True
    c1 = create_communicator(cluster.client(), s)
    assert isinstance(c1, AsyncCommunicator)
    s.a_sync_configs["k_steps"] = 800
    c2 = create_communicator(cluster.client(), s)
    assert isinstance(c2, GeoCommunicator)
    c1.stop()
    c2.stop()


def test_geo_local_replica_and_delta_sync(cluster):
    """Pushes apply to the local replica instantly; the server only sees
    them after geo_need_push_nums dirty ids accumulate (or flush)."""
    obs = cluster.client()
    geo = GeoCommunicator(cluster.client(), geo_need_push_nums=1000)
    ids = np.array([1, 2, 3], np.uint64)
    before = obs.pull(ids).copy()
    geo.pull(ids)
    g = np.ones((3, 4), np.float32)
    geo.push(ids, g, lr=0.5)
    # local replica moved...
    np.testing.assert_allclose(geo.pull(ids), before - 0.5, rtol=1e-5)
    # ...server has not (below the push threshold)
    np.testing.assert_allclose(obs.pull(ids), before, rtol=1e-6)
    geo.flush()
    np.testing.assert_allclose(obs.pull(ids), before - 0.5, rtol=1e-5)
    geo.stop()
    obs.close()


def test_geo_two_workers_see_each_other(cluster):
    """After both workers sync, each replica reflects the other's deltas."""
    a = GeoCommunicator(cluster.client(), geo_need_push_nums=1000)
    b = GeoCommunicator(cluster.client(), geo_need_push_nums=1000)
    ids = np.array([7], np.uint64)
    base = cluster.client().pull(ids).copy()
    a.pull(ids), b.pull(ids)
    a.push(ids, np.full((1, 4), 1.0, np.float32), lr=0.1)
    b.push(ids, np.full((1, 4), 1.0, np.float32), lr=0.2)
    a.flush(), b.flush()
    # refresh each replica (next threshold sync would; force via flush+pull
    # of an evicted row path: push a no-op delta and flush)
    a.push(ids, np.zeros((1, 4), np.float32), lr=0.0)
    a.flush()
    np.testing.assert_allclose(a.pull(ids), base - 0.3, rtol=1e-5)
    a.stop(), b.stop()


class _GatedClient:
    """Client wrapper whose push blocks until the test opens a gate —
    deterministically piles sync batches up in the geo queue."""

    def __init__(self, client):
        self._c = client
        self.gate = threading.Event()

    def push(self, ids, grads, lr):
        self.gate.wait(timeout=30)
        return self._c.push(ids, grads, lr)

    def __getattr__(self, name):
        return getattr(self._c, name)


def test_geo_queued_batches_not_unapplied(cluster):
    """A landing sync must not restore server rows that un-apply updates
    sitting in still-queued delta batches (the in-flight ledger)."""
    gated = _GatedClient(cluster.client())
    geo = GeoCommunicator(gated, geo_need_push_nums=1, send_queue_size=8)
    ids = np.array([42], np.uint64)
    base = cluster.client().pull(ids).copy()
    geo.pull(ids)
    g = np.ones((1, 4), np.float32)
    geo.push(ids, g, lr=0.1)   # batch A: queued, sync blocked at the gate
    geo.push(ids, g, lr=0.2)   # batch B: second swap while A is in flight
    local = geo.pull(ids)
    np.testing.assert_allclose(local, base - 0.3, rtol=1e-5)
    gated.gate.set()           # let A (then B) land
    geo.flush()
    # replica must still hold BOTH updates, before and after the syncs
    np.testing.assert_allclose(geo.pull(ids), base - 0.3, rtol=1e-5)
    np.testing.assert_allclose(cluster.client().pull(ids), base - 0.3,
                               rtol=1e-5)
    assert not geo._inflight  # ledger fully retired
    geo.stop()


def _train_widedeep_head(comm, steps=60, lr_emb=0.5):
    """Tiny Wide&Deep-style PS loop: PSEmbedding + dense head."""
    from paddle_tpu.distributed.ps import PSEmbedding
    from paddle_tpu import nn

    paddle.seed(0)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 5000, size=(64, 4)).astype(np.int64)
    w = rng.randn(4 * 4, 1).astype(np.float32)
    emb0 = PSEmbedding(comm, learning_rate=lr_emb)
    # labels from a fixed projection of the (deterministic) initial rows
    feats0 = emb0.forward(paddle.to_tensor(ids)).numpy().reshape(64, -1)
    y = paddle.to_tensor((feats0 @ w > 0).astype(np.float32))

    head = nn.Linear(4 * 4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.2, parameters=head.parameters())
    losses = []
    for _ in range(steps):
        feats = emb0.forward(paddle.to_tensor(ids))
        logits = head(feats.reshape((64, -1)))
        loss = nn.functional.binary_cross_entropy_with_logits(logits, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def test_widedeep_async_converges_like_sync():
    """Verdict item 3 acceptance: async & geo training converge within
    tolerance of the synchronous run on the Wide&Deep-tiny loop."""
    results = {}
    for mode in ("sync", "async", "geo"):
        svc = ps.start_local_cluster(dim=4, num_shards=2, rule="sgd")
        try:
            comm = create_communicator(
                svc.client(), mode=mode,
                max_merge_var_num=4, geo_need_push_nums=50)
            results[mode] = _train_widedeep_head(comm)
            if mode != "sync":
                comm.stop()
        finally:
            svc.stop()
    for mode in ("async", "geo"):
        # same data, same seed: staleness may wiggle the path, the endpoint
        # must land in the same place
        assert results[mode][-1] < results[mode][0], mode
        assert abs(results[mode][-1] - results["sync"][-1]) \
            <= 0.15 * results["sync"][0] + 0.02, (
                mode, results[mode][-1], results["sync"][-1])


def test_geo_concurrent_workers_converge(cluster):
    """Two geo workers training concurrently (threads) both drive the
    shared table; no crashes, finite losses, both improve."""
    out = {}

    def worker(name, seed):
        comm = GeoCommunicator(cluster.client(), geo_need_push_nums=20)
        rng = np.random.RandomState(seed)
        ids = np.arange(200, dtype=np.uint64)
        target = rng.randn(200, 4).astype(np.float32) * 0.05
        losses = []
        for _ in range(40):
            sel = rng.choice(200, 64)
            rows = comm.pull(ids[sel])
            err = rows - target[sel]
            losses.append(float((err ** 2).mean()))
            comm.push(ids[sel], 2 * err / len(sel), lr=0.5)
        comm.stop()
        out[name] = losses

    ts = [threading.Thread(target=worker, args=(f"w{i}", i)) for i in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    for name, losses in out.items():
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], name


def _geo_spawn_worker(endpoints):
    """Each spawned PROCESS trains its own geo replica against the shared
    server cluster — true process isolation, not threads."""
    import numpy as np

    from paddle_tpu.distributed import ps
    from paddle_tpu.distributed.ps import GeoCommunicator
    import paddle_tpu.distributed as dist

    rank = dist.get_rank()
    comm = GeoCommunicator(ps.SparseTableClient(endpoints, dim=4),
                           geo_need_push_nums=20)
    rng = np.random.RandomState(rank)
    ids = np.arange(100, dtype=np.uint64)
    losses = []
    target = np.full((100, 4), 0.05, np.float32)
    for _ in range(30):
        sel = rng.choice(100, 32)
        rows = comm.pull(ids[sel])
        err = rows - target[sel]
        losses.append(float((err ** 2).mean()))
        comm.push(ids[sel], 2 * err / len(sel), lr=0.5)
    comm.stop()
    return losses[0], losses[-1]


def test_geo_across_spawned_processes():
    from paddle_tpu.distributed.spawn import spawn

    svc = ps.start_local_cluster(dim=4, num_shards=2, rule="sgd")
    try:
        results = spawn(_geo_spawn_worker, args=(svc.endpoints,), nprocs=2)
        for first, last in results:
            assert np.isfinite(first) and np.isfinite(last)
            assert last < first  # both processes' replicas improved
        # the SHARED table converged toward the target too
        rows = svc.client().pull(np.arange(100, dtype=np.uint64))
        assert abs(float(rows.mean()) - 0.05) < 0.05
    finally:
        svc.stop()

"""Sparse embedding parameter-service tests.

Models the reference PS test pattern (server+client on one host,
ref:paddle/fluid/distributed/ps/ + test/ps/): in-process C++ table servers,
sharded client routing, server-side optimizer rules, save/load, and the
PS-mode Wide&Deep end-to-end training path.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import ps


@pytest.fixture
def cluster():
    svc = ps.start_local_cluster(dim=8, num_shards=3, rule="sgd")
    yield svc
    svc.stop()


def test_pull_lazy_init_deterministic(cluster):
    c = cluster.client()
    ids = np.array([1, 2, 3, 1 << 40], np.uint64)
    rows1 = c.pull(ids)
    rows2 = c.pull(ids)
    np.testing.assert_array_equal(rows1, rows2)  # init once, stable
    assert rows1.shape == (4, 8)
    assert np.abs(rows1).max() <= 0.01 + 1e-6
    assert not np.allclose(rows1[0], rows1[1])  # per-id streams differ
    rows, nbytes = c.stats()
    # row = 3 meta floats (tick/show/click) + 8 embedding floats
    assert rows == 4 and nbytes == 4 * (3 + 8) * 4
    c.close()


def test_push_sgd_rule(cluster):
    c = cluster.client()
    ids = np.array([7, 8], np.uint64)
    before = c.pull(ids)
    g = np.full((2, 8), 2.0, np.float32)
    c.push(ids, g, lr=0.25)
    after = c.pull(ids)
    np.testing.assert_allclose(before - after, np.full((2, 8), 0.5), rtol=1e-6)
    c.close()


def test_adagrad_rule_matches_numpy():
    svc = ps.start_local_cluster(dim=4, num_shards=1, rule="adagrad")
    try:
        c = svc.client()
        ids = np.array([3], np.uint64)
        w = c.pull(ids).copy()
        acc = np.zeros((1, 4), np.float32)
        for step in range(3):
            g = np.full((1, 4), 0.5 * (step + 1), np.float32)
            c.push(ids, g, lr=0.1)
            acc += g * g
            w -= 0.1 * g / (np.sqrt(acc) + 1e-8)
        np.testing.assert_allclose(c.pull(ids), w, rtol=1e-5)
        c.close()
    finally:
        svc.stop()


def test_adam_rule_matches_numpy():
    svc = ps.start_local_cluster(dim=4, num_shards=1, rule="adam")
    try:
        c = svc.client()
        ids = np.array([11], np.uint64)
        w = c.pull(ids).copy()
        m = np.zeros((1, 4), np.float32)
        v = np.zeros((1, 4), np.float32)
        b1, b2, eps = 0.9, 0.999, 1e-8
        for step in range(1, 4):
            g = np.full((1, 4), 0.3, np.float32)
            c.push(ids, g, lr=0.01)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            w -= 0.01 * (m / (1 - b1 ** step)) / (np.sqrt(v / (1 - b2 ** step)) + eps)
        np.testing.assert_allclose(c.pull(ids), w, rtol=1e-4)
        c.close()
    finally:
        svc.stop()


def test_save_load_roundtrip(cluster, tmp_path):
    c = cluster.client()
    ids = np.arange(100, dtype=np.uint64)
    rows = c.pull(ids)
    c.push(ids, np.ones((100, 8), np.float32), lr=0.1)
    trained = c.pull(ids)
    prefix = str(tmp_path / "table")
    c.save(prefix)
    c.clear()
    assert c.stats()[0] == 0
    c.load(prefix)
    np.testing.assert_array_equal(c.pull(ids), trained)
    assert not np.allclose(trained, rows)
    c.close()


def test_ps_embedding_layer_trains(cluster):
    """PS-mode training loop: pull -> device step -> push; loss decreases."""
    from paddle_tpu.distributed.ps import PSEmbedding

    emb = PSEmbedding(cluster.client(), learning_rate=0.5)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1 << 30, size=(32, 4)).astype(np.int64)
    # target depends on the ids through a fixed random projection
    labels = paddle.to_tensor(
        (rng.rand(32, 1) > 0.5).astype(np.float32))

    head = paddle.nn.Linear(4 * 8, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.3, parameters=head.parameters())
    losses = []
    for _ in range(40):
        e = emb(paddle.to_tensor(ids))          # [32, 4, 8] pulled rows
        flat = paddle.reshape(e, [32, -1])
        logits = head(flat)
        loss = paddle.nn.functional.binary_cross_entropy_with_logits(
            logits, labels, reduction="mean")
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.7, losses[::10]
    rows, _ = emb.client.stats()
    assert rows == len(np.unique(ids))  # lazy rows: only touched ids exist


def test_widedeep_ps_mode(cluster):
    """Wide&Deep with host-RAM PS tables: the VERDICT 'bigger than HBM' path
    (capacity bounded by host RAM; no vocab declared at build time)."""
    from paddle_tpu.distributed.ps import PSEmbedding
    from paddle_tpu.models.widedeep import WideDeep

    wide_svc = ps.start_local_cluster(dim=1, num_shards=2)
    try:
        model = WideDeep(
            num_fields=6, num_dense=4, hidden_sizes=(32, 16),
            sparse_embedding=PSEmbedding(cluster.client(), learning_rate=0.2),
            wide_embedding=PSEmbedding(wide_svc.client(), learning_rate=0.2),
            embedding_dim=8)
        dense_params = [p for p in model.parameters()]
        opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=dense_params)
        rng = np.random.RandomState(1)
        # feature hashes from the full 64-bit space (no bucket bound)
        sparse = rng.randint(0, 1 << 62, size=(64, 6)).astype(np.int64)
        dense = rng.rand(64, 4).astype(np.float32)
        w = rng.rand(4)
        labels = ((dense @ w) > w.sum() / 2).astype(np.float32)[:, None]

        losses = []
        for _ in range(30):
            logits = model(paddle.to_tensor(sparse), paddle.to_tensor(dense))
            loss = model.loss(logits, paddle.to_tensor(labels))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.8, losses[::10]
    finally:
        wide_svc.stop()


_SERVER_SCRIPT = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from paddle_tpu.distributed import ps
srv = ps.run_server(dim=8, port=0, rule="sgd")
print(srv.port, flush=True)
sys.stdin.readline()  # block until parent closes stdin
srv.stop()
"""


def test_cross_process_server():
    """Server in a separate OS process (the real deployment shape)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")
    proc = subprocess.Popen([sys.executable, "-c", _SERVER_SCRIPT],
                            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                            env=env, text=True)
    try:
        port = int(proc.stdout.readline().strip())
        client = ps.SparseTableClient([f"127.0.0.1:{port}"], dim=8)
        ids = np.array([42, 43], np.uint64)
        rows = client.pull(ids)
        client.push(ids, np.ones((2, 8), np.float32), lr=1.0)
        after = client.pull(ids)
        np.testing.assert_allclose(rows - after, 1.0, rtol=1e-6)
        client.close()
    finally:
        proc.stdin.close()
        proc.wait(timeout=10)

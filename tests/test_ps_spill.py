"""Beyond-RAM sparse table: spill tier, LRU page-out/page-in, CTR-accessor
eviction (ref:paddle/fluid/distributed/ps/table/ssd_sparse_table.cc,
ctr_accessor.cc)."""
import os

import numpy as np
import pytest

from paddle_tpu.distributed import ps


def _push_ids(client, ids, dim, lr=0.1):
    grads = np.ones((len(ids), dim), np.float32)
    client.push(ids, grads, lr)


def test_spill_pageout_and_pagein_roundtrip(tmp_path):
    dim = 16
    # adagrad row = 3 meta + 16 emb + 16 acc = 35 floats = 140B (+64B est)
    svc = ps.EmbeddingService(dim, num_shards=2, rule="adagrad",
                              ram_cap_bytes=600_000,
                              spill_dir=str(tmp_path))
    try:
        client = svc.client()
        rng = np.random.default_rng(0)
        n_ids = 20_000  # ~4MB of rows >> 600KB cap
        all_ids = rng.choice(2**50, size=n_ids, replace=False).astype(np.uint64)
        # push a known gradient so row values are deterministic: after one
        # adagrad step w = init - lr*g/(sqrt(g^2)+eps) = init - lr*sign(g)
        for i in range(0, n_ids, 2000):
            _push_ids(client, all_ids[i:i + 2000], dim)
        st = client.tier_stats()
        assert st["spill_rows"] > 0, st            # spill engaged
        assert st["pageouts"] > 0
        assert st["mem_bytes"] <= 2 * 600_000, st  # resident tier bounded
        assert st["mem_rows"] + st["spill_rows"] == n_ids
        # spilled rows page back in with their trained values intact
        probe = all_ids[:128]  # the earliest-pushed = most likely spilled
        rows = client.pull(probe)
        expect_delta = -0.1  # one adagrad step of the all-ones gradient
        # re-derive init deterministically by pulling a FRESH id
        st2 = client.tier_stats()
        assert st2["pageins"] > 0, st2
        assert np.all(np.abs(rows - expect_delta) < 0.02), rows[:2]
        # save/load includes spilled rows
        path = str(tmp_path / "ckpt")
        client.save(path)
        total_before = client.stats()[0]
        client.clear()
        assert client.stats()[0] == 0
        client.load(path)
        assert client.stats()[0] == total_before
        rows2 = client.pull(probe)
        assert np.allclose(rows, rows2, atol=1e-6)
        client.close()
    finally:
        svc.stop()


def test_ctr_accessor_shrink_evicts_cold_keeps_hot(tmp_path):
    dim = 8
    svc = ps.EmbeddingService(dim, num_shards=1, rule="sgd",
                              show_coeff=0.25, click_coeff=1.0)
    try:
        client = svc.client()
        hot = np.arange(100, dtype=np.uint64)
        cold = np.arange(1000, 1100, dtype=np.uint64)
        _push_ids(client, hot, dim)
        _push_ids(client, cold, dim)
        # hot ids get clicks; cold ids only the single push impression
        client.show_click(hot, np.full(100, 5.0, np.float32),
                          np.full(100, 2.0, np.float32))
        # score(hot) = 0.25*(1+5) + 1.0*2 = 3.5; score(cold) = 0.25
        evicted = client.shrink(threshold=1.0, decay=1.0)
        assert evicted == 100, evicted
        assert client.stats()[0] == 100
        st = client.tier_stats()
        assert st["evicted"] == 100
        # decay drives even hot rows below threshold eventually
        for _ in range(40):
            ev = client.shrink(threshold=1.0, decay=0.7)
            if client.stats()[0] == 0:
                break
        assert client.stats()[0] == 0
        client.close()
    finally:
        svc.stop()


def test_shrink_max_unseen_evicts_stale_spilled_rows(tmp_path):
    dim = 8
    svc = ps.EmbeddingService(dim, num_shards=1, rule="sgd",
                              ram_cap_bytes=100_000,
                              spill_dir=str(tmp_path))
    try:
        client = svc.client()
        stale = np.arange(5000, dtype=np.uint64)
        _push_ids(client, stale, dim)
        # advance the access clock far past the stale rows
        fresh = np.arange(10**6, 10**6 + 200, dtype=np.uint64)
        for _ in range(50):
            client.pull(fresh)
        st = client.tier_stats()
        assert st["spill_rows"] > 0
        evicted = client.shrink(threshold=-1.0, max_unseen=40, decay=1.0)
        assert evicted >= len(stale) * 0.9, (evicted, st)
        st2 = client.tier_stats()
        assert st2["spill_rows"] < st["spill_rows"]
        client.close()
    finally:
        svc.stop()


def test_checkpoint_roundtrip_across_spill_configs(tmp_path):
    # v2 roundtrip across differently-configured servers (no spill -> spill)
    dim = 8
    svc1 = ps.EmbeddingService(dim, num_shards=1, rule="sgd")
    c1 = svc1.client()
    ids = np.arange(5000, dtype=np.uint64)
    _push_ids(c1, ids, dim)
    vals = c1.pull(ids)
    c1.save(str(tmp_path / "t"))
    c1.close()
    svc1.stop()

    svc2 = ps.EmbeddingService(dim, num_shards=1, rule="sgd",
                               ram_cap_bytes=10_000,
                               spill_dir=str(tmp_path))
    c2 = svc2.client()
    c2.load(str(tmp_path / "t"))
    assert c2.stats()[0] == 5000
    st = c2.tier_stats()
    assert st["spill_rows"] > 0  # load respects the RAM cap by paging out
    assert np.allclose(c2.pull(ids), vals, atol=1e-6)
    c2.close()
    svc2.stop()


def test_v1_pre_meta_checkpoint_loads(tmp_path):
    """Hand-written v1-format file (pre-meta rows, old magic): the
    back-compat Load branch must place values at the post-meta offset."""
    import struct

    dim = 4
    n = 10
    path = str(tmp_path / "old.ckpt.shard0")
    rows = np.arange(n * dim, dtype=np.float32).reshape(n, dim)
    with open(path, "wb") as f:
        f.write(struct.pack("<QQQQQ", 0x70747370_61727365, dim, 0, dim, n))
        for i in range(n):
            f.write(struct.pack("<Q", i))
            f.write(rows[i].tobytes())
    svc = ps.EmbeddingService(dim, num_shards=1, rule="sgd")
    try:
        c = svc.client()
        c.load(str(tmp_path / "old.ckpt"))
        assert c.stats()[0] == n
        got = c.pull(np.arange(n, dtype=np.uint64))
        assert np.allclose(got, rows, atol=1e-6), got
        c.close()
    finally:
        svc.stop()


def test_spill_open_failure_fails_server_start(tmp_path):
    with pytest.raises(RuntimeError, match="failed to start"):
        ps.EmbeddingServer(8, ram_cap_bytes=1000,
                           spill_path=str(tmp_path / "no_dir" / "x.spill"))


def test_spill_path_without_cap_rejected():
    with pytest.raises(ValueError, match="ram_cap_bytes"):
        ps.EmbeddingServer(8, spill_path="/tmp/x.spill")
    with pytest.raises(ValueError, match="spill_path"):
        ps.EmbeddingServer(8, ram_cap_bytes=1000)


def test_shrink_concurrent_tick_no_underflow(tmp_path):
    # rows accessed AFTER shrink snapshots its clock must not be evicted
    # as "maximally stale" (uint32 wraparound guard)
    dim = 8
    svc = ps.EmbeddingService(dim, num_shards=1, rule="sgd")
    try:
        client = svc.client()
        ids = np.arange(200, dtype=np.uint64)
        _push_ids(client, ids, dim)
        # freshly-touched rows, tiny max_unseen: nothing should be evicted
        client.pull(ids)
        ev = client.shrink(threshold=-1.0, max_unseen=1000, decay=1.0)
        assert ev == 0, ev
        client.close()
    finally:
        svc.stop()


def test_concurrent_pull_push_shrink_chunked_locks(tmp_path):
    """VERDICT r3 weak-6: shrink must not hold a shard lock across file I/O
    of the whole spill tier. With a multi-thousand-row spilled tier, pulls
    issued WHILE shrink runs must keep completing quickly (chunked locks);
    the test also hammers push/pull/shrink concurrently for races."""
    import threading
    import time

    import numpy as np

    from paddle_tpu.distributed import ps

    svc = ps.EmbeddingService(dim=32, num_shards=1, rule="sgd",
                              ram_cap_bytes=600_000,
                              spill_dir=str(tmp_path))
    try:
        grow = svc.client()
        # grow the table well past the cap -> thousands of spilled rows
        for i in range(40):
            ids = np.arange(i * 2000, (i + 1) * 2000, dtype=np.uint64)
            grow.pull(ids)
        st = grow.tier_stats()
        assert st["spill_rows"] > 10_000, st

        stop = threading.Event()
        errors = []
        pull_lat = []

        def puller():
            try:
                c = svc.client()
                rng = np.random.RandomState(1)
                while not stop.is_set():
                    ids = rng.randint(0, 80_000, 64).astype(np.uint64)
                    t0 = time.perf_counter()
                    c.pull(ids)
                    pull_lat.append(time.perf_counter() - t0)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(("puller", repr(e)))

        def pusher():
            try:
                c = svc.client()
                rng = np.random.RandomState(2)
                g = np.ones((64, 32), np.float32)
                while not stop.is_set():
                    ids = rng.randint(0, 80_000, 64).astype(np.uint64)
                    c.push(ids, g, lr=0.01)
            except BaseException as e:  # noqa: BLE001
                errors.append(("pusher", repr(e)))

        threads = [threading.Thread(target=puller),
                   threading.Thread(target=pusher)]
        [t.start() for t in threads]
        try:
            shr = svc.client()
            total_evicted = 0
            for _ in range(4):  # decay-only shrinks touch every spilled row
                total_evicted += shr.shrink(threshold=0.0, max_unseen=0,
                                            decay=0.9)
        finally:
            stop.set()
            [t.join(timeout=30) for t in threads]
        assert not errors
        assert len(pull_lat) > 10  # pulls kept flowing during shrink
        # a pull may wait for one 64-row chunk of file I/O, never the tier
        assert max(pull_lat) < 2.0, max(pull_lat)
        # table still serves consistent rows
        ids = np.array([5, 50_000], np.uint64)
        r1, r2 = shr.pull(ids), shr.pull(ids)
        np.testing.assert_array_equal(r1, r2)
    finally:
        svc.stop()


def test_pageout_keeps_hot_rows_resident(tmp_path):
    """Balanced per-shard eviction (trim each shard to its share): a hot set
    pulled+pushed every step must stay resident while cold churn spills —
    draining shards in order used to evict hot rows wholesale."""
    import numpy as np

    from paddle_tpu.distributed import ps

    svc = ps.EmbeddingService(dim=64, num_shards=1, rule="adagrad",
                              ram_cap_bytes=32_000_000,
                              spill_dir=str(tmp_path))
    try:
        c = svc.client()
        hot = np.arange(13_000, dtype=np.uint64)
        g = np.ones((len(hot), 64), np.float32)
        rng = np.random.RandomState(0)
        for _ in range(6):  # grow past the cap with cold churn
            c.pull(hot)
            c.push(hot, g, 0.01)
            c.pull(rng.randint(1 << 20, 1 << 50, 10_000).astype(np.uint64))
        st0 = c.tier_stats()
        assert st0["spill_rows"] > 0  # the pager did run
        for _ in range(3):  # steady phase: hot only +  cold churn
            c.pull(hot)
            c.push(hot, g, 0.01)
            c.pull(rng.randint(1 << 20, 1 << 50, 10_000).astype(np.uint64))
        st1 = c.tier_stats()
        hot_lookups = 2 * 3 * len(hot)
        # hot traffic must not page in (cold-id collisions are ~0)
        assert st1["pageins"] - st0["pageins"] < 0.02 * hot_lookups, (
            st0, st1)
    finally:
        svc.stop()

"""PyLayer (user-defined vjp) + higher-order autograd.

Mirrors the reference's PyLayer contract
(ref:python/paddle/autograd/py_layer.py:29,234) and double-grad tests
(ref:test/autograd in the reference tree).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


class CusTanh(PyLayer):
    @staticmethod
    def forward(ctx, x):
        y = paddle.tanh(x)
        ctx.save_for_backward(y)
        return y

    @staticmethod
    def backward(ctx, dy):
        (y,) = ctx.saved_tensor()
        return dy * (1.0 - paddle.square(y))


def test_pylayer_matches_builtin():
    a = np.linspace(-2, 2, 7).astype(np.float32)
    x1 = paddle.to_tensor(a, stop_gradient=False)
    y1 = CusTanh.apply(x1)
    y1.sum().backward()

    x2 = paddle.to_tensor(a, stop_gradient=False)
    paddle.tanh(x2).sum().backward()

    np.testing.assert_allclose(y1.numpy(), np.tanh(a), rtol=1e-6)
    np.testing.assert_allclose(x1.grad.numpy(), x2.grad.numpy(), rtol=1e-5)


class ScaleAdd(PyLayer):
    """Two tensor inputs, non-tensor attr, two outputs."""

    @staticmethod
    def forward(ctx, x, y, alpha=2.0):
        ctx.alpha = alpha
        return x * alpha + y, x - y

    @staticmethod
    def backward(ctx, d0, d1):
        return d0 * ctx.alpha + d1, d0 - d1


def test_pylayer_multi_io():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle.to_tensor([3.0, 4.0], stop_gradient=False)
    o0, o1 = ScaleAdd.apply(x, y, alpha=3.0)
    (o0.sum() + 2 * o1.sum()).backward()
    # d/dx = alpha*1 + 2*1 = 5 ; d/dy = 1 - 2 = -1
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])
    np.testing.assert_allclose(y.grad.numpy(), [-1.0, -1.0])


def test_pylayer_unused_output_gets_zeros():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle.to_tensor([3.0, 4.0], stop_gradient=False)
    o0, o1 = ScaleAdd.apply(x, y)  # alpha=2; o1 unused
    o0.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])
    np.testing.assert_allclose(y.grad.numpy(), [1.0, 1.0])


class NoneGrad(PyLayer):
    @staticmethod
    def forward(ctx, x, y):
        return x * 2.0 + y.detach()

    @staticmethod
    def backward(ctx, dy):
        return dy * 2.0, None  # no grad for y


def test_pylayer_none_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([1.0], stop_gradient=False)
    NoneGrad.apply(x, y).backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_pylayer_materialize_grads_off():
    seen = {}

    class TwoOut(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.set_materialize_grads(False)
            return x * 1.0, x * 2.0

        @staticmethod
        def backward(ctx, d0, d1):
            seen["d1"] = d1
            return d0

    x = paddle.to_tensor([1.0], stop_gradient=False)
    o0, o1 = TwoOut.apply(x)
    o0.sum().backward()
    assert seen["d1"] is None
    np.testing.assert_allclose(x.grad.numpy(), [1.0])


def test_pylayer_wrong_grad_count_raises():
    class Bad(PyLayer):
        @staticmethod
        def forward(ctx, x, y):
            return x + y

        @staticmethod
        def backward(ctx, dy):
            return dy  # should be two

    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([1.0], stop_gradient=False)
    out = Bad.apply(x, y)
    with pytest.raises(RuntimeError, match="gradients"):
        out.backward()


def test_pylayer_no_grad_passthrough():
    x = paddle.to_tensor([1.0])  # stop_gradient=True
    y = CusTanh.apply(x)
    assert y.stop_gradient


# ---------------------------------------------------------------- double grad


def test_double_grad_square():
    # y = x^3: dy/dx = 3x^2, d2y/dx2 = 6x
    x = paddle.to_tensor([2.0, -1.0], stop_gradient=False)
    y = (x * x * x).sum()
    (g,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(g.numpy(), [12.0, 3.0], rtol=1e-5)
    (g2,) = paddle.grad(g.sum(), x)
    np.testing.assert_allclose(g2.numpy(), [12.0, -6.0], rtol=1e-5)


def test_double_grad_mixed_vars():
    # z = x^2 * y: dz/dx = 2xy, d(dz/dx)/dy = 2x
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = paddle.to_tensor([5.0], stop_gradient=False)
    z = (x * x * y).sum()
    (gx,) = paddle.grad(z, x, create_graph=True)
    np.testing.assert_allclose(gx.numpy(), [30.0], rtol=1e-5)
    (gxy,) = paddle.grad(gx.sum(), y)
    np.testing.assert_allclose(gxy.numpy(), [6.0], rtol=1e-5)


def test_double_grad_matches_finite_difference():
    rng = np.random.RandomState(0)
    a = rng.rand(5).astype(np.float32) + 0.5

    def f(arr):
        t = paddle.to_tensor(arr, stop_gradient=False)
        return t, (paddle.exp(t) * paddle.sin(t)).sum()

    x, y = f(a)
    (g,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad(g.sum(), x)

    eps = 1e-3
    fd = np.zeros_like(a)
    for i in range(len(a)):
        ap, am = a.copy(), a.copy()
        ap[i] += eps
        am[i] -= eps
        _, yp = f(ap)
        _, ym = f(am)
        xp = paddle.to_tensor(ap, stop_gradient=False)
        xm = paddle.to_tensor(am, stop_gradient=False)
        (gp,) = paddle.grad((paddle.exp(xp) * paddle.sin(xp)).sum(), xp)
        (gm,) = paddle.grad((paddle.exp(xm) * paddle.sin(xm)).sum(), xm)
        fd[i] = (gp.numpy()[i] - gm.numpy()[i]) / (2 * eps)
    np.testing.assert_allclose(g2.numpy(), fd, rtol=1e-2, atol=1e-2)


def test_backward_with_create_graph_then_grad():
    # second-order via backward(): grad of (dy/dx) w.r.t x using .grad chain
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x ** 4).sum()
    (g,) = paddle.grad(y, x, create_graph=True)  # 4x^3 = 32
    z = (g * g).sum()  # z = 16 x^6, dz/dx = 96 x^5 = 3072
    (gz,) = paddle.grad(z, x)
    np.testing.assert_allclose(gz.numpy(), [3072.0], rtol=1e-4)


def test_triple_grad():
    # y = x^4: y''' = 24x
    x = paddle.to_tensor([1.5], stop_gradient=False)
    y = (x ** 4).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad(g1.sum(), x, create_graph=True)
    (g3,) = paddle.grad(g2.sum(), x)
    np.testing.assert_allclose(g3.numpy(), [36.0], rtol=1e-4)


def test_double_grad_through_matmul():
    rng = np.random.RandomState(1)
    a = rng.rand(3, 3).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    y = paddle.matmul(x, x).sum()
    (g,) = paddle.grad(y, x, create_graph=True)
    # g_ab = rowsum(x)_b + colsum(x)_a, so sum(g) = 2*n*sum(x) and
    # d sum(g)/dx = 2*n = 6 for n=3
    (g2,) = paddle.grad(g.sum(), x)
    np.testing.assert_allclose(g2.numpy(), np.full((3, 3), 6.0), rtol=1e-5)


def test_pylayer_double_grad():
    class Square(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor()
            xt = paddle.to_tensor(x.numpy(), stop_gradient=True)
            return dy * 2.0 * xt

    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = Square.apply(x).sum()
    (g,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(g.numpy(), [6.0], rtol=1e-5)
    # d(g)/d(x) through the PyLayer's backward: dy is what carries the graph;
    # grad-of-grad w.r.t. dy-chain works, x-dependence inside backward is
    # through a constant here (documented limitation, as in the reference).


def test_no_grad_vars():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = paddle.to_tensor([3.0], stop_gradient=False)
    z = (x * y).sum()
    (gx,) = paddle.grad(z, [x], no_grad_vars=[y])
    np.testing.assert_allclose(gx.numpy(), [3.0])


# ------------------------------------------------- inplace version checking


def test_stale_inplace_consumer_raises():
    # a consumed y BEFORE tanh_; backward through the stale read must raise
    # (the reference's inplace-version error), not silently misroute grads
    w = paddle.to_tensor([0.5], stop_gradient=False)
    y = w * 1.0
    a = y + 0.0
    y.tanh_()
    with pytest.raises(RuntimeError, match="in-place"):
        a.sum().backward()


def test_stale_uniform_fill_consumer_raises():
    w = paddle.to_tensor([1.0], stop_gradient=False)
    y = w * 3.0
    b = y + 0.0
    paddle.uniform_(y)
    with pytest.raises(RuntimeError, match="in-place"):
        b.sum().backward()


def test_stale_assign_consumer_raises():
    w = paddle.to_tensor([1.0], stop_gradient=False)
    o = w * 2.0
    c = o * 5.0
    paddle.assign(paddle.to_tensor([9.0]), o)
    with pytest.raises(RuntimeError, match="in-place"):
        c.sum().backward()


def test_inplace_then_use_is_fine():
    # consumers AFTER the in-place op see the new version: no error
    w = paddle.to_tensor([0.5], stop_gradient=False)
    y = w * 1.0
    y.tanh_()
    z = y + 0.0
    z.sum().backward()
    np.testing.assert_allclose(
        w.grad.numpy(), 1.0 - np.tanh([0.5]) ** 2, rtol=1e-5)


# ---------------------------------------------------- PyLayer under tracing


class StraightThrough(PyLayer):
    """sign() forward, identity backward — grad differs from the true vjp
    (which is 0 a.e.), so this detects whether the custom backward is used."""

    @staticmethod
    def forward(ctx, x):
        return paddle.sign(x)

    @staticmethod
    def backward(ctx, dy):
        return dy


def test_pylayer_traced_uses_custom_backward():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor

    x = paddle.to_tensor([0.3, -0.7], stop_gradient=False)
    # eager: d/dx = 1 (straight-through) * 2
    StraightThrough.apply(x * 2.0).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])

    # traced/compiled: the same custom grad must survive jax autodiff
    # (without the custom_vjp lowering this would be 0 a.e. — sign's true vjp)
    def f_arr(xa):
        t = Tensor(xa, stop_gradient=False)
        return StraightThrough.apply(t * 2.0).sum()._data

    g = jax.jit(jax.grad(f_arr))(jnp.asarray([0.3, -0.7], jnp.float32))
    np.testing.assert_allclose(np.asarray(g), [2.0, 2.0])


def test_pylayer_traced_saved_tensors():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor

    class SquareSaved(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor()
            return dy * 2.0 * x

    def f_arr(xa):
        t = Tensor(xa, stop_gradient=False)
        return SquareSaved.apply(t).sum()._data

    g = jax.jit(jax.grad(f_arr))(jnp.asarray([3.0, -2.0], jnp.float32))
    np.testing.assert_allclose(np.asarray(g), [6.0, -4.0], rtol=1e-5)

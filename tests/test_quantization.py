"""QAT/PTQ quantization tests (ref:python/paddle/quantization/ + test/quantization).

Acceptance (VERDICT item 8): quantized LeNet accuracy within 1% of fp32.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import (
    PTQ, QAT, AbsmaxObserver, FakeQuanterWithAbsMaxObserver, QuantConfig,
    QuantedConv2D, QuantedLinear, dequantize_weight, fake_quant,
    quantize_weight)

RNG = np.random.RandomState(0)


def _digits_data(n=512):
    """Synthetic 8x8 'digits': class = which quadrant carries energy."""
    x = RNG.rand(n, 1, 8, 8).astype(np.float32) * 0.1
    y = RNG.randint(0, 4, n)
    for i, label in enumerate(y):
        r, c = divmod(int(label), 2)
        x[i, 0, r * 4:(r + 1) * 4, c * 4:(c + 1) * 4] += 1.0
    return x, y.astype(np.int64)


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(1, 8, 3, padding=1)
        self.relu = nn.ReLU()
        self.pool = nn.MaxPool2D(2)
        self.flatten = nn.Flatten()
        self.fc1 = nn.Linear(8 * 4 * 4, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        h = self.pool(self.relu(self.conv(x)))
        h = self.relu(self.fc1(self.flatten(h)))
        return self.fc2(h)


def _train(model, x, y, epochs=6, lr=5e-3):
    opt = paddle.optimizer.Adam(learning_rate=lr, parameters=model.parameters())
    for _ in range(epochs):
        for i in range(0, len(x), 64):
            xb = paddle.to_tensor(x[i:i + 64])
            yb = paddle.to_tensor(y[i:i + 64])
            loss = nn.functional.cross_entropy(model(xb), yb).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
    return model


def _acc(model, x, y):
    model.eval()
    pred = np.argmax(model(paddle.to_tensor(x)).numpy(), axis=1)
    model.train()
    return float((pred == y).mean())


def test_quantize_dequantize_roundtrip():
    w = RNG.randn(16, 8).astype(np.float32)
    q, s = quantize_weight(w)
    assert q.dtype == np.int8
    np.testing.assert_allclose(dequantize_weight(q, s), w, atol=float(s) + 1e-6)
    qc, sc = quantize_weight(w, channel_axis=1)
    assert sc.shape == (1, 8)
    np.testing.assert_allclose(dequantize_weight(qc, sc), w, atol=float(sc.max()) + 1e-6)


def test_fake_quant_ste_gradient():
    x = paddle.to_tensor(RNG.randn(10).astype(np.float32), stop_gradient=False)
    y = fake_quant(x * 1.0, paddle.to_tensor(np.float32(0.05)))
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones(10))  # straight-through
    # values quantized onto the grid
    np.testing.assert_allclose(y.numpy() / 0.05, np.round(y.numpy() / 0.05),
                               atol=1e-4)


def test_qat_structure_and_training():
    cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                      weight=FakeQuanterWithAbsMaxObserver)
    model = QAT(cfg).quantize(SmallNet())
    assert isinstance(model.conv, QuantedConv2D)
    assert isinstance(model.fc1, QuantedLinear)
    x, y = _digits_data(256)
    _train(model, x, y, epochs=4)
    assert _acc(model, x, y) > 0.9


def test_qat_accuracy_within_1pct_of_fp32():
    x, y = _digits_data(512)
    fp32 = _train(SmallNet(), x, y)
    base_acc = _acc(fp32, x, y)

    cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                      weight=FakeQuanterWithAbsMaxObserver)
    qat_model = QAT(cfg).quantize(fp32)          # fine-tune from fp32
    _train(qat_model, x, y, epochs=2, lr=1e-3)
    qat_acc = _acc(qat_model, x, y)

    converted = QAT(cfg).convert(qat_model)
    int8_acc = _acc(converted, x, y)
    print(f"fp32={base_acc:.4f} qat={qat_acc:.4f} int8={int8_acc:.4f}")
    assert qat_acc >= base_acc - 0.01
    assert int8_acc >= base_acc - 0.01
    # converted weights really are int8-valued
    qw = np.asarray(converted.fc1.qweight._data)
    np.testing.assert_array_equal(qw, np.round(qw))
    assert np.abs(qw).max() <= 128


def test_ptq_calibrate_convert():
    x, y = _digits_data(512)
    fp32 = _train(SmallNet(), x, y)
    base_acc = _acc(fp32, x, y)

    cfg = QuantConfig(activation=AbsmaxObserver, weight=None)
    ptq_model = PTQ(cfg).quantize(fp32)
    ptq_model.eval()
    for i in range(0, 256, 64):  # calibration passes
        ptq_model(paddle.to_tensor(x[i:i + 64]))
    converted = PTQ(cfg).convert(ptq_model)
    int8_acc = _acc(converted, x, y)
    print(f"fp32={base_acc:.4f} ptq-int8={int8_acc:.4f}")
    assert int8_acc >= base_acc - 0.01
    # activation scales were calibrated and frozen
    assert converted.fc1.act_scale is not None and converted.fc1.act_scale > 0


def test_converted_model_exports():
    """int8-converted model goes through to_static + save like any model."""
    import tempfile

    x, y = _digits_data(128)
    model = _train(SmallNet(), x, y, epochs=2)
    cfg = QuantConfig(activation=AbsmaxObserver)
    q = PTQ(cfg).quantize(model)
    q.eval()
    q(paddle.to_tensor(x[:64]))
    converted = PTQ(cfg).convert(q)
    converted.eval()

    from paddle_tpu import jit
    from paddle_tpu.static import InputSpec

    eager_out = converted(paddle.to_tensor(x[:4])).numpy()
    with tempfile.TemporaryDirectory() as td:
        path = td + "/qmodel"
        jit.save(converted, path,
                 input_spec=[InputSpec([None, 1, 8, 8], "float32")])
        loaded = jit.load(path)
        out = loaded(paddle.to_tensor(x[:4]))
        out = out[0] if isinstance(out, (list, tuple)) else out
        np.testing.assert_allclose(out.numpy(), eager_out, rtol=1e-3, atol=1e-4)

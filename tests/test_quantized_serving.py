"""Quantized serving (ISSUE 11): int8 weight-only decode, the int8 KV
arena with per-block scale pools, and the quantized draft.

The contract under test (docs/quantization.md "Parity policy"):

* **flag-off is bit-identical** — all three quant flags default off and
  the unquantized engine behaves exactly as before (2-tuple float pools,
  no weight_scale buffers, generate() parity);
* **structural invariants are exact** — a weight-quantized engine is
  token-for-token identical to generate() on the same quantized model; a
  quantized draft never changes emitted tokens; COW copies scale pools
  with their payload; rebuild+replay reconstructs quantized state;
* **tolerance vs the float baseline is documented** — greedy streams
  and teacher-forced top-1 agreement must clear the >=90% per-token
  gate (measured 100% on this tiny model — the gate is the contract,
  not the expectation); int8 round-trips obey their absmax/254 bound;
* **the memory win is real** — the int8 arena seats >=1.9x a bf16
  arena's slots at equal bytes_total() (scale pools charged), and the
  per-namespace byte/dtype breakdown is observable;
* **zero recompiles** — quantize-on-scatter / dequant-in-kernel live
  inside the same per-bucket programs; churn adds no compiles.
"""
import logging

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import quantization
from paddle_tpu.core import resilience
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import (
    GPTForCausalLM,
    gpt_tiny,
    quantize_serving_weights,
    serving_compute_dtype,
)
from paddle_tpu.serving import (
    EnginePredictor,
    RequestState,
    ServingAPI,
    ServingConfig,
)
from paddle_tpu.serving import metrics as serving_metrics
from paddle_tpu.serving.kv_arena import KVArena

pytestmark = pytest.mark.serving

MAX_LEN = 96
BS = 8
#: the documented per-token tolerance gate vs the float baseline
PARITY_GATE = 0.9


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


def _copy(model):
    """A fresh instance carrying ``model``'s float weights — quantizing
    engines mutate their model in place, so every quantized engine in
    this suite gets its own copy and the float fixture stays float."""
    m = GPTForCausalLM(model.cfg.__class__(**vars(model.cfg)))
    m.eval()
    m.set_state_dict(dict(model.state_dict()))
    return m


def _prompt(rng, n):
    return rng.integers(0, 1024, (n,), dtype=np.int32)


def _ref(model, prompt, max_new):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=max_new)
    return np.asarray(out._data)[0]


def _cfg(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("kv_block_size", BS)
    kw.setdefault("max_model_len", MAX_LEN)
    return ServingConfig(**kw)


def _run(api, prompts, max_new):
    reqs = [api.submit(p, max_new_tokens=max_new) for p in prompts]
    api.run_until_idle()
    for r in reqs:
        assert r.state == RequestState.FINISHED
    return [r.output_ids() for r in reqs]


def _gen_match(out, ref, plen):
    """Per-token agreement over GENERATED tokens only — output_ids() and
    generate() both return prompt + generation, and prompt tokens match
    by construction (counting them would floor the gate at
    plen/(plen+new) and make it vacuous)."""
    out, ref = np.asarray(out), np.asarray(ref)
    assert len(out) > plen
    return float((out[plen:] == ref[plen:]).mean())


# ------------------------------------------------------------ quantizers


def test_quantize_weight_per_channel_correctness():
    """The single weight quantizer: per-channel scales keep the declared
    axis, round-trip error is bounded by scale/2 per element, and a
    negative channel_axis quantizes the same channels as its positive
    twin (the normalization fix — it used to reduce over every axis)."""
    rng = np.random.default_rng(0)
    w = rng.normal(0, 0.3, (24, 16)).astype(np.float32)
    w[:, 3] *= 50.0  # a hot output channel must not poison the others
    q, scale = quantization.quantize_weight(w, channel_axis=1)
    assert q.dtype == np.int8 and scale.shape == (1, 16)
    deq = quantization.dequantize_weight(q, scale)
    assert np.all(np.abs(deq - w) <= scale / 2 + 1e-7)
    # the hot channel's scale is its own, not the tensor max's
    assert scale[0, 3] > 10 * scale[0, 0]
    q0, s0 = quantization.quantize_weight(w, channel_axis=0)
    assert s0.shape == (24, 1)
    qn, sn = quantization.quantize_weight(w, channel_axis=-1)
    np.testing.assert_array_equal(qn, q)
    np.testing.assert_array_equal(sn, scale)


def test_quantize_kv_round_trip_error_bound():
    """Per-token symmetric int8 KV: |dequant - x| <= absmax/254 per
    element, scales are per leading index, payload is int8."""
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(0, 2.0, (6, 4, 8)).astype(np.float32))
    q, scale = quantization.quantize_kv(x)
    assert q.dtype == jnp.int8 and scale.shape == (6,)
    deq = quantization.dequantize_kv(q, scale, jnp.float32)
    amax = np.abs(np.asarray(x)).max(axis=(-2, -1))
    bound = amax / 254.0 + 1e-6
    err = np.abs(np.asarray(deq) - np.asarray(x)).max(axis=(-2, -1))
    assert np.all(err <= bound)


def test_quantize_serving_weights_single_quantizer_and_idempotent(model,
                                                                  monkeypatch):
    """The serving path routes every layer through
    quantization.quantize_weight (no duplicate absmax math in gpt.py),
    registers f32 [1, out] scales as buffers, and a second call is a
    no-op — a gateway's replicas share one model instance."""
    m = _copy(model)
    calls = []
    real = quantization.quantize_weight

    def counting(w, channel_axis=None):
        calls.append(channel_axis)
        return real(w, channel_axis=channel_axis)

    monkeypatch.setattr(quantization, "quantize_weight", counting)
    n = quantize_serving_weights(m)
    # 4 linears per block (qkv/proj/up/down), every call per-channel
    assert n == len(calls) == 4 * m.cfg.num_layers
    assert all(c == 1 for c in calls)
    assert quantize_serving_weights(m) == 0 and len(calls) == n
    lin = m.gpt.layers[0].attn.qkv
    assert str(lin.weight._data.dtype) == "int8"
    assert str(lin.weight_scale._data.dtype) == "float32"
    assert tuple(lin.weight_scale.shape) == (1, lin.weight.shape[1])
    # the scale buffers ride functional_state into the compiled programs
    _, buffers = m.functional_state()
    assert any(k.endswith("weight_scale") for k in buffers)
    assert serving_compute_dtype(m) == "float32"


# ------------------------------------------------- flag-off / default path


def test_quant_flags_default_off_and_engine_unchanged(model):
    """All three flags default off; the default engine keeps 2-tuple
    float pools, quantizes nothing, and reproduces generate() exactly."""
    for f in ("serving_quant_weights", "serving_quant_kv",
              "serving_quant_draft"):
        assert paddle.get_flags(f)[f] is False
    rng = np.random.default_rng(2)
    prompts = [_prompt(rng, n) for n in (5, 11)]
    api = ServingAPI(model, _cfg())
    try:
        assert not api.engine.quant_weights and not api.engine.quant_kv
        assert len(api.engine.arena.pools[0]) == 2
        assert str(api.engine.arena.pools[0][0].dtype) == "float32"
        outs = _run(api, prompts, 10)
        for p, out in zip(prompts, outs):
            np.testing.assert_array_equal(out, _ref(model, p, 10))
        assert getattr(model.gpt.layers[0].attn.qkv, "weight_scale",
                       None) is None
    finally:
        api.close()


# ------------------------------------------------------------ parity gates


def test_weight_only_engine_exact_vs_quantized_generate(model):
    """Structural invariant: the weight-quantized engine and generate()
    on the SAME quantized model share one numerics contract — token-for-
    token identical. Tolerance gate: both clear >=90% agreement with the
    float baseline, greedy and teacher-forced."""
    import jax.numpy as jnp

    qm = _copy(model)
    api = ServingAPI(qm, _cfg(quant_weights=True))
    try:
        assert api.engine.quant_weights
        rng = np.random.default_rng(3)
        prompts = [_prompt(rng, n) for n in (5, 9, 14)]
        outs = _run(api, prompts, 12)
        for p, out in zip(prompts, outs):
            np.testing.assert_array_equal(out, _ref(qm, p, 12))  # exact
            ref = _ref(model, p, 12)
            assert _gen_match(out, ref, len(p)) >= PARITY_GATE
            # teacher-forced per-position top-1 agreement on the float
            # baseline's own greedy context
            lq = qm(Tensor(ref[None, :-1].astype(np.int32)))._data
            lf = model(Tensor(ref[None, :-1].astype(np.int32)))._data
            tf = (np.asarray(jnp.argmax(lq, -1))
                  == np.asarray(jnp.argmax(lf, -1))).mean()
            assert tf >= PARITY_GATE
    finally:
        api.close()


def test_kv_quant_engine_tolerance_gate(model):
    """Int8 KV decode clears the documented per-token gate vs the float
    engine (generate() has no paged-int8 path, so the float baseline is
    the reference)."""
    api = ServingAPI(model, _cfg(quant_kv=True))
    try:
        assert api.engine.arena.quantized
        assert len(api.engine.arena.pools[0]) == 4
        rng = np.random.default_rng(4)
        prompts = [_prompt(rng, n) for n in (6, 10, 17)]
        outs = _run(api, prompts, 12)
        for p, out in zip(prompts, outs):
            assert _gen_match(out, _ref(model, p, 12),
                              len(p)) >= PARITY_GATE
        api.engine.check_invariants()
    finally:
        api.close()


def test_combined_weight_and_kv_quant_churn_zero_recompiles(model):
    """Both modes together: the tolerance gate holds, and admit/retire
    churn across mixed lengths adds ZERO compiled programs after warmup
    — quantize/dequant is traced into the same per-bucket programs."""
    qm = _copy(model)
    api = ServingAPI(qm, _cfg(quant_weights=True, quant_kv=True))
    try:
        rng = np.random.default_rng(5)
        warm = _run(api, [_prompt(rng, 6)], 4)  # warm bucket + step
        traces0 = (api.engine.decode_traces,
                   dict(api.engine.prefill_traces))
        prompts = [_prompt(rng, n) for n in (5, 7, 9, 6, 8)]
        outs = _run(api, prompts, 10)
        for p, out in zip(prompts, outs):
            assert _gen_match(out, _ref(model, p, 10),
                              len(p)) >= PARITY_GATE
        assert api.engine.decode_traces == traces0[0] == 1
        assert dict(api.engine.prefill_traces) == traces0[1]
    finally:
        api.close()


# ------------------------------------------------ prefix cache / COW / arena


def test_prefix_cache_hit_and_cow_with_scales(model):
    """The radix cache over the int8 arena: shared prefixes attach by
    reference (suffix-only prefill), a fully-cached block-aligned prompt
    COWs its last block — and the COW copies the scale rows with the
    payload, so cache-on output equals cache-off output token-for-token
    under quantization. Refcount/structure invariants audited."""
    rng = np.random.default_rng(6)
    sys_p = _prompt(rng, 2 * BS)  # block-aligned shared prefix
    tails = [_prompt(rng, 5) for _ in range(2)]
    prompts = [np.concatenate([sys_p, t]) for t in tails] + [sys_p.copy()]

    off = ServingAPI(model, _cfg(quant_kv=True, prefix_cache=False))
    try:
        base = _run(off, prompts, 10)
    finally:
        off.close()

    api = ServingAPI(model, _cfg(quant_kv=True, prefix_cache=True))
    try:
        outs = _run(api, prompts, 10)
        for a, b in zip(outs, base):
            np.testing.assert_array_equal(a, b)
        st = api.engine.stats()
        assert st["prefix.hits"] >= 2       # tail shares + aligned reuse
        assert st["cow_traces"] == 1        # the aligned prompt COW'd
        api.engine.check_invariants()
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == a["blocks_cached"]  # only cache holds
    finally:
        api.close()


def test_cow_copies_scale_pools_unit(model):
    """Direct audit of the compiled COW program on a quantized arena:
    every array of each pool entry — int8 K/V payload AND both scale
    pools — lands in the destination block."""
    api = ServingAPI(model, _cfg(quant_kv=True))
    try:
        import jax.numpy as jnp

        arena = api.engine.arena
        src, dst = 3, 5
        seeded = []
        for li, entry in enumerate(arena.pools):
            new = []
            for ai, arr in enumerate(entry):
                fill = (li + 1) * 10 + ai + 1
                new.append(arr.at[src].set(
                    jnp.full(arr.shape[1:], fill, arr.dtype)))
                seeded.append(fill)
            arena.pools[li] = tuple(new)
        api.engine._cow_copy(src, dst)
        for li, entry in enumerate(arena.pools):
            for ai, arr in enumerate(entry):
                fill = (li + 1) * 10 + ai + 1
                got = np.asarray(arr[dst])
                assert np.all(got == fill), (li, ai)
        arena.check_invariants()
    finally:
        api.close()


def test_arena_seats_1p9x_bf16_slots_at_equal_bytes():
    """The acceptance gate: at equal bytes_total() (scale pools charged
    to the int8 side) the quantized arena seats >=1.9x the bf16 arena's
    slots. Probed at 32 slots so block flooring doesn't mask the real
    ratio 2*H*D/(H*D+4)."""
    cfg = gpt_tiny()
    heads, hdim = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    blocks_per_slot = -(-MAX_LEN // BS)
    slots = 32
    nb = slots * blocks_per_slot + 1
    bf16 = KVArena(cfg.num_layers, heads, hdim, nb, BS, dtype="bfloat16")
    q = KVArena(cfg.num_layers, heads, hdim, nb, BS, quantized=True)
    per_block_q = q.bytes_total() / nb
    slots_q = (int(bf16.bytes_total() // per_block_q) - 1) // blocks_per_slot
    assert slots_q / slots >= 1.9, (slots_q, slots)
    # and the breakdown is honest: scale bytes nonzero, dtype int8
    by = q.bytes_by_namespace()["primary"]
    assert by["dtype"] == "int8" and by["scale_bytes"] > 0
    assert by["kv_bytes"] + by["scale_bytes"] == q.bytes_total()
    # pin the shape arithmetic the --quantized bench probes with (it must
    # never instantiate device pools just to count bytes)
    row = BS * heads * hdim
    assert q.bytes_total() == nb * cfg.num_layers * 2 * (row + BS * 4)
    assert bf16.bytes_total() == nb * cfg.num_layers * 2 * row * 2


def test_adopting_pools_without_scales_fails_invariants(model):
    """A quantized pool set adopted without its scale pools (the silent-
    corruption shape the COW audit exists for) is caught structurally."""
    api = ServingAPI(model, _cfg(quant_kv=True))
    try:
        arena = api.engine.arena
        arena.set_pools([(e[0], e[1]) for e in arena.pools])  # drop scales
        with pytest.raises(RuntimeError, match="without its scales"):
            arena.check_invariants()
    finally:
        api.close()


def test_bytes_breakdown_covers_draft_namespace(model):
    """stats()/bytes_by_namespace break bytes and dtype out per namespace
    — the draft namespace included — and the engine publishes them as
    arena.* gauges."""
    qm = _copy(model)
    draft = _copy(model)
    api = ServingAPI(qm, _cfg(quant_weights=True, quant_kv=True,
                              spec_k=3, draft_model=draft,
                              quant_draft=True))
    try:
        by = api.engine.arena.bytes_by_namespace()
        assert set(by) == {"primary", "draft"}
        for ns in by.values():
            assert ns["quantized"] and ns["dtype"] == "int8"
            assert ns["scale_bytes"] > 0
        st = api.engine.arena.stats()
        assert st["kv_bytes"] == sum(d["bytes"] for d in by.values())
        g = serving_metrics.gauges()
        assert g["arena.bytes.draft"] == by["draft"]["bytes"]
        assert g["arena.dtype.primary"] == "int8"
        assert g["arena.scale_bytes"] == sum(d["scale_bytes"]
                                             for d in by.values())
        assert g["quant.weights"] == 1 and g["quant.kv"] == 1
        assert g["quant.draft"] == 1
    finally:
        api.close()


# ------------------------------------------------------------ quantized draft


def test_quantized_draft_is_output_neutral(model):
    """An int8-quantized draft changes speed, never tokens: output stays
    bit-identical to the float target's greedy stream (verification is
    target-greedy by construction), the mode reports draft-int8, and the
    per-mode acceptance telemetry lands."""
    draft = _copy(model)  # tied weights -> near-total acceptance
    api = ServingAPI(model, _cfg(spec_k=3, draft_model=draft,
                                 quant_draft=True))
    try:
        spec = api.engine.spec
        assert spec.quant_draft and spec.mode() == "draft-int8"
        assert str(
            draft.gpt.layers[0].attn.qkv.weight._data.dtype) == "int8"
        rng = np.random.default_rng(7)
        prompts = [_prompt(rng, n) for n in (6, 10, 13)]
        outs = _run(api, prompts, 12)
        for p, out in zip(prompts, outs):
            np.testing.assert_array_equal(out, _ref(model, p, 12))
        assert spec.proposed > 0
        st = spec.stats()
        assert st["spec.mode"] == "draft-int8"
        g = serving_metrics.gauges()
        assert g["quant.draft_acceptance"] == st["spec.acceptance_rate"]
        api.engine.check_invariants()
    finally:
        api.close()


# ----------------------------------------------------------- chaos / replay


@pytest.mark.chaos
def test_replay_parity_with_quant_on(model):
    """Supervisor rebuild+replay reconstructs quantized state exactly: a
    transient device fault mid-decode on a weights+KV-quantized engine
    resumes token-for-token (vs its own unfaulted run), rebuilds exactly
    once, keeps the rebuilt arena quantized, and leaves it clean."""
    keep = paddle.get_flags("fault_injection")["fault_injection"]
    paddle.set_flags({"fault_injection": 1})
    qm = _copy(model)
    api = ServingAPI(qm, _cfg(quant_weights=True, quant_kv=True))
    try:
        rng = np.random.default_rng(8)
        prompts = [_prompt(rng, n) for n in (5, 9)]
        refs = _run(api, prompts, 14)  # unfaulted quantized reference
        rb0 = resilience.stats().get("serving.rebuilds", 0)
        reqs = [api.submit(p, max_new_tokens=14) for p in prompts]
        for _ in range(3):
            api._pump_once()
        assert all(r.state == RequestState.RUNNING for r in reqs)
        resilience.inject_fault("serving_device", times=1)
        api.run_until_idle()
        for ref, r in zip(refs, reqs):
            assert r.state == RequestState.FINISHED
            np.testing.assert_array_equal(ref, r.output_ids())
        assert resilience.stats().get("serving.rebuilds", 0) == rb0 + 1
        assert api.engine.arena.quantized
        assert len(api.engine.arena.pools[0]) == 4
        assert api.engine.decode_traces == 1  # recovery never retraced
        api.drain(grace=5)
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
    finally:
        resilience.clear_faults()
        api.close()
        paddle.set_flags({"fault_injection": keep})


# ---------------------------------------------------------- observability


def test_predictor_close_logs_quant_summary(model, caplog):
    """EnginePredictor.close() reports the quantized-serving memory
    picture (per-namespace bytes/dtype, scale pools broken out) next to
    the prefix/speculation lines."""
    qm = _copy(model)
    pred = EnginePredictor(qm, max_new_tokens=4,
                           config=_cfg(num_slots=2, quant_weights=True,
                                       quant_kv=True))
    rng = np.random.default_rng(9)
    ids = np.stack([_prompt(rng, 8), _prompt(rng, 8)])
    out = pred.run([ids])[0]
    np.testing.assert_array_equal(
        out, np.asarray(qm.generate(Tensor(ids), max_new_tokens=4)._data))
    with caplog.at_level(logging.INFO, logger="paddle_tpu.serving"):
        pred.close()
    summary = [rec.getMessage() for rec in caplog.records
               if "EnginePredictor" in rec.getMessage()]
    assert summary
    line = summary[-1]
    assert "quantized serving [weights=1 kv=1 draft=0]" in line
    assert "primary int8" in line and "scales" in line


def test_serving_stats_cli_reports_quant_flags():
    """tools/serving_stats.py config mode (no jax init) surfaces the
    quant flag trio."""
    import json
    import subprocess
    import sys
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "serving_stats.py"),
         "--json"], capture_output=True, text=True, timeout=60, cwd=repo)
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    for k in ("serving_quant_weights", "serving_quant_kv",
              "serving_quant_draft"):
        assert k in rep and rep[k] == 0

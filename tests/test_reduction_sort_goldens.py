"""Flag-sensitive reduction/sort/search op semantics vs torch/numpy
(descending sort, topk flags, searchsorted sides, unique return bundles,
quantile interpolation, cumulative ops — ref:python/paddle/tensor/
{search,math,stat}.py contracts)."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle


RNG = np.random.default_rng(3)


def T(x):
    return paddle.to_tensor(np.asarray(x))


def test_sort_argsort_descending_axes():
    x = RNG.standard_normal((3, 5)).astype(np.float32)
    for axis in (0, 1, -1):
        for desc in (False, True):
            got = paddle.sort(T(x), axis=axis, descending=desc).numpy()
            want = np.sort(x, axis=axis)
            if desc:
                want = np.flip(want, axis=axis)
            np.testing.assert_array_equal(got, want)
            gi = paddle.argsort(T(x), axis=axis, descending=desc).numpy()
            np.testing.assert_array_equal(
                np.take_along_axis(x, gi, axis=axis), want)


def test_topk_flags():
    x = RNG.standard_normal((4, 7)).astype(np.float32)
    vals, idxs = paddle.topk(T(x), k=3, largest=True, sorted=True)
    tv, ti = torch.topk(torch.tensor(x), 3, largest=True, sorted=True)
    np.testing.assert_allclose(vals.numpy(), tv.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(idxs.numpy(), ti.numpy())
    vals, idxs = paddle.topk(T(x), k=2, largest=False)
    tv, ti = torch.topk(torch.tensor(x), 2, largest=False)
    np.testing.assert_allclose(np.sort(vals.numpy(), -1),
                               np.sort(tv.numpy(), -1), rtol=1e-6)


def test_searchsorted_sides():
    sorted_seq = np.array([[1.0, 3.0, 5.0, 7.0]], np.float32)
    vals = np.array([[3.0, 4.0, 7.0]], np.float32)
    got_l = paddle.searchsorted(T(sorted_seq), T(vals), right=False).numpy()
    got_r = paddle.searchsorted(T(sorted_seq), T(vals), right=True).numpy()
    np.testing.assert_array_equal(got_l[0], [1, 2, 3])
    np.testing.assert_array_equal(got_r[0], [2, 2, 4])


def test_unique_bundle():
    x = np.array([2, 1, 2, 3, 1], np.int64)
    out, index, inverse, counts = paddle.unique(
        T(x), return_index=True, return_inverse=True, return_counts=True)
    np.testing.assert_array_equal(out.numpy(), [1, 2, 3])
    np.testing.assert_array_equal(out.numpy()[inverse.numpy()], x)
    np.testing.assert_array_equal(counts.numpy(), [2, 2, 1])
    np.testing.assert_array_equal(x[index.numpy()], out.numpy())


def test_unique_consecutive():
    x = np.array([1, 1, 2, 2, 2, 3, 1, 1], np.int64)
    out, inverse, counts = paddle.unique_consecutive(
        T(x), return_inverse=True, return_counts=True)
    np.testing.assert_array_equal(out.numpy(), [1, 2, 3, 1])
    np.testing.assert_array_equal(counts.numpy(), [2, 3, 1, 2])
    np.testing.assert_array_equal(out.numpy()[inverse.numpy()], x)


def test_quantile_matches_numpy_linear():
    # the reference snapshot's quantile has no interpolation param: linear
    x = RNG.standard_normal((20,)).astype(np.float64)
    got = float(paddle.quantile(T(x), 0.3).numpy())
    assert abs(got - float(np.quantile(x, 0.3))) < 1e-6
    got2 = paddle.quantile(T(x.reshape(4, 5)), 0.7, axis=1).numpy()
    np.testing.assert_allclose(got2, np.quantile(x.reshape(4, 5), 0.7, axis=1),
                               rtol=1e-6)


def test_cumulative_ops():
    x = RNG.standard_normal((3, 4)).astype(np.float32)
    np.testing.assert_allclose(paddle.cumsum(T(x), axis=1).numpy(),
                               np.cumsum(x, 1), rtol=1e-6)
    np.testing.assert_allclose(paddle.cumprod(T(x), dim=0).numpy(),
                               np.cumprod(x, 0), rtol=1e-5)
    got = paddle.logcumsumexp(T(x), axis=1).numpy()
    want = np.log(np.cumsum(np.exp(x), 1))
    np.testing.assert_allclose(got, want, rtol=1e-5)
    np.testing.assert_allclose(paddle.cummax(T(x), axis=1)[0].numpy(),
                               np.maximum.accumulate(x, 1), rtol=1e-6)
    np.testing.assert_allclose(paddle.cummin(T(x), axis=1)[0].numpy(),
                               np.minimum.accumulate(x, 1), rtol=1e-6)


def test_median_modes():
    x = np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)
    # even count: paddle median averages the two middle values by default
    assert float(paddle.median(T(x), axis=1).numpy()[0]) == 2.5
    x_nan = np.array([1.0, np.nan, 3.0, 2.0], np.float32)
    assert float(paddle.nanmedian(T(x_nan)).numpy()) == 2.0


def test_kthvalue_and_mode():
    x = RNG.standard_normal((2, 6)).astype(np.float32)
    v, i = paddle.kthvalue(T(x), k=2, axis=1)
    tv, ti = torch.kthvalue(torch.tensor(x), 2, dim=1)
    np.testing.assert_allclose(v.numpy(), tv.numpy(), rtol=1e-6)
    xm = np.array([[1, 2, 2, 3], [4, 4, 5, 4]], np.int64)
    v, i = paddle.mode(T(xm), axis=1)
    np.testing.assert_array_equal(v.numpy(), [2, 4])


def test_histogram_and_bincount():
    x = np.array([0.5, 1.5, 1.6, 3.2], np.float32)
    got = paddle.histogram(T(x), bins=4, min=0, max=4).numpy()
    want, _ = np.histogram(x, bins=4, range=(0, 4))
    np.testing.assert_array_equal(got, want)
    xi = np.array([0, 1, 1, 3], np.int64)
    np.testing.assert_array_equal(paddle.bincount(T(xi)).numpy(),
                                  np.bincount(xi))

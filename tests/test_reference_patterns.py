"""Second tier of switching-user patterns: the idioms just past quickstart
that a reference (PaddlePaddle 2.x) user reaches for immediately —
ParamAttr/initializer/regularizer, PyLayer custom autograd, container
layers, buffers, no_grad, lr get/set, parameter traversal, value clipping.
All bodies are written exactly as reference code (only the import differs).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_param_attr_initializer_regularizer():
    fc = nn.Linear(
        4, 3,
        weight_attr=paddle.ParamAttr(
            initializer=nn.initializer.Constant(0.5),
            regularizer=paddle.regularizer.L2Decay(1e-4)),
        bias_attr=paddle.ParamAttr(initializer=nn.initializer.Constant(0.1)))
    np.testing.assert_allclose(fc.weight.numpy(), np.full((4, 3), 0.5),
                               atol=0)
    np.testing.assert_allclose(fc.bias.numpy(), np.full((3,), 0.1), atol=0)

    k = nn.Linear(16, 16,
                  weight_attr=nn.initializer.KaimingNormal())
    std = float(k.weight.numpy().std())
    assert 0.1 < std < 0.8  # fan-based scale, not constant/zeros

    x = nn.initializer.XavierUniform()
    lin = nn.Linear(8, 8, weight_attr=x)
    assert abs(float(lin.weight.numpy().mean())) < 0.2


def test_pylayer_custom_op():
    class Cube(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor()
            return dy * 3 * x * x

    t = paddle.to_tensor(np.array([2.0, -1.0], np.float32),
                         stop_gradient=False)
    y = Cube.apply(t)
    y.sum().backward()
    np.testing.assert_allclose(t.grad.numpy(), [12.0, 3.0], atol=1e-6)


def test_container_layers_and_traversal():
    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.layers = nn.LayerList([nn.Linear(4, 4) for _ in range(3)])
            self.extra = nn.ParameterList([
                paddle.create_parameter([4], "float32")])

        def forward(self, x):
            for l in self.layers:
                x = l(x)
            return x + self.extra[0]

    b = Block()
    names = [n for n, _ in b.named_parameters()]
    assert len(names) == 7  # 3 * (w, b) + 1
    assert any("layers.1" in n for n in names)
    assert len(list(b.sublayers())) >= 4
    out = b(paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert out.shape == [2, 4]

    seq = nn.Sequential(
        ("fc1", nn.Linear(4, 8)), ("act", nn.ReLU()), ("fc2", nn.Linear(8, 2)))
    assert seq(paddle.to_tensor(np.ones((1, 4), np.float32))).shape == [1, 2]


def test_register_buffer_and_state_dict():
    class WithStats(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)
            self.register_buffer("steps", paddle.zeros([1], dtype="float32"))

        def forward(self, x):
            return self.fc(x)

    m = WithStats()
    assert "steps" in m.state_dict()
    assert not any(n == "steps" for n, _ in m.named_parameters())


def test_no_grad_and_stop_gradient():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    z = x * 2
    assert not z.stop_gradient
    frozen = paddle.to_tensor(np.ones(3, np.float32))  # default stop_gradient
    with pytest.raises(RuntimeError):
        frozen.sum().backward()


def test_lr_get_set_and_clip_value():
    net = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters(),
                               grad_clip=nn.ClipGradByValue(0.01))
    assert opt.get_lr() == pytest.approx(0.1)
    opt.set_lr(0.05)
    assert opt.get_lr() == pytest.approx(0.05)

    w0 = net.weight.numpy().copy()
    x = paddle.to_tensor(np.full((2, 4), 100.0, np.float32))
    loss = net(x).sum()
    loss.backward()
    opt.step()
    # reference contract: clip applies to the UPDATE (p.grad keeps the raw
    # value); |grad| clipped to 0.01 at lr 0.05 moves weights <= 5e-4
    delta = np.abs(net.weight.numpy() - w0).max()
    assert delta <= 0.05 * 0.01 + 1e-7, delta


def test_apply_and_children():
    m = nn.Sequential(nn.Linear(2, 2), nn.Sequential(nn.Linear(2, 2)))
    hit = []

    def fn(layer):
        hit.append(type(layer).__name__)

    m.apply(fn)
    assert "Linear" in hit and "Sequential" in hit
    assert len(list(m.children())) == 2


def test_tensor_methods_a_reference_user_expects():
    t = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert t.mean(axis=0).shape == [4]
    assert t.max().item() == 11.0
    assert t.argmax(axis=1).numpy().tolist() == [3, 3, 3]
    # canonical-width policy (TPU-native, x64 off): 64-bit requests narrow
    # to 32-bit consistently for every spelling, warning-free
    assert t.astype("int64").dtype == paddle.int32
    assert t.astype(np.int64).dtype == t.astype("int64").dtype
    assert t.flatten().shape == [12]
    assert t.unsqueeze(0).squeeze(0).shape == [3, 4]
    assert paddle.concat([t, t], axis=0).shape == [6, 4]
    assert paddle.split(t, 2, axis=1)[0].shape == [3, 2]
    c = t.clone()
    c[0, 0] = 99.0
    assert float(t[0, 0]) == 0.0  # clone is a copy
    assert t.cpu().numpy().sum() == t.numpy().sum()
    assert not t.place.is_gpu_place() if hasattr(t.place, "is_gpu_place") else True


def test_einsum_matmul_broadcast_semantics():
    a = paddle.to_tensor(np.random.randn(2, 3, 4).astype(np.float32))
    b = paddle.to_tensor(np.random.randn(2, 4, 5).astype(np.float32))
    np.testing.assert_allclose(
        paddle.matmul(a, b).numpy(),
        paddle.einsum("bij,bjk->bik", a, b).numpy(), atol=1e-5)
    v = paddle.to_tensor(np.random.randn(4).astype(np.float32))
    assert paddle.matmul(a, v.unsqueeze(-1)).shape == [2, 3, 1]

"""The switching-user contract: canonical reference (PaddlePaddle 2.x)
quickstart patterns, written exactly as a reference user writes them, run
unchanged against this framework (only the import line differs).

Each test is one public-docs-style flow (tensor quickstart, subclass-Layer
training loop, Dataset/DataLoader, hapi Model.fit, save/load, to_static +
jit.save, AMP, static graph, fleet DP, schedulers/clip, vision transforms,
distribution/linalg/fft) — the shapes of code in the reference's
get-started and practice docs (ref:python/paddle/__init__.py surface,
ref:python/paddle/hapi/model.py, ref:python/paddle/jit/api.py).
"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_tensor_quickstart():
    x = paddle.to_tensor([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])
    y = paddle.to_tensor(np.ones((2, 3), np.float32))
    z = x + y * 2
    assert z.shape == [2, 3]
    np.testing.assert_allclose(z.numpy()[0], [3.0, 4.0, 5.0])
    assert float(paddle.sum(z)) == pytest.approx(33.0)
    # slicing / reshape / transpose / broadcasting
    assert z[0, 1:].shape == [2]
    assert paddle.reshape(z, [3, 2]).shape == [3, 2]
    assert paddle.transpose(z, [1, 0]).shape == [3, 2]
    a = paddle.arange(6, dtype="float32").reshape([2, 3])
    b = paddle.unsqueeze(paddle.to_tensor([1.0, 2.0]), 1)
    assert (a * b).shape == [2, 3]
    # dtype/device introspection
    assert "float32" in str(z.dtype)
    assert paddle.nn.functional.relu(paddle.to_tensor([-1.0, 2.0])).numpy().tolist() == [0.0, 2.0]


class _Net(nn.Layer):
    def __init__(self, num_classes=4):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, num_classes)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def _toy(n=64, d=16, c=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d), dtype=np.float32)
    w = rng.standard_normal((d, c), dtype=np.float32)
    y = (x @ w).argmax(1).astype(np.int64)
    return x, y


def test_subclass_layer_training_loop():
    """The canonical eager loop: forward -> loss -> backward -> step."""
    paddle.seed(0)
    x_np, y_np = _toy()
    net = _Net()
    loss_fn = nn.CrossEntropyLoss()
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=20, gamma=0.5)
    opt = paddle.optimizer.Momentum(learning_rate=sched, momentum=0.9,
                                    parameters=net.parameters(),
                                    grad_clip=nn.ClipGradByGlobalNorm(1.0))
    first = last = None
    for epoch in range(40):
        x = paddle.to_tensor(x_np)
        y = paddle.to_tensor(y_np)
        out = net(x)
        loss = loss_fn(out, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        sched.step()
        if first is None:
            first = float(loss)
        last = float(loss)
    assert last < 0.5 * first
    acc = (net(paddle.to_tensor(x_np)).numpy().argmax(1) == y_np).mean()
    assert acc > 0.8


def test_dataset_dataloader():
    from paddle_tpu import io

    x_np, y_np = _toy(n=32)

    class MyDataset(io.Dataset):
        def __init__(self):
            super().__init__()

        def __getitem__(self, idx):
            return x_np[idx], y_np[idx]

        def __len__(self):
            return len(x_np)

    loader = io.DataLoader(MyDataset(), batch_size=8, shuffle=True,
                           drop_last=False)
    seen = 0
    for xb, yb in loader:
        assert xb.shape == [8, 16]
        seen += int(xb.shape[0])
    assert seen == 32


def test_hapi_model_fit_evaluate_predict():
    from paddle_tpu import io

    x_np, y_np = _toy(n=48)

    class DS(io.Dataset):
        def __getitem__(self, i):
            return x_np[i], y_np[i]

        def __len__(self):
            return len(x_np)

    paddle.seed(0)
    model = paddle.Model(_Net())
    model.prepare(
        paddle.optimizer.Adam(learning_rate=0.05, parameters=model.parameters()),
        nn.CrossEntropyLoss(),
        paddle.metric.Accuracy())
    model.fit(DS(), epochs=8, batch_size=16, verbose=0)
    res = model.evaluate(DS(), batch_size=16, verbose=0)
    assert res["acc"] > 0.7
    preds = model.predict(DS(), batch_size=16)
    assert np.concatenate(preds[0]).shape[0] == 48


def test_save_load_state_dict(tmp_path):
    paddle.seed(1)
    net = _Net()
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    # one step so optimizer state exists
    x_np, y_np = _toy(n=8)
    loss = nn.CrossEntropyLoss()(net(paddle.to_tensor(x_np)), paddle.to_tensor(y_np))
    loss.backward()
    opt.step()
    pd = os.path.join(tmp_path, "net.pdparams")
    od = os.path.join(tmp_path, "opt.pdopt")
    paddle.save(net.state_dict(), pd)
    paddle.save(opt.state_dict(), od)

    net2 = _Net()
    opt2 = paddle.optimizer.Adam(learning_rate=0.01, parameters=net2.parameters())
    net2.set_state_dict(paddle.load(pd))
    opt2.set_state_dict(paddle.load(od))
    x = paddle.to_tensor(x_np)
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), atol=1e-6)


def test_to_static_and_jit_save_load(tmp_path):
    paddle.seed(2)
    net = _Net()
    net.eval()
    x_np = np.random.randn(4, 16).astype(np.float32)
    eager_out = net(paddle.to_tensor(x_np)).numpy()

    static_net = paddle.jit.to_static(net)
    static_out = static_net(paddle.to_tensor(x_np)).numpy()
    np.testing.assert_allclose(eager_out, static_out, atol=1e-5)

    path = os.path.join(tmp_path, "inference/net")
    paddle.jit.save(net, path, input_spec=[
        paddle.static.InputSpec(shape=[None, 16], dtype="float32")])
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(eager_out, loaded(paddle.to_tensor(x_np)).numpy(),
                               atol=1e-5)


def test_amp_training_pattern():
    paddle.seed(3)
    x_np, y_np = _toy()
    net = _Net()
    opt = paddle.optimizer.Adam(learning_rate=0.02, parameters=net.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024)
    first = last = None
    for _ in range(30):
        with paddle.amp.auto_cast(dtype="bfloat16"):
            loss = nn.CrossEntropyLoss()(net(paddle.to_tensor(x_np)),
                                         paddle.to_tensor(y_np))
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        if first is None:
            first = float(loss)
        last = float(loss)
    assert last < 0.6 * first


def test_static_graph_program():
    from paddle_tpu import static

    paddle.enable_static() if hasattr(paddle, "enable_static") else None
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 16], "float32")
            y = static.data("y", [None], "int64")
            hidden = static.nn.fc(x, size=32, activation="relu")
            out = static.nn.fc(hidden, size=4)
            loss = paddle.mean(
                paddle.nn.functional.cross_entropy(out, y, reduction="none"))
            opt = paddle.optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        x_np, y_np = _toy()
        first = last = None
        for _ in range(30):
            (lv,) = exe.run(main, feed={"x": x_np, "y": y_np},
                            fetch_list=[loss])
            if first is None:
                first = float(lv)
            last = float(lv)
        assert last < 0.5 * first
    finally:
        if hasattr(paddle, "disable_static"):
            paddle.disable_static()


def test_fleet_data_parallel():
    from paddle_tpu import distributed as dist
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(4)
    net = _Net()
    net = fleet.distributed_model(net)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    opt = fleet.distributed_optimizer(opt)
    x_np, y_np = _toy(n=32)
    first = last = None
    for _ in range(20):
        loss = nn.CrossEntropyLoss()(net(paddle.to_tensor(x_np)),
                                     paddle.to_tensor(y_np))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss)
        last = float(loss)
    assert last < 0.6 * first
    assert dist.get_world_size() >= 1


def test_vision_transforms_and_model():
    from paddle_tpu.vision import transforms

    t = transforms.Compose([
        transforms.Resize(36),
        transforms.CenterCrop(32),
        transforms.ToTensor(),
        transforms.Normalize(mean=[0.5, 0.5, 0.5], std=[0.5, 0.5, 0.5]),
    ])
    img = (np.random.rand(40, 48, 3) * 255).astype(np.uint8)
    out = t(img)
    assert list(out.shape) == [3, 32, 32]

    from paddle_tpu.vision.models import resnet18

    m = resnet18(num_classes=10)
    m.eval()
    logits = m(paddle.to_tensor(np.random.randn(2, 3, 32, 32).astype(np.float32)))
    assert logits.shape == [2, 10]


def test_distribution_linalg_fft():
    d = paddle.distribution.Normal(loc=0.0, scale=1.0)
    s = d.sample([256])
    assert abs(float(paddle.mean(s))) < 0.5
    lp = d.log_prob(paddle.to_tensor(0.0))
    assert float(lp) == pytest.approx(-0.9189, abs=1e-3)

    mat = paddle.to_tensor(np.random.randn(6, 4).astype(np.float32))
    u, sv, vh = paddle.linalg.svd(mat, full_matrices=False)
    rec = u @ paddle.diag(sv) @ vh
    np.testing.assert_allclose(rec.numpy(), mat.numpy(), atol=1e-4)

    sig = paddle.to_tensor(np.random.randn(64).astype(np.float32))
    spec = paddle.fft.rfft(sig)
    back = paddle.fft.irfft(spec, n=64)
    np.testing.assert_allclose(back.numpy(), sig.numpy(), atol=1e-4)

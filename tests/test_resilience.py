"""core.resilience: retry policy, fault injection, NaN/Inf step sentinel,
checkpoint integrity/fallback, preemption guard — the chaos suite
(ISSUE 3). Fault-driven cases carry the ``chaos`` marker; the end-to-end
SIGTERM preemption test is additionally ``slow`` (two subprocess runs)."""
import json
import os
import signal
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core import flags, resilience
from paddle_tpu.distributed.checkpoint import (
    CheckpointIntegrityError,
    TrainCheckpointer,
)
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import Adam

_FLAG_KEYS = ("fault_injection", "max_bad_steps", "trainstep_sentinel",
              "ckpt_manifest", "io_retries", "io_retry_backoff",
              "io_retry_deadline", "inject_faults", "check_nan_inf",
              "check_nan_inf_level")


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    keep = {k: paddle.get_flags(k)[k] for k in _FLAG_KEYS}
    resilience.reset_stats()
    try:
        yield
    finally:
        resilience.clear_faults()
        resilience.set_rollback_handler(None)
        paddle.set_flags(keep)


def _small_net(seed=0):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    opt = Adam(learning_rate=1e-2, parameters=net.parameters())
    return net, opt


def _batch():
    r = np.random.RandomState(0)
    return (paddle.to_tensor(r.rand(8, 4).astype(np.float32)),
            paddle.to_tensor(r.rand(8, 1).astype(np.float32)))


def _param_bytes(net):
    return {k: np.asarray(v._data).copy() for k, v in net.state_dict().items()}


# ------------------------------------------------------------------- retry


def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    out = resilience.call_with_retry(
        flaky, policy=resilience.RetryPolicy(max_attempts=5,
                                             base_delay=0.001),
        name="unit")
    assert out == "ok" and len(calls) == 3
    s = resilience.stats()
    assert s["retry.retries"] == 2 and s["retry.unit"] == 2


def test_retry_exhausts_and_reraises_original():
    def always():
        raise ValueError("permanent")

    with pytest.raises(ValueError, match="permanent"):
        resilience.call_with_retry(
            always, policy=resilience.RetryPolicy(max_attempts=2,
                                                  base_delay=0.001))
    assert resilience.stats()["retry.exhausted"] == 1


def test_retry_giveup_short_circuits():
    calls = []

    def fatal():
        calls.append(1)
        raise RuntimeError("already initialized")

    with pytest.raises(RuntimeError):
        resilience.call_with_retry(
            fatal,
            policy=resilience.RetryPolicy(
                max_attempts=5, base_delay=0.001,
                giveup=lambda e: "already" in str(e)))
    assert len(calls) == 1  # no retries for an unhealable error


def test_retry_deadline_bounds_attempts():
    calls = []

    def slow_fail():
        calls.append(1)
        time.sleep(0.03)
        raise OSError("x")

    with pytest.raises(OSError):
        resilience.call_with_retry(
            slow_fail, policy=resilience.RetryPolicy(
                max_attempts=100, base_delay=0.001, deadline=0.05))
    assert len(calls) < 10


# --------------------------------------------------------- fault injection


def test_inject_fault_requires_flag():
    with pytest.raises(RuntimeError, match="FLAGS_fault_injection"):
        resilience.inject_fault("ckpt_io")
    assert resilience.maybe_fault("ckpt_io") is False  # inert when off


@pytest.mark.chaos
def test_fault_fires_deterministically():
    paddle.set_flags({"FLAGS_fault_injection": True})
    resilience.inject_fault("preempt", times=2, after=1)
    assert resilience.maybe_fault("preempt") is False  # the `after` pass
    assert resilience.maybe_fault("preempt") is True
    assert resilience.maybe_fault("preempt") is True
    assert resilience.maybe_fault("preempt") is False  # disarmed
    assert resilience.stats()["fault.preempt"] == 2
    resilience.inject_fault("ckpt_io", exc=OSError("boom"))
    with pytest.raises(OSError, match="boom"):
        resilience.maybe_fault("ckpt_io")


@pytest.mark.chaos
def test_env_armed_faults():
    paddle.set_flags({"FLAGS_fault_injection": True,
                      "FLAGS_inject_faults": "preempt:1:1"})
    resilience.clear_faults()
    resilience._env_faults_loaded = False
    assert resilience.maybe_fault("preempt") is False
    assert resilience.maybe_fault("preempt") is True
    assert resilience.maybe_fault("preempt") is False


def test_serving_fault_kinds_and_error_taxonomy():
    """ISSUE 5: the registry knows the serving fault kinds, and the error
    classes the serving resilience layer is built on exist with the right
    ancestry (drained is retriable-by-contract; device/arena-corrupt are
    the supervisor-recoverable classes)."""
    for kind in ("serving_step", "serving_device", "arena_corrupt"):
        assert kind in resilience.KNOWN_FAULTS
    for klass in (resilience.ServingDeviceError, resilience.ArenaCorruptError,
                  resilience.RequestDrainedError):
        assert issubclass(klass, RuntimeError)


@pytest.mark.chaos
def test_serving_faults_default_to_their_error_classes():
    """serving_device/arena_corrupt probe sites are bare statements, so the
    injected fault defaults to raising the error class the real failure
    would — a flag-style fault would silently exercise nothing."""
    paddle.set_flags({"FLAGS_fault_injection": True})
    resilience.inject_fault("serving_device")
    with pytest.raises(resilience.ServingDeviceError, match="injected"):
        resilience.maybe_fault("serving_device")
    resilience.inject_fault("arena_corrupt")
    with pytest.raises(resilience.ArenaCorruptError, match="injected"):
        resilience.maybe_fault("arena_corrupt")
    # env arming defaults the same way
    paddle.set_flags({"FLAGS_inject_faults": "serving_device:1"})
    resilience.clear_faults()
    resilience._env_faults_loaded = False
    with pytest.raises(resilience.ServingDeviceError):
        resilience.maybe_fault("serving_device")
    resilience._env_faults_loaded = False


# ------------------------------------------------- atomic paddle_tpu.save


@pytest.mark.chaos
def test_kill_mid_save_preserves_previous_file(tmp_path):
    path = os.path.join(str(tmp_path), "model.pdparams")
    paddle.save({"w": np.arange(4, dtype=np.float32)}, path)
    paddle.set_flags({"FLAGS_fault_injection": True, "FLAGS_io_retries": 1})
    resilience.inject_fault("ckpt_io", exc=OSError("killed mid-save"))
    with pytest.raises(OSError):
        paddle.save({"w": np.zeros(999, dtype=np.float32)}, path)
    # the interrupted save left the previous complete pickle, no tmp litter
    out = paddle.load(path, return_numpy=True)
    np.testing.assert_array_equal(out["w"], np.arange(4, dtype=np.float32))
    assert not [f for f in os.listdir(str(tmp_path)) if f.endswith(".tmp")]


@pytest.mark.chaos
def test_save_retries_transient_io(tmp_path):
    path = os.path.join(str(tmp_path), "model.pdparams")
    paddle.set_flags({"FLAGS_fault_injection": True,
                      "FLAGS_io_retry_backoff": 0.001})
    resilience.inject_fault("ckpt_io", times=1, exc=OSError("transient"))
    paddle.save({"w": np.arange(3)}, path)  # first attempt fails, retry wins
    assert resilience.stats()["retry.paddle.save"] >= 1
    np.testing.assert_array_equal(
        paddle.load(path, return_numpy=True)["w"], np.arange(3))


# --------------------------------------------------- checkpoint integrity


def _ckpt_with_two_steps(tmp_path):
    net, _ = _small_net()
    ck = TrainCheckpointer(os.path.join(str(tmp_path), "mgr"), max_to_keep=4)
    step1_values = _param_bytes(net)  # set_value below mutates in place
    ck.save(1, {k: v for k, v in net.state_dict().items()})
    net[0].weight.set_value(paddle.to_tensor(np.ones((4, 8), np.float32)))
    ck.save(2, {k: v for k, v in net.state_dict().items()})
    ck.wait_until_finished()
    return ck, step1_values


def test_restore_missing_step_raises_clear_error(tmp_path):
    ck, _ = _ckpt_with_two_steps(tmp_path)
    with pytest.raises(ValueError, match=r"available steps: \[1, 2\]"):
        ck.restore(step=7)


def test_manifests_written_and_gcd(tmp_path):
    net, _ = _small_net()
    ck = TrainCheckpointer(os.path.join(str(tmp_path), "mgr"), max_to_keep=2)
    for s in (1, 2, 3):
        ck.save(s, {k: v for k, v in net.state_dict().items()})
        ck.wait_until_finished()
    mdir = os.path.join(str(tmp_path), "mgr", "manifests")
    kept = sorted(int(n.split(".")[0]) for n in os.listdir(mdir))
    assert kept == [2, 3]  # step 1 retired with orbax's retention


@pytest.mark.chaos
def test_truncated_newest_step_falls_back(tmp_path):
    import glob

    ck, sd1 = _ckpt_with_two_steps(tmp_path)
    step2 = os.path.join(str(tmp_path), "mgr", "2")
    victims = [p for p in glob.glob(os.path.join(step2, "**", "*"),
                                    recursive=True)
               if os.path.isfile(p) and os.path.getsize(p) > 0]
    assert victims, "expected data files in the step dir"
    for v in victims:  # simulate the kill mid-write: zero-length files
        open(v, "wb").close()
    paddle.set_flags({"FLAGS_io_retries": 1})  # fail fast on the dead step
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        out = ck.restore()
    assert ck.last_restored_step == 1
    np.testing.assert_array_equal(np.asarray(out["0.weight"]),
                                  sd1["0.weight"])
    assert resilience.stats()["ckpt.invalid_steps"] >= 1


@pytest.mark.chaos
def test_checksum_mismatch_falls_back_and_explicit_step_raises(tmp_path):
    ck, sd1 = _ckpt_with_two_steps(tmp_path)
    mpath = os.path.join(str(tmp_path), "mgr", "manifests", "2.json")
    with open(mpath) as f:
        manifest = json.load(f)
    leaf = next(iter(manifest["leaves"]))
    manifest["leaves"][leaf]["crc32"] = 12345  # silent-corruption stand-in
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    # explicit step: the caller asked for step 2 — fail loudly
    with pytest.raises(CheckpointIntegrityError, match="checksum"):
        ck.restore(step=2)
    # auto-resume: skip the bad step, land on the previous valid one
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ck.restore()
    assert ck.last_restored_step == 1
    assert ck.latest_valid_step() == 1


@pytest.mark.chaos
def test_ckpt_save_restore_retry_transient_fault(tmp_path):
    net, _ = _small_net()
    ck = TrainCheckpointer(os.path.join(str(tmp_path), "mgr"))
    paddle.set_flags({"FLAGS_fault_injection": True,
                      "FLAGS_io_retry_backoff": 0.001})
    resilience.inject_fault("ckpt_io", times=1, exc=OSError("flaky fs"))
    ck.save(1, {k: v for k, v in net.state_dict().items()})
    ck.wait_until_finished()
    assert resilience.stats()["retry.ckpt.save"] >= 1
    resilience.inject_fault("ckpt_io", times=1, exc=OSError("flaky fs"))
    assert ck.restore() is not None
    assert resilience.stats()["retry.ckpt.restore"] >= 1


# ------------------------------------------------------- NaN/Inf sentinel


@pytest.mark.chaos
def test_injected_nonfinite_step_skips_update_bit_identical():
    net, opt = _small_net()
    X, Y = _batch()
    step = TrainStep(lambda x, y: ((net(x) - y) ** 2).mean(), opt,
                     layers=net)
    step(X, Y)  # one good step so optimizer state exists
    before = _param_bytes(net)
    opt_step_before = opt._step_count
    paddle.set_flags({"FLAGS_fault_injection": True})
    resilience.inject_fault("nonfinite_grads", times=1)
    loss = step(X, Y)
    assert not np.isfinite(float(loss.numpy()))
    after = _param_bytes(net)
    for k in before:  # params bit-identical to pre-step
        np.testing.assert_array_equal(before[k], after[k])
    assert opt._step_count == opt_step_before  # no optimizer advance
    assert resilience.stats()["sentinel.skipped"] == 1
    # training recovers on the next (clean) step
    assert np.isfinite(float(step(X, Y).numpy()))
    assert opt._step_count == opt_step_before + 1


@pytest.mark.chaos
def test_skipped_step_does_not_poison_buffers():
    """BN running stats are computed during the (poisoned) forward; a
    skipped step must withhold them too, or eval-mode outputs go NaN even
    though the sentinel reported the step as safely skipped."""
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8), nn.Linear(8, 1))
    opt = Adam(learning_rate=1e-2, parameters=net.parameters())
    X, Y = _batch()
    step = TrainStep(lambda x, y: ((net(x) - y) ** 2).mean(), opt,
                     layers=net)
    step(X, Y)
    bufs_before = {k: np.asarray(b._data).copy()
                   for k, b in net.named_buffers()}
    assert bufs_before, "expected BN running-stat buffers"
    paddle.set_flags({"FLAGS_fault_injection": True})
    resilience.inject_fault("nonfinite_grads", times=1)
    step(X, Y)
    for k, b in net.named_buffers():
        np.testing.assert_array_equal(bufs_before[k], np.asarray(b._data),
                                      err_msg=k)
    assert all(np.isfinite(np.asarray(b._data)).all()
               for _, b in net.named_buffers())


def test_tensor_checker_debug_step_window_and_warn_once():
    from paddle_tpu.amp import debugging as dbg

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        dbg.enable_tensor_checker(
            dbg.TensorCheckerConfig(checked_op_list=["matmul"]))
        dbg.enable_tensor_checker(
            dbg.TensorCheckerConfig(checked_op_list=["matmul"]))
    assert len([x for x in w if "checked_op_list" in str(x.message)]) <= 1
    dbg.disable_tensor_checker()

    dbg.enable_tensor_checker(dbg.TensorCheckerConfig(debug_step=[0, 1]))
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError):
            x / x  # nan at index 1, step 0 -> inside the window
        # an optimizer step advances the window (marked at the END of
        # step(), so the step's own update ops were still covered)
        p = paddle.to_tensor(np.ones(2, np.float32))
        p.stop_gradient = False
        from paddle_tpu.optimizer import SGD

        opt = SGD(learning_rate=0.1, parameters=[p])
        (p * paddle.to_tensor(np.ones(2, np.float32))).sum().backward()
        opt.step()
        assert not dbg.step_check_active()
        x / x  # outside the window: no raise
    finally:
        dbg.disable_tensor_checker()


def test_natural_nan_input_is_skipped():
    net, opt = _small_net()
    X, Y = _batch()
    step = TrainStep(lambda x, y: ((net(x) - y) ** 2).mean(), opt,
                     layers=net)
    step(X, Y)
    before = _param_bytes(net)
    bad = np.asarray(X.numpy()).copy()
    bad[0, 0] = np.nan
    step(paddle.to_tensor(bad), Y)
    for k, v in _param_bytes(net).items():
        np.testing.assert_array_equal(before[k], v)
    assert resilience.stats()["sentinel.skipped"] == 1


def test_sentinel_results_bit_identical_to_disabled():
    X, Y = _batch()

    def run():
        net, opt = _small_net(seed=7)
        step = TrainStep(lambda x, y: ((net(x) - y) ** 2).mean(), opt,
                         layers=net)
        losses = [float(step(X, Y).numpy()) for _ in range(4)]
        return losses, _param_bytes(net)

    l_on, p_on = run()
    paddle.set_flags({"FLAGS_trainstep_sentinel": False})
    l_off, p_off = run()
    assert l_on == l_off
    for k in p_on:
        np.testing.assert_array_equal(p_on[k], p_off[k])


@pytest.mark.chaos
def test_rollback_after_max_bad_steps_restores_checkpoint(tmp_path):
    net, opt = _small_net()
    X, Y = _batch()
    step = TrainStep(lambda x, y: ((net(x) - y) ** 2).mean(), opt,
                     layers=net)
    step(X, Y)
    ck = TrainCheckpointer(os.path.join(str(tmp_path), "mgr"))
    ck.save(0, {"model": net.state_dict(), "opt": opt.state_dict()})
    ck.wait_until_finished()
    good = _param_bytes(net)

    def rollback(reason):
        restored = ck.restore()
        net.set_state_dict(restored["model"])
        opt.set_state_dict(restored["opt"])

    resilience.set_rollback_handler(rollback)
    paddle.set_flags({"FLAGS_fault_injection": True,
                      "FLAGS_max_bad_steps": 2})
    resilience.inject_fault("nonfinite_grads", times=2)
    step(X, Y)
    step(X, Y)  # second consecutive bad step triggers the rollback
    assert resilience.stats()["sentinel.rollbacks"] == 1
    for k, v in _param_bytes(net).items():
        np.testing.assert_array_equal(good[k], v)
    # post-rollback training proceeds (fresh compiled opt-state re-seed)
    assert np.isfinite(float(step(X, Y).numpy()))


@pytest.mark.chaos
def test_rollback_without_handler_raises():
    net, opt = _small_net()
    X, Y = _batch()
    step = TrainStep(lambda x, y: ((net(x) - y) ** 2).mean(), opt,
                     layers=net)
    paddle.set_flags({"FLAGS_fault_injection": True,
                      "FLAGS_max_bad_steps": 1})
    resilience.inject_fault("nonfinite_grads", times=1)
    with pytest.raises(resilience.NonfiniteStepError):
        step(X, Y)


# ----------------------------------------------------------- preemption


def test_preemption_guard_signal_requests_not_kills():
    guard = resilience.PreemptionGuard()
    try:
        assert not guard.requested()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5
        while not guard.requested() and time.time() < deadline:
            time.sleep(0.01)
        assert guard.requested()  # still alive: the signal became a request
        assert "signal" in guard.reason
    finally:
        guard.uninstall()


def test_preemption_guard_second_signal_escalates():
    """A hung step never reaches the boundary poll: the SECOND signal must
    fall through to the previous handler instead of being swallowed."""
    hits = []
    sig = signal.SIGUSR1
    prev = signal.signal(sig, lambda s, f: hits.append(s))
    guard = resilience.PreemptionGuard(signals=(sig,))
    try:
        os.kill(os.getpid(), sig)
        deadline = time.time() + 5
        while not guard.requested() and time.time() < deadline:
            time.sleep(0.01)
        assert guard.requested() and not hits  # first: request, no chain
        os.kill(os.getpid(), sig)
        deadline = time.time() + 5
        while not hits and time.time() < deadline:
            time.sleep(0.01)
        assert hits == [sig]  # second: escalated to the previous handler
        assert resilience.stats()["preempt.escalations"] == 1
    finally:
        guard.uninstall()
        signal.signal(sig, prev)


def test_trainstep_advances_checker_window():
    """debug_step windows must track compiled steps too — a TrainStep run
    never calls Optimizer.step, which would freeze the window open."""
    from paddle_tpu.amp import debugging as dbg

    net, opt = _small_net()
    X, Y = _batch()
    step = TrainStep(lambda x, y: ((net(x) - y) ** 2).mean(), opt,
                     layers=net)
    step(X, Y)  # build outside the checker
    dbg.enable_tensor_checker(dbg.TensorCheckerConfig(debug_step=[0, 1]))
    try:
        assert dbg.step_check_active()
        step(X, Y)  # one compiled optimizer step closes the [0, 1) window
        assert not dbg.step_check_active()
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        x / x  # nan outside the window: the eager scan stays quiet
    finally:
        dbg.disable_tensor_checker()


@pytest.mark.chaos
def test_preemption_finalize_saves_marker_and_exits(tmp_path):
    net, opt = _small_net()
    ck = TrainCheckpointer(os.path.join(str(tmp_path), "mgr"))
    guard = resilience.PreemptionGuard(install=False)
    state = lambda: {"model": net.state_dict()}  # noqa: E731
    assert guard.maybe_finalize(3, ck, state) is False  # nothing requested
    paddle.set_flags({"FLAGS_fault_injection": True})
    resilience.inject_fault("preempt", times=1)
    with pytest.raises(SystemExit) as e:
        guard.maybe_finalize(3, ck, state)
    assert e.value.code == 0
    assert ck.resume_marker()["step"] == 3
    assert ck.latest_step() == 3
    restored = ck.restore()
    assert ck.last_restored_step == 3 and "model" in restored


def test_elastic_dead_peer_feeds_guard():
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.distributed.store import TCPStore

    store = TCPStore("127.0.0.1", 0, is_master=True, world_size=2)
    try:
        m0 = ElasticManager(store, rank=0, world_size=2, lease=0.6).start()
        m1 = ElasticManager(store, rank=1, world_size=2, lease=0.6).start()
        assert m0.wait_for_world(timeout=5)
        guard = resilience.PreemptionGuard(install=False)
        m0.bind_preemption_guard(guard, interval=0.1)
        m1.stop()  # rank 1 stops heartbeating: the preemption signal
        deadline = time.time() + 5
        while not guard.requested() and time.time() < deadline:
            time.sleep(0.05)
        assert guard.requested() and "dead peers [1]" in guard.reason
        m0.stop()
    finally:
        store.close()


# ------------------------------------------------------- observability


def test_counters_ride_memory_stats():
    from paddle_tpu.core import memory_stats

    resilience.bump("sentinel.skipped", 3)
    out = memory_stats.memory_stats()
    assert out["provider.resilience.sentinel_skipped"] >= 3


def test_serving_counters_ride_memory_stats():
    """ISSUE 5: the serving resilience counters (supervisor replay,
    scheduler preemption, API drain) land on the shared memory_stats
    provider surface next to the training-side ones."""
    from paddle_tpu.core import memory_stats

    resilience.bump("serving.preemptions")
    resilience.bump("serving.replays", 2)
    resilience.bump("serving.rebuilds")
    resilience.bump("serving.drains")
    resilience.bump("serving.drain_stragglers", 3)
    out = memory_stats.memory_stats()
    assert out["provider.resilience.serving_preemptions"] >= 1
    assert out["provider.resilience.serving_replays"] >= 2
    assert out["provider.resilience.serving_rebuilds"] >= 1
    assert out["provider.resilience.serving_drains"] >= 1
    assert out["provider.resilience.serving_drain_stragglers"] >= 3


def test_resilience_stats_tool_reports_ckpt_dir(tmp_path):
    net, _ = _small_net()
    ck = TrainCheckpointer(os.path.join(str(tmp_path), "mgr"))
    ck.save(1, {k: v for k, v in net.state_dict().items()})
    ck.wait_until_finished()
    ck.write_resume_marker(1, reason="unit")
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "resilience_stats.py")
    r = subprocess.run(
        [sys.executable, tool, "--ckpt",
         os.path.join(str(tmp_path), "mgr"), "--json"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout)
    assert rep["steps"] == [1] and rep["manifest_steps"] == [1]
    assert rep["resume_marker"]["step"] == 1


# -------------------------------------------- SIGTERM end-to-end (chaos)

_PREEMPT_SCRIPT = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core import resilience
from paddle_tpu.distributed.checkpoint import TrainCheckpointer
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import Adam

work, total = sys.argv[1], int(sys.argv[2])
paddle.seed(3)
net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
opt = Adam(learning_rate=1e-2, parameters=net.parameters())
ck = TrainCheckpointer(os.path.join(work, "ckpt"), max_to_keep=2)
start = 0
restored = ck.restore()
if restored is not None:
    net.set_state_dict(restored["model"])
    opt.set_state_dict(restored["opt"])
    start = ck.last_restored_step + 1
guard = resilience.PreemptionGuard()
r = np.random.RandomState(0)
X = paddle.to_tensor(r.rand(16, 4).astype(np.float32))
Y = paddle.to_tensor(r.rand(16, 1).astype(np.float32))
step_fn = TrainStep(lambda x, y: ((net(x) - y) ** 2).mean(), opt, layers=net)
state = lambda: {"model": net.state_dict(), "opt": opt.state_dict()}
with open(os.path.join(work, "steps.log"), "a") as log:
    print(f"# start={start}", file=log, flush=True)
    for step in range(start, total):
        step_fn(X, Y)
        ck.save(step, state())
        print(step, file=log, flush=True)
        guard.maybe_finalize(step, ck, state)  # SystemExit(0) on preemption
        import time
        time.sleep(0.1)  # the parent's SIGTERM window
    ck.wait_until_finished()
    print("# done", file=log, flush=True)
"""


@pytest.mark.slow
@pytest.mark.chaos
def test_sigterm_preemption_checkpoint_and_resume(tmp_path):
    """Criterion (a): SIGTERM mid-training produces a final checkpoint and
    a restarted run resumes from it within one step."""
    work = str(tmp_path)
    script = os.path.join(work, "train.py")
    with open(script, "w") as f:
        f.write(_PREEMPT_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    total = 200  # large enough that SIGTERM always lands mid-run
    p = subprocess.Popen([sys.executable, script, work, str(total)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE)
    log = os.path.join(work, "steps.log")
    deadline = time.time() + 120
    while time.time() < deadline:  # wait for a few completed steps
        if os.path.exists(log) and sum(
                1 for l in open(log) if not l.startswith("#")) >= 3:
            break
        time.sleep(0.05)
        assert p.poll() is None, p.stderr.read().decode()[-2000:]
    p.send_signal(signal.SIGTERM)
    out, err = p.communicate(timeout=120)
    assert p.returncode == 0, err.decode()[-2000:]  # clean exit, not a kill

    ck = TrainCheckpointer(os.path.join(work, "ckpt"))
    marker = ck.resume_marker()
    assert marker is not None and "signal" in marker["reason"]
    final = marker["step"]
    assert ck.latest_valid_step() == final

    # restart: must resume from final+1 (within one step of the preemption)
    r2 = subprocess.run([sys.executable, script, work, str(final + 4)],
                        env=env, capture_output=True, timeout=180)
    assert r2.returncode == 0, r2.stderr.decode()[-2000:]
    lines = open(log).read().splitlines()
    starts = [int(l.split("=")[1]) for l in lines if l.startswith("# start=")]
    assert starts[1] == final + 1, (starts, final)
    assert "# done" in lines

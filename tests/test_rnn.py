"""RNN layers: shapes, torch-golden values, training."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _copy_rnn_weights(torch, ours, ref):
    """Map our per-gate l0 parameters onto torch's packed l0 weights."""
    with torch.no_grad():
        ref.weight_ih_l0.copy_(torch.tensor(np.asarray(ours.wi_l0_d0._data)))
        ref.weight_hh_l0.copy_(torch.tensor(np.asarray(ours.wh_l0_d0._data)))
        ref.bias_ih_l0.copy_(torch.tensor(np.asarray(ours.bi_l0_d0._data)))
        ref.bias_hh_l0.copy_(torch.tensor(np.asarray(ours.bh_l0_d0._data)))


def test_lstm_matches_torch():
    torch = pytest.importorskip("torch")
    paddle.seed(0)
    b, s, f, h = 2, 5, 4, 3
    ours = nn.LSTM(f, h, num_layers=1)
    ref = torch.nn.LSTM(f, h, num_layers=1, batch_first=True)
    _copy_rnn_weights(torch, ours, ref)
    x = np.random.rand(b, s, f).astype(np.float32)
    out, (hn, cn) = ours(paddle.to_tensor(x))
    tout, (thn, tcn) = ref(torch.tensor(x))
    np.testing.assert_allclose(out.numpy(), tout.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(hn.numpy(), thn.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(cn.numpy(), tcn.detach().numpy(), atol=1e-5)


def test_gru_matches_torch():
    torch = pytest.importorskip("torch")
    paddle.seed(0)
    b, s, f, h = 2, 6, 4, 3
    ours = nn.GRU(f, h)
    ref = torch.nn.GRU(f, h, batch_first=True)
    _copy_rnn_weights(torch, ours, ref)
    x = np.random.rand(b, s, f).astype(np.float32)
    out, hn = ours(paddle.to_tensor(x))
    tout, thn = ref(torch.tensor(x))
    np.testing.assert_allclose(out.numpy(), tout.detach().numpy(), atol=1e-5)


def test_bidirectional_lstm_shapes():
    paddle.seed(0)
    m = nn.LSTM(4, 3, num_layers=2, direction="bidirect")
    x = paddle.to_tensor(np.random.rand(2, 5, 4).astype(np.float32))
    out, (h, c) = m(x)
    assert out.shape == [2, 5, 6]       # 2 directions * hidden
    assert h.shape == [4, 2, 3]         # layers*dirs, batch, hidden
    assert c.shape == [4, 2, 3]


def test_lstm_trains():
    paddle.seed(0)
    m = nn.LSTM(4, 8)
    head = nn.Linear(8, 1)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=m.parameters() + head.parameters())
    X = np.random.rand(16, 6, 4).astype(np.float32)
    Y = X.sum(axis=(1, 2), keepdims=False).reshape(-1, 1).astype(np.float32)
    first = None
    for _ in range(40):
        out, (h, _) = m(paddle.to_tensor(X))
        loss = ((head(h[0]) - paddle.to_tensor(Y)) ** 2).mean()
        if first is None:
            first = float(loss.numpy())
        loss.backward()
        opt.step(); opt.clear_grad()
    assert float(loss.numpy()) < first * 0.5


def test_cells():
    paddle.seed(0)
    for cell_cls, states in ((nn.SimpleRNNCell, 1), (nn.LSTMCell, 2), (nn.GRUCell, 1)):
        cell = cell_cls(4, 3)
        x = paddle.to_tensor(np.random.rand(2, 4).astype(np.float32))
        out, st = cell(x)
        assert out.shape == [2, 3]


def test_lstm_multilayer_bidirectional_matches_torch():
    """2-layer bidirectional LSTM equals torch with copied weights — the
    layer-stacking/direction-concat conventions are where silent
    divergences live (single-layer goldens can't see them)."""
    torch = pytest.importorskip("torch")
    import paddle_tpu as paddle
    from paddle_tpu import nn

    f, h, L = 5, 7, 2
    paddle.seed(3)
    ours = nn.LSTM(f, h, num_layers=L, direction="bidirect")
    ref = torch.nn.LSTM(f, h, num_layers=L, batch_first=True,
                        bidirectional=True)
    with torch.no_grad():
        for layer in range(L):
            for d, suffix in ((0, ""), (1, "_reverse")):
                getattr(ref, f"weight_ih_l{layer}{suffix}").copy_(
                    torch.tensor(np.asarray(
                        getattr(ours, f"wi_l{layer}_d{d}")._data)))
                getattr(ref, f"weight_hh_l{layer}{suffix}").copy_(
                    torch.tensor(np.asarray(
                        getattr(ours, f"wh_l{layer}_d{d}")._data)))
                getattr(ref, f"bias_ih_l{layer}{suffix}").copy_(
                    torch.tensor(np.asarray(
                        getattr(ours, f"bi_l{layer}_d{d}")._data)))
                getattr(ref, f"bias_hh_l{layer}{suffix}").copy_(
                    torch.tensor(np.asarray(
                        getattr(ours, f"bh_l{layer}_d{d}")._data)))
    x = np.random.randn(3, 6, f).astype(np.float32)
    out, (hn, cn) = ours(paddle.to_tensor(x))
    tout, (thn, tcn) = ref(torch.tensor(x))
    np.testing.assert_allclose(out.numpy(), tout.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(hn.numpy(), thn.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(cn.numpy(), tcn.detach().numpy(), atol=1e-5)


@pytest.mark.parametrize("kind", ["lstm", "gru"])
def test_rnn_backward_matches_torch(kind):
    """Gradients of the scan-based recurrent backward vs torch autograd:
    input grad AND every weight/bias grad (the scan transpose is where
    subtle time-reversal bugs hide; forward parity alone would miss them)."""
    torch = pytest.importorskip("torch")
    paddle.seed(0)
    b, s, f, h = 2, 5, 4, 3
    if kind == "lstm":
        ours, ref = nn.LSTM(f, h), torch.nn.LSTM(f, h, batch_first=True)
    else:
        ours, ref = nn.GRU(f, h), torch.nn.GRU(f, h, batch_first=True)
    _copy_rnn_weights(torch, ours, ref)
    x = np.random.rand(b, s, f).astype(np.float32)
    w = np.random.RandomState(1).standard_normal((b, s, h)) \
        .astype(np.float32)

    px = paddle.to_tensor(x)
    px.stop_gradient = False
    p_out = ours(px)[0]
    (p_out * paddle.to_tensor(w)).sum().backward()

    tx = torch.tensor(x, requires_grad=True)
    t_out = ref(tx)[0]
    (t_out * torch.tensor(w)).sum().backward()

    np.testing.assert_allclose(np.asarray(px.grad._data),
                               tx.grad.numpy(), rtol=1e-4, atol=1e-5,
                               err_msg=f"{kind} input grad")
    pairs = [(ours.wi_l0_d0, ref.weight_ih_l0, "weight_ih"),
             (ours.wh_l0_d0, ref.weight_hh_l0, "weight_hh"),
             (ours.bi_l0_d0, ref.bias_ih_l0, "bias_ih"),
             (ours.bh_l0_d0, ref.bias_hh_l0, "bias_hh")]
    for pp, tp, name in pairs:
        np.testing.assert_allclose(np.asarray(pp.grad._data),
                                   tp.grad.numpy(), rtol=1e-4, atol=1e-5,
                                   err_msg=f"{kind} {name} grad")

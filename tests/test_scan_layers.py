"""jit.scan_layers as a public building block (beyond GPT/ERNIE).

The helper runs any homogeneous, buffer-free LayerList as one
lax.scan(block, x, stacked_params) — the compile-time lever for deep
stacks (see docs/performance.md #9)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit import TrainStep, scan_layers, to_static


class Block(nn.Layer):
    def __init__(self, width):
        super().__init__()
        self.fc = nn.Linear(width, width)
        self.norm = nn.LayerNorm(width)

    def forward(self, x, gain=None):
        y = self.norm(paddle.nn.functional.gelu(self.fc(x)))
        if gain is not None:
            y = y * gain
        return x + y


class Stack(nn.Layer):
    def __init__(self, width=16, depth=4, scan=False):
        super().__init__()
        self.scan = scan
        self.blocks = nn.LayerList([Block(width) for _ in range(depth)])
        self.head = nn.Linear(width, 1)

    def forward(self, x, gain=None):
        if self.scan and x._is_traced():
            x = (scan_layers(self.blocks, x, gain) if gain is not None
                 else scan_layers(self.blocks, x))
        else:
            for b in self.blocks:
                x = b(x, gain)
        return self.head(x).mean()


def _data(seed=0):
    rng = np.random.RandomState(seed)
    return paddle.to_tensor(rng.randn(8, 16).astype(np.float32))


def _train(scan, steps=3, gain=None):
    paddle.seed(123)
    m = Stack(scan=scan)
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=m.parameters())
    step = TrainStep(lambda a: m(a, gain), opt, layers=m)
    x = _data()
    return [float(step(x).numpy()) for _ in range(steps)]


def test_custom_stack_training_parity():
    base = _train(False)
    assert base[-1] != base[0]  # it actually trains
    np.testing.assert_allclose(_train(True), base, rtol=2e-5, atol=2e-6)


def test_extra_closure_arg_reaches_every_block():
    gain = paddle.to_tensor(np.float32(0.5))
    base = _train(False, gain=gain)
    np.testing.assert_allclose(_train(True, gain=gain), base,
                               rtol=2e-5, atol=2e-6)
    # and the gain is not a no-op (distinguishes from the gain=None path)
    assert abs(base[0] - _train(False)[0]) > 1e-6


def test_to_static_forward_parity():
    paddle.seed(7)
    m = Stack(scan=True)
    x = _data(1)
    eager = float(m(x).numpy())  # eager path unrolls
    compiled = float(to_static(lambda a: m(a))(x).numpy())  # traced: scans
    assert abs(eager - compiled) < 1e-5


def test_flash_attention_inside_scanned_block():
    """The Pallas flash kernel (fwd + custom-vjp bwd) must compose with
    scan-over-layers — the long-context configs route attention through
    it, and a scanned stack wraps it in a lax.scan body."""
    from paddle_tpu.core import flags, rng as prng
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.core.tensor import Tensor

    old = flags.flag("flash_attention_min_seqlen")
    flags.set_flags({"flash_attention_min_seqlen": 8})
    try:
        def run(scan):
            prng.seed(5)
            cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=2,
                            num_heads=2, max_position_embeddings=64,
                            use_scan_layers=scan)
            m = GPTForCausalLM(cfg)
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters())
            step = TrainStep(lambda a, b: m(a, b), opt, layers=m)
            ids = np.random.default_rng(3).integers(0, 256, (2, 32),
                                                    dtype=np.int32)
            x, y = Tensor(ids), Tensor(np.roll(ids, -1, 1))
            return [float(step(x, y).numpy()) for _ in range(2)]

        base = run(False)
        np.testing.assert_allclose(run(True), base, rtol=2e-5, atol=2e-6)
    finally:
        flags.set_flags({"flash_attention_min_seqlen": old})


def test_gradient_merge_outer_scan_composes():
    """accumulate_steps (microbatch lax.scan) wrapping scan-over-layers —
    nested scans, the realistic large-model recipe — must match the
    unrolled stack step-for-step."""
    from paddle_tpu.core import rng as prng
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    def run(scan):
        prng.seed(6)
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=3,
                        num_heads=4, max_position_embeddings=64,
                        use_scan_layers=scan)
        m = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        step = TrainStep(lambda a, b: m(a, b), opt, layers=m,
                         accumulate_steps=2)
        ids = np.random.default_rng(4).integers(0, 256, (4, 16),
                                                dtype=np.int32)
        x, y = Tensor(ids), Tensor(np.roll(ids, -1, 1))
        return [float(step(x, y).numpy()) for _ in range(3)]

    base = run(False)
    np.testing.assert_allclose(run(True), base, rtol=2e-5, atol=2e-6)


def test_buffer_carrying_block_rejected():
    class BufBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)
            self.register_buffer("calls", paddle.to_tensor(
                np.zeros((), np.float32)))

        def forward(self, x):
            return self.fc(x)

    blocks = nn.LayerList([BufBlock() for _ in range(2)])
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    with pytest.raises(NotImplementedError):
        to_static(lambda a: scan_layers(blocks, a))(x)

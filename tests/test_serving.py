"""paddle_tpu.serving: continuous-batching slot engine, paged KV arena,
iteration-level scheduler, submit/stream/cancel API, and the
``inference.Config`` predictor bridge (ISSUE 4).

The compiled-engine tests share one module-scoped ``ServingAPI`` so tier-1
pays its prefill/decode compiles once; assertions on trace counters are
written lifetime-safe (every bucket traced at most once, decode traced
exactly once) so test order can never flip them. Heavy churn and
fault-injection cases carry ``slow`` / ``chaos``.
"""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import compile_cache, flags, resilience
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (
    ArenaExhaustedError,
    KVArena,
    RequestState,
    ServingAPI,
    ServingConfig,
    ServingEngine,
)
from paddle_tpu.serving import metrics as serving_metrics

pytestmark = pytest.mark.serving

MAX_LEN = 64


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def api(model):
    a = ServingAPI(model, num_slots=4, kv_block_size=8, max_model_len=MAX_LEN)
    yield a
    a.close()


def _prompt(rng, n):
    return rng.integers(0, 1024, (n,), dtype=np.int32)


def _ref(model, prompt, max_new, stop=None):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=max_new, stop_token_id=stop)
    return np.asarray(out._data)[0]


# ---------------------------------------------------------------- engine


def test_engine_parity_with_generate(api, model):
    """Greedy decode through the paged-arena slot engine is token-for-token
    identical to the contiguous-cache generate() path."""
    rng = np.random.default_rng(1)
    prompts = [_prompt(rng, n) for n in (5, 11)]
    reqs = [api.submit(p, max_new_tokens=8) for p in prompts]
    api.run_until_idle()
    for p, r in zip(prompts, reqs):
        assert r.state == RequestState.FINISHED
        np.testing.assert_array_equal(r.output_ids(), _ref(model, p, 8))


def test_stop_token_parity_and_early_exit(api, model):
    """A stop-token request ends at the stop hit and matches
    generate(stop_token_id=...) up to its fill tail."""
    rng = np.random.default_rng(2)
    p = _prompt(rng, 6)
    # pick a stop token the greedy decode actually emits mid-stream
    full = _ref(model, p, 12)
    stop = int(full[len(p) + 3])
    ref = _ref(model, p, 12, stop=stop)
    req = api.submit(p, max_new_tokens=12, stop_token_id=stop)
    api.run_until_idle()
    got = req.output_ids()
    assert req.state == RequestState.FINISHED
    assert int(got[-1]) == stop
    assert len(got) < len(p) + 12  # genuinely stopped early
    np.testing.assert_array_equal(got, ref[: len(got)])
    assert np.all(ref[len(got):] == stop)  # generate() fills the tail


def test_admit_retire_never_recompiles(api):
    """The engine invariant: churning admits/retires across occupancy
    patterns adds zero decode traces and retraces no prefill bucket."""
    rng = np.random.default_rng(3)
    api.run_until_idle()
    # make sure the decode step has been traced at least once already
    api.submit(_prompt(rng, 5), max_new_tokens=3)
    api.run_until_idle()
    d0 = api.engine.decode_traces
    cc0 = compile_cache.stats().get("serving.decode_compiles", 0)
    for n_live in (1, 3, 4, 2):
        reqs = [api.submit(_prompt(rng, 4 + 3 * i), max_new_tokens=2 + i)
                for i in range(n_live)]
        api.run_until_idle()
        assert all(r.state == RequestState.FINISHED for r in reqs)
    assert api.engine.decode_traces == d0 == 1
    assert compile_cache.stats().get("serving.decode_compiles", 0) == cc0
    assert all(v == 1 for v in api.engine.prefill_traces.values())
    assert api.engine.active_slots() == 0


def test_mixed_lengths_bounded_by_bucket_count(api):
    """Mixed prompt lengths land in at most len({their buckets}) compiled
    prefill programs (shape bucketing from core.compile_cache)."""
    rng = np.random.default_rng(4)
    lens = (3, 5, 9, 14, 17, 21, 30)
    expected = {compile_cache.prefill_bucket(n, MAX_LEN) for n in lens}
    for n in lens:
        api.submit(_prompt(rng, n), max_new_tokens=2)
    api.run_until_idle()
    traced = set(api.engine.prefill_traces)
    assert expected <= traced  # every needed bucket exists...
    assert len(expected) < len(lens)  # ...and bucketing actually coalesced
    assert all(v == 1 for v in api.engine.prefill_traces.values())


def test_prefill_bucket_ladder():
    m = int(flags.flag("serving_prefill_bucket_min"))
    assert compile_cache.prefill_bucket(1) == m
    assert compile_cache.prefill_bucket(m) == m
    for n in (1, 7, 33, 100):
        assert compile_cache.prefill_bucket(n) >= n
    # clamped to the model's position budget
    assert compile_cache.prefill_bucket(70, max_len=100) <= 100
    # whole-range bucket count stays small (the "handful of compiles" claim)
    assert len({compile_cache.prefill_bucket(n, 2048)
                for n in range(1, 2049)}) <= 16


def test_engine_rejects_oversized_and_empty(api):
    with pytest.raises(ValueError):
        api.submit(np.arange(MAX_LEN, dtype=np.int32), max_new_tokens=1)
    with pytest.raises(ValueError):
        api.submit(np.zeros(0, np.int32), max_new_tokens=4)
    with pytest.raises(ValueError):
        api.submit(np.zeros(4, np.int32), max_new_tokens=0)


# ------------------------------------------------------- cancel / deadline


def test_cancel_mid_decode_frees_slot(api):
    rng = np.random.default_rng(5)
    req = api.submit(_prompt(rng, 5), max_new_tokens=40)
    for _ in range(3):
        api._pump_once()
    assert req.state == RequestState.RUNNING
    assert api.engine.active_slots() == 1
    api.cancel(req)
    assert req.state == RequestState.CANCELLED
    assert api.engine.active_slots() == 0
    a = api.engine.arena.stats()
    assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
    with pytest.raises(RuntimeError, match="cancelled"):
        api.result(req)


def test_cancel_while_queued_costs_no_prefill(api):
    rng = np.random.default_rng(6)
    before = dict(api.engine.prefill_traces)
    admits0 = serving_metrics.stats().get("engine.admits", 0)
    req = api.submit(_prompt(rng, 5), max_new_tokens=4)
    req.cancel()
    api.run_until_idle()
    assert req.state == RequestState.CANCELLED
    assert serving_metrics.stats().get("engine.admits", 0) == admits0
    assert api.engine.prefill_traces == before


def test_deadline_expiry_fails_request_and_frees_slot(api):
    rng = np.random.default_rng(7)
    dl0 = resilience.stats().get("deadline.exceeded", 0)
    req = api.submit(_prompt(rng, 5), max_new_tokens=50, timeout=0.02)
    time.sleep(0.03)
    api.run_until_idle()
    assert req.state == RequestState.FAILED
    assert isinstance(req.error, resilience.DeadlineExceededError)
    # expiry lands on the shared resilience counter dashboards watch
    assert resilience.stats().get("deadline.exceeded", 0) == dl0 + 1
    assert api.engine.active_slots() == 0
    with pytest.raises(resilience.DeadlineExceededError):
        api.result(req)


def test_queue_overload_shedding(api):
    rng = np.random.default_rng(8)
    old = api._max_queue
    api._max_queue = 2
    try:
        shed0 = resilience.stats().get("overload.shed", 0)
        reqs = [api.submit(_prompt(rng, 4), max_new_tokens=2)
                for _ in range(2)]
        with pytest.raises(resilience.QueueOverloadError):
            api.submit(_prompt(rng, 4), max_new_tokens=2)
        assert resilience.stats().get("overload.shed", 0) == shed0 + 1
    finally:
        api._max_queue = old
        for r in reqs:
            r.cancel()
        api.run_until_idle()


def test_stream_yields_generated_tokens(api, model):
    rng = np.random.default_rng(9)
    p = _prompt(rng, 7)
    req = api.submit(p, max_new_tokens=6)
    toks = list(api.stream(req))
    assert req.state == RequestState.FINISHED
    assert toks == req.tokens
    np.testing.assert_array_equal(
        np.concatenate([p, np.asarray(toks, np.int32)]), _ref(model, p, 6))


# --------------------------------------------------------------- KV arena


def test_arena_freelist_reuse_under_churn():
    arena = KVArena(num_layers=1, num_heads=2, head_dim=4,
                    num_blocks=9, block_size=4)
    serving_metrics_before = serving_metrics.stats().get("arena.reuse", 0)
    res = arena.reserve(3)
    first = [res.take() for _ in range(3)]
    assert 0 not in first  # scratch block is never handed out
    assert arena.blocks_in_use() == 3
    res.release()
    assert arena.blocks_free() == 8 and arena.blocks_in_use() == 0
    # LIFO: the churny path re-takes exactly the just-freed blocks
    res2 = arena.reserve(3)
    second = [res2.take() for _ in range(3)]
    assert set(second) == set(first)
    assert serving_metrics.stats().get("arena.reuse", 0) \
        == serving_metrics_before + 3
    res2.release()


def test_arena_two_phase_reservation_accounting():
    arena = KVArena(num_layers=1, num_heads=2, head_dim=4,
                    num_blocks=6, block_size=4)
    res = arena.reserve(3)
    # the budget is claimed up front: only 2 of 5 blocks remain grantable
    assert not arena.can_reserve(3)
    assert arena.can_reserve(2)
    with pytest.raises(ArenaExhaustedError):
        arena.reserve(3)
    # a reservation cannot take past its own budget either
    for _ in range(3):
        res.take()
    with pytest.raises(ArenaExhaustedError):
        res.take()
    res.release()
    assert arena.can_reserve(5)
    # releasing twice is a no-op, not a double-free
    res.release()
    assert arena.blocks_free() == 5


def test_engine_admission_gated_on_arena(model):
    """can_admit() is false when the arena cannot cover the worst case —
    a running request can never be starved of blocks mid-decode."""
    eng = ServingEngine(model, num_slots=2, kv_block_size=8,
                        max_model_len=32, num_blocks=5)  # 4 allocatable
    assert eng.can_admit(8, 24)  # needs all 4 blocks
    slot, _ = eng.admit(np.zeros(8, np.int32), max_new_tokens=24)
    assert not eng.can_admit(1, 1)  # slot free, arena full
    eng.retire(slot)
    assert eng.can_admit(8, 24)


def test_unadmittable_request_rejected_at_submit(model):
    """A request that fits max_model_len but needs more KV blocks than the
    whole arena holds is rejected by validate() — otherwise it would park
    un-admittable at the FCFS head and starve the queue forever."""
    eng = ServingEngine(model, num_slots=2, kv_block_size=8,
                        max_model_len=64, num_blocks=5)  # 4 allocatable
    eng.validate(8, 24)  # exactly the arena: fine
    with pytest.raises(ValueError, match="could never be admitted"):
        eng.validate(8, 56)  # 8 blocks > 4 allocatable, yet total <= 64


def test_foreground_step_failure_fails_all_requests(api, monkeypatch):
    """A decode-step exception during foreground pumping must not strand
    RUNNING requests holding slots and arena blocks: every in-flight
    request fails (error + done_event) and capacity is reclaimed, exactly
    like the background pump's fail_all path."""
    rng = np.random.default_rng(31)
    req = api.submit(_prompt(rng, 5), max_new_tokens=8)
    boom = RuntimeError("decode step died")

    def dead_step():
        raise boom

    monkeypatch.setattr(api.engine, "decode_step", dead_step)
    with pytest.raises(RuntimeError, match="decode step died"):
        api.run_until_idle()
    assert req.state == RequestState.FAILED
    assert req.error is boom
    assert req.done_event.is_set()
    assert api.engine.free_slots() == 4
    a = api.engine.arena.stats()
    assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0


# ----------------------------------------------- resilience hooks (unit)


def test_deadline_helpers():
    assert not resilience.Deadline.after(None).expired()
    assert resilience.Deadline.after(None).remaining() == float("inf")
    d = resilience.Deadline.after(0)
    assert d.expired()
    with pytest.raises(resilience.DeadlineExceededError):
        d.check("unit")
    resilience.Deadline.after(60).check("unit")  # far future: no raise


def test_check_overload_limits():
    resilience.check_overload(5, limit=0)  # 0 = unlimited
    resilience.check_overload(5, limit=None, name="")  # flag default 0
    with pytest.raises(resilience.QueueOverloadError):
        resilience.check_overload(3, limit=3, name="unit")
    assert resilience.stats().get("overload.unit.shed", 0) >= 1


# ------------------------------------------------- inference.Config bridge


def test_config_accepts_pdmodel_directory(tmp_path):
    from paddle_tpu import inference

    d = tmp_path / "exported"
    d.mkdir()
    (d / "model.pdmodel").write_bytes(b"")
    cfg = inference.Config(str(d))
    assert cfg.model_prefix == str(d / "model")
    (d / "other.pdmodel").write_bytes(b"")
    with pytest.raises(ValueError, match="exactly one"):
        inference.Config(str(d))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ValueError, match="exactly one"):
        inference.Config(str(empty))


def test_config_placement_decision():
    from paddle_tpu import inference

    cfg = inference.Config("m.pdmodel")
    assert cfg._resolve_placement() == "cpu"  # no request: report actual
    cfg.enable_use_gpu(100, 0)
    assert cfg._device == ("gpu", 0)
    assert cfg._resolve_placement() == "cpu"  # mismatch logged, runs on XLA
    cfg.enable_tpu()
    assert cfg._device == ("tpu", 0)
    assert cfg._resolve_placement() == "cpu"


def test_engine_predictor_bridge(api, model):
    """inference.Config.enable_serving_engine routes create_predictor
    through the slot engine with generate()'s output contract."""
    from paddle_tpu import inference

    rng = np.random.default_rng(10)
    ids = np.stack([_prompt(rng, 6), _prompt(rng, 6)])
    cfg = inference.Config()
    cfg.enable_serving_engine(model, max_new_tokens=5, num_slots=2,
                              kv_block_size=8, max_model_len=MAX_LEN)
    pred = inference.create_predictor(cfg)
    h = pred.get_input_handle("input_ids")
    h.copy_from_cpu(ids)
    pred.run()
    out = pred.get_output_handle("output_0").copy_to_cpu()
    assert out.shape == (2, 6 + 5)
    for i in range(2):
        np.testing.assert_array_equal(out[i], _ref(model, ids[i], 5))
    pred.close()
    with pytest.raises(ValueError, match="in-memory"):
        c2 = inference.Config()
        c2.enable_serving_engine(None)
        inference.create_predictor(c2)


def test_close_fails_outstanding_requests(model):
    """close() never strands a request: anything still queued fails with a
    clear error, its done_event set and stream sentinel delivered (a
    queued request costs no prefill, so this engine never compiles)."""
    a = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=MAX_LEN)
    rng = np.random.default_rng(15)
    req = a.submit(_prompt(rng, 5), max_new_tokens=4)  # stays QUEUED
    a.close()
    assert req.state == RequestState.FAILED
    assert isinstance(req.error, RuntimeError)
    assert req.done_event.is_set()
    with pytest.raises(RuntimeError, match="closed"):
        list(a.stream(req))  # sentinel delivered, then the error surfaces
    with pytest.raises(RuntimeError, match="closed"):
        a.submit(_prompt(rng, 5), max_new_tokens=4)


# ------------------------------------------------------- heavy / chaos


@pytest.mark.slow
def test_slot_churn_stress(model):
    """Many mixed requests through few slots: everything finishes, the
    free list is exercised (reuse counter climbs), and the arena ends
    clean with zero leaked blocks."""
    api = ServingAPI(model, num_slots=2, kv_block_size=8,
                     max_model_len=MAX_LEN)
    try:
        rng = np.random.default_rng(11)
        reuse0 = serving_metrics.stats().get("arena.reuse", 0)
        reqs = [api.submit(_prompt(rng, int(rng.integers(3, 30))),
                           max_new_tokens=int(rng.integers(2, 16)))
                for _ in range(12)]
        api.run_until_idle()
        assert all(r.state == RequestState.FINISHED for r in reqs)
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
        assert serving_metrics.stats().get("arena.reuse", 0) > reuse0
        assert api.engine.decode_traces == 1
    finally:
        api.close()


@pytest.mark.slow
def test_background_pump_thread(model):
    api = ServingAPI(model, num_slots=2, kv_block_size=8,
                     max_model_len=MAX_LEN, background=True)
    try:
        rng = np.random.default_rng(12)
        p = _prompt(rng, 5)
        req = api.submit(p, max_new_tokens=6)
        out = api.result(req, timeout=60)
        np.testing.assert_array_equal(out, _ref(model, p, 6))
    finally:
        api.close()


@pytest.mark.chaos
@pytest.mark.slow
def test_step_fault_retried_without_donation(model):
    """With donation off the engine wraps compiled calls in the io retry
    policy: a transient injected step fault is retried and the request
    still completes; with donation on the same config refuses to retry."""
    keep = paddle.get_flags("fault_injection")["fault_injection"]
    paddle.set_flags({"fault_injection": 1})
    try:
        api = ServingAPI(
            model, config=ServingConfig(num_slots=2, kv_block_size=8,
                                        max_model_len=MAX_LEN, donate=False))
        rng = np.random.default_rng(13)
        p = _prompt(rng, 5)
        retries0 = resilience.stats().get("retry.retries", 0)
        resilience.inject_fault("serving_step", times=1,
                                exc=OSError("injected step fault"))
        req = api.submit(p, max_new_tokens=6)
        api.run_until_idle()
        assert req.state == RequestState.FINISHED
        np.testing.assert_array_equal(req.output_ids(), _ref(model, p, 6))
        assert resilience.stats().get("retry.retries", 0) > retries0
        api.close()
    finally:
        resilience.clear_faults()
        paddle.set_flags({"fault_injection": keep})


@pytest.mark.chaos
@pytest.mark.slow
def test_failed_prefill_fails_request_not_engine(model):
    """A prefill failure that exhausts retries fails THAT request cleanly
    (error delivered, done_event set, no leaked arena blocks) and the
    engine keeps serving the next request."""
    keep = {k: paddle.get_flags(k)[k]
            for k in ("fault_injection", "io_retries", "io_retry_backoff")}
    paddle.set_flags({"fault_injection": 1, "io_retries": 2,
                      "io_retry_backoff": 0.001})
    try:
        api = ServingAPI(
            model, config=ServingConfig(num_slots=2, kv_block_size=8,
                                        max_model_len=MAX_LEN, donate=False))
        rng = np.random.default_rng(16)
        p = _prompt(rng, 5)
        resilience.inject_fault("serving_step", times=10,
                                exc=OSError("persistent step fault"))
        req = api.submit(p, max_new_tokens=4)
        api.run_until_idle()
        assert req.state == RequestState.FAILED
        assert isinstance(req.error, OSError)
        assert req.done_event.is_set()
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
        resilience.clear_faults()
        req2 = api.submit(p, max_new_tokens=4)  # engine still healthy
        api.run_until_idle()
        assert req2.state == RequestState.FINISHED
        np.testing.assert_array_equal(req2.output_ids(), _ref(model, p, 4))
        api.close()
    finally:
        resilience.clear_faults()
        paddle.set_flags(keep)


# ----------------------------------------------------------- stats wiring


def test_serving_stats_on_shared_surfaces(api):
    rng = np.random.default_rng(14)
    before = serving_metrics.stats()
    req = api.submit(_prompt(rng, 5), max_new_tokens=4)
    api.run_until_idle()
    delta = serving_metrics.stats_delta(before, serving_metrics.stats())
    assert delta.get("tokens.generated", 0) >= 4
    assert delta.get("requests.finished", 0) == 1
    # headline numbers ride the shared memory_stats provider surface
    from paddle_tpu.core import memory_stats

    stats = memory_stats.memory_stats()
    assert "provider.serving.tokens_generated" in stats
    assert stats["provider.serving.tokens_generated"] \
        == serving_metrics.stats().get("tokens.generated", 0)
    # the engine's Meter publishes a live aggregate decode rate
    assert serving_metrics.stats().get("tokens_per_sec", 0) > 0
    assert req.state == RequestState.FINISHED


def test_completed_output_beats_expired_deadline(api):
    """A request whose output is already whole when its deadline expires
    FINISHES with the result — completed work is never discarded."""
    from paddle_tpu.serving.scheduler import Request

    req = Request(np.arange(4, dtype=np.int32), max_new_tokens=8,
                  stop_token_id=3, tokens=[9, 3],
                  deadline=resilience.Deadline.after(0.0))
    assert req.deadline.expired()
    assert api.scheduler._check_boundary(req)
    assert req.state == RequestState.FINISHED and req.error is None


def test_predictor_mid_batch_submit_failure_strands_nothing(model):
    """If a row's submit sheds mid-batch, EnginePredictor.run cancels the
    rows it already queued instead of leaving unreachable handles that
    FCFS would still spend capacity on."""
    from paddle_tpu.serving.api import EnginePredictor

    pred = EnginePredictor(model, max_new_tokens=4,
                           config=ServingConfig(num_slots=1, kv_block_size=8,
                                                max_model_len=MAX_LEN),
                           max_queue=2)
    try:
        ids = np.tile(np.arange(5, dtype=np.int32), (6, 1))
        with pytest.raises(resilience.QueueOverloadError):
            pred.run([ids])
        assert not pred._api.scheduler.has_work()
    finally:
        pred.close()

"""paddle_tpu.serving: continuous-batching slot engine, paged KV arena,
iteration-level scheduler, submit/stream/cancel API, and the
``inference.Config`` predictor bridge (ISSUE 4); plus the resilience layer
(ISSUE 5): priority admission + starvation preemption, supervisor
rebuild-and-replay recovery with the crash-loop breaker, and graceful
drain / preemption-guard shutdown. The radix prefix cache's supervisor
interaction (ISSUE 6) chaos-tests here; its unit and tier-1 regression
coverage lives in ``tests/test_prefix_cache.py``.

The compiled-engine tests share one module-scoped ``ServingAPI`` so tier-1
pays its prefill/decode compiles once; assertions on trace counters are
written lifetime-safe (every bucket traced at most once, decode traced
exactly once) so test order can never flip them. Heavy churn and
fault-injection cases carry ``slow`` / ``chaos``. Tests that drain or
close an API always build their own instance — a drained API refuses
admissions forever, so the shared fixture must never be drained.
"""
import logging
import os
import queue as pyqueue
import time
import weakref

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import compile_cache, flags, resilience
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (
    ArenaExhaustedError,
    CrashLoopError,
    EngineSupervisor,
    KVArena,
    Request,
    RequestState,
    ReservationExhaustedError,
    Scheduler,
    ServingAPI,
    ServingConfig,
    ServingEngine,
)
from paddle_tpu.serving import metrics as serving_metrics
from paddle_tpu.serving.supervisor import is_transient_serving_error

pytestmark = pytest.mark.serving

MAX_LEN = 64


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def api(model):
    a = ServingAPI(model, num_slots=4, kv_block_size=8, max_model_len=MAX_LEN)
    yield a
    a.close()


def _prompt(rng, n):
    return rng.integers(0, 1024, (n,), dtype=np.int32)


def _ref(model, prompt, max_new, stop=None):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=max_new, stop_token_id=stop)
    return np.asarray(out._data)[0]


# ---------------------------------------------------------------- engine


def test_engine_parity_with_generate(api, model):
    """Greedy decode through the paged-arena slot engine is token-for-token
    identical to the contiguous-cache generate() path."""
    rng = np.random.default_rng(1)
    prompts = [_prompt(rng, n) for n in (5, 11)]
    reqs = [api.submit(p, max_new_tokens=8) for p in prompts]
    api.run_until_idle()
    for p, r in zip(prompts, reqs):
        assert r.state == RequestState.FINISHED
        np.testing.assert_array_equal(r.output_ids(), _ref(model, p, 8))


def test_stop_token_parity_and_early_exit(api, model):
    """A stop-token request ends at the stop hit and matches
    generate(stop_token_id=...) up to its fill tail."""
    rng = np.random.default_rng(2)
    p = _prompt(rng, 6)
    # pick a stop token the greedy decode actually emits mid-stream
    full = _ref(model, p, 12)
    stop = int(full[len(p) + 3])
    ref = _ref(model, p, 12, stop=stop)
    req = api.submit(p, max_new_tokens=12, stop_token_id=stop)
    api.run_until_idle()
    got = req.output_ids()
    assert req.state == RequestState.FINISHED
    assert int(got[-1]) == stop
    assert len(got) < len(p) + 12  # genuinely stopped early
    np.testing.assert_array_equal(got, ref[: len(got)])
    assert np.all(ref[len(got):] == stop)  # generate() fills the tail


def test_admit_retire_never_recompiles(api):
    """The engine invariant: churning admits/retires across occupancy
    patterns adds zero decode traces and retraces no prefill bucket."""
    rng = np.random.default_rng(3)
    api.run_until_idle()
    # make sure the decode step has been traced at least once already
    api.submit(_prompt(rng, 5), max_new_tokens=3)
    api.run_until_idle()
    d0 = api.engine.decode_traces
    cc0 = compile_cache.stats().get("serving.decode_compiles", 0)
    for n_live in (1, 3, 4, 2):
        reqs = [api.submit(_prompt(rng, 4 + 3 * i), max_new_tokens=2 + i)
                for i in range(n_live)]
        api.run_until_idle()
        assert all(r.state == RequestState.FINISHED for r in reqs)
    assert api.engine.decode_traces == d0 == 1
    assert compile_cache.stats().get("serving.decode_compiles", 0) == cc0
    assert all(v == 1 for v in api.engine.prefill_traces.values())
    assert api.engine.active_slots() == 0


def test_mixed_lengths_bounded_by_bucket_count(api):
    """Mixed prompt lengths land in at most len({their buckets}) compiled
    prefill programs (shape bucketing from core.compile_cache)."""
    rng = np.random.default_rng(4)
    lens = (3, 5, 9, 14, 17, 21, 30)
    expected = {compile_cache.prefill_bucket(n, MAX_LEN) for n in lens}
    for n in lens:
        api.submit(_prompt(rng, n), max_new_tokens=2)
    api.run_until_idle()
    traced = set(api.engine.prefill_traces)
    assert expected <= traced  # every needed bucket exists...
    assert len(expected) < len(lens)  # ...and bucketing actually coalesced
    assert all(v == 1 for v in api.engine.prefill_traces.values())


def test_prefill_bucket_ladder():
    m = int(flags.flag("serving_prefill_bucket_min"))
    assert compile_cache.prefill_bucket(1) == m
    assert compile_cache.prefill_bucket(m) == m
    for n in (1, 7, 33, 100):
        assert compile_cache.prefill_bucket(n) >= n
    # clamped to the model's position budget
    assert compile_cache.prefill_bucket(70, max_len=100) <= 100
    # whole-range bucket count stays small (the "handful of compiles" claim)
    assert len({compile_cache.prefill_bucket(n, 2048)
                for n in range(1, 2049)}) <= 16


def test_engine_rejects_oversized_and_empty(api):
    with pytest.raises(ValueError):
        api.submit(np.arange(MAX_LEN, dtype=np.int32), max_new_tokens=1)
    with pytest.raises(ValueError):
        api.submit(np.zeros(0, np.int32), max_new_tokens=4)
    with pytest.raises(ValueError):
        api.submit(np.zeros(4, np.int32), max_new_tokens=0)


# ------------------------------------------------------- cancel / deadline


def test_cancel_mid_decode_frees_slot(api):
    rng = np.random.default_rng(5)
    req = api.submit(_prompt(rng, 5), max_new_tokens=40)
    for _ in range(3):
        api._pump_once()
    assert req.state == RequestState.RUNNING
    assert api.engine.active_slots() == 1
    api.cancel(req)
    assert req.state == RequestState.CANCELLED
    assert api.engine.active_slots() == 0
    a = api.engine.arena.stats()
    assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
    with pytest.raises(RuntimeError, match="cancelled"):
        api.result(req)


def test_cancel_while_queued_costs_no_prefill(api):
    rng = np.random.default_rng(6)
    before = dict(api.engine.prefill_traces)
    admits0 = serving_metrics.stats().get("engine.admits", 0)
    req = api.submit(_prompt(rng, 5), max_new_tokens=4)
    req.cancel()
    api.run_until_idle()
    assert req.state == RequestState.CANCELLED
    assert serving_metrics.stats().get("engine.admits", 0) == admits0
    assert api.engine.prefill_traces == before


def test_deadline_expiry_fails_request_and_frees_slot(api):
    rng = np.random.default_rng(7)
    dl0 = resilience.stats().get("deadline.exceeded", 0)
    req = api.submit(_prompt(rng, 5), max_new_tokens=50, timeout=0.02)
    time.sleep(0.03)
    api.run_until_idle()
    assert req.state == RequestState.FAILED
    assert isinstance(req.error, resilience.DeadlineExceededError)
    # expiry lands on the shared resilience counter dashboards watch
    assert resilience.stats().get("deadline.exceeded", 0) == dl0 + 1
    assert api.engine.active_slots() == 0
    with pytest.raises(resilience.DeadlineExceededError):
        api.result(req)


def test_queue_overload_shedding(api):
    rng = np.random.default_rng(8)
    old = api._max_queue
    api._max_queue = 2
    try:
        shed0 = resilience.stats().get("overload.shed", 0)
        reqs = [api.submit(_prompt(rng, 4), max_new_tokens=2)
                for _ in range(2)]
        with pytest.raises(resilience.QueueOverloadError):
            api.submit(_prompt(rng, 4), max_new_tokens=2)
        assert resilience.stats().get("overload.shed", 0) == shed0 + 1
    finally:
        api._max_queue = old
        for r in reqs:
            r.cancel()
        api.run_until_idle()


def test_stream_yields_generated_tokens(api, model):
    rng = np.random.default_rng(9)
    p = _prompt(rng, 7)
    req = api.submit(p, max_new_tokens=6)
    toks = list(api.stream(req))
    assert req.state == RequestState.FINISHED
    assert toks == req.tokens
    np.testing.assert_array_equal(
        np.concatenate([p, np.asarray(toks, np.int32)]), _ref(model, p, 6))


# --------------------------------------------------------------- KV arena


def test_arena_freelist_reuse_under_churn():
    arena = KVArena(num_layers=1, num_heads=2, head_dim=4,
                    num_blocks=9, block_size=4)
    serving_metrics_before = serving_metrics.stats().get("arena.reuse", 0)
    res = arena.reserve(3)
    first = [res.take() for _ in range(3)]
    assert 0 not in first  # scratch block is never handed out
    assert arena.blocks_in_use() == 3
    res.release()
    assert arena.blocks_free() == 8 and arena.blocks_in_use() == 0
    # LIFO: the churny path re-takes exactly the just-freed blocks
    res2 = arena.reserve(3)
    second = [res2.take() for _ in range(3)]
    assert set(second) == set(first)
    assert serving_metrics.stats().get("arena.reuse", 0) \
        == serving_metrics_before + 3
    res2.release()


def test_arena_two_phase_reservation_accounting():
    arena = KVArena(num_layers=1, num_heads=2, head_dim=4,
                    num_blocks=6, block_size=4)
    res = arena.reserve(3)
    # the budget is claimed up front: only 2 of 5 blocks remain grantable
    assert not arena.can_reserve(3)
    assert arena.can_reserve(2)
    with pytest.raises(ArenaExhaustedError):
        arena.reserve(3)
    # a reservation cannot take past its own budget either
    for _ in range(3):
        res.take()
    with pytest.raises(ArenaExhaustedError):
        res.take()
    res.release()
    assert arena.can_reserve(5)
    # releasing twice is a no-op, not a double-free
    res.release()
    assert arena.blocks_free() == 5


def test_engine_admission_gated_on_arena(model):
    """can_admit() is false when the arena cannot cover the worst case —
    a running request can never be starved of blocks mid-decode."""
    eng = ServingEngine(model, num_slots=2, kv_block_size=8,
                        max_model_len=32, num_blocks=5)  # 4 allocatable
    assert eng.can_admit(8, 24)  # needs all 4 blocks
    slot, _ = eng.admit(np.zeros(8, np.int32), max_new_tokens=24)
    assert not eng.can_admit(1, 1)  # slot free, arena full
    eng.retire(slot)
    assert eng.can_admit(8, 24)


def test_unadmittable_request_rejected_at_submit(model):
    """A request that fits max_model_len but needs more KV blocks than the
    whole arena holds is rejected by validate() — otherwise it would park
    un-admittable at the FCFS head and starve the queue forever."""
    eng = ServingEngine(model, num_slots=2, kv_block_size=8,
                        max_model_len=64, num_blocks=5)  # 4 allocatable
    eng.validate(8, 24)  # exactly the arena: fine
    with pytest.raises(ValueError, match="could never be admitted"):
        eng.validate(8, 56)  # 8 blocks > 4 allocatable, yet total <= 64


def test_foreground_step_failure_fails_all_requests(api, monkeypatch):
    """A decode-step exception during foreground pumping must not strand
    RUNNING requests holding slots and arena blocks: every in-flight
    request fails (error + done_event) and capacity is reclaimed, exactly
    like the background pump's fail_all path."""
    rng = np.random.default_rng(31)
    req = api.submit(_prompt(rng, 5), max_new_tokens=8)
    boom = RuntimeError("decode step died")

    def dead_step():
        raise boom

    monkeypatch.setattr(api.engine, "decode_step", dead_step)
    with pytest.raises(RuntimeError, match="decode step died"):
        api.run_until_idle()
    assert req.state == RequestState.FAILED
    assert req.error is boom
    assert req.done_event.is_set()
    assert api.engine.free_slots() == 4
    a = api.engine.arena.stats()
    assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0


# ----------------------------------------------- resilience hooks (unit)


def test_deadline_helpers():
    assert not resilience.Deadline.after(None).expired()
    assert resilience.Deadline.after(None).remaining() == float("inf")
    d = resilience.Deadline.after(0)
    assert d.expired()
    with pytest.raises(resilience.DeadlineExceededError):
        d.check("unit")
    resilience.Deadline.after(60).check("unit")  # far future: no raise


def test_check_overload_limits():
    resilience.check_overload(5, limit=0)  # 0 = unlimited
    resilience.check_overload(5, limit=None, name="")  # flag default 0
    with pytest.raises(resilience.QueueOverloadError):
        resilience.check_overload(3, limit=3, name="unit")
    assert resilience.stats().get("overload.unit.shed", 0) >= 1


# ------------------------------------------------- inference.Config bridge


def test_config_accepts_pdmodel_directory(tmp_path):
    from paddle_tpu import inference

    d = tmp_path / "exported"
    d.mkdir()
    (d / "model.pdmodel").write_bytes(b"")
    cfg = inference.Config(str(d))
    assert cfg.model_prefix == str(d / "model")
    (d / "other.pdmodel").write_bytes(b"")
    with pytest.raises(ValueError, match="exactly one"):
        inference.Config(str(d))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ValueError, match="exactly one"):
        inference.Config(str(empty))


def test_config_placement_decision():
    from paddle_tpu import inference

    cfg = inference.Config("m.pdmodel")
    assert cfg._resolve_placement() == "cpu"  # no request: report actual
    cfg.enable_use_gpu(100, 0)
    assert cfg._device == ("gpu", 0)
    assert cfg._resolve_placement() == "cpu"  # mismatch logged, runs on XLA
    cfg.enable_tpu()
    assert cfg._device == ("tpu", 0)
    assert cfg._resolve_placement() == "cpu"


def test_engine_predictor_bridge(api, model):
    """inference.Config.enable_serving_engine routes create_predictor
    through the slot engine with generate()'s output contract."""
    from paddle_tpu import inference

    rng = np.random.default_rng(10)
    ids = np.stack([_prompt(rng, 6), _prompt(rng, 6)])
    cfg = inference.Config()
    cfg.enable_serving_engine(model, max_new_tokens=5, num_slots=2,
                              kv_block_size=8, max_model_len=MAX_LEN)
    pred = inference.create_predictor(cfg)
    h = pred.get_input_handle("input_ids")
    h.copy_from_cpu(ids)
    pred.run()
    out = pred.get_output_handle("output_0").copy_to_cpu()
    assert out.shape == (2, 6 + 5)
    for i in range(2):
        np.testing.assert_array_equal(out[i], _ref(model, ids[i], 5))
    pred.close()
    with pytest.raises(ValueError, match="in-memory"):
        c2 = inference.Config()
        c2.enable_serving_engine(None)
        inference.create_predictor(c2)


def test_close_fails_outstanding_requests(model):
    """close() never strands a request: anything still queued fails with a
    clear error, its done_event set and stream sentinel delivered (a
    queued request costs no prefill, so this engine never compiles)."""
    a = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=MAX_LEN)
    rng = np.random.default_rng(15)
    req = a.submit(_prompt(rng, 5), max_new_tokens=4)  # stays QUEUED
    a.close()
    assert req.state == RequestState.FAILED
    assert isinstance(req.error, RuntimeError)
    assert req.done_event.is_set()
    with pytest.raises(RuntimeError, match="closed"):
        list(a.stream(req))  # sentinel delivered, then the error surfaces
    with pytest.raises(RuntimeError, match="closed"):
        a.submit(_prompt(rng, 5), max_new_tokens=4)


# ------------------------------------------------------- heavy / chaos


@pytest.mark.slow
def test_slot_churn_stress(model):
    """Many mixed requests through few slots: everything finishes, the
    free list is exercised (reuse counter climbs), and the arena ends
    clean with zero leaked blocks."""
    api = ServingAPI(model, num_slots=2, kv_block_size=8,
                     max_model_len=MAX_LEN)
    try:
        rng = np.random.default_rng(11)
        reuse0 = serving_metrics.stats().get("arena.reuse", 0)
        reqs = [api.submit(_prompt(rng, int(rng.integers(3, 30))),
                           max_new_tokens=int(rng.integers(2, 16)))
                for _ in range(12)]
        api.run_until_idle()
        assert all(r.state == RequestState.FINISHED for r in reqs)
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
        assert serving_metrics.stats().get("arena.reuse", 0) > reuse0
        assert api.engine.decode_traces == 1
    finally:
        api.close()


@pytest.mark.slow
def test_background_pump_thread(model):
    api = ServingAPI(model, num_slots=2, kv_block_size=8,
                     max_model_len=MAX_LEN, background=True)
    try:
        rng = np.random.default_rng(12)
        p = _prompt(rng, 5)
        req = api.submit(p, max_new_tokens=6)
        out = api.result(req, timeout=60)
        np.testing.assert_array_equal(out, _ref(model, p, 6))
    finally:
        api.close()


@pytest.mark.chaos
@pytest.mark.slow
def test_step_fault_retried_without_donation(model):
    """With donation off the engine wraps compiled calls in the io retry
    policy: a transient injected step fault is retried and the request
    still completes; with donation on the same config refuses to retry."""
    keep = paddle.get_flags("fault_injection")["fault_injection"]
    paddle.set_flags({"fault_injection": 1})
    try:
        api = ServingAPI(
            model, config=ServingConfig(num_slots=2, kv_block_size=8,
                                        max_model_len=MAX_LEN, donate=False))
        rng = np.random.default_rng(13)
        p = _prompt(rng, 5)
        retries0 = resilience.stats().get("retry.retries", 0)
        resilience.inject_fault("serving_step", times=1,
                                exc=OSError("injected step fault"))
        req = api.submit(p, max_new_tokens=6)
        api.run_until_idle()
        assert req.state == RequestState.FINISHED
        np.testing.assert_array_equal(req.output_ids(), _ref(model, p, 6))
        assert resilience.stats().get("retry.retries", 0) > retries0
        api.close()
    finally:
        resilience.clear_faults()
        paddle.set_flags({"fault_injection": keep})


@pytest.mark.chaos
@pytest.mark.slow
def test_failed_prefill_fails_request_not_engine(model):
    """A prefill failure that exhausts retries fails THAT request cleanly
    (error delivered, done_event set, no leaked arena blocks) and the
    engine keeps serving the next request."""
    keep = {k: paddle.get_flags(k)[k]
            for k in ("fault_injection", "io_retries", "io_retry_backoff")}
    paddle.set_flags({"fault_injection": 1, "io_retries": 2,
                      "io_retry_backoff": 0.001})
    try:
        api = ServingAPI(
            model, config=ServingConfig(num_slots=2, kv_block_size=8,
                                        max_model_len=MAX_LEN, donate=False))
        rng = np.random.default_rng(16)
        p = _prompt(rng, 5)
        resilience.inject_fault("serving_step", times=10,
                                exc=OSError("persistent step fault"))
        req = api.submit(p, max_new_tokens=4)
        api.run_until_idle()
        assert req.state == RequestState.FAILED
        assert isinstance(req.error, OSError)
        assert req.done_event.is_set()
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
        resilience.clear_faults()
        req2 = api.submit(p, max_new_tokens=4)  # engine still healthy
        api.run_until_idle()
        assert req2.state == RequestState.FINISHED
        np.testing.assert_array_equal(req2.output_ids(), _ref(model, p, 4))
        api.close()
    finally:
        resilience.clear_faults()
        paddle.set_flags(keep)


# ----------------------------------------------------------- stats wiring


def test_serving_stats_on_shared_surfaces(api):
    rng = np.random.default_rng(14)
    before = serving_metrics.stats()
    req = api.submit(_prompt(rng, 5), max_new_tokens=4)
    api.run_until_idle()
    delta = serving_metrics.stats_delta(before, serving_metrics.stats())
    assert delta.get("tokens.generated", 0) >= 4
    assert delta.get("requests.finished", 0) == 1
    # headline numbers ride the shared memory_stats provider surface
    from paddle_tpu.core import memory_stats

    stats = memory_stats.memory_stats()
    assert "provider.serving.tokens_generated" in stats
    assert stats["provider.serving.tokens_generated"] \
        == serving_metrics.stats().get("tokens.generated", 0)
    # the engine's Meter publishes a live aggregate decode rate
    assert serving_metrics.stats().get("tokens_per_sec", 0) > 0
    assert req.state == RequestState.FINISHED


def test_completed_output_beats_expired_deadline(api):
    """A request whose output is already whole when its deadline expires
    FINISHES with the result — completed work is never discarded."""
    from paddle_tpu.serving.scheduler import Request

    req = Request(np.arange(4, dtype=np.int32), max_new_tokens=8,
                  stop_token_id=3, tokens=[9, 3],
                  deadline=resilience.Deadline.after(0.0))
    assert req.deadline.expired()
    assert api.scheduler._check_boundary(req)
    assert req.state == RequestState.FINISHED and req.error is None


# ------------------------------------------- priority admission (ISSUE 5)


def test_priority_admission_order(api):
    """Lower priority value is admitted first; FCFS within a class."""
    rng = np.random.default_rng(20)
    rs = [api.submit(_prompt(rng, 4), max_new_tokens=2, priority=p)
          for p in (5, 0, 5)]
    api.run_until_idle()
    assert all(r.state == RequestState.FINISHED for r in rs)
    # admission ticks: the priority-0 request went first, then the two
    # priority-5 requests in arrival order
    assert rs[1]._admit_seq < rs[0]._admit_seq < rs[2]._admit_seq


def test_reservation_exhausted_distinct_from_pressure():
    """take() past a reservation's own budget is an under-reservation BUG
    (ReservationExhaustedError, total/taken in the message) — distinct from
    arena *pressure* (base ArenaExhaustedError), which preemption can heal."""
    arena = KVArena(num_layers=1, num_heads=2, head_dim=4,
                    num_blocks=6, block_size=4)
    res = arena.reserve(2)
    for _ in range(2):
        res.take()
    with pytest.raises(ReservationExhaustedError) as ei:
        res.take()
    assert isinstance(ei.value, ArenaExhaustedError)  # still catchable broadly
    assert "all 2 budgeted blocks" in str(ei.value)
    assert "2 taken" in str(ei.value)
    # genuine pressure raises the base class, never the reservation one
    with pytest.raises(ArenaExhaustedError) as pei:
        arena.reserve(5)
    assert not isinstance(pei.value, ReservationExhaustedError)
    res.release()


# --------------------------------------------- supervisor units (ISSUE 5)


def test_transient_serving_error_classifier():
    assert is_transient_serving_error(resilience.ServingDeviceError("x"))
    assert is_transient_serving_error(resilience.ArenaCorruptError("x"))

    class XlaRuntimeError(Exception):  # jaxlib's class, matched by name
        pass

    assert is_transient_serving_error(XlaRuntimeError("dead tunnel"))
    # bugs / IO / validation / interrupts keep the fail-fast (or retry) path
    assert not is_transient_serving_error(OSError("io"))
    assert not is_transient_serving_error(ValueError("bad request"))
    assert not is_transient_serving_error(KeyboardInterrupt())


class _FakeEngine:
    def __init__(self):
        self.rebuilds = 0

    def rebuild(self):
        self.rebuilds += 1


class _FakeSched:
    def __init__(self):
        self.running = []

    def _gauges(self):
        pass


def test_crash_loop_breaker_opens_and_wraps():
    eng = _FakeEngine()
    sup = EngineSupervisor(eng, _FakeSched(), max_rebuilds=2, window=100)
    err = resilience.ServingDeviceError("flaky")
    assert sup.handle(err) and sup.handle(err)
    assert eng.rebuilds == 2
    assert not sup.handle(err)  # third rebuild within the window: breaker
    assert sup.breaker_open
    wrapped = sup.wrap(err)
    assert isinstance(wrapped, CrashLoopError)
    assert wrapped.__cause__ is err
    assert "FLAGS_serving_max_rebuilds" in str(wrapped)
    # non-transient errors are never handled and pass through wrap()
    bug = ValueError("bug")
    assert not sup.handle(bug)
    assert sup.wrap(bug) is bug


def test_crash_loop_breaker_window_slides():
    eng = _FakeEngine()
    sup = EngineSupervisor(eng, _FakeSched(), max_rebuilds=1, window=5)
    err = resilience.ServingDeviceError("flaky")
    assert sup.handle(err)
    for _ in range(5):
        sup.note_step()  # five steps of real progress: the rebuild ages out
    assert sup.handle(err)
    assert eng.rebuilds == 2 and not sup.breaker_open


def test_recovery_failure_fails_staged_requests():
    """If recovery itself dies (the fresh arena allocation failing on a
    still-dead device), requests staged for replay are failed with that
    error — never left slot-less and RUNNING with done_event unset."""

    class DeadEngine:
        def rebuild(self):
            raise MemoryError("fresh arena allocation failed")

    sched = Scheduler(DeadEngine())
    reqs = [Request(np.arange(4, dtype=np.int32), max_new_tokens=4)
            for _ in range(2)]
    for slot, r in enumerate(reqs):
        r.state = RequestState.RUNNING
        r.slot = slot
        sched.running.append(r)
    sup = EngineSupervisor(DeadEngine(), sched, max_rebuilds=3, window=10)
    with pytest.raises(MemoryError):
        sup.handle(resilience.ServingDeviceError("step died"))
    for r in reqs:
        assert r.state == RequestState.FAILED
        assert isinstance(r.error, MemoryError)
        assert r.done_event.is_set()
    assert not sched.running


# ------------------------------------------------ drain / close (ISSUE 5)


def test_drain_zero_grace_fails_stragglers_retriably(model):
    """drain(grace=0) stops admissions and fails anything still in flight
    with the retriable RequestDrainedError (a queued request costs no
    prefill, so this never compiles)."""
    a = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=MAX_LEN)
    rng = np.random.default_rng(44)
    req = a.submit(_prompt(rng, 5), max_new_tokens=4)  # stays QUEUED
    d0 = resilience.stats().get("serving.drains", 0)
    s0 = resilience.stats().get("serving.drain_stragglers", 0)
    a.drain(grace=0)
    assert req.state == RequestState.FAILED
    assert isinstance(req.error, resilience.RequestDrainedError)
    assert resilience.stats().get("serving.drains", 0) == d0 + 1
    assert resilience.stats().get("serving.drain_stragglers", 0) == s0 + 1
    with pytest.raises(resilience.RequestDrainedError, match="draining"):
        a.submit(_prompt(rng, 5), max_new_tokens=4)
    a.drain()  # idempotent: no second drain counter, no re-fail
    assert resilience.stats().get("serving.drains", 0) == d0 + 1
    a.close()  # close shares the drain path; the dead request is untouched
    assert isinstance(req.error, resilience.RequestDrainedError)


def test_close_after_failed_pump_single_fail(model, monkeypatch):
    """ISSUE 5 satellite: close() routes through drain(grace=0), and
    close() after a failed pump never double-fails requests — one error,
    one stream sentinel, one done_event edge."""
    a = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=MAX_LEN)
    rng = np.random.default_rng(45)
    req = a.submit(_prompt(rng, 5), max_new_tokens=4)
    boom = RuntimeError("pump died")

    def dead_step():
        raise boom

    monkeypatch.setattr(a.scheduler, "step", dead_step)
    with pytest.raises(RuntimeError, match="pump died"):
        a.run_until_idle()
    assert req.state == RequestState.FAILED and req.error is boom
    d0 = resilience.stats().get("serving.drains", 0)
    a.close()  # one shared code path: close == drain(grace=0)
    assert resilience.stats().get("serving.drains", 0) == d0 + 1
    assert req.error is boom  # not replaced by a drain error
    assert req.stream_queue.get_nowait() is None  # exactly one sentinel
    with pytest.raises(pyqueue.Empty):
        req.stream_queue.get_nowait()


def test_drain_all_covers_live_apis(model, monkeypatch):
    import paddle_tpu.serving.api as api_mod

    a = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=MAX_LEN)
    b = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=MAX_LEN)
    b.close()
    monkeypatch.setattr(api_mod, "_live_apis", weakref.WeakSet((a, b)))
    rng = np.random.default_rng(46)
    req = a.submit(_prompt(rng, 5), max_new_tokens=4)
    assert api_mod.drain_all() == 1  # b is already closed: skipped
    assert req.state == RequestState.FAILED
    assert isinstance(req.error, resilience.RequestDrainedError)
    a.close()


def test_preemption_guard_binds_to_drain(model):
    """SIGTERM (stood in by guard.request()) drains the API at the next
    pump boundary instead of killing it mid-decode: admissions stop and
    stragglers fail with the retriable RequestDrainedError — the serving
    mirror of the training loop's step-boundary finalize."""
    a = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=MAX_LEN)
    guard = resilience.PreemptionGuard(install=False)
    assert a.bind_preemption_guard(guard, grace=0.0) is a
    rng = np.random.default_rng(47)
    req = a.submit(_prompt(rng, 5), max_new_tokens=4)  # stays QUEUED
    g0 = serving_metrics.stats().get("api.guard_drains", 0)
    guard.request("test eviction")
    a._pump_once()
    assert req.state == RequestState.FAILED
    assert isinstance(req.error, resilience.RequestDrainedError)
    assert "preemption requested" in str(req.error)
    assert serving_metrics.stats().get("api.guard_drains", 0) == g0 + 1
    with pytest.raises(resilience.RequestDrainedError):
        a.submit(_prompt(rng, 5), max_new_tokens=4)
    a.close()


def test_close_during_inflight_drain_still_sweeps(model, monkeypatch):
    """close() racing an already-running long-grace drain must not return
    with requests still alive: drain() early-returns on the idempotency
    guard, so close() sweeps stragglers itself with its zero grace."""
    a = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=MAX_LEN)
    rng = np.random.default_rng(50)
    req = a.submit(_prompt(rng, 5), max_new_tokens=4)  # stays QUEUED
    a._draining = True  # stand-in for a guard drain mid-grace elsewhere
    a.close()
    assert req.state == RequestState.FAILED
    assert isinstance(req.error, resilience.RequestDrainedError)
    assert req.done_event.is_set()


def test_predictor_priority_kwarg_and_close_summary(model, monkeypatch,
                                                    caplog):
    """ISSUE 5 satellite: EnginePredictor.run honors priorities (kwarg
    defaulting to the constructor's class) and close() logs the replay /
    preemption / drain picture."""
    from paddle_tpu.serving.api import EnginePredictor

    pred = EnginePredictor(model, max_new_tokens=2, priority=7,
                           config=ServingConfig(num_slots=2, kv_block_size=8,
                                                max_model_len=MAX_LEN))
    seen = []

    def fake_submit(prompt, max_new_tokens=32, stop_token_id=None,
                    priority=0, sampling=None, adapter=0):
        seen.append(priority)
        r = Request(prompt, max_new_tokens=max_new_tokens, priority=priority)
        r.state = RequestState.FINISHED
        r.tokens = [1] * max_new_tokens
        return r

    monkeypatch.setattr(pred._api, "submit", fake_submit)
    monkeypatch.setattr(pred._api, "run_until_idle", lambda: None)
    ids = np.ones((2, 4), np.int32)
    pred.run([ids])
    assert seen == [7, 7]  # constructor default rides every row
    pred.run([ids], priority=1)
    assert seen[2:] == [1, 1]  # per-run override
    with caplog.at_level(logging.INFO, logger="paddle_tpu.serving"):
        pred.close()
    assert "supervisor replays" in caplog.text
    assert "preemptions" in caplog.text and "drains" in caplog.text


def test_predictor_mid_batch_submit_failure_strands_nothing(model):
    """If a row's submit sheds mid-batch, EnginePredictor.run cancels the
    rows it already queued instead of leaving unreachable handles that
    FCFS would still spend capacity on."""
    from paddle_tpu.serving.api import EnginePredictor

    pred = EnginePredictor(model, max_new_tokens=4,
                           config=ServingConfig(num_slots=1, kv_block_size=8,
                                                max_model_len=MAX_LEN),
                           max_queue=2)
    try:
        ids = np.tile(np.arange(5, dtype=np.int32), (6, 1))
        with pytest.raises(resilience.QueueOverloadError):
            pred.run([ids])
        assert not pred._api.scheduler.has_work()
    finally:
        pred.close()


# -------------------------------------------- chaos serving (ISSUE 5)


@pytest.mark.chaos
@pytest.mark.slow
def test_supervisor_replay_token_parity_mid_decode(model):
    """ISSUE 5 acceptance: a transient device fault injected mid-decode
    recovers through supervisor rebuild+replay with byte-identical final
    output_ids() for every live request, zero new decode compiles across
    fail/rebuild/replay/resume, and a clean arena (blocks_in_use == 0, all
    slots free) once the workload drains."""
    keep = paddle.get_flags("fault_injection")["fault_injection"]
    paddle.set_flags({"fault_injection": 1})
    api = ServingAPI(model, num_slots=4, kv_block_size=8,
                     max_model_len=MAX_LEN)
    try:
        rng = np.random.default_rng(40)
        prompts = [_prompt(rng, n) for n in (5, 9, 12)]
        # unfaulted reference pass through the same engine
        reqs = [api.submit(p, max_new_tokens=10) for p in prompts]
        api.run_until_idle()
        refs = [r.output_ids() for r in reqs]
        cc0 = compile_cache.stats().get("serving.decode_compiles", 0)
        d0 = api.engine.decode_traces
        rp0 = serving_metrics.stats().get("supervisor.replays", 0)
        rb0 = resilience.stats().get("serving.rebuilds", 0)
        # faulted pass: all three live mid-decode when the device dies
        reqs2 = [api.submit(p, max_new_tokens=10) for p in prompts]
        for _ in range(3):
            api._pump_once()
        assert all(r.state == RequestState.RUNNING for r in reqs2)
        resilience.inject_fault("serving_device", times=1)
        api.run_until_idle()
        for ref, r in zip(refs, reqs2):
            assert r.state == RequestState.FINISHED
            np.testing.assert_array_equal(ref, r.output_ids())
        assert serving_metrics.stats().get("supervisor.replays", 0) \
            == rp0 + 3
        assert resilience.stats().get("serving.rebuilds", 0) == rb0 + 1
        # the arena_corrupt fault class recovers through the same path
        reqs3 = [api.submit(p, max_new_tokens=10) for p in prompts]
        for _ in range(2):
            api._pump_once()
        resilience.inject_fault("arena_corrupt", times=1)
        api.run_until_idle()
        for ref, r in zip(refs, reqs3):
            assert r.state == RequestState.FINISHED
            np.testing.assert_array_equal(ref, r.output_ids())
        # no recompiles anywhere in fail/rebuild/replay/resume
        assert api.engine.decode_traces == d0 == 1
        assert compile_cache.stats().get("serving.decode_compiles", 0) == cc0
        # graceful drain leaves the engine empty: zero stranded slots/blocks
        api.drain(grace=5)
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
        assert api.engine.active_slots() == 0
    finally:
        resilience.clear_faults()
        api.close()
        paddle.set_flags({"fault_injection": keep})


@pytest.mark.chaos
@pytest.mark.slow
def test_preemption_starvation_regression(model):
    """Oversubscribed mixed-priority workload: a high-priority arrival that
    cannot fit preempts the lowest-priority most-recent victim once the
    starvation threshold trips; EVERY request still completes (the victim
    resumes from its journal token-for-token) and nothing recompiles."""
    keep = paddle.get_flags(
        "serving_starvation_steps")["serving_starvation_steps"]
    paddle.set_flags({"serving_starvation_steps": 2})
    api = ServingAPI(model, num_slots=2, kv_block_size=8,
                     max_model_len=MAX_LEN)
    try:
        rng = np.random.default_rng(41)
        pre0 = serving_metrics.stats().get("scheduler.preemptions", 0)
        low_prompts = [_prompt(rng, 6) for _ in range(2)]
        low = [api.submit(p, max_new_tokens=20, priority=5)
               for p in low_prompts]
        api._pump_once()  # both low-priority admitted: slots full
        assert all(r.state == RequestState.RUNNING for r in low)
        hp = _prompt(rng, 20)
        hi = api.submit(hp, max_new_tokens=30, priority=0)
        api.run_until_idle()
        assert all(r.state == RequestState.FINISHED for r in low + [hi])
        assert serving_metrics.stats().get("scheduler.preemptions", 0) > pre0
        # the most recently admitted of the lowest-priority class was evicted
        assert low[1].preemptions >= 1
        # preempted output is identical to an uninterrupted run
        for p, r in zip(low_prompts, low):
            np.testing.assert_array_equal(r.output_ids(), _ref(model, p, 20))
        np.testing.assert_array_equal(hi.output_ids(), _ref(model, hp, 30))
        assert api.engine.decode_traces == 1  # preempt/resume: no recompile
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
    finally:
        api.close()
        paddle.set_flags({"serving_starvation_steps": keep})


@pytest.mark.chaos
@pytest.mark.slow
def test_crash_loop_breaker_end_to_end(model):
    """A persistently dying device stops being rebuilt after the breaker
    budget: in-flight requests fail fast with CrashLoopError (transient
    cause chained) instead of replaying forever, capacity is reclaimed,
    and later pumps surface the same fail-fast error."""
    keep = paddle.get_flags("fault_injection")["fault_injection"]
    paddle.set_flags({"fault_injection": 1})
    api = ServingAPI(model, num_slots=2, kv_block_size=8,
                     max_model_len=MAX_LEN)
    api.supervisor.max_rebuilds = 2
    try:
        rng = np.random.default_rng(42)
        req = api.submit(_prompt(rng, 5), max_new_tokens=8)
        api._pump_once()
        assert req.state == RequestState.RUNNING
        rb0 = serving_metrics.stats().get("supervisor.rebuilds", 0)
        resilience.inject_fault("serving_device", times=100)
        # breaker exhaustion mid-recovery surfaces CrashLoopError to the
        # pumping caller right away (a total failure is not a "recovery")
        with pytest.raises(CrashLoopError):
            api.run_until_idle()
        assert req.state == RequestState.FAILED
        assert isinstance(req.error, CrashLoopError)
        assert isinstance(req.error.__cause__,
                          resilience.ServingDeviceError)
        assert api.supervisor.breaker_open
        assert serving_metrics.stats().get("supervisor.rebuilds", 0) \
            == rb0 + 2
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
        assert api.engine.active_slots() == 0
        # after the breaker opens, queued work fails fast through the pump
        req2 = api.submit(_prompt(rng, 5), max_new_tokens=4)
        with pytest.raises(CrashLoopError):
            api.run_until_idle()
        assert isinstance(req2.error, CrashLoopError)
    finally:
        resilience.clear_faults()
        api.close()
        paddle.set_flags({"fault_injection": keep})


@pytest.mark.chaos
@pytest.mark.slow
def test_breaker_mid_replay_death_leaks_nothing(model, monkeypatch):
    """Regression: the engine dying AGAIN during replay — after some
    requests were already re-admitted into the fresh arena — exhausts the
    breaker without leaking those slots/blocks: everything re-admitted is
    retired before the fail-fast sweep."""
    api = ServingAPI(model, num_slots=2, kv_block_size=8,
                     max_model_len=MAX_LEN)
    api.supervisor.max_rebuilds = 1
    try:
        rng = np.random.default_rng(48)
        r1 = api.submit(_prompt(rng, 5), max_new_tokens=8)
        r2 = api.submit(_prompt(rng, 9), max_new_tokens=8)
        api._pump_once()
        assert all(r.state == RequestState.RUNNING for r in (r1, r2))
        real_admit = api.engine.admit
        calls = {"n": 0}

        def flaky_admit(prompt, max_new_tokens, tokens=None):
            calls["n"] += 1
            if calls["n"] == 2:  # first replay succeeds, second one dies
                raise resilience.ServingDeviceError("died during replay")
            return real_admit(prompt, max_new_tokens, tokens=tokens)

        monkeypatch.setattr(api.engine, "admit", flaky_admit)
        # breaker exhaustion mid-recovery is NOT a recovery: handle()
        # returns False so the pump surfaces CrashLoopError instead of
        # counting a total failure as api.recoveries
        assert not api.supervisor.handle(
            resilience.ServingDeviceError("step died"))
        assert api.supervisor.breaker_open
        for r in (r1, r2):
            assert r.state == RequestState.FAILED
            assert isinstance(r.error, CrashLoopError)
            assert r.done_event.is_set()
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
        assert api.engine.active_slots() == 0
    finally:
        api.close()


@pytest.mark.slow
def test_preemption_declines_when_eviction_cannot_help(model):
    """Feasibility gate: when higher-priority runners hold the arena and
    evicting every strictly-lower-priority victim still could not seat the
    waiter, nothing is preempted — the victims' prefilled work is not
    thrown away for unreachable capacity."""
    keep = paddle.get_flags(
        "serving_starvation_steps")["serving_starvation_steps"]
    paddle.set_flags({"serving_starvation_steps": 1})
    eng_kw = dict(num_slots=3, kv_block_size=8, max_model_len=MAX_LEN,
                  num_blocks=5)  # 4 allocatable blocks
    api = ServingAPI(model, **eng_kw)
    try:
        rng = np.random.default_rng(49)
        # priority-0 holder: 2 blocks; priority-9 victim candidate: 1 block
        holder = api.submit(_prompt(rng, 8), max_new_tokens=8, priority=0)
        victim = api.submit(_prompt(rng, 4), max_new_tokens=4, priority=9)
        api._pump_once()
        assert all(r.state == RequestState.RUNNING for r in (holder, victim))
        # waiter needs 4 blocks; grantable(1) + victim's budget(1) == 2 < 4
        waiter = api.submit(_prompt(rng, 8), max_new_tokens=24, priority=0)
        for _ in range(4):  # well past the starvation threshold
            api._pump_once()
        assert victim.preemptions == 0  # eviction declined, work preserved
        assert victim.state in (RequestState.RUNNING, RequestState.FINISHED)
        api.run_until_idle()  # capacity frees naturally; everyone completes
        for r in (holder, victim, waiter):
            assert r.state == RequestState.FINISHED
    finally:
        api.close()
        paddle.set_flags({"serving_starvation_steps": keep})


@pytest.mark.chaos
@pytest.mark.slow
def test_supervisor_replay_with_live_shared_prefixes(model, monkeypatch):
    """ISSUE 6 satellite: a ``serving_device`` fault mid-decode while
    several slots SHARE radix-cache prefix blocks rebuilds the arena
    (resetting the tree), replays every journal token-for-token — the
    replays re-inserting and re-sharing the prefix with fresh blocks —
    and leaves zero leaked blocks and consistent refcounts after
    ``drain_all()``."""
    keep = {k: paddle.get_flags(k)[k]
            for k in ("fault_injection", "serving_arena_invariants")}
    paddle.set_flags({"fault_injection": 1, "serving_arena_invariants": 1})
    api = ServingAPI(model, num_slots=4, kv_block_size=8,
                     max_model_len=MAX_LEN, prefix_cache=True)
    try:
        import paddle_tpu.serving.api as api_mod

        # drain_all must only sweep THIS test's api, not the shared
        # module fixture (a drained API refuses admissions forever)
        monkeypatch.setattr(api_mod, "_live_apis", weakref.WeakSet((api,)))
        rng = np.random.default_rng(60)
        shared = _prompt(rng, 24)  # 3 full blocks shared by every request
        prompts = [np.concatenate([shared, _prompt(rng, n)])
                   for n in (4, 6, 9)]
        # unfaulted reference pass through the same engine (and the same
        # cache — the second/third admissions already share blocks)
        reqs = [api.submit(p, max_new_tokens=10) for p in prompts]
        api.run_until_idle()
        refs = [r.output_ids() for r in reqs]
        d0 = api.engine.decode_traces
        rb0 = resilience.stats().get("serving.rebuilds", 0)
        # faulted pass: all three live (and sharing) when the device dies
        reqs2 = [api.submit(p, max_new_tokens=10) for p in prompts]
        for _ in range(3):
            api._pump_once()
        assert all(r.state == RequestState.RUNNING for r in reqs2)
        assert api.engine.arena.refcount(
            api.engine.prefix_cache.match(shared)[0].block) >= 2
        resilience.inject_fault("serving_device", times=1)
        api.run_until_idle()
        for ref, r in zip(refs, reqs2):
            assert r.state == RequestState.FINISHED
            np.testing.assert_array_equal(ref, r.output_ids())
        assert resilience.stats().get("serving.rebuilds", 0) == rb0 + 1
        assert api.engine.decode_traces == d0  # replay never recompiles
        # the replays re-populated the FRESH tree and re-shared it
        assert api.engine.prefix_cache.resident_blocks() >= 3
        assert api.engine.prefix_cache.hits >= 2
        # drain everything: no leaked blocks, refcounts all zero, only
        # cache-resident blocks may remain allocated
        import paddle_tpu.serving as serving_mod

        assert serving_mod.drain_all(grace=5) == 1
        api.engine.check_invariants()
        a = api.engine.arena.stats()
        assert a["blocks_reserved"] == 0
        assert a["blocks_in_use"] == a["blocks_cached"]
        assert api.engine.active_slots() == 0
        assert all(api.engine.arena.refcount(b) == 0
                   for b in range(1, api.engine.arena.num_blocks))
    finally:
        resilience.clear_faults()
        api.close()
        paddle.set_flags(keep)


@pytest.mark.slow
def test_drain_completes_in_flight_within_grace(model):
    """drain(grace) pumps already-admitted work to completion — the graceful
    half of shutdown: the in-flight request finishes with its full (parity-
    checked) output before the engine goes away."""
    api = ServingAPI(model, num_slots=2, kv_block_size=8,
                     max_model_len=MAX_LEN)
    try:
        rng = np.random.default_rng(43)
        p = _prompt(rng, 5)
        req = api.submit(p, max_new_tokens=6)
        api._pump_once()  # admitted and decoding
        assert req.state == RequestState.RUNNING
        api.drain(grace=30)
        assert req.state == RequestState.FINISHED
        np.testing.assert_array_equal(req.output_ids(), _ref(model, p, 6))
        assert api.engine.active_slots() == 0
        with pytest.raises(resilience.RequestDrainedError):
            api.submit(p, max_new_tokens=2)
    finally:
        api.close()

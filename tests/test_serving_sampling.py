"""ISSUE 12: per-slot sampling, constrained decoding, and multi-LoRA
adapters in the ONE compiled decode step.

The invariants under test, in the order the issue states them:

* **parity anchors** — temperature=0 / mask-off / adapter-0 are
  token-identical to the classic greedy engine (and to ``generate()``);
  ``generate(sampling=...)`` routes through the same sampling core as
  the engine, so a seeded engine request and a seeded generate() call
  emit identical tokens.
* **seeded determinism** — same seed ⇒ the identical stream, across
  fresh engines, journal-seeded resubmits, and supervisor
  rebuild+replay (positional PRNG keys: ``fold_in(PRNGKey(seed), i)``).
* **zero recompiles** — one batch mixing greedy, sampled, constrained,
  and ≥2 adapter slots decodes with zero new compiles under
  admit/retire/param churn (trace-counter asserted).
* **compose rule** — with speculation on, sampled/constrained/adapter
  slots fall back to the plain per-slot decode step (never an
  off-distribution token); greedy slots keep spec parity.

The engine fixture is module-scoped (tier-1 pays its compiles once);
trace assertions are written lifetime-safe. Chaos cases carry ``chaos``.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import resilience
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (
    AdapterExhaustedError,
    LoraAdapter,
    RequestState,
    SamplingParams,
    ServingAPI,
    ServingConfig,
    TokenDFA,
    TrieConstraint,
)

pytestmark = pytest.mark.serving

MAX_LEN = 64
VOCAB = 1024
SP = SamplingParams(temperature=0.8, top_k=50, top_p=0.95, seed=123)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def api(model):
    a = ServingAPI(model, config=ServingConfig(
        num_slots=4, kv_block_size=8, max_model_len=MAX_LEN,
        lora_rank=4, lora_adapters=3))
    yield a
    a.close()


@pytest.fixture(scope="module")
def adapters(api, model):
    """Two registered fine-tunes the whole module shares."""
    id1 = api.register_adapter(
        LoraAdapter.random(model.cfg, rank=4, seed=7, scale=0.25,
                           name="tenant-a"))
    id2 = api.register_adapter(
        LoraAdapter.random(model.cfg, rank=4, seed=8, scale=0.25,
                           name="tenant-b"))
    return id1, id2


def _prompt(rng, n):
    return rng.integers(0, VOCAB, (n,), dtype=np.int32)


def _ref(model, prompt, max_new):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=max_new)
    return np.asarray(out._data)[0]


# ------------------------------------------------------------- parity


def test_greedy_and_adapter0_parity(api, model):
    """temperature=0 (explicit AND implicit) and adapter-0 on a
    lora-enabled engine are token-identical to generate()."""
    rng = np.random.default_rng(1)
    p = _prompt(rng, 6)
    ref = _ref(model, p, 8)
    reqs = [api.submit(p, max_new_tokens=8),
            api.submit(p, max_new_tokens=8,
                       sampling=SamplingParams(temperature=0.0, seed=99)),
            api.submit(p, max_new_tokens=8, adapter=0)]
    api.run_until_idle()
    for r in reqs:
        assert r.state == RequestState.FINISHED
        np.testing.assert_array_equal(r.output_ids(), ref)


def test_mask_off_is_greedy_identity(api, model):
    """An all-True constraint mask is the bitwise identity: a constraint
    whose walker immediately goes unconstrained emits the greedy stream."""
    rng = np.random.default_rng(2)
    p = _prompt(rng, 5)
    ref = _ref(model, p, 6)
    # a trie whose one choice is the greedy first token, with no stop:
    # after matching it the walker is unconstrained (mask off)
    first = int(ref[len(p)])
    c = TrieConstraint([[first]], vocab_size=VOCAB)
    r = api.submit(p, max_new_tokens=6, constraint=c)
    api.run_until_idle()
    np.testing.assert_array_equal(r.output_ids(), ref)


def test_generate_sampling_parity_anchor(api, model):
    """The satellite anchor: engine request and generate(sampling=...)
    share one sampling core + positional keys ⇒ identical tokens."""
    rng = np.random.default_rng(3)
    p = _prompt(rng, 7)
    r = api.submit(p, max_new_tokens=8, sampling=SP)
    api.run_until_idle()
    g = np.asarray(model.generate(Tensor(p[None]), max_new_tokens=8,
                                  sampling=SP)._data)[0]
    np.testing.assert_array_equal(r.output_ids(), g)
    # and a genuinely different seed gives a different stream (the
    # sampled path is not argmax in disguise)
    r2 = api.submit(p, max_new_tokens=8,
                    sampling=SamplingParams(temperature=0.8, top_k=50,
                                            top_p=0.95, seed=124))
    api.run_until_idle()
    assert r2.tokens != r.tokens


def test_seeded_determinism_and_journal_resume(api):
    """Same seed ⇒ identical stream; a journal-seeded resubmit (the
    gateway re-route path) continues the exact stream from any split."""
    rng = np.random.default_rng(4)
    p = _prompt(rng, 6)
    r1 = api.submit(p, max_new_tokens=10, sampling=SP)
    api.run_until_idle()
    r2 = api.submit(p, max_new_tokens=10, sampling=SP)
    api.run_until_idle()
    assert r1.tokens == r2.tokens
    rj = api.submit(p, max_new_tokens=10, sampling=SP,
                    journal=r1.tokens[:4])
    api.run_until_idle()
    assert rj.tokens == r1.tokens


def test_top_k_top_p_actually_truncate(api, model):
    """top_k=1 degenerates to greedy even at high temperature (the
    truncation machinery provably engages per slot)."""
    rng = np.random.default_rng(5)
    p = _prompt(rng, 6)
    ref = _ref(model, p, 8)
    r = api.submit(p, max_new_tokens=8,
                   sampling=SamplingParams(temperature=5.0, top_k=1,
                                           seed=11))
    api.run_until_idle()
    np.testing.assert_array_equal(r.output_ids(), ref)
    # top_p ~ 0 keeps only the top token: greedy again
    r2 = api.submit(p, max_new_tokens=8,
                    sampling=SamplingParams(temperature=5.0, top_p=1e-9,
                                            seed=11))
    api.run_until_idle()
    np.testing.assert_array_equal(r2.output_ids(), ref)


# -------------------------------------------------------- constrained


def test_trie_constraint_walks_choices(api):
    rng = np.random.default_rng(6)
    p = _prompt(rng, 5)
    choices = [[5, 6, 7], [5, 9]]
    c = TrieConstraint(choices, vocab_size=VOCAB, stop_token_id=3)
    r = api.submit(p, max_new_tokens=8, constraint=c, stop_token_id=3)
    api.run_until_idle()
    assert r.state == RequestState.FINISHED
    assert r.tokens in ([5, 6, 7, 3], [5, 9, 3]), r.tokens


def test_constrained_sampled_stays_in_grammar(api):
    """Sampling + constraint compose: every emitted token is inside the
    walker's allowed set at its step."""
    rng = np.random.default_rng(7)
    p = _prompt(rng, 5)
    dfa = TokenDFA({0: {10: 1, 11: 1}, 1: {20: 0}},
                   vocab_size=VOCAB, accept=(0,), stop_token_id=3)
    r = api.submit(p, max_new_tokens=9, constraint=dfa, stop_token_id=3,
                   sampling=SamplingParams(temperature=1.5, seed=21))
    api.run_until_idle()
    state = dfa.initial()
    for t in r.tokens:
        mask = dfa.allowed(state)
        assert mask[t], (t, r.tokens)
        state = dfa.advance(state, t)


def test_constraint_replay_from_journal(api):
    """A journal-seeded constrained resubmit rebuilds the walker from the
    journal and finishes the same in-grammar stream."""
    rng = np.random.default_rng(8)
    p = _prompt(rng, 5)

    def fresh():
        return TrieConstraint([[5, 6, 7, 8]], vocab_size=VOCAB,
                              stop_token_id=3)

    r1 = api.submit(p, max_new_tokens=8, constraint=fresh(),
                    stop_token_id=3)
    api.run_until_idle()
    rj = api.submit(p, max_new_tokens=8, constraint=fresh(),
                    stop_token_id=3, journal=r1.tokens[:2])
    api.run_until_idle()
    assert rj.tokens == r1.tokens


def test_bad_mask_admission_leaks_nothing(api):
    """Regression: a constraint mask of the wrong vocab size fails the
    REQUEST at admission but must unwind the claim completely — no
    leaked slot, reservation, or shared refs (a handful of such
    requests used to exhaust every slot permanently)."""

    class WrongVocab:
        def initial(self):
            return 0

        def advance(self, state, token):
            return 0

        def allowed(self, state):
            return np.ones(VOCAB // 2, bool)  # wrong size

    free0 = api.engine.free_slots()
    blocks0 = api.engine.arena.blocks_free()
    r = api.submit(np.arange(5) + 1, max_new_tokens=4,
                   constraint=WrongVocab())
    api.run_until_idle()
    assert r.state == RequestState.FAILED
    with pytest.raises(ValueError, match="vocab"):
        raise r.error
    assert api.engine.free_slots() == free0
    assert api.engine.arena.blocks_free() == blocks0
    api.engine.check_invariants()


def test_generate_reseed_no_rebuild(api, model):
    """Regression: the sampling seed is runtime data in generate() too —
    re-seeding reuses the compiled program (no decode.builds growth)."""
    from paddle_tpu.core import compile_cache

    rng = np.random.default_rng(20)
    p = _prompt(rng, 6)
    outs = []
    for s in (1, 2):
        outs.append(np.asarray(model.generate(
            Tensor(p[None]), max_new_tokens=6,
            sampling=SamplingParams(temperature=0.9, seed=s))._data)[0])
        if s == 1:
            builds = compile_cache.stats().get("decode.builds", 0)
    assert compile_cache.stats().get("decode.builds", 0) == builds
    assert outs[0].tolist() != outs[1].tolist()
    # and the re-seeded compiled program still matches the engine
    r = api.submit(p, max_new_tokens=6,
                   sampling=SamplingParams(temperature=0.9, seed=2))
    api.run_until_idle()
    np.testing.assert_array_equal(r.output_ids(), outs[1])


def test_token_dfa_rejects_dead_end():
    with pytest.raises(ValueError, match="dead end"):
        TokenDFA({0: {1: 2}}, vocab_size=16)  # state 2: no exit, no accept
    with pytest.raises(ValueError, match="stop_token_id"):
        TokenDFA({0: {1: 0}}, vocab_size=16, accept=(0,))


# --------------------------------------------------------------- lora


def test_adapters_change_output_and_are_isolated(api, model, adapters):
    """Two adapters in one batch: each differs from base, from each
    other, and matches its own single-slot run (batch independence)."""
    id1, id2 = adapters
    rng = np.random.default_rng(9)
    p = _prompt(rng, 6)
    ref = _ref(model, p, 8)
    r0 = api.submit(p, max_new_tokens=8)
    r1 = api.submit(p, max_new_tokens=8, adapter=id1)
    r2 = api.submit(p, max_new_tokens=8, adapter=id2)
    api.run_until_idle()
    np.testing.assert_array_equal(r0.output_ids(), ref)
    assert r1.tokens != r0.tokens
    assert r2.tokens != r0.tokens
    assert r1.tokens != r2.tokens
    solo = api.submit(p, max_new_tokens=8, adapter=id1)
    api.run_until_idle()
    assert solo.tokens == r1.tokens


def test_adapter_arena_lifecycle(api, model, adapters):
    """Register/unregister recycles rows LIFO; capacity exhausts loudly;
    unknown ids fail at submit, not silently as base."""
    lora = api.engine.lora
    id3 = api.register_adapter(
        LoraAdapter.random(model.cfg, rank=4, seed=9, name="t3"))
    with pytest.raises(AdapterExhaustedError):
        api.register_adapter(
            LoraAdapter.random(model.cfg, rank=4, seed=10, name="t4"))
    api.unregister_adapter(id3)
    with pytest.raises(ValueError, match="not registered"):
        api.submit(np.arange(4) + 1, max_new_tokens=4, adapter=id3)
    id4 = api.register_adapter(
        LoraAdapter.random(model.cfg, rank=4, seed=10, name="t4"))
    assert id4 == id3  # LIFO row reuse
    api.unregister_adapter("t4")
    assert lora.stats()["lora.live"] == 2
    with pytest.raises(ValueError, match="rank"):
        api.register_adapter(LoraAdapter(
            {"0.attn.qkv": (np.zeros((model.cfg.hidden_size, 2)),
                            np.zeros((2, 3 * model.cfg.hidden_size)))},
            name="bad-rank"))


def test_unregister_refused_while_worn(api, model, adapters):
    """Regression: unregistering (and LIFO-recycling) a row a live OR
    QUEUED request names would silently swap the stream's weights (or
    bleed another registrant's) — refused at both layers: the API guard
    covers queued requests, the arena's engine guard occupied slots."""
    id1, _ = adapters
    rng = np.random.default_rng(14)
    p = _prompt(rng, 5)
    # queued (not yet admitted): the API-level guard must already refuse
    rq = api.submit(p, max_new_tokens=4, adapter=id1)
    with pytest.raises(RuntimeError, match="in-flight|in use"):
        api.unregister_adapter(id1)
    api.run_until_idle()
    assert rq.state == RequestState.FINISHED
    r = api.submit(p, max_new_tokens=16, adapter=id1)
    it = api.stream(r)
    next(it)  # pump until the request holds a slot mid-decode
    try:
        with pytest.raises(RuntimeError, match="in use|in-flight"):
            api.unregister_adapter(id1)
    finally:
        r.cancel()
        api.run_until_idle()
    assert api.engine.lora.stats()["lora.live"] == 2  # still registered


def test_spec_ineligibility_sticky_after_constraint_lifts(api):
    """A constraint that goes unconstrained mid-stream must not hand the
    lane back to speculation: the draft cache missed the fallback-phase
    tokens (engine.spec_ineligible stays True for the request's life)."""
    rng = np.random.default_rng(15)
    p = _prompt(rng, 5)
    c = TrieConstraint([[5]], vocab_size=VOCAB)  # lifts after one token
    r = api.submit(p, max_new_tokens=6, constraint=c)
    it = api.stream(r)
    toks = [next(it), next(it)]  # past the trie: mask is lifted now
    assert toks[0] == 5
    assert r.slot is not None
    assert not api.engine._constrained[r.slot]  # constraint lifted...
    assert api.engine.spec_ineligible()[r.slot]  # ...ineligible anyway
    for _ in it:
        pass
    api.run_until_idle()


def test_adapter_requires_arena(model):
    """Naming an adapter on an arena-less engine fails at submit."""
    a = ServingAPI(model, num_slots=2, kv_block_size=8, max_model_len=MAX_LEN)
    try:
        with pytest.raises(ValueError, match="no adapter arena"):
            a.submit(np.arange(4) + 1, max_new_tokens=4, adapter=1)
    finally:
        a.close()


# ---------------------------------------------- the zero-recompile gate


def test_mixed_batch_churn_zero_recompiles(api, model, adapters):
    """The acceptance criterion: one batch mixing greedy, sampled,
    constrained, and two adapter slots decodes with ZERO new compiles
    under admit/retire/param churn — trace-counters asserted, outputs
    parity-checked against their single-scenario references."""
    from paddle_tpu.core import compile_cache

    id1, id2 = adapters
    rng = np.random.default_rng(10)
    p = _prompt(rng, 6)
    ref = _ref(model, p, 8)
    # everything warm (the fixture's earlier tests traced the programs);
    # snapshot the counters
    api.run_until_idle()
    d0 = api.engine.decode_traces
    pf0 = dict(api.engine.prefill_traces)
    cc0 = compile_cache.stats().get("serving.decode_compiles", 0) \
        + compile_cache.stats().get("serving.prefill_compiles", 0)
    sampled_ref = None
    for round_seed in (1, 2, 3):
        sp = SamplingParams(temperature=0.8, top_k=20, seed=round_seed)
        c = TrieConstraint([[5, 6], [7, 8, 9]], vocab_size=VOCAB,
                           stop_token_id=3)
        reqs = [api.submit(p, max_new_tokens=8),
                api.submit(p, max_new_tokens=8, sampling=sp),
                api.submit(p, max_new_tokens=8, constraint=c,
                           stop_token_id=3),
                api.submit(p, max_new_tokens=8, adapter=id1)]
        api.run_until_idle()
        # param churn: the same slots now wear different scenarios
        reqs.append(api.submit(p, max_new_tokens=8, adapter=id2))
        reqs.append(api.submit(p, max_new_tokens=8,
                               sampling=SamplingParams(temperature=0.0)))
        api.run_until_idle()
        np.testing.assert_array_equal(reqs[0].output_ids(), ref)
        np.testing.assert_array_equal(reqs[5].output_ids(), ref)
        assert reqs[2].tokens in ([5, 6, 3], [7, 8, 9, 3])
        if round_seed == 1:
            sampled_ref = reqs[1].tokens
    assert api.engine.decode_traces == d0, "mixed batch recompiled decode"
    assert dict(api.engine.prefill_traces) == pf0, "prefill retraced"
    cc1 = compile_cache.stats().get("serving.decode_compiles", 0) \
        + compile_cache.stats().get("serving.prefill_compiles", 0)
    assert cc1 == cc0
    # single-scenario cross-check: the sampled slot in the mixed batch
    # equals a solo sampled run (slot/batch independence)
    solo = api.submit(p, max_new_tokens=8,
                      sampling=SamplingParams(temperature=0.8, top_k=20,
                                              seed=1))
    api.run_until_idle()
    assert solo.tokens == sampled_ref


# ----------------------------------------------------- spec × sampling


def test_spec_compose_sampled_slots_fall_back(model):
    """Speculation on: greedy slots keep generate() parity through the
    fused path; sampled/constrained/adapter slots fall back to the plain
    per-slot step and emit exactly the speculation-off stream — the
    combination can never emit off-distribution tokens."""
    rng = np.random.default_rng(11)
    p1, p2 = _prompt(rng, 5), _prompt(rng, 7)
    sp = SamplingParams(temperature=0.7, top_k=30, seed=42)

    plain = ServingAPI(model, config=ServingConfig(
        num_slots=4, kv_block_size=8, max_model_len=MAX_LEN,
        lora_rank=4, lora_adapters=2))
    try:
        aid = plain.register_adapter(
            LoraAdapter.random(model.cfg, rank=4, seed=12, scale=0.25))
        rs = plain.submit(p1, max_new_tokens=8, sampling=sp)
        ra = plain.submit(p1, max_new_tokens=8, adapter=aid)
        plain.run_until_idle()
        sampled_ref, adapter_ref = list(rs.tokens), list(ra.tokens)
    finally:
        plain.close()

    spec = ServingAPI(model, config=ServingConfig(
        num_slots=4, kv_block_size=8, max_model_len=MAX_LEN,
        lora_rank=4, lora_adapters=2, spec_k=2))
    try:
        aid2 = spec.register_adapter(
            LoraAdapter.random(model.cfg, rank=4, seed=12, scale=0.25))
        r1 = spec.submit(p1, max_new_tokens=8, sampling=sp)
        r2 = spec.submit(p2, max_new_tokens=8)
        r3 = spec.submit(p1, max_new_tokens=8, adapter=aid2)
        spec.run_until_idle()
        np.testing.assert_array_equal(r2.output_ids(),
                                      _ref(model, p2, 8))
        assert r1.tokens == sampled_ref
        assert r3.tokens == adapter_ref
        st = spec.engine.stats()
        assert st["spec.emitted"] > 0  # the greedy lane did speculate
        # the fallback actually engaged (counted per ineligible lane)
        from paddle_tpu.serving import metrics as serving_metrics

        assert serving_metrics.stats().get(
            "sampling.spec_fallback_slots", 0) > 0
    finally:
        spec.close()


# --------------------------------------------------------------- chaos


@pytest.mark.chaos
def test_chaos_replay_with_sampling_and_adapters(model):
    """Mid-decode serving_device fault with sampled + adapter + greedy
    slots live: rebuild+replay resumes every stream token-identically
    (positional keys + journal-rebuilt state), zero new decode traces."""
    rng = np.random.default_rng(12)
    p1, p2 = _prompt(rng, 5), _prompt(rng, 6)
    sp = SamplingParams(temperature=0.9, top_k=40, seed=77)
    cfg = ServingConfig(num_slots=4, kv_block_size=8, max_model_len=MAX_LEN,
                        lora_rank=4, lora_adapters=2)
    adapter = LoraAdapter.random(model.cfg, rank=4, seed=13, scale=0.25)

    ref_api = ServingAPI(model, config=cfg)
    try:
        aid = ref_api.register_adapter(adapter)
        r_s = ref_api.submit(p1, max_new_tokens=10, sampling=sp)
        r_a = ref_api.submit(p2, max_new_tokens=10, adapter=aid)
        r_g = ref_api.submit(p2, max_new_tokens=10)
        ref_api.run_until_idle()
        refs = [list(r.tokens) for r in (r_s, r_a, r_g)]
    finally:
        ref_api.close()

    keep = paddle.get_flags(["fault_injection"])
    paddle.set_flags({"FLAGS_fault_injection": True})
    api = ServingAPI(model, config=cfg)
    try:
        aid = api.register_adapter(adapter)
        warm = api.submit(p2, max_new_tokens=2)
        api.run_until_idle()
        assert warm.state == RequestState.FINISHED
        d0 = api.engine.decode_traces
        r_s = api.submit(p1, max_new_tokens=10, sampling=sp)
        r_a = api.submit(p2, max_new_tokens=10, adapter=aid)
        r_g = api.submit(p2, max_new_tokens=10)
        got = []
        for tok in api.stream(r_s):
            got.append(tok)
            if len(got) == 3:  # all three slots mid-decode
                resilience.inject_fault("serving_device", times=1)
        api.run_until_idle()
        assert api.supervisor.rebuild_count == 1
        assert [got, list(r_a.tokens), list(r_g.tokens)] == refs
        assert api.engine.decode_traces == d0, "replay recompiled"
        api.engine.check_invariants()
    finally:
        api.close()
        paddle.set_flags(keep)


# ------------------------------------------------------------- gateway


def test_gateway_tenant_scenario_defaults(model):
    """TenantConfig carries adapter id + sampling defaults: a tenant's
    requests decode with its fine-tune and params without per-request
    plumbing; per-request values still override."""
    from paddle_tpu.serving import ReplicaPool, TenantConfig, TenantManager

    rng = np.random.default_rng(13)
    p = _prompt(rng, 6)
    cfg = ServingConfig(num_slots=4, kv_block_size=8, max_model_len=MAX_LEN,
                        lora_rank=4, lora_adapters=2)
    pool = ReplicaPool(model, replicas=2, config=cfg)
    try:
        aid = pool.register_adapter(
            LoraAdapter.random(model.cfg, rank=4, seed=14, scale=0.25,
                               name="ft-acme"))
        sp = SamplingParams(temperature=0.8, top_k=20, seed=5)
        pool.tenants.configure(TenantConfig("acme", adapter=aid,
                                            sampling=sp))
        rr = pool.submit(p, max_new_tokens=8, tenant="acme")
        rr_base = pool.submit(p, max_new_tokens=8, tenant="acme",
                              adapter=0,
                              sampling=SamplingParams(temperature=0.0))
        pool.run_until_idle()
        np.testing.assert_array_equal(pool.result(rr_base, timeout=60),
                                      _ref(model, p, 8))
        assert rr.tokens() != rr_base.tokens()
        # adapter AUTHORIZATION: another tenant may use acme's fine-tune
        # only when its allowed_adapters says so — fine-tunes are tenant
        # property, a guessed row id must not serve them
        with pytest.raises(ValueError, match="not authorized"):
            pool.submit(p, max_new_tokens=8, tenant="intruder",
                        adapter=aid)
        pool.tenants.configure(TenantConfig("partner",
                                            allowed_adapters=(aid,)))
        rr2 = pool.submit(p, max_new_tokens=8, tenant="partner",
                          adapter=aid, sampling=sp)
        pool.run_until_idle()
        # the tenant default reproduces an explicit submit of the same
        # scenario (deterministic: positional keys + registered adapter)
        assert rr2.tokens() == rr.tokens()
    finally:
        pool.close()

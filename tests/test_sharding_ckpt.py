"""ZeRO group_sharded levels, recompute API, sharded checkpoint + reshard."""
import os

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.distributed.checkpoint import (
    TrainCheckpointer,
    apply_state_dict,
    load_state_dict,
    save_state_dict,
)
from paddle_tpu.distributed.fleet.recompute import recompute, recompute_sequential
from paddle_tpu.jit import TrainStep


def _model(d=8):
    return nn.Sequential(nn.Linear(d, 2 * d), nn.ReLU(), nn.Linear(2 * d, 1))


def test_group_sharded_os_levels_train():
    paddle.seed(0)
    dist.init_hybrid_mesh(sharding=4, dp=2)
    for level in ("os", "os_g", "p_g_os"):
        model = _model(8)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=model.parameters())
        model, opt, _ = dist.group_sharded_parallel(model, opt, level=level)
        X = paddle.to_tensor(np.random.rand(16, 8).astype(np.float32))
        Y = paddle.to_tensor(np.random.rand(16, 1).astype(np.float32))
        step = TrainStep(lambda x, y: ((model(x) - y) ** 2).mean(), opt, layers=model)
        l0 = float(step(X, Y).numpy())
        for _ in range(5):
            l = float(step(X, Y).numpy())
        assert np.isfinite(l) and l < l0
        # optimizer slots carry the sharding-axis placement (when divisible)
        slot = step._opt_state["slots"][0]["moment1"]
        assert "sharding" in str(slot.sharding.spec) or all(
            s % 4 for s in slot.shape[:1])


def test_group_sharded_p_places_params():
    dist.init_hybrid_mesh(sharding=8)
    model = _model(16)  # weight [16, 32]: dim0 divisible by 8
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    dist.group_sharded_parallel(model, opt, level="p_g_os")
    w = model[0].weight
    assert "sharding" in str(w._data.sharding.spec)


def test_recompute_matches_plain():
    paddle.seed(0)
    m = _model(8)
    X = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))

    @paddle.jit.to_static
    def f_plain(x):
        return m(x)

    @paddle.jit.to_static
    def f_rc(x):
        return recompute(m, x)

    np.testing.assert_allclose(f_plain(X).numpy(), f_rc(X).numpy(), atol=1e-6)


def test_recompute_sequential():
    paddle.seed(0)
    layers = [nn.Linear(8, 8) for _ in range(4)]
    X = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
    ref = X
    for l in layers:
        ref = l(ref)
    out = recompute_sequential({"segments": 2}, layers, X)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-6)


def test_checkpoint_roundtrip(tmp_path):
    paddle.seed(0)
    m = _model(8)
    sd = m.state_dict()
    path = os.path.join(str(tmp_path), "ckpt1")
    save_state_dict(sd, path)
    restored = load_state_dict(path, target=sd)
    for k, v in m.state_dict().items():
        np.testing.assert_allclose(np.asarray(restored[k]), v.numpy(), atol=0)


def test_checkpoint_reshard_on_load(tmp_path):
    """Save replicated, load onto a sharded target: values identical."""
    paddle.seed(0)
    dist.init_hybrid_mesh(sharding=8)
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = dist.get_mesh()
    arr = np.arange(64, dtype=np.float32).reshape(16, 4)
    path = os.path.join(str(tmp_path), "ckpt2")
    save_state_dict({"w": paddle.to_tensor(arr)}, path)
    target = {
        "w": jax.device_put(
            np.zeros_like(arr), NamedSharding(mesh, PartitionSpec("sharding", None)))
    }
    restored = load_state_dict(path, target=target)
    np.testing.assert_allclose(np.asarray(restored["w"]), arr)
    assert "sharding" in str(restored["w"].sharding.spec)


def test_train_checkpointer_resume(tmp_path):
    paddle.seed(0)
    m = _model(8)
    ck = TrainCheckpointer(os.path.join(str(tmp_path), "mgr"), max_to_keep=2)
    sd = m.state_dict()
    ck.save(1, sd)
    ck.save(2, sd)
    ck.wait_until_finished()
    assert ck.latest_step() == 2
    m2 = _model(8)
    restored = ck.restore(m2.state_dict())
    apply_state_dict(m2, restored)
    for (k, a), (_, b) in zip(m.state_dict().items(), m2.state_dict().items()):
        np.testing.assert_allclose(a.numpy(), b.numpy())
    ck.close()


def test_async_save_overlaps_training(tmp_path):
    """VERDICT r4 #4: an async save must return while the write is still in
    flight so training steps overlap it; the result must load identically.
    Proof of overlap: the async call returns in a fraction of the measured
    synchronous write time for the same tree, and >=1 training step executes
    between the save call and wait()."""
    import time

    paddle.seed(0)
    # ~128 MB: large enough that the write visibly dominates the timings
    big = {f"w{i}": paddle.to_tensor(
        np.random.rand(1024, 1024, 8).astype(np.float32)) for i in range(4)}
    sync_path = os.path.join(str(tmp_path), "sync")
    t0 = time.perf_counter()
    save_state_dict(big, sync_path, blocking=True)
    sync_t = time.perf_counter() - t0

    m = _model(8)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    X = paddle.to_tensor(np.random.rand(8, 8).astype(np.float32))
    Y = paddle.to_tensor(np.random.rand(8, 1).astype(np.float32))
    step = TrainStep(lambda x, y: ((m(x) - y) ** 2).mean(), opt, layers=m)
    step(X, Y)  # compile outside the timed window

    async_path = os.path.join(str(tmp_path), "async")
    t0 = time.perf_counter()
    handle = save_state_dict(big, async_path, blocking=False)
    async_return_t = time.perf_counter() - t0
    steps_between = 0
    for _ in range(3):  # training overlaps the in-flight write
        step(X, Y)
        steps_between += 1
    handle.wait()
    assert steps_between >= 1
    # the async call must not have blocked for the whole write
    assert async_return_t < max(0.5 * sync_t, 0.2), (async_return_t, sync_t)
    restored = load_state_dict(async_path, target=big)
    for k in big:
        np.testing.assert_allclose(np.asarray(restored[k]),
                                   big[k].numpy(), atol=0)


def test_kill_during_async_save_resumes_previous_step(tmp_path):
    """A process killed mid-async-save must leave the PREVIOUS complete
    checkpoint as latest_step(): orbax's temp-dir+rename commit means the
    torn step-2 write is invisible to restore."""
    import subprocess
    import sys
    import textwrap

    ckdir = os.path.join(str(tmp_path), "mgr")
    script = textwrap.dedent(f"""
        import os
        import numpy as np
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from paddle_tpu.distributed.checkpoint import TrainCheckpointer
        ck = TrainCheckpointer({ckdir!r}, async_save=True)
        small = {{"w": np.arange(8, dtype=np.float32), "step": 1}}
        ck.save(1, small)
        ck.wait_until_finished()
        # step 2: big enough that the background write cannot finish
        # before the hard exit below
        big = {{"w": np.random.rand(1024, 1024, 32).astype(np.float32),
               "step": 2}}
        ck.save(2, big)
        print("SAVED2", flush=True)
        os._exit(9)  # SIGKILL-equivalent: no atexit, no finalization
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=300)
    assert "SAVED2" in r.stdout, r.stderr[-500:]
    assert r.returncode == 9
    ck = TrainCheckpointer(ckdir, async_save=True)
    latest = ck.latest_step()
    # The guarantee under test: a kill mid-save NEVER leaves a torn
    # checkpoint visible. Near-always the 128 MB step-2 write cannot commit
    # in the ~ms before os._exit and latest == 1; on an absurdly fast disk
    # step 2 may have committed — then it must restore COMPLETE and correct.
    assert latest in (1, 2)
    restored = ck.restore()
    if latest == 1:
        np.testing.assert_allclose(np.asarray(restored["w"]),
                                   np.arange(8, dtype=np.float32))
        assert int(restored["step"]) == 1
    else:  # pragma: no cover — racy fast-disk fallback
        assert np.asarray(restored["w"]).shape == (1024, 1024, 32)
        assert int(restored["step"]) == 2
    ck.close()


def test_async_overwrite_keeps_previous_until_commit(tmp_path):
    """Fixed-path periodic async saves: the previous complete checkpoint is
    kept aside until the new one commits, and load_state_dict falls back to
    it — a death mid-overwrite can never lose ALL progress."""
    path = os.path.join(str(tmp_path), "fixed")
    v1 = {"w": paddle.to_tensor(np.full(4, 1.0, np.float32))}
    v2 = {"w": paddle.to_tensor(np.full(4, 2.0, np.float32))}

    h = save_state_dict(v1, path, blocking=False)
    h.wait()
    # simulate the state a mid-overwrite death leaves behind: save_state_dict
    # had renamed the old checkpoint aside and the new write never committed
    os.replace(path, path + ".prev")
    restored = load_state_dict(path, target=v1)  # falls back to .prev
    np.testing.assert_allclose(np.asarray(restored["w"]), 1.0)

    # a completed overwrite cleans the kept-aside copy
    save_state_dict(v1, path, blocking=True)
    h2 = save_state_dict(v2, path, blocking=False)
    h2.wait()
    assert not os.path.exists(path + ".prev")
    restored = load_state_dict(path, target=v2)
    np.testing.assert_allclose(np.asarray(restored["w"]), 2.0)

    # repeated async overwrites to one path serialize cleanly
    for val in (3.0, 4.0):
        h = save_state_dict(
            {"w": paddle.to_tensor(np.full(4, val, np.float32))},
            path, blocking=False)
    h.wait()
    restored = load_state_dict(path, target=v2)
    np.testing.assert_allclose(np.asarray(restored["w"]), 4.0)

    # the BLOCKING overwrite path keeps the previous checkpoint aside during
    # the write too (orbax force=True would delete it first) and cleans up
    # after its synchronous commit
    save_state_dict(v1, path, blocking=True)
    assert not os.path.exists(path + ".prev")
    restored = load_state_dict(path, target=v1)
    np.testing.assert_allclose(np.asarray(restored["w"]), 1.0)


def test_trainstep_resume_across_sharding_topology_change(tmp_path):
    """The preemptible-pod story end-to-end on virtual devices: train under
    ZeRO sharding=8, checkpoint (sharded orbax save), rebuild the WORLD at
    sharding=4, restore via reshard-on-load, continue — the trajectory
    matches an uninterrupted run."""
    import paddle_tpu.distributed as dist

    x = np.random.RandomState(3).rand(16, 8).astype(np.float32)
    y = np.random.RandomState(4).rand(16, 1).astype(np.float32)

    def build(sharding):
        dist.destroy_process_group()
        dist.set_mesh(None)
        dist.init_hybrid_mesh(sharding=sharding)
        paddle.seed(77)
        m = _model(8)
        o = paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=m.parameters())
        m, o, _ = dist.group_sharded_parallel(m, o, level="os_g")
        s = TrainStep(lambda a, b: ((m(a) - b) ** 2).mean(), o, layers=m)
        return m, o, s

    # uninterrupted control at sharding=8
    m1, o1, s1 = build(8)
    for _ in range(5):
        l_ref = s1(paddle.to_tensor(x), paddle.to_tensor(y))

    # interrupted: 2 steps at sharding=8, checkpoint, resume at sharding=4
    m2, o2, s2 = build(8)
    for _ in range(2):
        s2(paddle.to_tensor(x), paddle.to_tensor(y))
    ck = TrainCheckpointer(os.path.join(str(tmp_path), "topo"))
    ck.save(2, {"model": m2.state_dict(), "opt": o2.state_dict()})
    ck.wait_until_finished()

    m3, o3, s3 = build(4)  # the new, smaller world
    # one throwaway step so o3's accumulators exist: the restore TARGET
    # then carries the NEW mesh's placements and the saved values are
    # RESHARDED onto them (the actual reshard-on-load path; a templateless
    # restore would come back as plain replicated arrays)
    s3(paddle.to_tensor(x), paddle.to_tensor(y))
    target = {"model": m3.state_dict(), "opt": o3.state_dict()}
    restored = ck.restore(target=target)
    m3.set_state_dict(restored["model"])
    o3.set_state_dict(restored["opt"])  # bumps the optimizer state version:
    # the already-stepped TrainStep drops its cached compiled state and
    # re-seeds from the restored accumulators on the next call
    for _ in range(3):
        l_res = s3(paddle.to_tensor(x), paddle.to_tensor(y))

    np.testing.assert_allclose(float(np.asarray(l_ref._data)),
                               float(np.asarray(l_res._data)), rtol=1e-4)
    for p1, p3 in zip(m1.parameters(), m3.parameters()):
        np.testing.assert_allclose(np.asarray(p1._data),
                                   np.asarray(p3._data), atol=1e-5)
    ck.close()

"""Signature-level API parity (VERDICT r3 missing #5).

tools/sig_audit.py compares argument names/defaults against signatures
extracted from the reference source (tools/ref_signatures.json). The audit
must stay >= 95% per surface; behavior tests below cover the parameters the
round-4 parity pass added semantics for (not just signature cosmetics).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_signature_audit_above_bar(capsys):
    from tools.sig_audit import audit

    pct, report = audit()
    assert pct >= 95.0, capsys.readouterr().out
    for mod, r in report.items():
        n = len(r["pass"]) + len(r["diverge"])
        assert len(r["pass"]) >= 0.95 * n, (mod, r["diverge"])


def test_isclose_tolerances():
    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    y = paddle.to_tensor(np.array([1.05, np.nan], np.float32))
    out = paddle.isclose(x, y, rtol=0.1)
    np.testing.assert_array_equal(out.numpy(), [True, False])
    out = paddle.isclose(x, y, rtol=1e-6)
    np.testing.assert_array_equal(out.numpy(), [False, False])
    both_nan = paddle.isclose(paddle.to_tensor(np.array([np.nan], np.float32)),
                              paddle.to_tensor(np.array([np.nan], np.float32)),
                              equal_nan=True)
    np.testing.assert_array_equal(both_nan.numpy(), [True])


def test_cross_default_axis_sentinel():
    """axis=9 (ref sentinel) picks the first size-3 axis, here axis 1."""
    rng = np.random.RandomState(0)
    a = rng.randn(2, 3, 4).astype(np.float32)
    b = rng.randn(2, 3, 4).astype(np.float32)
    out = paddle.cross(paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), np.cross(a, b, axis=1),
                               rtol=1e-5)
    out0 = paddle.cross(paddle.to_tensor(a), paddle.to_tensor(b), axis=1)
    np.testing.assert_allclose(out0.numpy(), np.cross(a, b, axis=1),
                               rtol=1e-5)


def test_sum_prod_dtype_kwarg():
    x = paddle.to_tensor(np.array([[250, 250], [250, 250]], np.uint8))
    # the cast happens BEFORE reducing: uint8 would overflow at 1000
    # (int64 demotes to int32 under jax's default x64-disabled mode)
    assert int(paddle.sum(x, dtype="int64")) == 1000
    assert "int" in str(paddle.sum(x, dtype="int64").dtype)
    p = paddle.prod(paddle.to_tensor(np.array([2, 3], np.int32)),
                    dtype="float32")
    assert float(p) == 6.0 and "float32" in str(p.dtype)


def test_nanmedian_default_keepdim_matches_reference():
    """ref:python/paddle/tensor/stat.py:259 defaults keepdim=True."""
    x = paddle.to_tensor(np.array([[1.0, np.nan, 3.0]], np.float32))
    out = paddle.nanmedian(x, axis=1)
    assert tuple(out.shape) == (1, 1)  # keepdim=True by default
    assert float(out.numpy().ravel()[0]) == 2.0
    out2 = paddle.nanmedian(x, axis=1, keepdim=False)
    assert tuple(out2.shape) == (1,)


def test_logical_bitwise_out_param():
    x = paddle.to_tensor(np.array([True, False]))
    y = paddle.to_tensor(np.array([True, True]))
    out = paddle.to_tensor(np.array([False, False]))
    r = paddle.logical_and(x, y, out=out)
    assert r is out
    np.testing.assert_array_equal(out.numpy(), [True, False])
    b = paddle.to_tensor(np.array([1, 2], np.int32))
    ob = paddle.to_tensor(np.array([0, 0], np.int32))
    r2 = paddle.bitwise_not(b, out=ob)
    assert r2 is ob
    np.testing.assert_array_equal(ob.numpy(), [-2, -3])


def test_gather_kthvalue_none_axis():
    x = paddle.to_tensor(np.arange(6, np.float32).reshape(3, 2)
                         if False else np.arange(6).reshape(3, 2)
                         .astype(np.float32))
    idx = paddle.to_tensor(np.array([2, 0]))
    np.testing.assert_array_equal(paddle.gather(x, idx).numpy(),
                                  x.numpy()[[2, 0]])
    v, i = paddle.kthvalue(x, k=1)  # axis=None -> last dim
    np.testing.assert_array_equal(v.numpy(), [0.0, 2.0, 4.0])


def test_momentum_rescale_grad():
    p = paddle.to_tensor(np.ones(2, np.float32))
    p.stop_gradient = False
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.0,
                                    parameters=[p], rescale_grad=0.5)
    loss = (p * paddle.to_tensor(np.array([2.0, 2.0], np.float32))).sum()
    loss.backward()
    opt.step()
    # grad 2.0 rescaled to 1.0, lr 0.1 -> p = 1 - 0.1
    np.testing.assert_allclose(p.numpy(), [0.9, 0.9], rtol=1e-6)


def test_seed_and_rng_state_param_names():
    paddle.seed(seed=123)
    st = paddle.get_rng_state(device=None)
    a = paddle.randn([3]).numpy()
    paddle.set_rng_state(st)
    b = paddle.randn([3]).numpy()
    np.testing.assert_array_equal(a, b)


def test_check_shape_reference_contract():
    paddle.check_shape([1, 2, 3])
    with pytest.raises(ValueError):
        paddle.check_shape([1, -2])
    with pytest.raises(TypeError):
        paddle.check_shape([1, 2.5])


def test_random_crop_reference_behaviors():
    from paddle_tpu.vision import transforms as T

    img = np.arange(36, dtype=np.uint8).reshape(6, 6)
    # pad_if_needed grows a too-small image instead of crashing
    out = T.RandomCrop(8, pad_if_needed=True)(img)
    assert out.shape == (8, 8)
    # constant fill value lands in the padding
    out = T.RandomCrop(6, padding=2, fill=7)(np.zeros((2, 2), np.uint8))
    assert (out == 7).sum() > 0
    # non-constant mode accepted
    out = T.RandomCrop(4, padding=2, padding_mode="reflect")(img)
    assert out.shape == (4, 4)


def test_normalize_to_rgb_and_resize_interpolation():
    from paddle_tpu.vision import transforms as T

    img = np.zeros((4, 4, 3), np.float32)
    img[..., 0] = 1.0  # "B" channel hot
    out = T.normalize(img, mean=[0, 0, 0], std=[1, 1, 1],
                      data_format="HWC", to_rgb=True)
    assert out[..., 2].max() == 1.0 and out[..., 0].max() == 0.0
    r = T.resize(np.zeros((8, 8), np.uint8), 4, interpolation="nearest")
    assert np.asarray(r).shape[:2] == (4, 4)


def test_transform_keys_tuple_semantics():
    """keys routes tuple inputs through per-key handlers: elements without
    a handler (e.g. a mask/label) pass through untouched."""
    from paddle_tpu.vision import transforms as T

    img = np.full((2, 2, 3), 4.0, np.float32)
    mask = np.ones((2, 2), np.int32)
    t = T.Normalize(mean=[1, 1, 1], std=[2, 2, 2], data_format="HWC",
                    keys=("image", "mask"))
    out_img, out_mask = t((img, mask))
    np.testing.assert_allclose(out_img, np.full((2, 2, 3), 1.5), rtol=1e-6)
    assert out_mask is mask  # untouched

    with pytest.raises(ValueError, match="padding_mode"):
        T.RandomCrop(4, padding_mode="wrap")

    # pad_if_needed pads BOTH sides: the crop offset stays random
    crops = {T.RandomCrop(8, pad_if_needed=True)(
        np.arange(36, dtype=np.uint8).reshape(6, 6)).tobytes()
        for _ in range(25)}
    assert len(crops) > 1


def test_name_audit_no_missing(capsys):
    """The name-level surface audit (op_coverage) must stay at zero
    missing — regressions in module wiring show up here, not just in the
    standalone tool."""
    from tools.op_coverage import audit

    totals = audit()
    capsys.readouterr()  # swallow the table
    assert totals["missing"] == 0, totals

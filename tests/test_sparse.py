"""paddle.sparse: creation, conversion, ops."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _coo():
    indices = np.asarray([[0, 1, 2], [1, 2, 0]])  # [ndim, nnz] paddle layout
    values = np.asarray([1.0, 2.0, 3.0], np.float32)
    return sparse.sparse_coo_tensor(indices, values, shape=[3, 3])


def test_coo_roundtrip():
    s = _coo()
    dense = s.to_dense().numpy()
    expect = np.zeros((3, 3), np.float32)
    expect[0, 1], expect[1, 2], expect[2, 0] = 1, 2, 3
    np.testing.assert_allclose(dense, expect)
    assert s.nnz() == 3
    np.testing.assert_allclose(s.values().numpy(), [1, 2, 3])
    assert s.indices().shape == [2, 3]


def test_to_sparse_and_back():
    x = paddle.to_tensor(np.asarray([[0, 5.0], [7.0, 0]], np.float32))
    s = sparse.to_sparse_coo(x)
    np.testing.assert_allclose(s.to_dense().numpy(), x.numpy())
    csr = s.to_sparse_csr()
    np.testing.assert_allclose(csr.to_dense().numpy(), x.numpy())
    assert csr.nnz() == 2


def test_sparse_dense_matmul():
    s = _coo()
    d = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
    out = sparse.matmul(s, d)
    np.testing.assert_allclose(out.numpy(), s.to_dense().numpy() @ d.numpy(),
                               atol=1e-6)


def test_sparse_add_and_unary():
    s = _coo()
    out = sparse.add(s, s)
    np.testing.assert_allclose(out.to_dense().numpy(), 2 * s.to_dense().numpy())
    r = sparse.relu(sparse.add(s, s))
    assert isinstance(r, sparse.SparseCooTensor)
    neg = sparse.neg(s)
    np.testing.assert_allclose(neg.to_dense().numpy(), -s.to_dense().numpy())


def test_csr_creation():
    crows = np.asarray([0, 1, 2, 3])
    cols = np.asarray([1, 2, 0])
    vals = np.asarray([1.0, 2.0, 3.0], np.float32)
    s = sparse.sparse_csr_tensor(crows, cols, vals, [3, 3])
    expect = np.zeros((3, 3), np.float32)
    expect[0, 1], expect[1, 2], expect[2, 0] = 1, 2, 3
    np.testing.assert_allclose(s.to_dense().numpy(), expect)


def test_masked_matmul():
    mask = _coo()
    a = paddle.to_tensor(np.random.rand(3, 5).astype(np.float32))
    b = paddle.to_tensor(np.random.rand(5, 3).astype(np.float32))
    out = sparse.masked_matmul(a, b, mask)
    full = a.numpy() @ b.numpy()
    dense = out.to_dense().numpy()
    for (i, j) in [(0, 1), (1, 2), (2, 0)]:
        np.testing.assert_allclose(dense[i, j], full[i, j], atol=1e-5)

"""paddle.sparse: creation, conversion, ops."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _coo():
    indices = np.asarray([[0, 1, 2], [1, 2, 0]])  # [ndim, nnz] paddle layout
    values = np.asarray([1.0, 2.0, 3.0], np.float32)
    return sparse.sparse_coo_tensor(indices, values, shape=[3, 3])


def test_coo_roundtrip():
    s = _coo()
    dense = s.to_dense().numpy()
    expect = np.zeros((3, 3), np.float32)
    expect[0, 1], expect[1, 2], expect[2, 0] = 1, 2, 3
    np.testing.assert_allclose(dense, expect)
    assert s.nnz() == 3
    np.testing.assert_allclose(s.values().numpy(), [1, 2, 3])
    assert s.indices().shape == [2, 3]


def test_to_sparse_and_back():
    x = paddle.to_tensor(np.asarray([[0, 5.0], [7.0, 0]], np.float32))
    s = sparse.to_sparse_coo(x)
    np.testing.assert_allclose(s.to_dense().numpy(), x.numpy())
    csr = s.to_sparse_csr()
    np.testing.assert_allclose(csr.to_dense().numpy(), x.numpy())
    assert csr.nnz() == 2


def test_sparse_dense_matmul():
    s = _coo()
    d = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
    out = sparse.matmul(s, d)
    np.testing.assert_allclose(out.numpy(), s.to_dense().numpy() @ d.numpy(),
                               atol=1e-6)


def test_sparse_add_and_unary():
    s = _coo()
    out = sparse.add(s, s)
    np.testing.assert_allclose(out.to_dense().numpy(), 2 * s.to_dense().numpy())
    r = sparse.relu(sparse.add(s, s))
    assert isinstance(r, sparse.SparseCooTensor)
    neg = sparse.neg(s)
    np.testing.assert_allclose(neg.to_dense().numpy(), -s.to_dense().numpy())


def test_csr_creation():
    crows = np.asarray([0, 1, 2, 3])
    cols = np.asarray([1, 2, 0])
    vals = np.asarray([1.0, 2.0, 3.0], np.float32)
    s = sparse.sparse_csr_tensor(crows, cols, vals, [3, 3])
    expect = np.zeros((3, 3), np.float32)
    expect[0, 1], expect[1, 2], expect[2, 0] = 1, 2, 3
    np.testing.assert_allclose(s.to_dense().numpy(), expect)


def test_masked_matmul():
    mask = _coo()
    a = paddle.to_tensor(np.random.rand(3, 5).astype(np.float32))
    b = paddle.to_tensor(np.random.rand(5, 3).astype(np.float32))
    out = sparse.masked_matmul(a, b, mask)
    full = a.numpy() @ b.numpy()
    dense = out.to_dense().numpy()
    for (i, j) in [(0, 1), (1, 2), (2, 0)]:
        np.testing.assert_allclose(dense[i, j], full[i, j], atol=1e-5)


class TestSparseAutograd:
    """Dense-operand gradients through sparse ops (the GNN training path:
    adj @ features must backprop into features; ref sparse grad contract)."""

    def _coo(self, dense_np):
        import paddle_tpu.sparse as sparse

        idx = np.argwhere(dense_np != 0)
        vals = dense_np[tuple(idx.T)]
        return sparse.sparse_coo_tensor(
            paddle.to_tensor(idx.T.astype(np.int64)),
            paddle.to_tensor(vals), shape=list(dense_np.shape))

    def test_spmm_grad_matches_dense(self):
        import paddle_tpu.sparse as sparse

        rng = np.random.RandomState(40)
        adj = (rng.rand(5, 5) > 0.6).astype(np.float32) * rng.rand(5, 5) \
            .astype(np.float32)
        feats = rng.rand(5, 3).astype(np.float32)
        w = rng.randn(5, 3).astype(np.float32)

        sp = self._coo(adj)
        x1 = paddle.to_tensor(feats)
        x1.stop_gradient = False
        (sparse.matmul(sp, x1) * paddle.to_tensor(w)).sum().backward()

        x2 = paddle.to_tensor(feats)
        x2.stop_gradient = False
        (paddle.matmul(paddle.to_tensor(adj), x2)
         * paddle.to_tensor(w)).sum().backward()
        np.testing.assert_allclose(np.asarray(x1.grad._data),
                                   np.asarray(x2.grad._data),
                                   rtol=1e-5, atol=1e-6)

    def test_sparse_add_dense_grad(self):
        import paddle_tpu.sparse as sparse

        rng = np.random.RandomState(41)
        a = (rng.rand(4, 4) > 0.5).astype(np.float32)
        y = paddle.to_tensor(rng.rand(4, 4).astype(np.float32))
        y.stop_gradient = False
        out = sparse.add(self._coo(a), y)
        (out ** 2).sum().backward()
        # d/dy (a+y)^2 = 2(a+y)
        np.testing.assert_allclose(
            np.asarray(y.grad._data),
            2 * (a + np.asarray(y._data)), rtol=1e-5)

    def test_masked_matmul_grads_both_operands(self):
        import paddle_tpu.sparse as sparse

        rng = np.random.RandomState(42)
        xd = rng.rand(4, 6).astype(np.float32)
        yd = rng.rand(6, 4).astype(np.float32)
        mask_np = np.zeros((4, 4), np.float32)
        mask_np[[0, 1, 3], [2, 0, 3]] = 1.0

        px, py = paddle.to_tensor(xd), paddle.to_tensor(yd)
        px.stop_gradient = py.stop_gradient = False
        out = sparse.masked_matmul(px, py, self._coo(mask_np))
        (out.values() ** 2).sum().backward()

        tx, ty = paddle.to_tensor(xd), paddle.to_tensor(yd)
        tx.stop_gradient = ty.stop_gradient = False
        dense = paddle.matmul(tx, ty) * paddle.to_tensor(mask_np)
        (dense ** 2).sum().backward()
        np.testing.assert_allclose(np.asarray(px.grad._data),
                                   np.asarray(tx.grad._data),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(py.grad._data),
                                   np.asarray(ty.grad._data),
                                   rtol=1e-5, atol=1e-6)

    def test_masked_matmul_to_dense_keeps_tape(self):
        import paddle_tpu.sparse as sparse

        rng = np.random.RandomState(43)
        xd = rng.rand(3, 5).astype(np.float32)
        yd = rng.rand(5, 3).astype(np.float32)
        mask_np = np.eye(3, dtype=np.float32)
        px, py = paddle.to_tensor(xd), paddle.to_tensor(yd)
        px.stop_gradient = py.stop_gradient = False
        dense_out = sparse.masked_matmul(px, py, self._coo(mask_np)) \
            .to_dense()
        (dense_out ** 2).sum().backward()
        assert px.grad is not None and py.grad is not None
        # equals the dense masked computation's grads
        tx, ty = paddle.to_tensor(xd), paddle.to_tensor(yd)
        tx.stop_gradient = ty.stop_gradient = False
        ((paddle.matmul(tx, ty) * paddle.to_tensor(mask_np)) ** 2) \
            .sum().backward()
        np.testing.assert_allclose(np.asarray(px.grad._data),
                                   np.asarray(tx.grad._data), rtol=1e-5,
                                   atol=1e-6)

    def test_csr_matmul_grad(self):
        import paddle_tpu.sparse as sparse

        rng = np.random.RandomState(44)
        adj = (rng.rand(4, 4) > 0.5).astype(np.float32)
        csr = self._coo(adj).to_sparse_csr()
        x = paddle.to_tensor(rng.rand(4, 2).astype(np.float32))
        x.stop_gradient = False
        sparse.matmul(csr, x).sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad._data),
                                   adj.sum(0)[:, None].repeat(2, 1),
                                   rtol=1e-5)

    def test_spmm_repeated_calls_reuse_jit_cache(self):
        """Stable module-level kernels: repeated sparse.matmul calls with
        the same structure must NOT grow the dispatch jit cache per call
        (a per-call closure would retrace and leak an executable each
        step of a GNN loop)."""
        import paddle_tpu.sparse as sparse
        from paddle_tpu.core import dispatch

        rng = np.random.RandomState(45)
        adj = (rng.rand(6, 6) > 0.5).astype(np.float32)
        sp = self._coo(adj)
        x = paddle.to_tensor(rng.rand(6, 2).astype(np.float32))
        sparse.matmul(sp, x)  # prime
        before = len(dispatch._JIT_CACHE)
        for _ in range(5):
            sparse.matmul(sp, x)
        assert len(dispatch._JIT_CACHE) == before

    def test_mv_and_addmm_grads(self):
        import paddle_tpu.sparse as sparse

        rng = np.random.RandomState(46)
        adj = (rng.rand(4, 4) > 0.4).astype(np.float32)
        sp = self._coo(adj)
        v = paddle.to_tensor(rng.rand(4).astype(np.float32))
        v.stop_gradient = False
        sparse.mv(sp, v).sum().backward()
        np.testing.assert_allclose(np.asarray(v.grad._data), adj.sum(0),
                                   rtol=1e-5)

        inp = paddle.to_tensor(rng.rand(4, 2).astype(np.float32))
        y = paddle.to_tensor(rng.rand(4, 2).astype(np.float32))
        inp.stop_gradient = y.stop_gradient = False
        out = sparse.addmm(inp, sp, y, beta=0.5, alpha=2.0)
        out.sum().backward()
        np.testing.assert_allclose(np.asarray(inp.grad._data),
                                   np.full((4, 2), 0.5), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(y.grad._data),
                                   2.0 * adj.sum(0)[:, None]
                                   .repeat(2, 1), rtol=1e-5)

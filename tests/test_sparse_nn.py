"""paddle.sparse.nn layers (ref:python/paddle/sparse/nn/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse
from paddle_tpu.sparse import nn as snn


def _coo4d(shape=(1, 4, 4, 4, 3), density=0.3, seed=0):
    rng = np.random.default_rng(seed)
    dense = rng.standard_normal(shape).astype(np.float32)
    mask = rng.random(shape[:-1]) < density
    dense = dense * mask[..., None]
    t = paddle.to_tensor(dense)
    return sparse.to_sparse_coo(t, sparse_dim=len(shape) - 1), dense


def test_sparse_relu_family():
    s, dense = _coo4d()
    out = snn.ReLU()(s).to_dense().numpy()
    np.testing.assert_allclose(out, np.maximum(dense, 0), rtol=1e-6)
    out = snn.ReLU6()(s).to_dense().numpy()
    np.testing.assert_allclose(out, np.clip(dense, 0, 6), rtol=1e-6)
    out = snn.LeakyReLU(0.1)(s).to_dense().numpy()
    np.testing.assert_allclose(out, np.where(dense >= 0, dense, 0.1 * dense),
                               rtol=1e-6)
    f = snn.functional.relu(s).to_dense().numpy()
    np.testing.assert_allclose(f, np.maximum(dense, 0), rtol=1e-6)


def test_sparse_softmax_rows():
    rng = np.random.default_rng(1)
    dense = rng.standard_normal((4, 6)).astype(np.float32)
    mask = rng.random((4, 6)) < 0.5
    mask[:, 0] = True  # no empty rows
    dense = dense * mask
    s = sparse.to_sparse_coo(paddle.to_tensor(dense), sparse_dim=2)
    out = snn.Softmax()(s).to_dense().numpy()
    for r in range(4):
        nz = mask[r]
        e = np.exp(dense[r][nz] - dense[r][nz].max())
        np.testing.assert_allclose(out[r][nz], e / e.sum(), rtol=1e-5)
        np.testing.assert_allclose(out[r][~nz], 0.0)


def test_sparse_batchnorm_normalizes_active_values():
    s, dense = _coo4d(density=0.5, seed=2)
    bn = snn.BatchNorm(3)
    out = bn(s)
    v = out.values().numpy()
    # active-site statistics ~ standardized
    np.testing.assert_allclose(v.mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(v.std(0), 1.0, atol=1e-2)
    bn.eval()
    out2 = bn(s)
    assert out2.to_dense().numpy().shape == dense.shape


def test_subm_conv3d_preserves_sites():
    s, dense = _coo4d(density=0.3, seed=3)
    conv = snn.SubmConv3D(3, 5, 3)
    out = conv(s)
    assert tuple(out.shape) == (1, 4, 4, 4, 5)
    od = out.to_dense().numpy()
    active = (dense != 0).any(-1)
    assert (od[~active] == 0).all()  # inactive sites stay empty
    assert (od[active] != 0).any()


def test_sparse_conv3d_and_maxpool():
    s, dense = _coo4d(density=0.4, seed=4)
    conv = snn.Conv3D(3, 2, 3, padding=1)
    out = conv(s)
    assert tuple(out.shape) == (1, 4, 4, 4, 2)
    pool = snn.MaxPool3D(2, 2)
    p = pool(s)
    assert tuple(p.shape) == (1, 2, 2, 2, 3)
    # pooled dense equals dense maxpool (zeros participate, as reference)
    import torch
    import torch.nn.functional as TF

    want = TF.max_pool3d(torch.tensor(dense).permute(0, 4, 1, 2, 3), 2, 2)
    want = want.permute(0, 2, 3, 4, 1).numpy()
    np.testing.assert_allclose(p.to_dense().numpy(), want, rtol=1e-5)


def test_sparse_attention_masked():
    rng = np.random.default_rng(5)
    q = paddle.to_tensor(rng.standard_normal((1, 2, 4, 8)).astype(np.float32))
    k = paddle.to_tensor(rng.standard_normal((1, 2, 4, 8)).astype(np.float32))
    v = paddle.to_tensor(rng.standard_normal((1, 2, 4, 8)).astype(np.float32))
    mask = np.tril(np.ones((4, 4), np.float32))
    sm = sparse.to_sparse_coo(paddle.to_tensor(mask), sparse_dim=2)
    out = snn.functional.attention(q, k, v, sm)
    assert out.shape == [1, 2, 4, 8]
    # row 0 attends only to position 0 -> equals v[..., 0, :]
    np.testing.assert_allclose(out.numpy()[:, :, 0], v.numpy()[:, :, 0],
                               rtol=1e-5)


def test_subm_conv3d_noncubic_kernel_same_shape():
    s, dense = _coo4d(density=0.3, seed=6)
    out = snn.SubmConv3D(3, 2, (1, 3, 3))(s)
    assert tuple(out.shape) == (1, 4, 4, 4, 2)
    with pytest.raises(ValueError, match="odd kernel"):
        snn.SubmConv3D(3, 2, 2)(s)


def test_subm_conv3d_rejects_fully_sparse_layout():
    rng = np.random.default_rng(7)
    dense = rng.standard_normal((1, 3, 3, 3, 2)).astype(np.float32)
    fully = sparse.to_sparse_coo(paddle.to_tensor(dense))  # no dense dims
    with pytest.raises(ValueError, match="channel dim dense"):
        snn.SubmConv3D(2, 2, 3)(fully)


def test_sparse_functional_maxpool_ceil_and_attention_masks():
    s, dense = _coo4d(shape=(1, 5, 5, 5, 2), density=0.4, seed=8)
    out = snn.functional.max_pool3d(s, 2, 2, ceil_mode=True)
    assert tuple(out.shape) == (1, 3, 3, 3, 2)
    rng = np.random.default_rng(9)
    q = paddle.to_tensor(rng.standard_normal((1, 1, 3, 4)).astype(np.float32))
    mask = sparse.to_sparse_coo(
        paddle.to_tensor(np.ones((3, 3), np.float32)), sparse_dim=2)
    pad = paddle.to_tensor(np.array([[1, 1, 0]], np.float32))  # key 2 padded
    out = snn.functional.attention(q, q, q, mask, key_padding_mask=pad)
    # with key 2 masked everywhere, output is a mix of keys 0/1 only:
    # replacing key 2's value must not change the result
    q2 = q.numpy().copy()
    q2[:, :, 2] = 99.0
    out2 = snn.functional.attention(paddle.to_tensor(q.numpy()),
                                    paddle.to_tensor(q.numpy()),
                                    paddle.to_tensor(q2), mask,
                                    key_padding_mask=pad)
    np.testing.assert_allclose(out.numpy(), out2.numpy(), rtol=1e-5)

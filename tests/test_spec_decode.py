"""Speculative decoding + chunked prefill (ISSUE 10).

Parity is the whole contract: with ``FLAGS_serving_spec_k`` > 0 the engine
must emit *bit-identical* greedy output to plain decode — for a perfect
draft, a garbage draft (pure rejection fallback), the lockstep self-draft,
and across supervisor rebuild+replay with a live draft cache. Accept /
reject / chunk admission must add ZERO compiled programs after warmup
(trace-counter asserted), and draft-block rollback must leave the arena's
refcount layer clean (invariant-checker asserted).

Fast cases run in tier-1; the chaos replay and heavier churn cases carry
``chaos`` / ``slow`` like the rest of the serving suite. Everything here
builds its own ServingAPI (spec/chunk config is captured at engine
construction), so the shared fixtures of test_serving.py are untouched —
and the flag-off default path is exercised by that whole suite unmodified.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import compile_cache, flags, resilience
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (
    RequestState,
    ServingAPI,
    ServingConfig,
    SpecDecoder,
)
from paddle_tpu.serving import metrics as serving_metrics

pytestmark = pytest.mark.serving

MAX_LEN = 96


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def bad_draft():
    """An independently initialized draft: proposes near-pure garbage, so
    every iteration exercises the rejection/rollback path."""
    paddle.seed(1234)
    d = GPTForCausalLM(gpt_tiny())
    d.eval()
    return d


@pytest.fixture(scope="module")
def tied_draft(model):
    """A separate draft instance carrying the target's weights: agrees
    with the target everywhere (acceptance 1.0) while still running the
    full draft machinery (own arrays, own arena namespace, own prefills)."""
    paddle.seed(77)
    d = GPTForCausalLM(gpt_tiny())
    d.eval()
    d.set_state_dict(dict(model.state_dict()))
    return d


def _prompt(rng, n):
    return rng.integers(0, 1024, (n,), dtype=np.int32)


def _ref(model, prompt, max_new, stop=None):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=max_new, stop_token_id=stop)
    return np.asarray(out._data)[0]


def _spec_api(model, draft=None, k=4, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("kv_block_size", 8)
    kw.setdefault("max_model_len", MAX_LEN)
    return ServingAPI(model, ServingConfig(spec_k=k, draft_model=draft,
                                           **kw))


# ------------------------------------------------------------- parity


def test_lockstep_parity_with_generate(model):
    """Self-draft fused decode (no draft model): k target sub-steps per
    compiled call, token-for-token identical to generate() across mixed
    prompt/output lengths — including budgets that don't divide k."""
    api = _spec_api(model, k=4)
    try:
        rng = np.random.default_rng(1)
        cases = [(5, 8), (11, 13), (17, 1), (9, 4), (23, 19)]
        prompts = [_prompt(rng, p) for p, _ in cases]
        reqs = [api.submit(p, max_new_tokens=n)
                for p, (_, n) in zip(prompts, cases)]
        api.run_until_idle()
        for p, (_, n), r in zip(prompts, cases, reqs):
            assert r.state == RequestState.FINISHED
            np.testing.assert_array_equal(r.output_ids(), _ref(model, p, n))
        st = api.engine.spec.stats()
        assert st["spec.acceptance_rate"] == 1.0  # structural, not lucky
        # every decode-phase token came through speculation (each
        # request's FIRST token is emitted by its prefill): exact count —
        # the engine never over-emits past a budget and discards nothing
        assert st["spec.emitted"] == sum(n - 1 for _, n in cases)
    finally:
        api.close()


def test_bad_draft_rejection_fallback_is_bit_identical(model, bad_draft):
    """A garbage draft is a pure slowdown, never a correctness change:
    acceptance collapses toward zero (every iteration rolls speculation
    back) and the output still equals plain greedy decode exactly."""
    api = _spec_api(model, draft=bad_draft, k=3)
    try:
        rng = np.random.default_rng(2)
        prompts = [_prompt(rng, n) for n in (5, 9, 14)]
        reqs = [api.submit(p, max_new_tokens=12) for p in prompts]
        api.run_until_idle()
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(r.output_ids(),
                                          _ref(model, p, 12))
        st = api.engine.spec.stats()
        assert st["spec.rollback_tokens"] > 0  # rejections really happened
        assert st["spec.acceptance_rate"] < 0.5
    finally:
        api.close()


def test_tied_draft_full_acceptance_parity(model, tied_draft):
    """A draft carrying the target's weights accepts everything — the
    longest-prefix machinery, the second block table, and the draft
    prefills all run, and the output is still bit-identical."""
    api = _spec_api(model, draft=tied_draft, k=3)
    try:
        rng = np.random.default_rng(3)
        prompts = [_prompt(rng, n) for n in (6, 10)]
        reqs = [api.submit(p, max_new_tokens=10) for p in prompts]
        api.run_until_idle()
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(r.output_ids(),
                                          _ref(model, p, 10))
        st = api.engine.spec.stats()
        assert st["spec.acceptance_rate"] == 1.0
        assert st["spec.rollback_tokens"] == 0
        assert st["spec.draft_prefill_traces"]  # the draft really prefilled
    finally:
        api.close()


def test_stop_token_parity_under_speculation(model):
    """Tokens speculated past a stop hit are dropped, exactly like the
    sequential path that would never have generated them."""
    api = _spec_api(model, k=4)
    try:
        rng = np.random.default_rng(4)
        p = _prompt(rng, 6)
        full = _ref(model, p, 12)
        stop = int(full[len(p) + 3])  # a token greedy decode really emits
        ref = _ref(model, p, 12, stop=stop)
        req = api.submit(p, max_new_tokens=12, stop_token_id=stop)
        api.run_until_idle()
        got = req.output_ids()
        assert req.state == RequestState.FINISHED
        assert int(got[-1]) == stop
        assert len(got) < len(p) + 12
        np.testing.assert_array_equal(got, ref[: len(got)])
    finally:
        api.close()


# -------------------------------------------------- no-recompile invariant


def test_accept_reject_churn_zero_new_compiles(model, bad_draft):
    """Accept/reject churn is pure runtime data: after the first
    iteration traces the fused program, an arbitrary mix of acceptance
    depths, admissions, retirements and budget-clamped lanes adds ZERO
    decode/prefill compiles (engine trace counters AND the shared
    compile_cache counters agree)."""
    api = _spec_api(model, draft=bad_draft, k=3)
    try:
        rng = np.random.default_rng(5)
        # warm: one admission per prefill bucket the churn will touch,
        # plus the fused spec-step program
        api.submit(_prompt(rng, 5), max_new_tokens=4)
        api.submit(_prompt(rng, 12), max_new_tokens=4)
        api.run_until_idle()
        s0 = api.engine.spec.spec_traces
        cc0 = compile_cache.stats().get("serving.decode_compiles", 0)
        pf0 = compile_cache.stats().get("serving.prefill_compiles", 0)
        for round_ in range(3):
            reqs = [api.submit(_prompt(rng, int(rng.integers(4, 14))),
                               max_new_tokens=int(rng.integers(2, 9)))
                    for _ in range(6)]
            api.run_until_idle()
            assert all(r.state == RequestState.FINISHED for r in reqs)
        assert api.engine.spec.spec_traces == s0 == 1
        assert compile_cache.stats().get("serving.decode_compiles", 0) == cc0
        assert compile_cache.stats().get("serving.prefill_compiles", 0) == pf0
    finally:
        api.close()


# ------------------------------------------------------- arena invariants


def test_arena_invariants_after_draft_rollback_churn(model, bad_draft):
    """The second (draft) block-table namespace obeys the refcount layer:
    after rejection-heavy churn every draft block is accounted exactly
    once, retirement returns both tables' budgets, and the drained arena
    is empty."""
    keep = paddle.get_flags("serving_arena_invariants")
    paddle.set_flags({"serving_arena_invariants": 1})
    api = _spec_api(model, draft=bad_draft, k=3)
    try:
        rng = np.random.default_rng(6)
        reqs = [api.submit(_prompt(rng, n), max_new_tokens=8)
                for n in (5, 9, 13, 7)]
        # mid-flight audit: active target tables + draft tables vs refcounts
        for _ in range(2):
            api._pump_once()
        api.engine.check_invariants()
        api.run_until_idle()
        assert all(r.state == RequestState.FINISHED for r in reqs)
        api.engine.check_invariants()
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
        assert a["namespaces"] == 1  # the draft namespace exists
    finally:
        api.close()
        paddle.set_flags(keep)


def test_draft_mode_doubles_default_arena_and_reservations(model,
                                                          tied_draft):
    """Draft mode budgets a second worst-case table per slot: the default
    arena doubles, admission reserves both, and retire returns both."""
    api = _spec_api(model, draft=tied_draft, k=2, num_slots=2)
    try:
        eng = api.engine
        assert eng.arena.num_blocks == 2 * 2 * eng.blocks_per_slot + 1
        rng = np.random.default_rng(7)
        req = api.submit(_prompt(rng, 9), max_new_tokens=4)
        api._pump_once()
        slot = req.slot
        assert slot is not None
        # both namespaces' budgets counted (preemption feasibility sums)
        per_table = -(-(9 + 4) // eng.block_size)
        assert eng.reserved_blocks(slot) == 2 * per_table
        api.run_until_idle()
        assert eng.arena.stats()["blocks_in_use"] == 0
    finally:
        api.close()


# ----------------------------------------------------------- flag gating


def test_flag_off_engine_has_no_spec_surface(model):
    """Default flags reproduce the PR 9 engine exactly: no SpecDecoder,
    no chunk state, plain decode_step semantics (the whole existing
    serving suite runs against this path unmodified)."""
    api = ServingAPI(model, num_slots=2, kv_block_size=8,
                     max_model_len=MAX_LEN)
    try:
        assert api.engine.spec is None
        assert api.engine.chunk_size == 0
        assert flags.flag("serving_spec_k") == 0
        assert flags.flag("serving_chunked_prefill") == 0
        rng = np.random.default_rng(8)
        p = _prompt(rng, 7)
        req = api.submit(p, max_new_tokens=6)
        api.run_until_idle()
        np.testing.assert_array_equal(req.output_ids(), _ref(model, p, 6))
    finally:
        api.close()


def test_spec_decoder_rejects_bad_config(model):
    with pytest.raises(ValueError):
        SpecDecoder(object(), None, k=0)
    small_vocab = GPTForCausalLM(gpt_tiny())
    small_vocab.cfg.vocab_size = 999
    with pytest.raises(ValueError, match="vocab"):
        _spec_api(model, draft=small_vocab, k=2)


# -------------------------------------------------------- chunked prefill


def test_chunked_prefill_interleaves_and_keeps_parity(model):
    """A long prompt admits in chunks while a running stream keeps
    decoding every iteration: the running stream gains >= one token per
    chunk step (the bounded-stall contract), and both outputs equal
    generate()'s bit-for-bit."""
    api = ServingAPI(model, ServingConfig(num_slots=4, kv_block_size=8,
                                          max_model_len=MAX_LEN,
                                          chunked_prefill=8))
    try:
        rng = np.random.default_rng(9)
        small = _prompt(rng, 5)
        big = _prompt(rng, 41)  # 41 tokens -> several 8-token chunks
        r1 = api.submit(small, max_new_tokens=24)
        for _ in range(2):
            api.scheduler.step()
        r2 = api.submit(big, max_new_tokens=6)
        api.scheduler.step()  # admission: the big prompt begins chunking
        assert r2 in api.scheduler.prefilling
        interleaved = 0
        while api.scheduler.prefilling:
            before = len(r1.tokens)
            api.scheduler.step()
            if not r1.finished and len(r1.tokens) > before:
                interleaved += 1
        assert interleaved >= 3  # decode really ran between chunks
        api.run_until_idle()
        np.testing.assert_array_equal(r1.output_ids(),
                                      _ref(model, small, 24))
        np.testing.assert_array_equal(r2.output_ids(),
                                      _ref(model, big, 6))
        sm = serving_metrics.stats()
        assert sm.get("chunk.admits", 0) >= 1
        assert sm.get("chunk.chunks", 0) >= 5
    finally:
        api.close()


def test_chunked_prefill_bounded_compiles(model):
    """Chunks reuse the suffix-prefill ladder: N chunked admissions of
    assorted lengths mint at most the chunk-bucket programs once, then
    zero — chunk admission is runtime data like everything else."""
    api = ServingAPI(model, ServingConfig(num_slots=4, kv_block_size=8,
                                          max_model_len=MAX_LEN,
                                          chunked_prefill=8))
    try:
        rng = np.random.default_rng(10)
        r = api.submit(_prompt(rng, 30), max_new_tokens=3)
        api.run_until_idle()
        assert r.state == RequestState.FINISHED
        pf0 = compile_cache.stats().get("serving.prefill_compiles", 0)
        d0 = api.engine.decode_traces
        reqs = [api.submit(_prompt(rng, n), max_new_tokens=3)
                for n in (25, 33, 17, 30)]
        api.run_until_idle()
        assert all(q.state == RequestState.FINISHED for q in reqs)
        assert compile_cache.stats().get("serving.prefill_compiles", 0) == pf0
        assert api.engine.decode_traces == d0
    finally:
        api.close()


def test_cancel_mid_chunked_prefill_frees_everything(model):
    """Cancelling a request whose prompt is still scattering releases the
    slot, both reservations, and the chunk state — nothing leaks, and the
    next admission reuses the slot."""
    keep = paddle.get_flags("serving_arena_invariants")
    paddle.set_flags({"serving_arena_invariants": 1})
    api = ServingAPI(model, ServingConfig(num_slots=2, kv_block_size=8,
                                          max_model_len=MAX_LEN,
                                          chunked_prefill=8))
    try:
        rng = np.random.default_rng(11)
        big = _prompt(rng, 40)
        req = api.submit(big, max_new_tokens=6)
        api.scheduler.step()  # admit_begin: slot claimed, chunks pending
        assert req in api.scheduler.prefilling
        in_use = api.engine.arena.stats()["blocks_in_use"]
        assert in_use > 0
        req.cancel()
        api.scheduler.step()
        assert req.state == RequestState.CANCELLED
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
        assert api.engine.free_slots() == 2
        api.engine.check_invariants()
        # slot is genuinely reusable
        r2 = api.submit(_prompt(rng, 6), max_new_tokens=4)
        api.run_until_idle()
        assert r2.state == RequestState.FINISHED
    finally:
        api.close()
        paddle.set_flags(keep)


def test_cancel_behind_prefilling_head_frees_immediately(model):
    """Regression (review finding): a cancelled chunked admission BEHIND
    the queue head must release its slot/blocks at the next step, not
    after the head's remaining chunks."""
    api = ServingAPI(model, ServingConfig(num_slots=4, kv_block_size=8,
                                          max_model_len=MAX_LEN,
                                          chunked_prefill=8))
    try:
        rng = np.random.default_rng(16)
        a = api.submit(_prompt(rng, 40), max_new_tokens=4)
        b = api.submit(_prompt(rng, 40), max_new_tokens=4)
        api.scheduler.step()  # both admitted chunked
        assert [a, b] == api.scheduler.prefilling
        free_before = api.engine.free_slots()
        b.cancel()
        api.scheduler.step()  # head A advances ONE chunk; B culled NOW
        assert b.state == RequestState.CANCELLED
        assert b not in api.scheduler.prefilling
        assert api.engine.free_slots() == free_before + 1
        assert a in api.scheduler.prefilling  # head unaffected
        api.run_until_idle()
        assert a.state == RequestState.FINISHED
    finally:
        api.close()


def test_chunked_plus_speculation_compose(model, tied_draft):
    """Both flags on: chunked admission scatters the target cache, the
    final chunk triggers the draft prefill, and speculative decode takes
    over — output still bit-identical."""
    api = ServingAPI(model, ServingConfig(num_slots=2, kv_block_size=8,
                                          max_model_len=MAX_LEN,
                                          chunked_prefill=8, spec_k=3,
                                          draft_model=tied_draft))
    try:
        rng = np.random.default_rng(12)
        big = _prompt(rng, 37)
        req = api.submit(big, max_new_tokens=10)
        api.run_until_idle()
        assert req.state == RequestState.FINISHED
        np.testing.assert_array_equal(req.output_ids(),
                                      _ref(model, big, 10))
        assert api.engine.spec.stats()["spec.acceptance_rate"] == 1.0
    finally:
        api.close()


# ------------------------------------------------------------ chaos/replay


@pytest.mark.chaos
@pytest.mark.slow
def test_spec_replay_parity_mid_verify_fault(model, tied_draft):
    """A transient device fault during speculative decode recovers through
    supervisor rebuild + journal replay: the draft cache is reconstructed
    (admit re-prefills both namespaces), outputs are byte-identical to the
    unfaulted run, the fused spec program never retraces, and the drained
    arena is clean."""
    keep = paddle.get_flags("fault_injection")["fault_injection"]
    paddle.set_flags({"fault_injection": 1})
    api = _spec_api(model, draft=tied_draft, k=3)
    try:
        rng = np.random.default_rng(13)
        prompts = [_prompt(rng, n) for n in (5, 9, 12)]
        reqs = [api.submit(p, max_new_tokens=14) for p in prompts]
        api.run_until_idle()
        refs = [r.output_ids() for r in reqs]
        s0 = api.engine.spec.spec_traces
        rb0 = resilience.stats().get("serving.rebuilds", 0)
        reqs2 = [api.submit(p, max_new_tokens=14) for p in prompts]
        for _ in range(2):
            api._pump_once()
        assert all(r.state == RequestState.RUNNING for r in reqs2)
        # the fault probe fires inside the fused propose+verify dispatch
        resilience.inject_fault("serving_device", times=1)
        api.run_until_idle()
        for ref, r in zip(refs, reqs2):
            assert r.state == RequestState.FINISHED
            np.testing.assert_array_equal(ref, r.output_ids())
        assert resilience.stats().get("serving.rebuilds", 0) == rb0 + 1
        assert api.engine.spec.spec_traces == s0 == 1  # no retrace anywhere
        api.drain(grace=5)
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
    finally:
        resilience.clear_faults()
        api.close()
        paddle.set_flags({"fault_injection": keep})


@pytest.mark.chaos
@pytest.mark.slow
def test_chunked_prefill_replay_after_mid_chunk_fault(model):
    """A device fault while a long prompt is mid-chunk re-queues it (the
    engine unwound the half-scattered admission) and the supervisor's
    rebuild resumes everything token-for-token."""
    keep = paddle.get_flags("fault_injection")["fault_injection"]
    paddle.set_flags({"fault_injection": 1})
    api = ServingAPI(model, ServingConfig(num_slots=4, kv_block_size=8,
                                          max_model_len=MAX_LEN,
                                          chunked_prefill=8))
    try:
        rng = np.random.default_rng(14)
        small, big = _prompt(rng, 6), _prompt(rng, 40)
        r_small = api.submit(small, max_new_tokens=20)
        for _ in range(2):
            api._pump_once()
        r_big = api.submit(big, max_new_tokens=6)
        api._pump_once()  # admit_begin; first chunks pending
        assert r_big in api.scheduler.prefilling
        resilience.inject_fault("serving_device", times=1)
        api.run_until_idle()
        assert r_small.state == RequestState.FINISHED
        assert r_big.state == RequestState.FINISHED
        np.testing.assert_array_equal(r_small.output_ids(),
                                      _ref(model, small, 20))
        np.testing.assert_array_equal(r_big.output_ids(),
                                      _ref(model, big, 6))
        a = api.engine.arena.stats()
        api.drain(grace=5)
        a = api.engine.arena.stats()
        assert a["blocks_in_use"] == 0 and a["blocks_reserved"] == 0
    finally:
        resilience.clear_faults()
        api.close()
        paddle.set_flags({"fault_injection": keep})


# ------------------------------------------------------------ observability


def test_spec_stats_and_predictor_summary(model, caplog):
    """Engine stats carry the spec.* keys; EnginePredictor.close() logs the
    speculation line next to the PR 6 prefix hit-rate line."""
    from paddle_tpu.serving import EnginePredictor

    pred = EnginePredictor(model, max_new_tokens=6,
                           config=ServingConfig(num_slots=2,
                                                kv_block_size=8,
                                                max_model_len=MAX_LEN,
                                                spec_k=3))
    rng = np.random.default_rng(15)
    ids = np.stack([_prompt(rng, 8), _prompt(rng, 8)])
    out = pred.run([ids])[0]
    ref = np.asarray(model.generate(Tensor(ids), max_new_tokens=6)._data)
    np.testing.assert_array_equal(out, ref)
    st = pred._api.engine.stats()
    assert st["spec.mode"] == "lockstep" and st["spec.k"] == 3
    assert st["spec.emitted"] == 10  # 2 rows x (6 - 1 prefill-emitted)
    import logging

    with caplog.at_level(logging.INFO, logger="paddle_tpu.serving"):
        pred.close()
    summary = [rec.getMessage() for rec in caplog.records
               if "EnginePredictor" in rec.getMessage()]
    assert summary and "speculation" in summary[-1]
    assert "lockstep k=3" in summary[-1]

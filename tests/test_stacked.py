"""StackedLayers: scan-over-layers == per-layer sequential, eager + jit."""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn.stacked import StackedLayers


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc = nn.Linear(d, d)

    def forward(self, x):
        return paddle.tanh(self.fc(x))


def test_stacked_matches_sequential_forward():
    paddle.seed(0)
    d, L = 8, 4
    blocks = [Block(d) for _ in range(L)]
    stacked = StackedLayers(lambda i: Block(d), L)
    # copy the per-layer weights into the stacked params
    sd = {}
    for j, name in enumerate(stacked._t_names):
        key = name.replace(".", "__")
        sd[key] = paddle.to_tensor(np.stack(
            [np.asarray(dict(b.named_parameters())[name]._data) for b in blocks]))
    stacked.set_state_dict(sd)

    x = paddle.to_tensor(np.random.rand(3, d).astype(np.float32))
    ref = x
    for b in blocks:
        ref = b(ref)
    out = stacked(x)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)


def test_stacked_eager_backward():
    paddle.seed(0)
    d, L = 4, 3
    stacked = StackedLayers(lambda i: Block(d), L)
    x = paddle.to_tensor(np.random.rand(5, d).astype(np.float32))
    loss = stacked(x).mean()
    loss.backward()
    for p in stacked.parameters():
        assert p.grad is not None
        assert np.isfinite(p.grad.numpy()).all()


def test_stacked_trains():
    paddle.seed(0)
    d, L = 6, 3
    stacked = StackedLayers(lambda i: Block(d), L)
    head = nn.Linear(d, 1)
    opt = paddle.optimizer.Adam(
        learning_rate=0.01, parameters=stacked.parameters() + head.parameters())
    X = np.random.rand(64, d).astype(np.float32)
    Y = (X.sum(1, keepdims=True) > d / 2).astype(np.float32)
    first = None
    for _ in range(60):
        loss = ((head(stacked(paddle.to_tensor(X))) - paddle.to_tensor(Y)) ** 2).mean()
        if first is None:
            first = float(loss.numpy())
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss.numpy()) < first * 0.5


def test_stacked_rejects_buffered_layers():
    with pytest.raises(ValueError):
        StackedLayers(lambda i: nn.BatchNorm1D(4), 2)


def test_stacked_remat_same_result():
    paddle.seed(0)
    d, L = 4, 3
    s1 = StackedLayers(lambda i: Block(d), L)
    s2 = StackedLayers(lambda i: Block(d), L, remat=True)
    s2.set_state_dict(s1.state_dict())
    x = paddle.to_tensor(np.random.rand(2, d).astype(np.float32))
    np.testing.assert_allclose(s1(x).numpy(), s2(x).numpy(), atol=1e-6)

"""Tier-1 gate + regression suite for the framework lint
(``paddle_tpu.analysis`` / ``tools/analyze.py``).

Three layers:

* **fixture corpus** (``tests/fixtures/analysis/``) — every rule must flag
  its known-bad fixture and stay silent on the known-good twin;
* **the gate** — the full suite over the live package must report zero
  non-baseline findings in under 10 seconds, with no stale baseline
  entries and a real one-line justification on every entry;
* **regressions** for the real findings this lint surfaced and fixed:
  the ``RoutedRequest._attach`` state race, the undeclared
  ``FLAGS_selected_devices``, the four dead flags, and the documented
  GIL-atomic bump pattern.
"""
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from paddle_tpu import analysis
from paddle_tpu.analysis.common import SourceFile, load_baseline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join("tests", "fixtures", "analysis")
BASELINE = os.path.join(REPO, "tools", "analysis_baseline.json")


def _fixture_corpus(*names, support=()):
    """Fixture files with relpaths faked into the analyzed tree (the
    corpus default excludes tests/), plus real support modules the
    registry analyzer resolves against."""
    corpus = []
    for name in names:
        path = os.path.join(REPO, FIXTURES, name + ".py")
        with open(path, "r", encoding="utf-8") as f:
            corpus.append(SourceFile(
                path, f"paddle_tpu/serving/_fixture_{name}.py", f.read()))
    for rel in support:
        with open(os.path.join(REPO, rel), "r", encoding="utf-8") as f:
            corpus.append(SourceFile(rel, rel, f.read()))
    return corpus


def _rules(corpus, full_corpus=False):
    report = analysis.run_analysis(corpus=corpus, root=REPO,
                                   full_corpus=full_corpus)
    return [f.rule for f in report.findings]


# ----------------------------------------------------------- fixture corpus

FIXTURE_CASES = [
    ("unguarded-mutation", "concurrency_unguarded", ()),
    ("lock-order-cycle", "concurrency_lock_order", ()),
    ("blocking-call-in-lock", "concurrency_blocking", ()),
    ("traced-branch", "compiled_traced_branch", ()),
    ("traced-cast", "compiled_traced_cast", ()),
    ("mutable-global-capture", "compiled_mutable_global", ()),
    ("shape-from-data", "compiled_shape_from_data", ()),
    ("use-after-donate", "compiled_donation", ()),
    # the PR 10 speculative verify-k shape: donated-pool rollback and
    # traced acceptance branching (serving/spec_decode.py's two hazards)
    ("use-after-donate", "compiled_spec_verify", ()),
    ("traced-branch", "compiled_spec_verify", ()),
    # the quantized-serving dequant shape: host-cast scale and
    # data-dependent quantization support (quantization.quantize_kv /
    # engine._scatter_rows must stay all-array math)
    ("traced-cast", "compiled_quant", ()),
    ("shape-from-data", "compiled_quant", ()),
    # the ISSUE 12 per-slot sampling shape: traced branch on a per-slot
    # top-k and data-dependent constraint-mask indexing
    # (serving.sampling.sample_tokens must stay all-array math)
    ("traced-branch", "compiled_sampling", ()),
    ("shape-from-data", "compiled_sampling", ()),
    # the ISSUE 13 paged-kernel dispatch shape: data-dependent workload
    # from a block table's contents and a traced branch on the filled
    # block count (ops.paged_attention / engine views must key on the
    # table's static shape only)
    ("shape-from-data", "compiled_paged", ()),
    ("traced-branch", "compiled_paged", ()),
    # the ISSUE 14 mesh shape: a Python branch on a per-device traced
    # value (lax.axis_index — the mesh-aware tracedness extension) and a
    # mesh-committed pool donated into the sharded step then read again
    # (the donation rule over NamedSharding-placed buffers)
    ("traced-branch", "compiled_mesh", ()),
    ("use-after-donate", "compiled_mesh", ()),
    # the ISSUE 15 tiered-restore shape: a traced branch on tier
    # residency and a host np.asarray of the donated pool inside the
    # restore program (engine._get_restore must keep residency host-side
    # and the scatter all-array)
    ("traced-branch", "compiled_tiered", ()),
    ("traced-cast", "compiled_tiered", ()),
    # the ISSUE 16 SPMD-kernel shape: the model-axis degree recovered as
    # a traced per-device value (lax.psum of 1), host-cast into a
    # per-shard head count and Python-branched on (headwise_shard_map
    # must read the STATIC mesh shape / local q.shape instead)
    ("traced-cast", "compiled_spmd_kernel", ()),
    ("traced-branch", "compiled_spmd_kernel", ()),
    ("undefined-flag", "registry_flags",
     ("paddle_tpu/core/flags.py",)),
    ("unknown-metric-key", "registry_metrics",
     ("paddle_tpu/serving/metrics.py",
      "paddle_tpu/serving/telemetry.py")),
    # the ISSUE 17 observability shape: telemetry from INSIDE a compiled
    # region — a trace-time-baked clock read smuggled out through a
    # float() cast of a traced value (timestamps + histogram records
    # belong AROUND the dispatch; docs/observability.md overhead policy)
    ("traced-cast", "compiled_telemetry",
     ("paddle_tpu/serving/telemetry.py",)),
    # the ISSUE 18 process-worker shapes: (a) poll-RPC serialization from
    # inside the compiled decode step — the token tail int()-cast under
    # trace instead of materialized around the dispatch; (b) the
    # WorkerHandle pending-RPC table registered under the handle lock but
    # popped lock-free in the reader loop (a strand-the-caller race)
    ("traced-cast", "compiled_worker", ()),
    ("unguarded-mutation", "concurrency_worker", ()),
    # the ISSUE 19 disagg shapes: (a) restore-ahead prefetch deciding
    # published-chain residency INSIDE the compiled restore — a traced
    # branch on the residency mask plus a host int() of the traced chain
    # length (the planner's radix walk is host-side; the restore must
    # stay the one shared scatter); (b) the handoff claim-and-flip done
    # lock-free while the pump/watchdog movers race on the same FINISH
    ("traced-branch", "compiled_disagg", ()),
    ("traced-cast", "compiled_disagg", ()),
    ("unguarded-mutation", "concurrency_disagg", ()),
    # the ISSUE 20 crash-safe-gateway shapes: (a) WAL record serialization
    # from inside the compiled decode step — the token delta int()-cast
    # under trace instead of materialized once per commit batch around
    # the dispatch; (b) the per-stream journal high-water mark advanced
    # lock-free while the finalizer's terminal sweep reads it under the
    # stream lock (a journal-the-same-token-twice race)
    ("traced-cast", "compiled_wal", ()),
    ("unguarded-mutation", "concurrency_wal", ()),
    ("broad-except", "hygiene_broad_except", ()),
]


@pytest.mark.parametrize("rule,stem,support",
                         FIXTURE_CASES, ids=[c[0] for c in FIXTURE_CASES])
def test_rule_flags_bad_fixture(rule, stem, support):
    rules = _rules(_fixture_corpus(stem + "_bad", support=support))
    assert rule in rules, f"{rule} missed its known-bad fixture: {rules}"


@pytest.mark.parametrize("rule,stem,support",
                         FIXTURE_CASES, ids=[c[0] for c in FIXTURE_CASES])
def test_rule_passes_good_fixture(rule, stem, support):
    rules = _rules(_fixture_corpus(stem + "_good", support=support))
    assert rule not in rules, \
        f"{rule} false-positived on its known-good twin"


def test_bad_fixtures_are_specific():
    """A bad fixture must trip (at least) its own rule, not collateral
    noise from unrelated analyzers — one seeded defect class per file."""
    for rule, stem, support in FIXTURE_CASES:
        rules = set(_rules(_fixture_corpus(stem + "_bad", support=support)))
        allowed = {rule}
        if stem.startswith("compiled_traced"):
            # casts and branches legitimately co-occur in trace hazards
            allowed |= {"traced-branch", "traced-cast"}
        if stem == "compiled_spec_verify":
            # this fixture deliberately seeds BOTH spec-decode hazards:
            # donated-pool rollback + traced acceptance branching
            allowed |= {"use-after-donate", "traced-branch"}
        if stem == "compiled_quant":
            # deliberately seeds BOTH dequant hazards: host-cast scale +
            # data-dependent support
            allowed |= {"traced-cast", "shape-from-data"}
        if stem == "compiled_sampling":
            # deliberately seeds BOTH sampling hazards: traced top-k
            # branch + data-dependent mask shape
            allowed |= {"traced-branch", "shape-from-data"}
        if stem == "compiled_paged":
            # deliberately seeds BOTH paged-dispatch hazards: table-
            # content shape + traced block-count branch (the int() cast
            # feeding it legitimately co-fires traced-cast)
            allowed |= {"shape-from-data", "traced-branch", "traced-cast"}
        if stem == "compiled_mesh":
            # deliberately seeds BOTH mesh hazards: per-device traced
            # branch + donated sharded pool read-back
            allowed |= {"traced-branch", "use-after-donate"}
        if stem == "compiled_tiered":
            # deliberately seeds BOTH restore hazards: traced residency
            # branch + host np.asarray of the donated pool
            allowed |= {"traced-branch", "traced-cast"}
        if stem == "compiled_spmd_kernel":
            # deliberately seeds BOTH SPMD-kernel hazards: host-cast of
            # the traced axis degree + the head-count branch it feeds
            allowed |= {"traced-cast", "traced-branch"}
        if stem == "compiled_disagg":
            # deliberately seeds BOTH prefetch-restore hazards: traced
            # residency branch + host int() of the traced chain length
            allowed |= {"traced-branch", "traced-cast"}
        assert rules <= allowed, (stem, rules)


def test_dead_flag_detection_synthetic():
    """dead-flag needs a full corpus view; prove it on a synthetic
    registry: one flag read by a user module, one zombie."""
    flags_src = (
        "def define_flag(name, default, doc=''):\n    pass\n"
        "define_flag('live_flag', 1, 'read below')\n"
        "define_flag('zombie_flag', 1, 'read by nothing')\n")
    user_src = ("from paddle_tpu.core import flags\n"
                "x = flags.flag('live_flag')\n")
    corpus = [
        SourceFile("<mem>", "paddle_tpu/core/flags.py", flags_src),
        SourceFile("<mem>", "paddle_tpu/user.py", user_src),
    ]
    report = analysis.run_analysis(corpus=corpus, root=REPO,
                                   full_corpus=True)
    dead = [f for f in report.findings if f.rule == "dead-flag"]
    assert len(dead) == 1 and "zombie_flag" in dead[0].message


def test_suppression_requires_reason():
    src = ("def f(x):\n"
           "    try:\n"
           "        return x()\n"
           "    except Exception:  # analysis: allow(broad-except)\n"
           "        return None\n")
    corpus = [SourceFile("<mem>", "paddle_tpu/serving/_r.py", src)]
    report = analysis.run_analysis(corpus=corpus, root=REPO,
                                   full_corpus=False)
    rules = [f.rule for f in report.findings]
    assert "suppression-missing-reason" in rules
    assert "broad-except" not in rules  # suppressed, but flagged as bare


# ------------------------------------------------------------------ the gate

@pytest.fixture(scope="module")
def gate_report():
    return analysis.run_analysis(root=REPO)


def test_gate_zero_nonbaseline_findings(gate_report):
    baseline = load_baseline(BASELINE)
    new, stale = gate_report.apply_baseline(baseline)
    assert not new, "non-baseline findings:\n" + "\n".join(
        str(f) for f in new)
    assert not stale, (
        "stale baseline entries (match nothing — remove them):\n"
        + "\n".join(f"[{e.rule}] {e.path} :: {e.scope}" for e in stale))


def test_gate_no_parse_errors(gate_report):
    assert not gate_report.parse_errors


def test_gate_fast_enough(gate_report):
    # the whole point of a tier-1 gate: the full suite stays cheap
    assert gate_report.elapsed < 10.0, gate_report.elapsed


def test_baseline_entries_all_justified():
    with open(BASELINE, "r", encoding="utf-8") as f:
        data = json.load(f)
    assert data.get("entries"), "baseline should exist (may be empty list)"
    for e in data["entries"]:
        why = e.get("why", "")
        assert why and "TODO" not in why, (
            f"baseline entry [{e['rule']}] {e['path']} :: {e['scope']} "
            f"has no real justification")


def test_inline_suppressions_all_carry_reasons(gate_report):
    # every suppression that fired carried a reason (the ones that did
    # not would have surfaced as suppression-missing-reason findings)
    assert all(f.rule != "suppression-missing-reason"
               for f in gate_report.findings)
    assert gate_report.suppressed, "expected inline allow()s in the tree"


def test_cli_gate_subprocess():
    """tools/analyze.py runs standalone (no jax import) and exits 0."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "analyze.py")],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 finding(s)" in out.stdout


def test_cli_update_baseline_refuses_subset_runs():
    """Rewriting the baseline from a subset view would silently delete
    every entry for files outside the scanned corpus (with their
    hand-written justifications) — the CLI must refuse."""
    before = open(BASELINE, "rb").read()
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "analyze.py"),
         "paddle_tpu/serving", "--update-baseline"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "requires a full run" in out.stderr
    assert open(BASELINE, "rb").read() == before


def test_cli_rule_filter_and_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "analyze.py"),
         "--rules", "undefined-flag", "--json", "paddle_tpu/core",
         "paddle_tpu/distributed"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] == []


# ---------------------------------------------- regressions (real findings)

def test_flags_selected_devices_resolves():
    """Real finding: FLAGS_selected_devices was referenced by the
    launcher/ParallelEnv with no define_flag declaration."""
    from paddle_tpu.core import flags
    assert flags.get_flags("FLAGS_selected_devices") is not None
    assert "selected_devices" in flags.all_flags()


def test_dead_flags_deleted():
    """Real finding: four flags nothing read. They must stay gone (the
    dead-flag rule keeps them from coming back silently)."""
    from paddle_tpu.core import flags
    for name in ("benchmark", "tracer_mkldnn_ops_on",
                 "allocator_strategy", "use_stream_safe_allocator"):
        with pytest.raises(KeyError):
            flags.get_flags(name)


def test_registry_lint_proves_all_flags_resolve(gate_report):
    assert not any(f.rule in ("undefined-flag", "dead-flag")
                   for f in gate_report.findings)


def test_attach_never_resurrects_finalized_request():
    """Real finding (unguarded-mutation): RoutedRequest._attach mutated
    ``state`` outside the lock — a _finalize racing between its check and
    its set was overwritten back to RUNNING. The transition now happens
    under the lock; a finalized handle must stay terminal through a late
    _attach (the exact submit-vs-cancel interleaving of the race)."""
    from paddle_tpu.serving.gateway.router import RoutedRequest
    from paddle_tpu.serving.scheduler import Request, RequestState
    from paddle_tpu.core import resilience

    class _Rep:
        idx, generation = 0, 0

    rr = RoutedRequest(pool=None, prompt=np.array([1, 2], np.int32),
                       max_new_tokens=4, stop_token_id=None,
                       tenant="t", priority=0,
                       deadline=resilience.Deadline.after(None),
                       request_id="race")
    backend = Request(np.array([1, 2], np.int32))
    rr._finalize(RequestState.CANCELLED)
    rr._attach(backend, _Rep(), 0)
    assert rr.state == RequestState.CANCELLED
    assert rr.finished and rr.done_event.is_set()


def test_concurrency_lint_clean_on_router_and_metrics(gate_report):
    """Regression for the fixed/triaged unguarded-mutation findings: the
    router and the metrics modules stay clean (reintroducing the _attach
    pattern or an unannotated helper mutation fails here)."""
    assert not any(
        f.rule == "unguarded-mutation"
        and ("serving/gateway" in f.path or "serving/metrics" in f.path)
        for f in gate_report.findings)


def test_gil_atomic_bump_is_allowed_pattern():
    """The documented GIL-atomic single-key bump (metrics.bump /
    resilience.bump / compile_cache.bump) is an allowed pattern, not a
    finding — asserted against the real modules."""
    report = analysis.run_analysis(
        ["paddle_tpu/serving/metrics.py", "paddle_tpu/core/resilience.py",
         "paddle_tpu/core/compile_cache.py"],
        root=REPO, full_corpus=False)
    assert not any(f.rule == "unguarded-mutation"
                   for f in report.findings), report.findings


def test_documented_namespaces_cover_runtime_keys():
    """The namespace registries match what the modules actually emit."""
    from paddle_tpu.serving import metrics
    from paddle_tpu.core import resilience
    metrics.bump("requests.finished", 0)
    for key in metrics.stats():
        ns = key.split(".", 1)[0]
        assert ns in metrics.DOCUMENTED_NAMESPACES, key
    resilience.bump("retry.retries", 0)
    for key in resilience.stats():
        ns = key.split(".", 1)[0]
        assert ns in resilience.DOCUMENTED_NAMESPACES, key

"""Property fuzz: random op chains executed EAGERLY must equal the same
chain captured into a static Program and replayed by the Executor — the
capture-the-eager-dispatch design's core invariant, probed across randomly
composed graphs rather than hand-picked ones."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static

OPS = ["add", "mul", "matmul", "relu", "tanh", "mean_keep", "transpose",
       "scale"]


def _apply_op(op, x, aux):
    import paddle_tpu.nn.functional as F

    if op == "add":
        return x + aux
    if op == "mul":
        return x * 0.5 + x * aux * 0.1
    if op == "matmul":
        return paddle.matmul(x, paddle.transpose(x, [1, 0]))
    if op == "relu":
        return F.relu(x - 0.2)
    if op == "tanh":
        return paddle.tanh(x)
    if op == "mean_keep":
        return x - x.mean(axis=-1, keepdim=True)
    if op == "transpose":
        # NOTE: no shape-dependent python branching here — under capture,
        # dim 0 is symbolic (None) and a `shape[0] != shape[1]` branch
        # would diverge from eager. (That is the documented static
        # contract, not a bug: data/shape-dependent control flow belongs
        # in static.nn.cond.)
        return paddle.transpose(x, [1, 0])
    if op == "scale":
        return paddle.scale(x, scale=1.3, bias=-0.05)
    raise AssertionError(op)


def _run_chain(ops, x, aux):
    for op in ops:
        x = _apply_op(op, x, aux)
    return x


@pytest.mark.parametrize("seed", range(8))
def test_random_chain_eager_equals_captured(seed):
    rng = np.random.RandomState(seed)
    n = 4  # square keeps every op shape-stable
    ops = [OPS[i] for i in rng.randint(0, len(OPS), size=6)]
    x_np = rng.randn(n, n).astype(np.float32)
    aux_np = rng.randn(n, n).astype(np.float32)

    eager = _run_chain(ops, paddle.to_tensor(x_np),
                       paddle.to_tensor(aux_np)).numpy()

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        xv = static.data("x", [None, n], "float32")
        av = static.data("aux", [None, n], "float32")
        out = _run_chain(ops, xv, av)
    exe = static.Executor()
    exe.run(startup)
    (got,) = exe.run(main, feed={"x": x_np, "aux": aux_np},
                     fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got), eager, rtol=1e-5,
                               atol=1e-5, err_msg=f"ops={ops}")


@pytest.mark.parametrize("seed", range(8, 12))
def test_random_chain_eager_equals_to_static(seed):
    """Same property through the jit path: to_static(chain) == eager."""
    rng = np.random.RandomState(seed)
    n = 4
    ops = [OPS[i] for i in rng.randint(0, len(OPS), size=6)]
    x_np = rng.randn(n, n).astype(np.float32)
    aux_np = rng.randn(n, n).astype(np.float32)

    eager = _run_chain(ops, paddle.to_tensor(x_np),
                       paddle.to_tensor(aux_np)).numpy()

    @paddle.jit.to_static
    def fn(x, aux):
        return _run_chain(ops, x, aux)

    got = fn(paddle.to_tensor(x_np), paddle.to_tensor(aux_np)).numpy()
    np.testing.assert_allclose(got, eager, rtol=1e-5, atol=1e-5,
                               err_msg=f"ops={ops}")


@pytest.mark.parametrize("seed", range(12, 15))
def test_random_chain_gradients_eager_equals_to_static(seed):
    """And the BACKWARD of random chains: compiled grads == tape grads."""
    rng = np.random.RandomState(seed)
    n = 4
    ops = [OPS[i] for i in rng.randint(0, len(OPS), size=5)]
    x_np = rng.randn(n, n).astype(np.float32)
    aux_np = rng.randn(n, n).astype(np.float32)

    xe = paddle.to_tensor(x_np)
    xe.stop_gradient = False
    _run_chain(ops, xe, paddle.to_tensor(aux_np)).sum().backward()
    eager_grad = np.asarray(xe.grad._data)

    import jax

    def loss(xa):
        out = _run_chain(ops, paddle.to_tensor(xa),
                         paddle.to_tensor(aux_np))
        return out._data.sum()

    # same chain under jax.grad via the traced path
    from paddle_tpu.jit import to_static

    @to_static
    def fwd(x, aux):
        return _run_chain(ops, x, aux).sum()

    xs = paddle.to_tensor(x_np)
    xs.stop_gradient = False
    fwd(xs, paddle.to_tensor(aux_np)).backward()
    np.testing.assert_allclose(np.asarray(xs.grad._data), eager_grad,
                               rtol=1e-5, atol=1e-5, err_msg=f"ops={ops}")


def test_to_static_layer_trains_like_reference_pattern():
    """The reference's canonical dy2static flow: decorate the LAYER with
    @to_static, then train with eager loss.backward() + opt.step(). The
    compiled forward must join the tape so parameter grads flow."""
    from paddle_tpu import nn

    paddle.seed(0)
    net = paddle.jit.to_static(
        nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1)))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    rng = np.random.RandomState(0)
    X = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
    Y = paddle.to_tensor(rng.rand(16, 1).astype(np.float32))
    first = last = None
    for _ in range(25):
        loss = ((net(X) - Y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss.numpy())
        last = float(loss.numpy())
    assert last < first * 0.3, (first, last)


def test_to_static_inference_stays_fast_path_under_no_grad():
    """Inference under no_grad keeps the detached fast path: no tape node
    is attached to the output (nothing retained for a backward that can
    never come)."""
    from paddle_tpu import nn

    net = paddle.jit.to_static(nn.Linear(4, 2))
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    with paddle.no_grad():
        out = net(x)
    assert out._node is None


def test_to_static_bn_buffers_update_through_taped_path():
    """Buffer mutations (BN running stats) must survive the taped
    training path exactly as they do on the fast path."""
    from paddle_tpu import nn

    paddle.seed(0)
    net = paddle.jit.to_static(nn.Sequential(nn.Linear(4, 6),
                                             nn.BatchNorm1D(6)))
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.rand(8, 4).astype(np.float32) + 2.0)
    before = np.asarray(net[1]._mean._data).copy()
    loss = net(x).sum()
    loss.backward()  # taped path (params live)
    after = np.asarray(net[1]._mean._data)
    assert not np.allclose(before, after), "running mean did not update"
    assert net[0].weight.grad is not None


def test_to_static_dict_output_trains():
    """Arbitrary output pytrees (dicts) must round-trip identically on the
    taped training path."""
    from paddle_tpu import nn

    paddle.seed(1)
    lin = nn.Linear(4, 2)

    @paddle.jit.to_static
    def fwd(x):
        h = lin(x)
        return {"logits": h, "sum": h.sum(), "tag": 7}

    x = paddle.to_tensor(np.ones((3, 4), np.float32))
    out = fwd(x)
    assert set(out) == {"logits", "sum", "tag"} and out["tag"] == 7
    out["sum"].backward()
    assert lin.weight.grad is not None


def test_to_static_unhashable_static_leaf_falls_back_to_eager():
    """A non-hashable STATIC leaf (e.g. a config object) must not leak a
    retrace per call — the eager tape handles it (correct, uncompiled)."""
    from paddle_tpu import nn
    from paddle_tpu.core import dispatch

    lin = nn.Linear(4, 2)

    class Cfg:  # deliberately unhashable config object
        __hash__ = None
        scale = 2.0

    @paddle.jit.to_static
    def fwd(x, cfg):
        return lin(x) * cfg.scale

    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    fwd(x, Cfg())
    before = len(dispatch._JIT_CACHE)
    for _ in range(4):
        out = fwd(x, Cfg())
    # per-op entries may exist from the eager ops, but no per-call growth
    grown = len(dispatch._JIT_CACHE) - before
    assert grown == 0, grown
    out.sum().backward()
    assert lin.weight.grad is not None


def test_to_static_global_model_weights_stay_live(tmp_path):
    """A module/global-scope model referenced by a free @to_static function
    must NOT bake its weights into the compiled program: updates made
    outside (optimizer steps, manual assignment, ckpt restore) must be
    visible to the next call."""
    import textwrap
    import subprocess
    import sys
    import os

    script = textwrap.dedent("""
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        import paddle_tpu as paddle
        from paddle_tpu import nn

        m = nn.Linear(4, 1)       # module scope -> reached via __globals__
        @paddle.jit.to_static
        def infer(x):
            return m(x)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        with paddle.no_grad():
            before = infer(x).numpy().copy()
        m.weight._data = m.weight._data * 2.0
        with paddle.no_grad():
            after = infer(x).numpy()
        assert not np.allclose(before, after), "stale baked weights"
        print("LIVE-OK")
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0 and "LIVE-OK" in r.stdout, r.stderr[-500:]


def test_to_static_float_arg_does_not_retrace_per_value():
    """A per-step python float (lr, temperature) rides as a TRACED arg:
    distinct values must NOT mint new executables."""
    from paddle_tpu import nn
    from paddle_tpu.core import dispatch

    lin = nn.Linear(4, 2)

    @paddle.jit.to_static
    def fwd(x, scale):
        return lin(x) * scale

    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    x.stop_gradient = False
    out0 = fwd(x, 1.0)
    before = len(dispatch._JIT_CACHE)
    vals = [fwd(x, s).sum().numpy() for s in (2.0, 3.0, 4.5)]
    assert len(dispatch._JIT_CACHE) == before, "per-value retrace"
    np.testing.assert_allclose(
        np.asarray(vals) / float(out0.sum().numpy()), [2.0, 3.0, 4.5],
        rtol=1e-5)


def test_pylayer_custom_vjp_inside_to_static():
    """PyLayer custom backward composes with the taped compiled call: the
    custom 2x vjp must scale the input gradient exactly."""
    from paddle_tpu import nn
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2.0

        @staticmethod
        def backward(ctx, dy):
            return dy * 2.0

    paddle.seed(2)
    lin = nn.Linear(4, 2)

    @paddle.jit.to_static
    def with_pylayer(x):
        return Double.apply(lin(x)).sum()

    @paddle.jit.to_static
    def plain(x):
        return lin(x).sum()

    x1 = paddle.to_tensor(np.ones((2, 4), np.float32))
    x1.stop_gradient = False
    with_pylayer(x1).backward()
    x2 = paddle.to_tensor(np.ones((2, 4), np.float32))
    x2.stop_gradient = False
    plain(x2).backward()
    np.testing.assert_allclose(np.asarray(x1.grad._data),
                               2 * np.asarray(x2.grad._data), rtol=1e-6)


def test_nested_to_static_grads_flow():
    """A @to_static function calling another @to_static function: the
    inner executes traced inside the outer's program; grads flow."""
    from paddle_tpu import nn

    paddle.seed(3)
    lin = nn.Linear(4, 2)

    @paddle.jit.to_static
    def inner(x):
        return lin(x)

    @paddle.jit.to_static
    def outer(x):
        return inner(x).sum()

    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    x.stop_gradient = False
    outer(x).backward()
    assert x.grad is not None and lin.weight.grad is not None


def test_recursive_to_static_does_not_hang_discovery():
    """A @to_static function that REFERENCES itself (LOAD_GLOBAL of its own
    name) must not infinitely recurse in state discovery — the hazard is at
    build time, whether or not the recursive branch ever executes."""
    global _self_ref_fn

    @paddle.jit.to_static
    def _self_ref_fn(x, depth=0):
        if depth > 0:  # static python flag: branch never taken at trace
            return _self_ref_fn(x)
        return x * 2.0

    out = _self_ref_fn(paddle.to_tensor(np.ones(3, np.float32)))
    np.testing.assert_allclose(out.numpy(), 2.0)


def test_nested_to_static_bn_stats_reach_outer():
    """BN running stats mutated by an INNER @to_static must survive the
    outer program's state restore (the ambient-sink forwarding path)."""
    from paddle_tpu import nn

    paddle.seed(4)
    bn = nn.BatchNorm1D(3, momentum=0.5)

    @paddle.jit.to_static
    def inner(x):
        return bn(x)

    @paddle.jit.to_static
    def outer(x):
        return inner(x).sum()

    x = paddle.to_tensor(
        np.random.RandomState(0).rand(8, 3).astype(np.float32) + 2.0)
    x.stop_gradient = False
    before = np.asarray(bn._mean._data).copy()
    outer(x).backward()
    after = np.asarray(bn._mean._data)
    assert not np.allclose(before, after), \
        "inner BN stats silently dropped by the outer restore"
    assert np.isfinite(after).all()


def test_to_static_inside_trainstep_loss():
    """A @to_static function used INSIDE a TrainStep loss: the inner
    executes traced within the outer compiled program and training
    converges (the PRNG-key arg must not trip differentiability checks)."""
    from paddle_tpu import nn
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    net = nn.Linear(4, 1)

    @paddle.jit.to_static
    def fwd(x):
        return net(x)

    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    step = TrainStep(lambda a, b: ((fwd(a) - b) ** 2).mean(), opt,
                     layers=net)
    X = paddle.to_tensor(np.random.RandomState(0).rand(8, 4)
                         .astype(np.float32))
    Y = paddle.to_tensor(np.random.RandomState(1).rand(8, 1)
                         .astype(np.float32))
    ls = [float(step(X, Y).numpy()) for _ in range(10)]
    assert ls[-1] < ls[0]


def test_double_grad_through_to_static():
    """create_graph double-grad composes with the taped compiled call:
    exact d/dx and d2/dx2 of x^3."""
    @paddle.jit.to_static
    def g(x):
        return (x ** 3).sum()

    x = paddle.to_tensor(np.array([2.0], np.float32))
    x.stop_gradient = False
    (dx,) = paddle.grad(g(x), [x], create_graph=True)
    (d2x,) = paddle.grad(dx.sum(), [x])
    np.testing.assert_allclose(float(dx.numpy()[0]), 12.0, rtol=1e-5)
    np.testing.assert_allclose(float(d2x.numpy()[0]), 12.0, rtol=1e-5)


def test_to_static_under_autocast_with_gradscaler():
    """AMP interplay: @to_static forward under auto_cast + GradScaler
    training. The autocast policy is SNAPSHOTTED into the taped call —
    backward re-executes after the context exits and must see the same
    casts (a policy change would make jax.vjp reject the ct dtype)."""
    from paddle_tpu import amp, nn

    paddle.seed(0)
    net = nn.Linear(4, 1)

    @paddle.jit.to_static
    def fwd(x):
        return net(x)

    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    scaler = amp.GradScaler(init_loss_scaling=1024.0)
    X = paddle.to_tensor(np.random.RandomState(0).rand(8, 4)
                         .astype(np.float32))
    Y = paddle.to_tensor(np.random.RandomState(1).rand(8, 1)
                         .astype(np.float32))
    first = last = None
    for _ in range(15):
        with amp.auto_cast(level="O1", dtype="bfloat16"):
            loss = ((fwd(X) - Y) ** 2).mean()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        first = first if first is not None else float(loss.numpy())
        last = float(loss.numpy())
    assert last < first, (first, last)


def test_to_static_inference_respects_policy_changes():
    """The no-grad fast path compiles PER autocast policy: a function first
    traced under bf16 autocast must NOT reuse that executable for a later
    call without autocast (and vice versa)."""
    from paddle_tpu import amp, nn

    paddle.seed(5)
    net = nn.Linear(4, 2)

    @paddle.jit.to_static
    def fwd(x):
        return net(x)

    x = paddle.to_tensor(np.random.RandomState(0).rand(2, 4)
                         .astype(np.float32))
    with paddle.no_grad():
        with amp.auto_cast(level="O1", dtype="bfloat16"):
            out_amp = fwd(x)
        out_plain = fwd(x)
    assert "bfloat16" in str(out_amp._data.dtype)
    assert "float32" in str(out_plain._data.dtype), \
        "bf16 executable reused outside autocast"
    # and back again: the per-policy cache serves the right one
    with paddle.no_grad():
        with amp.auto_cast(level="O1", dtype="bfloat16"):
            assert "bfloat16" in str(fwd(x)._data.dtype)

"""static.nn layer builders (ref:python/paddle/static/nn/__init__.py) over
the capture Program."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.static import nn as snn


def _run(main, feed, fetch):
    return static.Executor().run(main, feed=feed, fetch_list=fetch)


def test_fc_capture_and_run():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 6], "float32")
        y = snn.fc(x, 4, activation="relu")
    (out,) = _run(main, {"x": np.ones((2, 6), np.float32)}, [y])
    assert out.shape == (2, 4) and (out >= 0).all()


def test_fc_num_flatten_dims():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3, 4], "float32")
        y = snn.fc(x, 5, num_flatten_dims=1)
    (out,) = _run(main, {"x": np.ones((2, 3, 4), np.float32)}, [y])
    assert out.shape == (2, 5)


def test_named_fc_shares_parameters():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        a = snn.fc(x, 4, name="shared_fc")
        b = snn.fc(x, 4, name="shared_fc")
    oa, ob = _run(main, {"x": np.ones((1, 4), np.float32)}, [a, b])
    np.testing.assert_array_equal(oa, ob)


def test_embedding_and_conv():
    main = static.Program()
    with static.program_guard(main):
        ids = static.data("ids", [None, 3], "int64")
        emb = snn.embedding(ids, size=[10, 8])
        img = static.data("img", [None, 3, 8, 8], "float32")
        c = snn.conv2d(img, num_filters=4, filter_size=3, padding=1,
                       act="relu")
    e, co = _run(main, {"ids": np.zeros((2, 3), np.int64),
                        "img": np.ones((2, 3, 8, 8), np.float32)}, [emb, c])
    assert e.shape == (2, 3, 8) and co.shape == (2, 4, 8, 8)


def test_batch_norm_updates_running_stats_through_tape():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3, 4, 4], "float32")
        y = snn.batch_norm(x, momentum=0.5, name="bn0")
        bn = snn.get_layer("bn0")
    mean0 = np.asarray(bn._mean._data).copy()
    arr = np.random.RandomState(0).standard_normal((8, 3, 4, 4)).astype(np.float32) + 5.0
    _run(main, {"x": arr}, [y])
    mean1 = np.asarray(bn._mean._data)
    assert not np.allclose(mean0, mean1)  # running mean moved toward ~5
    assert (mean1 > 1.0).all()

    # stats must ACCUMULATE run over run (live-buffer read, not a snapshot)
    _run(main, {"x": arr}, [y])
    mean2 = np.asarray(bn._mean._data)
    assert (np.abs(mean2 - arr.mean(axis=(0, 2, 3)))
            < np.abs(mean1 - arr.mean(axis=(0, 2, 3)))).all()

    # eval clone: no stat updates
    test_prog = main.clone(for_test=True)
    _run(test_prog, {"x": arr}, [y])
    np.testing.assert_array_equal(np.asarray(bn._mean._data), mean2)


def test_batch_norm_nhwc_axes():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4, 4, 3], "float32")
        y = snn.batch_norm(x, data_layout="NHWC", name="bn_nhwc")
        bn = snn.get_layer("bn_nhwc")
    arr = np.random.RandomState(1).standard_normal((8, 4, 4, 3)).astype(np.float32)
    (out,) = _run(main, {"x": arr}, [y])
    assert out.shape == arr.shape
    assert np.asarray(bn._mean._data).shape == (3,)  # channel-shaped stats


def test_named_layers_scoped_per_program():
    pa, pb = static.Program(), static.Program()
    with static.program_guard(pa):
        xa = static.data("x", [None, 4], "float32")
        snn.fc(xa, 4, name="proj")
        la = snn.get_layer("proj")
    with static.program_guard(pb):
        xb = static.data("x", [None, 6], "float32")
        snn.fc(xb, 8, name="proj")  # same name, different shape: NEW layer
        lb = snn.get_layer("proj")
    assert la is not lb
    assert la.weight.shape == [4, 4] and lb.weight.shape == [6, 8]


def test_dropped_program_is_garbage_collected():
    import gc
    import weakref

    def build():
        p = static.Program()
        with static.program_guard(p):
            x = static.data("x", [2], "float32")
            _ = x * 2.0
        return weakref.ref(p)

    ref = build()
    gc.collect()
    assert ref() is None  # the owner registry must not pin it


def test_shared_batch_norm_updates_fold_sequentially():
    """A name-shared batch_norm applied twice in one program must fold BOTH
    stat contributions (chained through the pending update), not last-wins."""
    main = static.Program()
    with static.program_guard(main):
        a = static.data("a", [None, 2, 4, 4], "float32")
        b = static.data("b", [None, 2, 4, 4], "float32")
        ya = snn.batch_norm(a, momentum=0.5, name="sbn")
        yb = snn.batch_norm(b, momentum=0.5, name="sbn")
        bn = snn.get_layer("sbn")
    arr_a = np.full((4, 2, 4, 4), 2.0, np.float32)
    arr_b = np.full((4, 2, 4, 4), 10.0, np.float32)
    _run(main, {"a": arr_a, "b": arr_b}, [ya, yb])
    # start 0 -> after a: 0.5*2 = 1 -> after b: 1 + 0.5*(10-1) = 5.5
    np.testing.assert_allclose(np.asarray(bn._mean._data), 5.5, rtol=1e-5)


def test_conv_nhwc_and_transpose_output_size():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 8, 8, 3], "float32")   # NHWC
        c = snn.conv2d(x, num_filters=5, filter_size=3, padding=1,
                       data_format="NHWC")
        z = static.data("z", [None, 2, 4, 4], "float32")
        # k3 s2 p1 on 4 -> base 7; output_size=8 selects output_padding=1
        t = snn.conv2d_transpose(z, num_filters=3, filter_size=3, stride=2,
                                 padding=1, output_size=[8, 8])
    co, to = _run(main, {"x": np.ones((2, 8, 8, 3), np.float32),
                         "z": np.ones((2, 2, 4, 4), np.float32)}, [c, t])
    assert co.shape == (2, 8, 8, 5)
    assert to.shape == (2, 3, 8, 8)


def test_sparse_embedding_routes_to_registered_ps_table():
    native = pytest.importorskip("paddle_tpu.native")
    try:
        native.load()
    except Exception:
        pytest.skip("native lib unavailable")
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.ps import EmbeddingServer, SparseTableClient

    srv = EmbeddingServer(dim=8, rule="sgd")
    client = SparseTableClient([f"127.0.0.1:{srv.port}"], dim=8)
    fleet.register_sparse_table(0, client)
    try:
        ids = paddle.to_tensor(np.array([[1, 2]], np.int64))
        out = snn.sparse_embedding(ids, size=[1 << 40, 8], slot=0)
        assert list(out.shape) == [1, 2, 8]
    finally:
        fleet._registered_tables.clear()
        srv.stop()


def test_layer_group_instance_prelu():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4, 4, 4], "float32")
        a = snn.layer_norm(x)
        g = snn.group_norm(x, groups=2)
        i = snn.instance_norm(x)
        p = snn.prelu(x, mode="channel")
    outs = _run(main, {"x": np.random.RandomState(1).standard_normal(
        (2, 4, 4, 4)).astype(np.float32)}, [a, g, i, p])
    for o in outs:
        assert o.shape == (2, 4, 4, 4)


def test_bilinear_and_cvm():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3], "float32")
        y = static.data("y", [None, 5], "float32")
        b = snn.bilinear_tensor_product(x, y, size=7)
        c = snn.continuous_value_model(y, None, use_cvm=False)
    ob, oc = _run(main, {"x": np.ones((2, 3), np.float32),
                         "y": np.ones((2, 5), np.float32)}, [b, c])
    assert ob.shape == (2, 7) and oc.shape == (2, 3)


def test_control_flow_eager_semantics():
    t = paddle.to_tensor(np.asarray(True))
    assert snn.cond(t, lambda: 1, lambda: 2) == 1
    r = snn.case([(paddle.to_tensor(np.asarray(False)), lambda: "a"),
                  (paddle.to_tensor(np.asarray(True)), lambda: "b")],
                 default=lambda: "c")
    assert r == "b"
    assert snn.switch_case(paddle.to_tensor(np.asarray(1)),
                           {0: lambda: "x", 1: lambda: "y"}) == "y"
    i = paddle.to_tensor(np.asarray(0.0, np.float32))
    (final,) = snn.while_loop(lambda v: v < 3, lambda v: v + 1, [i])
    assert float(final._data) == 3.0


def test_widedeep_static_recipe_trains():
    """The reference's Wide&Deep static recipe shape — sparse_embedding
    (dense-table variant) + fc tower + minimize — end to end through
    Program/Executor (ref:python/paddle/fluid/tests demo topology)."""
    from paddle_tpu import optimizer

    rng = np.random.RandomState(3)
    n, slots, vocab = 256, 4, 50
    ids_np = rng.randint(0, vocab, (n, slots)).astype(np.int64)
    # clickiness depends on whether slot-0 id is even (learnable signal)
    y_np = ((ids_np[:, 0] % 2) == 0).astype(np.float32).reshape(-1, 1)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = static.data("ids", [None, slots], "int64")
        label = static.data("label", [None, 1], "float32")
        emb = snn.sparse_embedding(ids, size=[vocab, 8], name="slot_emb")
        deep = snn.fc(emb, 32, activation="relu", name="deep1")
        deep = snn.fc(deep, 16, activation="relu", name="deep2")
        wide = snn.fc(emb, 1, name="wide")
        logit = snn.fc(deep, 1, name="head") + wide
        loss = paddle.nn.functional.binary_cross_entropy_with_logits(
            logit, label).mean()
        optimizer.Adam(learning_rate=0.05).minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    first = None
    for _ in range(30):
        for i in range(0, n, 64):
            (lv,) = exe.run(main, feed={"ids": ids_np[i:i+64],
                                        "label": y_np[i:i+64]},
                            fetch_list=[loss])
            if first is None:
                first = float(lv)
    infer = main.clone(for_test=True)
    (pv,) = exe.run(infer, feed={"ids": ids_np, "label": y_np},
                    fetch_list=[logit])
    acc = ((pv[:, 0] > 0) == (y_np[:, 0] > 0.5)).mean()
    assert acc > 0.95, (first, float(lv), acc)


def test_lod_sequence_ops_raise_with_guidance():
    with pytest.raises(NotImplementedError, match="padded batches"):
        snn.sequence_pool(None, "max")
    with pytest.raises(NotImplementedError, match="padded batches"):
        snn.StaticRNN()


def test_row_conv_mixes_future_context():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 5, 2], "float32")
        y = snn.row_conv(x, future_context_size=2)
    arr = np.zeros((1, 5, 2), np.float32)
    arr[0, 4] = 3.0  # only the last step is nonzero
    (out,) = _run(main, {"x": arr}, [y])
    # with uniform init weights 1/3, steps 2..4 see the future value
    assert out[0, 4].sum() > 0 and out[0, 2].sum() > 0
    assert out[0, 0].sum() == 0

"""Static-graph mode: Program capture + Executor replay.

Parity surface: paddle.static.Program/program_guard/data/Executor
(ref:python/paddle/static/__init__.py; the reference interprets an OpDesc
Program, here Executor.run jit-replays the captured tape — SURVEY.md §7's
compiler-is-the-executor stance through the legacy API).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, static


def test_feed_fetch_roundtrip():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        y = (x * 2.0 + 1.0).sum(axis=1)
    exe = static.Executor()
    arr = np.arange(8, dtype=np.float32).reshape(2, 4)
    (out,) = exe.run(main, feed={"x": arr}, fetch_list=[y])
    np.testing.assert_allclose(out, (arr * 2 + 1).sum(1), rtol=1e-6)


def test_none_dims_respecialize_per_feed_shape():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3], "float32")
        y = paddle.nn.functional.relu(x - 1.0)
    exe = static.Executor()
    for b in (1, 5, 8):
        arr = np.random.RandomState(b).standard_normal((b, 3)).astype(np.float32)
        (out,) = exe.run(main, feed={"x": arr}, fetch_list=[y])
        np.testing.assert_allclose(out, np.maximum(arr - 1, 0), rtol=1e-6)


def test_layers_capture_with_live_parameters():
    """nn layers under program_guard record by parameter REFERENCE: updating
    the parameter is visible on the next run without re-capture."""
    lin = nn.Linear(4, 2)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        out = lin(x)
    exe = static.Executor()
    arr = np.ones((3, 4), np.float32)
    (o1,) = exe.run(main, feed={"x": arr}, fetch_list=[out])
    expect = arr @ np.asarray(lin.weight._data) + np.asarray(lin.bias._data)
    np.testing.assert_allclose(o1, expect, rtol=1e-5)

    lin.bias._data = lin.bias._data + 10.0
    (o2,) = exe.run(main, feed={"x": arr}, fetch_list=[out])
    np.testing.assert_allclose(o2, expect + 10.0, rtol=1e-5)


def test_minimize_trains_in_one_compiled_step():
    rng = np.random.RandomState(0)
    X = rng.standard_normal((64, 8)).astype(np.float32)
    Yv = (X[:, :2].sum(1, keepdims=True) + 0.1).astype(np.float32)

    lin = nn.Linear(8, 1)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 8], "float32")
        y = static.data("y", [None, 1], "float32")
        pred = lin(x)
        loss = ((pred - y) ** 2).mean()
        opt = optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(static.default_startup_program())
    losses = []
    for _ in range(40):
        (lv,) = exe.run(main, feed={"x": X, "y": Yv}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_clone_for_test_drops_train_section():
    lin = nn.Linear(4, 1)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        loss = (lin(x) ** 2).mean()
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    test_prog = main.clone(for_test=True)
    assert test_prog._train is None and main._train is not None
    exe = static.Executor()
    w0 = np.asarray(lin.weight._data).copy()
    exe.run(test_prog, feed={"x": np.ones((2, 4), np.float32)},
            fetch_list=[loss])
    np.testing.assert_array_equal(np.asarray(lin.weight._data), w0)


def test_symbolic_concretization_raises():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        y = x + 1.0
    with pytest.raises(RuntimeError, match="placeholder"):
        y.numpy()
    with pytest.raises(RuntimeError, match="placeholder"):
        bool(y)
    with pytest.raises(RuntimeError, match="placeholder"):
        float(y)


def test_executor_validates_feeds_and_fetches():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2], "float32")
        y = x * 3.0
    exe = static.Executor()
    with pytest.raises(ValueError, match="missing feed"):
        exe.run(main, feed={}, fetch_list=[y])
    with pytest.raises(ValueError, match="unknown feed"):
        exe.run(main, feed={"x": np.zeros(2, np.float32),
                            "zz": np.zeros(2)}, fetch_list=[y])
    with pytest.raises(ValueError, match="symbolic"):
        exe.run(main, feed={"x": np.zeros(2, np.float32)},
                fetch_list=[paddle.ones([2])])


def test_optimizer_state_survives_feed_shape_change():
    """A new (fetch, feed-shape) signature builds a new runner; the Adam
    moments/step must carry over (they live on the Program, not the
    runner)."""
    lin = nn.Linear(4, 1)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        loss = (lin(x) ** 2).mean()
        opt = optimizer.Adam(learning_rate=0.01)
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(main, feed={"x": np.ones((8, 4), np.float32)}, fetch_list=[loss])
    exe.run(main, feed={"x": np.ones((8, 4), np.float32)}, fetch_list=[loss])
    step_before = int(main._opt_state["step"])
    # last partial batch: different feed shape -> new compiled runner
    exe.run(main, feed={"x": np.ones((3, 4), np.float32)}, fetch_list=[loss])
    assert int(main._opt_state["step"]) == step_before + 1 == 3
    # and the slots are keyed by REAL param names (name-conditional
    # optimizer logic depends on it)
    keys = set(main._opt_state["slots"])
    assert all(not k.isdigit() for k in keys), keys


def test_fetch_placeholder_through_opless_program():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3], "float32")
    exe = static.Executor()
    (out,) = exe.run(main, feed={"x": np.arange(3).astype(np.float32)},
                     fetch_list=[x])
    np.testing.assert_allclose(out, [0, 1, 2])


def test_np_asarray_on_placeholder_raises():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2], "float32")
        y = x + 1.0
    with pytest.raises(RuntimeError, match="placeholder"):
        np.asarray(y)
    with pytest.raises(RuntimeError, match="placeholder"):
        y.tolist()


def test_fetch_from_other_program_after_ops_is_loud():
    p1 = static.Program()
    with static.program_guard(p1):
        a = static.data("a", [2], "float32")
        b = a * 2.0
    p2 = static.Program()
    with static.program_guard(p2):
        c = static.data("c", [2], "float32")
        _ = c + 1.0
    exe = static.Executor()
    with pytest.raises(ValueError, match="not computed by this program"):
        exe.run(p2, feed={"c": np.zeros(2, np.float32)}, fetch_list=[b])


def test_static_save_load_roundtrip(tmp_path):
    def build():
        lin = nn.Linear(4, 2)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4], "float32")
            out = lin(x)
        return main, out

    main, out = build()
    exe = static.Executor()
    arr = np.ones((2, 4), np.float32)
    (o1,) = exe.run(main, feed={"x": arr}, fetch_list=[out])
    static.save(main, str(tmp_path / "m"))

    main2, out2 = build()  # fresh params
    static.load(main2, str(tmp_path / "m"))
    (o2,) = static.Executor().run(main2, feed={"x": arr}, fetch_list=[out2])
    np.testing.assert_allclose(o1, o2, rtol=1e-6)

    with pytest.raises(ValueError, match="shape mismatch|references"):
        bad = nn.Linear(3, 2)
        p3 = static.Program()
        with static.program_guard(p3):
            x3 = static.data("x", [None, 3], "float32")
            _ = bad(x3)
        static.load(p3, str(tmp_path / "m"))

    state = static.load_program_state(str(tmp_path / "m"))
    static.set_program_state(main2, state)
    (o3,) = static.Executor().run(main2, feed={"x": arr}, fetch_list=[out2])
    np.testing.assert_allclose(o1, o3, rtol=1e-6)


def test_enable_static_mode_flag():
    assert paddle.in_dynamic_mode()
    paddle.enable_static()
    try:
        assert not paddle.in_dynamic_mode()
    finally:
        paddle.disable_static()
    assert paddle.in_dynamic_mode()


def test_default_main_program_capture_without_guard():
    before = len(static.default_main_program().ops)
    x = static.data("dmp_x", [3], "float32")
    y = x + 2.0
    exe = static.Executor()
    (out,) = exe.run(feed={"dmp_x": np.arange(3, dtype=np.float32)},
                     fetch_list=[y])
    np.testing.assert_allclose(out, [2, 3, 4])
    assert len(static.default_main_program().ops) > before


class TestStaticAmp:
    """paddle.static.amp: capture-time mixed precision (the reference
    rewrites the Program inserting casts; here the guard records them)."""

    def test_fp16_guard_records_bf16_and_trains(self):
        import numpy as np

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 8], "float32")
            y = static.data("y", [None, 1], "float32")
            with static.amp.fp16_guard("bfloat16"):
                h = static.nn.fc(x, size=16, activation="relu")
                out = static.nn.fc(h, size=1)
            assert "bfloat16" in str(h.dtype)  # the capture recorded casts
            loss = paddle.mean((out - y) ** 2)
            opt = static.amp.decorate(
                paddle.optimizer.SGD(learning_rate=0.05),
                amp_dtype="bfloat16")
            opt.minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.default_rng(0)
        X = rng.standard_normal((32, 8), dtype=np.float32)
        Y = (X @ rng.standard_normal((8, 1), dtype=np.float32)).astype(
            np.float32)
        first = last = None
        for _ in range(40):
            (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            first = first if first is not None else float(lv)
            last = float(lv)
        assert last < 0.5 * first

    def test_pure_mode_casts_parameters(self):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4], "float32")
            with static.amp.bf16_guard():
                out = static.nn.fc(x, size=3)
            opt = static.amp.decorate(
                paddle.optimizer.SGD(learning_rate=0.1),
                amp_dtype="bfloat16", use_pure_fp16=True)
            assert opt.level == "O2"
            assert opt._inner._multi_precision  # f32 master slots engaged
            assert opt.use_dynamic_loss_scaling  # reference default
            opt.minimize(paddle.mean(out))
        # amp_init OUTSIDE the guard must still cast THE loss's program
        # (minimize recorded it), not whatever default is current
        opt.amp_init()
        assert all("bfloat16" in str(p._data.dtype) for p in main._params)

    def test_pure_o2_static_training_uses_master_weights(self):
        """Full pure-bf16 static train: params bf16, f32 master slots in the
        compiled update, loss converges (sub-bf16-ulp updates survive)."""
        import numpy as np

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 8], "float32")
            y = static.data("y", [None, 1], "float32")
            with static.amp.bf16_guard():
                h = static.nn.fc(x, size=16, activation="relu")
                out = static.nn.fc(h, size=1)
            loss = paddle.mean((out - y) ** 2)
            opt = static.amp.decorate(
                paddle.optimizer.Adam(learning_rate=1e-2),
                amp_dtype="bfloat16", use_pure_fp16=True)
            opt.minimize(loss)
        opt.amp_init()
        assert all("bfloat16" in str(p._data.dtype) for p in main._params)
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.default_rng(1)
        X = rng.standard_normal((32, 8), dtype=np.float32)
        Y = (X @ rng.standard_normal((8, 1), dtype=np.float32)).astype(
            np.float32)
        first = last = None
        for _ in range(50):
            (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            first = first if first is not None else float(lv)
            last = float(lv)
        assert last < 0.3 * first, (first, last)
        slots = main._opt_state["slots"]
        assert any("master_weight" in s for s in
                   (slots.values() if isinstance(slots, dict) else slots))

    def test_fp16_loss_scaling_trains_and_grows_scale(self):
        """float16 static AMP applies REAL loss scaling in the compiled
        step (ref decorator.py: scale loss, unscale grads, dynamic
        update_loss_scaling): loss converges and the scale grows after
        incr_every_n_steps consecutive finite steps."""
        import numpy as np

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 8], "float32")
            y = static.data("y", [None, 1], "float32")
            with static.amp.fp16_guard("float16"):
                h = static.nn.fc(x, size=16, activation="relu")
                out = static.nn.fc(h, size=1)
            loss = paddle.mean((out.astype("float32") - y) ** 2)
            opt = static.amp.decorate(
                paddle.optimizer.SGD(learning_rate=0.05),
                init_loss_scaling=4.0, incr_every_n_steps=2,
                incr_ratio=2.0, amp_dtype="float16")
            opt.minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.default_rng(2)
        X = rng.standard_normal((32, 8), dtype=np.float32)
        Y = (X @ rng.standard_normal((8, 1), dtype=np.float32)).astype(
            np.float32)
        first = last = None
        for _ in range(40):
            (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            first = first if first is not None else float(lv)
            last = float(lv)
        assert last < 0.5 * first, (first, last)
        # 40 finite steps with incr every 2: scale grew (clipped at 2^32)
        assert opt.get_loss_scaling() > 4.0
        assert "amp_loss_scaling" in main._opt_state

    def test_fp16_overflow_skips_update_and_decreases_scale(self):
        """A non-finite gradient must leave params AND optimizer state
        untouched and cut the scale by decr_ratio (ref decorator.py
        _check_finite_and_unscale + update_loss_scaling)."""
        import numpy as np

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4], "float32")
            with static.amp.fp16_guard("float16"):
                out = static.nn.fc(x, size=3)
            loss = paddle.mean(out.astype("float32"))
            opt = static.amp.decorate(
                paddle.optimizer.SGD(learning_rate=0.1),
                init_loss_scaling=1024.0, decr_every_n_nan_or_inf=1,
                decr_ratio=0.5, amp_dtype="float16")
            opt.minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        ok = np.ones((4, 4), np.float32)
        exe.run(main, feed={"x": ok}, fetch_list=[loss])  # healthy step
        before = [np.asarray(p._data).copy() for p in main._params]
        step_before = int(main._opt_state["step"])
        # 1e30 overflows float16 at the cast -> inf activations -> inf loss
        bad = np.full((4, 4), 1e30, np.float32)
        exe.run(main, feed={"x": bad}, fetch_list=[loss])
        after = [np.asarray(p._data) for p in main._params]
        for b, a in zip(before, after):
            np.testing.assert_array_equal(b, a)
        assert int(main._opt_state["step"]) == step_before  # update skipped
        assert opt.get_loss_scaling() == 512.0  # 1024 * 0.5
        # and the run recovers: a healthy step after the skip still trains
        exe.run(main, feed={"x": ok}, fetch_list=[loss])
        assert int(main._opt_state["step"]) == step_before + 1


class TestStaticInferenceExport:
    def test_legacy_save_inference_model_round_trip(self, tmp_path):
        """The legacy (feed, fetch, exe, program) export form: Program
        replay -> StableHLO .pdmodel, dynamic batch, Predictor-servable."""
        import numpy as np

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 8], "float32")
            h = static.nn.fc(x, size=16, activation="relu")
            out = static.nn.fc(h, size=3)
        exe = static.Executor()
        exe.run(startup)
        X = np.random.randn(4, 8).astype(np.float32)
        (want,) = exe.run(main, feed={"x": X}, fetch_list=[out])

        prefix = str(tmp_path / "m")
        static.save_inference_model(prefix, [x], [out], exe, program=main)
        loaded = static.load_inference_model(prefix)
        np.testing.assert_allclose(loaded(paddle.to_tensor(X)).numpy(),
                                   np.asarray(want), atol=1e-5)
        X2 = np.random.randn(7, 8).astype(np.float32)  # dynamic batch
        assert loaded(paddle.to_tensor(X2)).numpy().shape == (7, 3)

    def test_bad_feed_vars_raise(self):
        import pytest

        with pytest.raises(ValueError, match="symbolic"):
            static.save_inference_model("/tmp/never", [paddle.to_tensor(1.0)],
                                        [paddle.to_tensor(2.0)], None)

    def test_multi_fetch_export(self, tmp_path):
        import numpy as np

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4], "float32")
            h = static.nn.fc(x, size=8, activation="relu")
            out = static.nn.fc(h, size=2)
        static.Executor().run(startup)
        X = np.random.randn(3, 4).astype(np.float32)
        exe = static.Executor()
        want_h, want_out = exe.run(main, feed={"x": X},
                                   fetch_list=[h, out])
        prefix = str(tmp_path / "mm")
        static.save_inference_model(prefix, [x], [h, out], exe, program=main)
        got_h, got_out = static.load_inference_model(prefix)(
            paddle.to_tensor(X))
        np.testing.assert_allclose(got_h.numpy(), np.asarray(want_h),
                                   atol=1e-5)
        np.testing.assert_allclose(got_out.numpy(), np.asarray(want_out),
                                   atol=1e-5)
        import pytest

        with pytest.raises(ValueError, match="symbolic"):
            static.save_inference_model(prefix, [x], None, exe, program=main)

    def test_export_shares_batch_symbol_across_feeds(self, tmp_path):
        """Two feeds with dynamic leading dims combined elementwise: the
        batch symbol must be SHARED (independent symbols would fail the
        broadcast at trace time), and the export serves any batch size."""
        import numpy as np

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            a = static.data("a", [None, 4], "float32")
            b = static.data("b", [None, 4], "float32")
            out = paddle.add(a, b)
        exe = static.Executor()
        exe.run(startup)
        prefix = str(tmp_path / "twofeed")
        static.save_inference_model(prefix, [a, b], [out], exe, program=main)
        served = static.load_inference_model(prefix)
        for n in (2, 5):
            A = np.random.randn(n, 4).astype(np.float32)
            B = np.random.randn(n, 4).astype(np.float32)
            np.testing.assert_allclose(
                served(paddle.to_tensor(A), paddle.to_tensor(B)).numpy(),
                A + B, atol=1e-6)

    def test_export_keeps_non_batch_dynamic_dims_independent(self, tmp_path):
        """Dynamic dims PAST dim 0 stay per-feed: two None seq-lengths must
        not be constrained equal by the export (ADVICE r4)."""
        import numpy as np

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            a = static.data("a", [2, None], "float32")
            b = static.data("b", [2, None], "float32")
            out = paddle.concat([a, b], axis=1)
        exe = static.Executor()
        exe.run(startup)
        prefix = str(tmp_path / "seqs")
        static.save_inference_model(prefix, [a, b], [out], exe, program=main)
        served = static.load_inference_model(prefix)
        A = np.random.randn(2, 3).astype(np.float32)
        B = np.random.randn(2, 7).astype(np.float32)  # different seq-len
        np.testing.assert_allclose(
            served(paddle.to_tensor(A), paddle.to_tensor(B)).numpy(),
            np.concatenate([A, B], axis=1), atol=1e-6)

"""Tests for the coverage-sweep additions: LBFGS, schedulers, incubate
segment/graph ops, distributions, jacobian/hessian, saved_tensors_hooks,
vision zoo/transforms, static working surface, distributed api extras."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
from paddle_tpu import incubate, nn

RNG = np.random.RandomState(5)


def _t(a):
    return paddle.to_tensor(a)


# ---------------------------------------------------------------- optimizers


def test_lbfgs_converges_on_rosenbrock():
    x = paddle.create_parameter([2])
    x._data = x._data * 0 + paddle.to_tensor(np.array([-1.2, 1.0], np.float32))._data
    opt = paddle.optimizer.LBFGS(learning_rate=1.0, max_iter=60,
                                 line_search_fn="strong_wolfe", parameters=[x])

    def closure():
        opt.clear_grad()
        a = x[0]
        b = x[1]
        loss = (1 - a) ** 2 + 100 * (b - a * a) ** 2
        loss.backward()
        return loss

    for _ in range(8):
        loss = opt.step(closure)
    np.testing.assert_allclose(x.numpy(), [1.0, 1.0], atol=1e-2)


def test_cyclic_and_multiplicative_lr():
    from paddle_tpu.optimizer.lr import CyclicLR, MultiplicativeDecay

    s = CyclicLR(base_learning_rate=0.1, max_learning_rate=0.5, step_size_up=4)
    vals = []
    for _ in range(8):
        vals.append(s())
        s.step()
    assert max(vals) > 0.4 and min(vals) <= 0.11

    m = MultiplicativeDecay(0.5, lambda e: 0.5)
    m.step()
    m.step()
    assert abs(m() - 0.125) < 1e-9


def test_lookahead_and_model_average():
    net = nn.Linear(4, 1)
    inner = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    opt = incubate.LookAhead(inner, alpha=0.5, k=2)
    X = RNG.rand(32, 4).astype(np.float32)
    Y = (X @ np.array([1, 2, 3, 4], np.float32))[:, None]
    first = None
    for _ in range(20):
        loss = ((net(_t(X)) - _t(Y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss.numpy())
    assert float(loss.numpy()) < first

    ma = incubate.ModelAverage(parameters=net.parameters())
    w0 = np.asarray(net.weight._data).copy()
    ma.step()
    net.weight._data = net.weight._data * 0
    ma.apply()
    np.testing.assert_allclose(np.asarray(net.weight._data), w0, rtol=1e-6)
    ma.restore()
    np.testing.assert_allclose(np.asarray(net.weight._data), 0)


# ------------------------------------------------------------------ incubate


def test_segment_ops_match_torch():
    data = RNG.randn(8, 3).astype(np.float32)
    ids = np.array([0, 0, 1, 1, 1, 2, 3, 3], np.int32)
    got = incubate.segment_sum(_t(data), _t(ids)).numpy()
    exp = torch.zeros(4, 3).index_add_(0, torch.tensor(ids, dtype=torch.int64),
                                       torch.tensor(data)).numpy()
    np.testing.assert_allclose(got, exp, rtol=1e-5)
    gm = incubate.segment_mean(_t(data), _t(ids)).numpy()
    np.testing.assert_allclose(gm[0], data[:2].mean(0), rtol=1e-5)
    gx = incubate.segment_max(_t(data), _t(ids)).numpy()
    np.testing.assert_allclose(gx[2], data[5], rtol=1e-6)


def test_graph_send_recv_and_reindex():
    x = RNG.randn(5, 2).astype(np.float32)
    src = np.array([0, 1, 2, 3], np.int32)
    dst = np.array([1, 1, 4, 4], np.int32)
    out = incubate.graph_send_recv(_t(x), _t(src), _t(dst), "sum").numpy()
    np.testing.assert_allclose(out[1], x[0] + x[1], rtol=1e-5)
    np.testing.assert_allclose(out[4], x[2] + x[3], rtol=1e-5)

    # csc graph: edges (row=neighbors) for 3 nodes
    row = _t(np.array([1, 2, 0, 2, 0, 1], np.int64))
    colptr = _t(np.array([0, 2, 4, 6], np.int64))
    neigh, cnt = incubate.graph_sample_neighbors(row, colptr,
                                                _t(np.array([0, 2], np.int64)))
    assert cnt.numpy().tolist() == [2, 2]
    s, d, nodes = incubate.graph_reindex(_t(np.array([0, 2], np.int64)),
                                         neigh, cnt)
    assert len(s.numpy()) == 4 and len(d.numpy()) == 4
    assert set(nodes.numpy().tolist()) >= {0, 2}


def test_softmax_mask_fuse():
    x = RNG.randn(2, 2, 4, 4).astype(np.float32)
    out = incubate.softmax_mask_fuse_upper_triangle(_t(x)).numpy()
    assert np.allclose(out.sum(-1), 1.0, atol=1e-5)
    assert (np.triu(np.ones((4, 4)), 1)[None, None] * out < 1e-6).all()


# ------------------------------------------------------------ distributions


def test_cauchy_and_transformed():
    from paddle_tpu import distribution as D

    c = D.Cauchy(0.0, 2.0)
    np.testing.assert_allclose(float(c.cdf(_t(0.0)).numpy()), 0.5, atol=1e-6)
    td = D.TransformedDistribution(D.Normal(0.0, 1.0), [D.ExpTransform()])
    ln = D.LogNormal(0.0, 1.0)
    for v in (0.5, 1.0, 3.0):
        np.testing.assert_allclose(float(td.log_prob(_t(v)).numpy()),
                                   float(ln.log_prob(_t(v)).numpy()), rtol=1e-5)
    ind = D.Independent(D.Normal(np.zeros(4, np.float32), np.ones(4, np.float32)), 1)
    lp = ind.log_prob(_t(np.zeros(4, np.float32)))
    assert lp.shape == []


# ------------------------------------------------------------ autograd extra


def test_jacobian_and_hessian():
    from paddle_tpu.autograd import hessian, jacobian

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32), stop_gradient=False)
    y = x * x * 3.0
    jac = jacobian(y, x)
    np.testing.assert_allclose(jac.numpy(), np.diag([6.0, 12.0]), rtol=1e-5)

    x2 = paddle.to_tensor(np.array([1.0, 2.0], np.float32), stop_gradient=False)
    out = (x2 * x2 * x2).sum()
    h = hessian(out, x2)
    np.testing.assert_allclose(h.numpy(), np.diag([6.0, 12.0]), rtol=1e-5)


def test_saved_tensors_hooks_roundtrip():
    from paddle_tpu.autograd import saved_tensors_hooks

    packed, unpacked = [], []

    def pack(arr):
        packed.append(1)
        return np.asarray(arr)  # offload to host

    def unpack(obj):
        unpacked.append(1)
        import jax.numpy as jnp

        return jnp.asarray(obj)

    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    with saved_tensors_hooks(pack, unpack):
        y = x * x
    y.backward()
    assert packed and unpacked
    np.testing.assert_allclose(x.grad.numpy(), [6.0], rtol=1e-6)


# ----------------------------------------------------------------- vision


def test_transforms_functional_golden():
    import paddle_tpu.vision.transforms as T

    img = (RNG.rand(8, 10, 3) * 255).astype(np.uint8)
    np.testing.assert_array_equal(T.hflip(img), img[:, ::-1])
    np.testing.assert_array_equal(T.vflip(img), img[::-1])
    assert T.center_crop(img, 4).shape == (4, 4, 3)
    assert T.pad(img, 2).shape == (12, 14, 3)
    b = T.adjust_brightness(img, 1.5)
    assert b.dtype == np.uint8 and b.mean() >= img.mean()
    g = T.to_grayscale(img, 3)
    assert np.allclose(g[..., 0], g[..., 1])
    r = T.rotate(img, 90)
    assert r.shape == img.shape
    e = T.erase(img, 1, 1, 3, 3, 0)
    assert (e[1:4, 1:4] == 0).all()


def test_small_zoo_trains_one_step():
    import paddle_tpu.vision.models as m

    net = m.squeezenet1_1(num_classes=4)
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=net.parameters())
    x = _t(RNG.rand(2, 3, 32, 32).astype(np.float32))
    y = _t(np.array([0, 1], np.int64))
    loss = nn.functional.cross_entropy(net(x), y).mean()
    loss.backward()
    opt.step()
    assert np.isfinite(float(loss.numpy()))


# ------------------------------------------------------------------ static


def test_static_working_surface():
    import paddle_tpu.static as st

    net = nn.Linear(3, 2)
    ema = st.ExponentialMovingAverage(0.5)
    ema.update(net.parameters())
    w0 = np.asarray(net.weight._data).copy()
    net.weight._data = net.weight._data + 1.0
    ema.update()
    ema.apply()
    expected = 0.5 * w0 + 0.5 * (w0 + 1.0)
    np.testing.assert_allclose(np.asarray(net.weight._data), expected, rtol=1e-5)
    ema.restore()

    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = (x * x).sum()
    (g,) = st.gradients(y, [x])
    np.testing.assert_allclose(g.numpy(), [4.0])

    v = st.create_global_var([2, 2], 1.5, "float32")
    np.testing.assert_allclose(v.numpy(), np.full((2, 2), 1.5))
    assert st.Program() is not None  # real capture Program since round 4


# -------------------------------------------------------------- distributed


def test_parallel_env_and_backend():
    import paddle_tpu.distributed as dist

    env = dist.ParallelEnv()
    assert env.world_size >= 1
    assert dist.get_backend() == "XCCL"
    assert dist.is_available()


def test_in_memory_dataset(tmp_path):
    import paddle_tpu.distributed as dist

    f = tmp_path / "data.txt"
    f.write_text("\n".join(f"{i} {i*2}" for i in range(10)))
    ds = dist.InMemoryDataset()
    ds.init(batch_size=3, parse_fn=lambda line: tuple(map(int, line.split())))
    ds.set_filelist([str(f)])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 10
    ds.global_shuffle()
    batches = list(ds)  # collated: tuple of per-field arrays
    assert sum(b[0].shape[0] for b in batches) == 10


def test_metric_accuracy_topk():
    scores = np.array([[0.1, 0.9, 0.0], [0.8, 0.05, 0.15]], np.float32)
    label = np.array([[1], [2]])
    a1 = float(paddle.metric.accuracy(_t(scores), _t(label), k=1).numpy())
    a2 = float(paddle.metric.accuracy(_t(scores), _t(label), k=2).numpy())
    assert a1 == 0.5 and a2 == 1.0

"""paddle_tpu.serving.telemetry (ISSUE 17): latency histograms (fixed
log buckets, merge/minus, percentile interpolation), the request-lifecycle
trace ring and its ``FLAGS_serving_telemetry`` gate, trace_id propagation
through a real ServingAPI run, Prometheus text rendering, Chrome
trace-event conversion, the windowed ``metrics.Meter`` decay regression,
and the profiler's per-run latency delta."""
import json
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import RequestState, ServingAPI, telemetry
from paddle_tpu.serving import metrics as serving_metrics

pytestmark = pytest.mark.serving

MAX_LEN = 64
API_KW = dict(num_slots=4, kv_block_size=8, max_model_len=MAX_LEN)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture()
def spans_on():
    keep = paddle.get_flags(["serving_telemetry"])
    paddle.set_flags({"serving_telemetry": True})
    telemetry.reset_tracelog()
    yield
    telemetry.reset_tracelog()
    paddle.set_flags(keep)


# ------------------------------------------------------------- histograms


def test_histogram_percentile_within_one_bucket():
    h = telemetry.Histogram()
    for _ in range(90):
        h.record(1e-3)
    for _ in range(10):
        h.record(0.5)
    assert h.n == 100
    # each percentile lands inside the recorded sample's bucket
    # (log-bucket relative error is bounded by the 1.25x factor)
    assert 1e-3 / 1.25 <= h.percentile(50) <= 1e-3 * 1.25
    assert 0.5 / 1.25 <= h.percentile(99) <= 0.5 * 1.25
    assert h.percentile(5) <= h.percentile(50) <= h.percentile(99)
    assert abs(h.mean() - (90 * 1e-3 + 10 * 0.5) / 100) < 1e-9
    # negative skew clamps, never throws or corrupts counts
    h.record(-1.0)
    assert h.n == 101
    assert telemetry.Histogram().percentile(99) == 0.0


def test_histogram_merge_minus_and_buckets():
    a, b = telemetry.Histogram(), telemetry.Histogram()
    for _ in range(10):
        a.record(2e-3)
    for _ in range(30):
        b.record(8e-2)
    m = a.merge(b)
    assert m.n == 40 and abs(m.total - (a.total + b.total)) < 1e-12
    # merged percentiles see BOTH replicas' samples (p25 from a, p75 from b)
    assert m.percentile(20) <= 2e-3 * 1.25
    assert m.percentile(80) >= 8e-2 / 1.25
    d = m.minus(a)
    assert d.n == b.n and d.percentile(50) == b.percentile(50)
    # buckets(): cumulative, monotone, +Inf-free for in-range samples
    buckets = m.buckets()
    cums = [c for _, c in buckets]
    assert cums == sorted(cums) and cums[-1] == m.n
    assert all(bound > 0 for bound, _ in buckets)


def test_observe_records_global_and_extra_sets():
    telemetry.reset_histograms()
    extra = telemetry.HistogramSet()
    telemetry.observe("latency.ttft", 0.01, extra, None)
    telemetry.observe("latency.ttft", 0.02)
    assert telemetry.histogram("latency.ttft").n == 2
    assert extra.peek("latency.ttft").n == 1
    delta = telemetry.histograms_delta({})
    assert delta["latency.ttft"].n == 2
    table = telemetry.percentile_table()
    assert "latency.ttft" in table and "p99(ms)" in table


def test_meter_rate_decays_when_idle():
    """Satellite regression: tokens_per_sec is a sliding-window rate, not
    a lifetime average — 10s of idle tail must decay the gauge to 0."""
    t = [0.0]
    m = serving_metrics.Meter(window=10.0, now=lambda: t[0])
    for s in range(5):
        t[0] = float(s)
        m.tick(10)
    t[0] = 5.0
    assert m.rate() == pytest.approx(10.0, rel=0.25)
    assert m.tokens() == 50
    # the old lifetime-average bug: at t=16 it still reported ~3 tok/s
    t[0] = 16.0
    assert m.rate() == 0.0
    assert m.tokens() == 50  # lifetime count survives the window
    # traffic resumes: the rate reflects only the fresh window
    t[0] = 17.0
    m.tick(20)
    assert m.rate() == pytest.approx(2.0, rel=0.25)  # 20 tokens / 10s window
    m.reset()
    assert m.rate() == 0.0 and m.tokens() == 0


# ---------------------------------------------------------------- tracing


def test_span_gated_by_flag(spans_on):
    paddle.set_flags({"serving_telemetry": False})
    telemetry.span("tdeadbeef0001", telemetry.QUEUED, request_id="r1")
    assert telemetry.trace("tdeadbeef0001") == []
    paddle.set_flags({"serving_telemetry": True})
    telemetry.span("tdeadbeef0001", telemetry.QUEUED, request_id="r1")
    telemetry.span("", telemetry.QUEUED)  # no trace_id -> dropped silently
    evs = telemetry.trace("tdeadbeef0001")
    assert [e["event"] for e in evs] == [telemetry.QUEUED]
    assert evs[0]["request_id"] == "r1" and evs[0]["ts"] > 0


def test_tracelog_ring_drops_oldest_and_counts():
    log = telemetry.TraceLog(capacity=16)
    s0 = serving_metrics.stats().get("telemetry.spans_dropped", 0)
    for i in range(20):
        log.append("tring", telemetry.QUEUED, {"i": i})
    evs = log.trace("tring")
    assert len(evs) == 16
    assert [e["i"] for e in evs] == list(range(4, 20))  # oldest 4 dropped
    assert serving_metrics.stats()["telemetry.spans_dropped"] == s0 + 4
    # seq stays strictly increasing across the wrap
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


def test_request_lifecycle_spans_and_histograms(model, spans_on):
    """One real request through ServingAPI: a single trace_id carries the
    SUBMITTED -> QUEUED -> ADMITTED -> FIRST_TOKEN -> FINISHED sequence in
    seq order, and the ttft/e2e/queue_wait histograms record it."""
    telemetry.reset_histograms()
    api = ServingAPI(model, **API_KW)
    try:
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, 1024, (6,), dtype=np.int32)
        req = api.submit(prompt, max_new_tokens=4)
        assert req.trace_id.startswith("t") and len(req.trace_id) == 13
        api.run_until_idle()
        assert req.state == RequestState.FINISHED
        evs = telemetry.trace(req.trace_id)
        kinds = [e["event"] for e in evs]
        for k in (telemetry.SUBMITTED, telemetry.QUEUED, telemetry.ADMITTED,
                  telemetry.FIRST_TOKEN, telemetry.FINISHED):
            assert kinds.count(k) == 1, (k, kinds)
        assert kinds.index(telemetry.SUBMITTED) \
            < kinds.index(telemetry.QUEUED) \
            < kinds.index(telemetry.ADMITTED) \
            < kinds.index(telemetry.FIRST_TOKEN) \
            < kinds.index(telemetry.FINISHED)
        # every span of this trace names the same request
        assert {e["trace_id"] for e in evs} == {req.trace_id}
        hists = telemetry.histograms()
        for name in ("latency.ttft", "latency.e2e", "latency.queue_wait",
                     "latency.prefill", "latency.decode_step",
                     "latency.inter_token"):
            assert hists[name].n > 0, name
        assert hists["latency.ttft"].n == 1  # one request, one first token
        assert hists["latency.e2e"].n == 1
        # the engine's per-replica set saw the same request-scoped samples
        assert api.engine.hists.peek("latency.ttft").n == 1
    finally:
        api.close()


def test_preemption_keeps_trace_id_and_requeues(model, spans_on):
    """A preempted victim keeps its trace_id: the timeline shows
    PREEMPTED followed by a second QUEUED/ADMITTED, then FINISHED —
    one contiguous story, not two requests."""
    keep = paddle.get_flags(["serving_starvation_steps"])
    paddle.set_flags({"serving_starvation_steps": 1})
    # tiny arena: two long requests can't both hold blocks
    api = ServingAPI(model, num_slots=2, kv_block_size=8,
                     max_model_len=MAX_LEN, num_blocks=8)
    try:
        rng = np.random.default_rng(8)
        low = api.submit(rng.integers(0, 1024, (24,), dtype=np.int32),
                         max_new_tokens=24, priority=1)
        for _ in range(3):
            api.scheduler.step()
        high = api.submit(rng.integers(0, 1024, (24,), dtype=np.int32),
                          max_new_tokens=8, priority=0)
        api.run_until_idle()
        assert high.state == RequestState.FINISHED
        assert low.state == RequestState.FINISHED
        if low.preemptions:  # arena pressure actually bit
            kinds = [e["event"] for e in telemetry.trace(low.trace_id)]
            i = kinds.index(telemetry.PREEMPTED)
            assert telemetry.QUEUED in kinds[i:], kinds
            assert telemetry.ADMITTED in kinds[i:], kinds
            assert kinds[-1] == telemetry.FINISHED
            assert kinds.count(telemetry.SUBMITTED) == 1
    finally:
        api.close()
        paddle.set_flags(keep)


# ------------------------------------------------------------ export plane


_PROM_LINE = re.compile(
    r"^[a-zA-Z_][a-zA-Z0-9_]*(\{[^{}]*\})? -?\d+(\.\d+)?([eE][+-]?\d+)?$")


def test_prometheus_text_is_valid_and_complete(model):
    telemetry.reset_histograms()
    api = ServingAPI(model, **API_KW)
    try:
        rng = np.random.default_rng(9)
        api.submit(rng.integers(0, 1024, (5,), dtype=np.int32),
                   max_new_tokens=3)
        api.run_until_idle()
    finally:
        api.close()
    text = telemetry.prometheus_text()
    assert text.endswith("\n")
    families = set()
    for line in text.splitlines():
        if line.startswith("# TYPE"):
            _, _, fam, kind = line.split()
            assert kind in ("counter", "gauge", "histogram")
            families.add(fam)
        else:
            assert _PROM_LINE.match(line) or "+Inf" in line, line
    assert "paddle_serving_tokens_generated" in families
    assert "paddle_latency_ttft_seconds" in families
    # histogram contract: cumulative buckets end at +Inf == _count,
    # and the precomputed quantiles are present for the pool view
    assert 'paddle_latency_e2e_seconds_bucket{replica="pool",le="+Inf"}' \
        in text
    count = [ln for ln in text.splitlines()
             if ln.startswith("paddle_latency_e2e_seconds_count")]
    inf = [ln for ln in text.splitlines()
           if ln.startswith("paddle_latency_e2e_seconds_bucket")
           and 'le="+Inf"' in ln]
    assert count[0].rsplit(" ", 1)[1] == inf[0].rsplit(" ", 1)[1]
    assert 'quantile="0.99"' in text and 'quantile="0.50"' in text
    bucket_counts = [
        float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
        if ln.startswith("paddle_latency_e2e_seconds_bucket")]
    assert bucket_counts == sorted(bucket_counts)  # cumulative, monotone


def test_chrome_events_structure(spans_on):
    for i in range(3):
        telemetry.span("tchrome000001", telemetry.SPAN_KINDS[i], step=i)
    telemetry.span("tchrome000002", telemetry.FINISHED)
    evs = telemetry.chrome_events(telemetry.trace_events())
    json.dumps(evs)  # must be serializable as-is
    lanes = {e["args"]["name"] for e in evs if e["ph"] == "M"}
    assert lanes == {"tchrome000001", "tchrome000002"}
    slices = [e for e in evs if e["ph"] == "X"]
    instants = [e for e in evs if e["ph"] == "i"]
    assert len(slices) == 2 and len(instants) == 2  # terminal = instant
    assert all(e["dur"] >= 0 and e["ts"] > 0 for e in slices)
    assert all(e["args"]["trace_id"] for e in slices + instants)


def test_trace_dump_converts_input_file(tmp_path, spans_on):
    telemetry.span("tdump00000001", telemetry.SUBMITTED, request_id="d1")
    telemetry.span("tdump00000001", telemetry.FINISHED, request_id="d1")
    src = tmp_path / "spans.json"
    src.write_text(json.dumps({"events": telemetry.trace("tdump00000001")}))
    dst = tmp_path / "trace.json"
    from tools import trace_dump

    assert trace_dump.main(["--input", str(src), "-o", str(dst)]) == 0
    out = json.loads(dst.read_text())
    assert out["traceEvents"], out
    assert any(e.get("ph") == "i" and e["name"] == telemetry.FINISHED
               for e in out["traceEvents"])


def test_profiler_reports_latency_delta(model):
    from paddle_tpu import profiler

    telemetry.reset_histograms()
    telemetry.observe("latency.ttft", 0.5)  # pre-run noise: not in delta
    prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU])
    prof.start()
    api = ServingAPI(model, **API_KW)
    try:
        rng = np.random.default_rng(11)
        api.submit(rng.integers(0, 1024, (5,), dtype=np.int32),
                   max_new_tokens=3)
        api.run_until_idle()
    finally:
        api.close()
    prof.stop()
    assert prof.latency_stats["latency.e2e.count"] == 1
    assert prof.latency_stats["latency.e2e.p99_ms"] > 0
    assert prof.latency_stats["latency.ttft.count"] == 1  # noise excluded

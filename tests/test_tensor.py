import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def test_to_tensor_basics():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == paddle.float32  # float64 input downcast per paddle contract
    assert t.ndim == 2
    assert t.size == 4
    np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])


def test_dtypes():
    assert paddle.to_tensor([1, 2]).dtype == paddle.int64 or paddle.to_tensor([1, 2]).dtype == paddle.int32
    assert paddle.to_tensor([1.0], dtype="bfloat16").dtype == paddle.bfloat16
    t = paddle.to_tensor([1.5], dtype="int32")
    assert t.dtype == paddle.int32


def test_item_scalar():
    assert paddle.to_tensor(3.5).item() == pytest.approx(3.5)
    assert int(paddle.to_tensor(7)) == 7


def test_indexing():
    t = paddle.to_tensor(np.arange(24).reshape(2, 3, 4).astype(np.float32))
    assert t[0].shape == [3, 4]
    assert t[0, 1, 2].item() == 6
    assert t[:, 1].shape == [2, 4]
    assert t[..., -1].shape == [2, 3]
    assert t[0:1, ::2].shape == [1, 2, 4]


def test_setitem():
    t = paddle.zeros([3, 3])
    t[1, 1] = 5.0
    assert t[1, 1].item() == 5.0
    t[0] = paddle.ones([3])
    np.testing.assert_array_equal(t[0].numpy(), [1, 1, 1])


def test_astype_cast():
    t = paddle.to_tensor([1.7, 2.3])
    i = t.astype("int32")
    np.testing.assert_array_equal(i.numpy(), [1, 2])


def test_operators():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a - b).numpy(), [-2, -2])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a**2).numpy(), [1, 4])
    np.testing.assert_allclose((2.0 + a).numpy(), [3, 4])
    np.testing.assert_allclose((1.0 - a).numpy(), [0, -1])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])
    assert (a < b).numpy().all()
    assert (a == a).numpy().all()


def test_detach_clone():
    t = paddle.to_tensor([1.0], stop_gradient=False)
    d = t.detach()
    assert d.stop_gradient
    c = t.clone()
    np.testing.assert_array_equal(c.numpy(), t.numpy())


def test_pytree_registration():
    import jax

    t = paddle.to_tensor([1.0, 2.0])
    leaves, treedef = jax.tree_util.tree_flatten(t)
    assert len(leaves) == 1
    t2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert isinstance(t2, Tensor)


def test_repr_does_not_crash():
    repr(paddle.to_tensor([1.0]))
    repr(paddle.to_tensor([1.0], stop_gradient=False))


def test_zero_dim():
    t = paddle.to_tensor(2.0)
    assert t.shape == []
    assert (t + 1).item() == 3.0


def test_setitem_bool_mask_per_nonzero():
    # a value vector maps to selected positions in nonzero order, not by
    # broadcast against the full shape (numpy/paddle set_value semantics)
    m = np.array([[1, 0, 0, 0], [0, 0, 1, 0], [1, 0, 0, 1]], bool)
    x = paddle.zeros([3, 4])
    x[paddle.to_tensor(m)] = paddle.to_tensor(np.array([1., 2., 3., 4.], np.float32))
    assert np.allclose(x.numpy(), [[1, 0, 0, 0], [0, 0, 2, 0], [3, 0, 0, 4]])

    # scalar value: where() fast path
    y = paddle.zeros([3, 4])
    y[paddle.to_tensor(m)] = 7.0
    assert y.numpy().sum() == 28

    # leading-dim mask, value broadcast over the unmasked trailing dim
    rm = np.array([True, False, True])
    z = paddle.zeros([3, 4])
    z[paddle.to_tensor(rm)] = paddle.to_tensor(np.arange(4, dtype=np.float32))
    zn = np.zeros((3, 4), np.float32)
    zn[rm] = np.arange(4, dtype=np.float32)
    assert np.allclose(z.numpy(), zn)

    # leading-dim mask with a per-selected-row value block
    w = paddle.zeros([3, 4])
    w[paddle.to_tensor(rm)] = paddle.to_tensor(
        np.arange(8, dtype=np.float32).reshape(2, 4))
    wn = np.zeros((3, 4), np.float32)
    wn[rm] = np.arange(8, dtype=np.float32).reshape(2, 4)
    assert np.allclose(w.numpy(), wn)


def test_setitem_bool_mask_per_nonzero_grad():
    m = np.array([[1, 0, 0, 0], [0, 0, 1, 0], [1, 0, 0, 1]], bool)
    v = paddle.to_tensor(np.array([1., 2., 3., 4.], np.float32),
                         stop_gradient=False)
    g = paddle.ones([3, 4]) * 2
    g.stop_gradient = False
    g2 = g * 1.0
    g2[paddle.to_tensor(m)] = v * 2
    g2.sum().backward()
    assert np.allclose(v.grad.numpy(), [2, 2, 2, 2])


def test_uniform_inplace_seed_deterministic():
    a = paddle.ones([16])
    b = paddle.ones([16])
    a.uniform_(seed=123)
    b.uniform_(seed=123)
    assert np.allclose(a.numpy(), b.numpy())
    c = paddle.ones([16])
    d = paddle.ones([16])
    c.uniform_()
    d.uniform_()
    assert not np.allclose(c.numpy(), d.numpy())

"""Tiered KV cache (ISSUE 15): host-RAM/disk spill with compiled restore.

Unit half: the :class:`~paddle_tpu.serving.tiered.HostKVCache` LRU byte
budget with disk overflow, the crc-checked disk tier (a corrupt file is a
MISS, never garbage), and the :class:`GlobalRadixIndex` residency
accounting. Engine half: spill/restore byte-exactness (int8 payload AND
per-row scale pools), restore-cost admission sizing, disk-corruption
fallback to recompute with token parity, the one-trace restore program
under churn, the cross-replica host hit through a shared store, chaos
``serving_device`` rebuild with a warm host tier (token parity,
``decode_traces`` frozen), and the flag-off build being tier-free.

Engine tests pin tiering per-instance (``kv_tiering=True`` +
an explicit ``tier_store``) rather than flipping the global flag, so the
rest of the suite — which must pass byte-identically with
``FLAGS_serving_kv_tiering=0`` — is never affected by ordering."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import resilience
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (
    GlobalRadixIndex,
    HostKVCache,
    ReplicaPool,
    RequestState,
    ServingAPI,
)
from paddle_tpu.serving import metrics as serving_metrics
from paddle_tpu.serving.tiered import _payload_bytes

pytestmark = pytest.mark.serving

MAX_LEN = 48
BS = 8


@pytest.fixture(scope="module", autouse=True)
def _invariants_on():
    keep = paddle.get_flags(
        "serving_arena_invariants")["serving_arena_invariants"]
    paddle.set_flags({"serving_arena_invariants": 1})
    yield
    paddle.set_flags({"serving_arena_invariants": keep})


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


def _prompt(rng, n):
    return rng.integers(0, 1024, (n,), dtype=np.int32)


def _ref(model, prompt, max_new):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=max_new)
    return np.asarray(out._data)[0]


def _tiered_api(model, store, num_blocks=6, **kw):
    return ServingAPI(model, num_slots=2, kv_block_size=BS,
                      max_model_len=MAX_LEN, num_blocks=num_blocks,
                      prefix_cache=True, kv_tiering=True,
                      tier_store=store, **kw)


def _serve(api, prompt, max_new=4):
    req = api.submit(prompt, max_new_tokens=max_new)
    api.run_until_idle()
    assert req.state == RequestState.FINISHED, req.error
    return req.output_ids()


def _pressure(api, rng, n=2):
    """Cycle distinct prompts through the tiny arena so cold cached
    prefixes get evicted (spilled)."""
    for _ in range(n):
        _serve(api, _prompt(rng, 18))


# ------------------------------------------------------------ store units


def _fake_payload(fill, nbytes=256):
    return [(np.full(nbytes // 4, fill, np.float32),)]


def test_host_lru_byte_budget_drops_without_disk():
    store = HostKVCache(max_bytes=3 * 1024, disk_dir="")
    for i in range(6):
        store.put(bytes([i]) * 4, _fake_payload(i, 1024))
    st = store.stats()
    assert st["host_bytes"] <= 3 * 1024
    assert st["host_entries"] == 3
    # oldest dropped (no disk tier): a miss, recompute
    assert not store.has(bytes([0]) * 4)
    assert store.get(bytes([0]) * 4) == (None, None)
    # newest retained and LRU-touch keeps an old-but-hot entry alive
    assert store.has(bytes([5]) * 4)
    store.get(bytes([3]) * 4)  # touch
    store.put(b"new1" * 1, _fake_payload(9, 1024))
    assert store.has(bytes([3]) * 4)
    assert not store.has(bytes([4]) * 4)


def test_host_budget_overflows_to_disk_and_promotes(tmp_path):
    store = HostKVCache(max_bytes=2 * 1024, disk_dir=str(tmp_path))
    for i in range(4):
        store.put(bytes([i]) * 4, _fake_payload(i, 1024))
    # overflowed entries live on disk, still resident
    assert store.has(bytes([0]) * 4)
    assert store.tier_of(bytes([0]) * 4) == "disk"
    payload, tier = store.get(bytes([0]) * 4)
    assert tier == "disk"
    np.testing.assert_array_equal(payload[0][0],
                                  _fake_payload(0, 1024)[0][0])
    # a disk hit promotes back into the host tier
    assert store.tier_of(bytes([0]) * 4) == "host"


def test_disk_tier_byte_budget_deletes_oldest(tmp_path):
    from paddle_tpu.serving.tiered import DiskTier

    tier = DiskTier(str(tmp_path), max_bytes=3000)
    for i in range(5):
        tier.put(bytes([i]) * 4, _fake_payload(i, 1024))
    st = tier.stats()
    assert st["bytes"] <= 3000 and st["entries"] >= 1
    assert not tier.has(bytes([0]) * 4)  # oldest deleted
    assert tier.has(bytes([4]) * 4)      # newest kept
    # a fresh scan of the directory sees the same bounded population
    again = DiskTier(str(tmp_path), max_bytes=3000)
    assert again.stats()["entries"] == st["entries"]
    assert serving_metrics.stats().get("tier.disk_evictions", 0) > 0


def test_disk_crc_corruption_reads_as_miss(tmp_path):
    store = HostKVCache(max_bytes=1, disk_dir=str(tmp_path))
    store.put(b"key1key1", _fake_payload(7, 1024))
    store.put(b"key2key2", _fake_payload(8, 1024))  # pushes key1 to disk
    assert store.tier_of(b"key1key1") == "disk"
    files = list(tmp_path.glob("*.kv"))
    assert files
    for f in files:
        raw = bytearray(f.read_bytes())
        raw[40] ^= 0xFF  # flip a body byte: crc must catch it
        f.write_bytes(bytes(raw))
    before = serving_metrics.stats().get("tier.disk_corrupt", 0)
    assert store.get(b"key1key1") == (None, None)
    assert serving_metrics.stats().get("tier.disk_corrupt", 0) == before + 1
    # resilience dashboards see the corruption event too
    assert resilience.stats().get("tier.disk_corrupt", 0) >= 1
    # the corrupt file was deleted — no repeat alarms for a dead entry
    assert not store.has(b"key1key1")


def test_global_radix_index_residency():
    idx = GlobalRadixIndex()
    keys = [b"a", b"b", b"c"]
    idx.publish_insert(0, keys)
    idx.publish_insert(1, keys[:1])
    assert idx.resident_blocks(keys, 0) == 3
    assert idx.resident_blocks(keys, 1) == 1
    # chain-prefix semantics: losing the MIDDLE key truncates the match
    idx.publish_evict(0, b"b")
    assert idx.resident_blocks(keys, 0) == 1
    res = idx.residency(keys)
    assert res["device"] == {0: 1, 1: 1}
    idx.publish_reset(1)
    assert idx.resident_blocks(keys, 1) == 0
    assert idx.stats()["keys"] == 2  # a and c (held by replica 0)


# ---------------------------------------------------------- engine: spill


def test_spill_restore_byte_exact_including_int8_scales(model):
    """An evicted prefix spilled to the host tier restores byte-identical
    — the int8 payload AND the f32 per-row scale pools — and the restore
    program never re-traces."""
    store = HostKVCache(max_bytes=1 << 30, disk_dir="")
    api = _tiered_api(model, store, quant_kv=True)
    try:
        rng = np.random.default_rng(1)
        p1 = _prompt(rng, 18)  # 2 full blocks + private tail
        out1 = _serve(api, p1)
        np.testing.assert_array_equal(out1, _ref(model, p1, 4)[:len(out1)])
        eng = api.engine
        nodes = eng.prefix_cache.match(p1)
        assert len(nodes) == 2 and not any(n.spilled for n in nodes)
        before = [eng.arena.read_block(n.block) for n in nodes]
        assert all(len(entry) == 4 for blk in before for entry in blk), \
            "int8 arena entries must carry payload + scale rows"

        _pressure(api, rng)
        assert eng.prefix_cache.spills >= 2
        assert all(n.spilled and n.block == -1 for n in nodes)

        out2 = _serve(api, p1)
        np.testing.assert_array_equal(out2, out1)
        assert not any(n.spilled for n in nodes)
        after = [eng.arena.read_block(n.block) for n in nodes]
        for blk_before, blk_after in zip(before, after):
            for e_before, e_after in zip(blk_before, blk_after):
                assert len(e_before) == len(e_after) == 4
                for a, b in zip(e_before, e_after):
                    assert a.dtype == b.dtype
                    np.testing.assert_array_equal(a, b)
        assert eng.tier.restored_blocks == 2
        assert eng.restore_traces == 1
        # churn more spill/restore cycles: ONE compiled restore, ever
        for _ in range(2):
            _pressure(api, rng)
            np.testing.assert_array_equal(_serve(api, p1), out1)
        assert eng.restore_traces == 1
        eng.check_invariants()
    finally:
        api.close()


def test_admit_sizing_counts_restore_cost_not_prefill_cost(model):
    """A matched-but-SPILLED block avoids prefill compute but still needs
    one fresh block (its restore target): admission sizing must keep it
    in the block budget while a device-resident match subtracts out."""
    store = HostKVCache(max_bytes=1 << 30, disk_dir="")
    api = _tiered_api(model, store, num_blocks=8)
    try:
        rng = np.random.default_rng(2)
        p1 = _prompt(rng, 18)
        _serve(api, p1)
        eng = api.engine
        resident_need, _ = eng.admit_sizing(18, 4, prompt=p1)
        # 3 blocks worst case, 2 resident matched -> reserve only 1
        assert resident_need == 1
        # spill the prefix: the same admission now budgets 3 (2 restore
        # targets + 1 private) — restore cost, not free attachment
        eng.prefix_cache.evict(2)
        assert eng.prefix_cache.spilled_nodes() == 2
        spilled_need, _ = eng.admit_sizing(18, 4, prompt=p1)
        assert spilled_need == 3
        # and the restored admission still avoids the prefill COMPUTE
        sm0 = serving_metrics.stats()
        _serve(api, p1)
        sm1 = serving_metrics.stats()
        avoided = (sm1.get("tokens.prefill_avoided", 0)
                   - sm0.get("tokens.prefill_avoided", 0))
        assert avoided == 16  # both restored blocks' tokens
    finally:
        api.close()


def test_disk_corruption_falls_back_to_recompute(model, tmp_path):
    """A spilled prefix whose disk entry is corrupted is pruned on the
    next walk and the admission recomputes — token output stays correct,
    nothing serves the damaged bytes."""
    # budget below one real entry: every spill lands on disk
    store = HostKVCache(max_bytes=1, disk_dir=str(tmp_path))
    api = _tiered_api(model, store)
    try:
        rng = np.random.default_rng(3)
        p1 = _prompt(rng, 18)
        out1 = _serve(api, p1)
        _pressure(api, rng)
        assert api.engine.prefix_cache.spilled_nodes() >= 2
        for f in tmp_path.glob("*.kv"):
            raw = bytearray(f.read_bytes())
            raw[len(raw) // 2] ^= 0xFF
            f.write_bytes(bytes(raw))
        before = api.engine.tier.misses
        out2 = _serve(api, p1)
        np.testing.assert_array_equal(out2, out1)
        assert api.engine.tier.misses > before  # lost entry, recomputed
        assert api.engine.tier.restored_blocks == 0
        api.engine.check_invariants()
    finally:
        api.close()


def test_flag_off_is_tier_free(model):
    """The default build (FLAGS_serving_kv_tiering=0) carries no tier:
    no store attached, no restore program ever built, eviction discards
    (PR 14 behavior), and outputs match the explicit kv_tiering=False
    build token-for-token."""
    rng = np.random.default_rng(4)
    p1 = _prompt(rng, 18)
    outs = []
    for kw in ({}, {"kv_tiering": False}):
        api = ServingAPI(model, num_slots=2, kv_block_size=BS,
                         max_model_len=MAX_LEN, num_blocks=6,
                         prefix_cache=True, **kw)
        try:
            eng = api.engine
            assert eng.tier is None
            assert eng.prefix_cache.tier is None
            out1 = _serve(api, p1)
            _pressure(api, np.random.default_rng(5))
            # eviction DISCARDED: no spilled nodes, nothing restorable
            assert eng.prefix_cache.spilled_nodes() == 0
            outs.append(np.concatenate([out1, _serve(api, p1)]))
            assert eng._restore_jit is None and eng.restore_traces == 0
            assert "tier.spilled_blocks" not in eng.stats()
        finally:
            api.close()
    np.testing.assert_array_equal(outs[0], outs[1])


# ------------------------------------------------------------ gateway


def test_cross_replica_host_hit_through_gateway(model):
    """A prefix prefilled on replica A is a host-tier hit on replica B:
    both engines attach to ONE HostKVCache, B's walk materializes the
    shared chunk keys and restores them — token-identical, and the
    GlobalRadixIndex reports true per-replica device residency."""
    store = HostKVCache(max_bytes=1 << 30, disk_dir="")
    pool = ReplicaPool(model, replicas=2, num_slots=2, kv_block_size=BS,
                       max_model_len=MAX_LEN, prefix_cache=True,
                       kv_tiering=True, tier_store=store,
                       affinity_slack=2)
    try:
        rng = np.random.default_rng(6)
        sysp = _prompt(rng, 16)
        p1 = np.concatenate([sysp, _prompt(rng, 4)])
        rr = pool.submit(p1, max_new_tokens=4)
        pool.run_until_idle()
        out1 = rr.output_ids()
        cache0 = pool._replicas[0].api.engine.prefix_cache
        keys = cache0.chunk_keys(p1)
        # replicas published their deltas: residency is per-replica truth
        assert pool.index.resident_blocks(keys, 0) == 2
        assert pool.index.resident_blocks(keys, 1) == 0
        res = pool.index.residency(keys,
                                   tier=pool._replicas[0].api.engine.tier)
        assert res["device"] == {0: 2} and res["host"] == 2
        # drive replica B directly: its tree has never seen the prompt,
        # but the shared host tier has — restore, not re-prefill
        rep_b = pool._replicas[1]
        req_b = rep_b.api.submit(p1, max_new_tokens=4)
        while rep_b.api.scheduler.has_work():
            rep_b.api.scheduler.step()
        np.testing.assert_array_equal(req_b.output_ids(), out1)
        eng_b = rep_b.api.engine
        assert eng_b.tier.host_hits == 2
        assert eng_b.tier.restored_blocks == 2
        assert eng_b.prefix_cache.hits == 1
        # B now serves from device too — the index shows both replicas
        assert pool.index.resident_blocks(keys, 1) == 2
        assert "tier" in pool.stats()
    finally:
        pool.close()


def test_gateway_affinity_consults_index(model):
    """Routing warmth comes from the shared index, not tree probes: a
    warm-on-replica-1 prompt wins the affinity override within slack."""
    pool = ReplicaPool(model, replicas=2, num_slots=2, kv_block_size=BS,
                       max_model_len=MAX_LEN, prefix_cache=True,
                       affinity_slack=2)
    try:
        rng = np.random.default_rng(7)
        sysp = _prompt(rng, 16)
        # seed replica 1's cache directly (replica 0 stays cold)
        rep1 = pool._replicas[1]
        req = rep1.api.submit(np.concatenate([sysp, _prompt(rng, 3)]),
                              max_new_tokens=2)
        while rep1.api.scheduler.has_work():
            rep1.api.scheduler.step()
        assert req.state == RequestState.FINISHED
        before = serving_metrics.stats().get("gateway.affinity_routes", 0)
        rr = pool.submit(np.concatenate([sysp, _prompt(rng, 3)]),
                         max_new_tokens=2)
        pool.run_until_idle()
        assert rr.state == RequestState.FINISHED
        assert (serving_metrics.stats().get("gateway.affinity_routes", 0)
                == before + 1)
        assert rr._replica_idx == 1  # the index steered it warm
    finally:
        pool.close()


# --------------------------------------------------------------- chaos


@pytest.mark.chaos
def test_chaos_rebuild_replays_warm_from_host_tier(model):
    """ISSUE 15 (c): a ``serving_device`` fault mid-decode rebuilds the
    arena, but the host tier is off-device and SURVIVES — the replay's
    admissions restore their prefix blocks from it instead of
    re-prefilling. Token-for-token parity, ``decode_traces`` frozen, and
    the restore program warm from before the crash."""
    keep = paddle.get_flags("fault_injection")["fault_injection"]
    paddle.set_flags({"fault_injection": 1})
    store = HostKVCache(max_bytes=1 << 30, disk_dir="")
    api = _tiered_api(model, store, num_blocks=8)
    try:
        rng = np.random.default_rng(8)
        shared = _prompt(rng, 16)  # 2 shared full blocks
        prompts = [np.concatenate([shared, _prompt(rng, n)])
                   for n in (2, 4)]
        # reference pass (also warms every program incl. one restore)
        reqs = [api.submit(p, max_new_tokens=6) for p in prompts]
        api.run_until_idle()
        refs = [r.output_ids() for r in reqs]
        _pressure(api, rng, n=4)  # spill, then restore: warm program
        assert api.engine.prefix_cache.spills > 0
        r = api.submit(prompts[0], max_new_tokens=6)
        api.run_until_idle()
        np.testing.assert_array_equal(r.output_ids(), refs[0])
        assert api.engine.restore_traces == 1

        d0 = api.engine.decode_traces
        restored0 = api.engine.tier.restored_blocks
        reqs2 = [api.submit(p, max_new_tokens=6) for p in prompts]
        for _ in range(2):
            api._pump_once()
        assert all(r2.state == RequestState.RUNNING for r2 in reqs2)
        resilience.inject_fault("serving_device", times=1)
        api.run_until_idle()
        for ref, r2 in zip(refs, reqs2):
            assert r2.state == RequestState.FINISHED
            np.testing.assert_array_equal(ref, r2.output_ids())
        assert api.supervisor.rebuild_count == 1
        assert api.engine.decode_traces == d0     # replay: no recompiles
        assert api.engine.restore_traces == 1     # restore program reused
        # warm-cache replay: the rebuilt (empty) tree pulled the crashed
        # arena's prefixes back from the surviving host tier
        assert api.engine.tier.restored_blocks > restored0
        api.engine.check_invariants()
        a = api.engine.arena.stats()
        assert a["blocks_reserved"] == 0
        assert a["blocks_in_use"] == a["blocks_cached"]
    finally:
        resilience.clear_faults()
        api.close()
        paddle.set_flags({"fault_injection": keep})


def test_tier_view_counters_and_entry_bytes(model):
    """The per-engine TierView counters EnginePredictor.close() reports
    match the store's ground truth (spilled bytes only counted when the
    write-through copy was already gone)."""
    store = HostKVCache(max_bytes=1 << 30, disk_dir="")
    api = _tiered_api(model, store)
    try:
        rng = np.random.default_rng(9)
        p1 = _prompt(rng, 18)
        _serve(api, p1)
        st = store.stats()
        # write-through: both full blocks host-resident while still on
        # device; per-entry bytes match the arena's row shapes
        assert st["host_entries"] == 2
        node = api.engine.prefix_cache.match(p1)[0]
        payload = api.engine.arena.read_block(node.block)
        assert st["host_bytes"] == 2 * _payload_bytes(payload)
        _pressure(api, rng)
        view = api.engine.tier
        assert view.spilled_blocks >= 2
        assert view.spilled_bytes == 0  # write-through made spills free
        _serve(api, p1)
        assert view.restored_blocks == 2
        assert view.restored_bytes == 2 * _payload_bytes(payload)
        assert view.stats()["tier.host_hits"] == view.host_hits
    finally:
        api.close()

"""paddle.vision.ops detection op tests: hand-computed goldens for the
geometry ops, structural/identity properties for the big kernels
(ref:test/legacy_test/test_roi_align_op.py, test_yolov3_loss_op.py ...)."""
import io

import numpy as np
import pytest
from PIL import Image

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F
from paddle_tpu.vision import ops


def T(x, dtype=np.float32):
    return paddle.to_tensor(np.asarray(x, dtype))


# ----------------------------------------------------------------- nms


def test_nms_suppresses_overlaps():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     np.float32)
    keep = np.asarray(ops.nms(T(boxes), 0.5).numpy())
    assert list(keep) == [0, 2]


def test_nms_with_scores_sorts_first():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     np.float32)
    scores = np.array([0.3, 0.9, 0.5], np.float32)
    keep = list(np.asarray(ops.nms(T(boxes), 0.5, T(scores)).numpy()))
    assert keep == [1, 2]  # box 1 beats box 0


def test_nms_categories_batched():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [0, 0, 10, 10]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    cats = np.array([0, 0, 1], np.int64)
    keep = list(np.asarray(ops.nms(T(boxes), 0.5, T(scores),
                                   paddle.to_tensor(cats), [0, 1]).numpy()))
    # boxes 0 and 1 overlap within category 0 -> keep 0; box 2 is category 1
    assert keep == [0, 2]


def test_matrix_nms_contract():
    boxes = np.zeros((1, 3, 4), np.float32)
    boxes[0] = [[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7]  # class 1 (class 0 is background)
    out, index, rois_num = ops.matrix_nms(
        T(boxes), T(scores), score_threshold=0.1, post_threshold=0.0,
        nms_top_k=10, keep_top_k=10, return_index=True)
    o = np.asarray(out.numpy())
    assert o.shape[1] == 6
    assert int(np.asarray(rois_num.numpy())[0]) == o.shape[0] == 3
    assert (o[:, 0] == 1.0).all()  # class label column
    # scores decayed for overlapping box, untouched for the top one
    assert abs(o[0, 1] - 0.9) < 1e-6
    assert o[1, 1] <= 0.8


# ------------------------------------------------------------ roi family


def test_roi_align_constant_map():
    x = np.full((1, 2, 8, 8), 3.0, np.float32)
    boxes = np.array([[1.0, 1.0, 5.0, 5.0]], np.float32)
    out = ops.roi_align(T(x), T(boxes), T([1], np.int32), 2).numpy()
    assert out.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(out, 3.0, rtol=1e-5)


def test_roi_align_linear_ramp():
    # f(y, x) = x: bilinear sampling of a linear ramp is exact
    x = np.tile(np.arange(8, dtype=np.float32), (8, 1))[None, None]
    boxes = np.array([[2.0, 2.0, 6.0, 6.0]], np.float32)
    out = ops.roi_align(T(x), T(boxes), T([1], np.int32), 2,
                        sampling_ratio=2, aligned=False).numpy()
    # bins span x in [2,4] and [4,6]; mean of samples on a ramp = bin center
    np.testing.assert_allclose(out[0, 0, :, 0], 3.0, atol=1e-5)
    np.testing.assert_allclose(out[0, 0, :, 1], 5.0, atol=1e-5)


def test_roi_pool_exact_max():
    x = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
    boxes = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
    out = ops.roi_pool(T(x), T(boxes), T([1], np.int32), 2).numpy()
    # roi rounds to [0,3]x[0,3] (4x4 incl. +1), bins 2x2 -> maxes
    assert out.shape == (1, 1, 2, 2)
    assert out[0, 0, 1, 1] == x[0, 0, :4, :4].max()


def test_psroi_pool_channel_mapping():
    # each channel c holds constant value c; output bin (i,j) must read
    # channel group (i*pw+j)
    C = 8  # oc=2 with 2x2 bins
    x = np.zeros((1, C, 6, 6), np.float32)
    for c in range(C):
        x[0, c] = c
    boxes = np.array([[0.0, 0.0, 6.0, 6.0]], np.float32)
    out = ops.psroi_pool(T(x), T(boxes), T([1], np.int32), 2).numpy()
    assert out.shape == (1, 2, 2, 2)
    for i in range(2):
        for j in range(2):
            assert out[0, 0, i, j] == (i * 2 + j) * 2
            assert out[0, 1, i, j] == (i * 2 + j) * 2 + 1


def test_roi_layers():
    x = T(np.random.default_rng(0).standard_normal((1, 4, 8, 8)), np.float32)
    boxes = T([[1.0, 1.0, 6.0, 6.0]])
    bn = T([1], np.int32)
    assert ops.RoIAlign(2)(x, boxes, bn).shape == [1, 4, 2, 2]
    assert ops.RoIPool(2)(x, boxes, bn).shape == [1, 4, 2, 2]
    assert ops.PSRoIPool(2)(x, boxes, bn).shape == [1, 1, 2, 2]


# ---------------------------------------------------------- deform conv


def test_deform_conv2d_zero_offset_is_conv():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
    w = rng.standard_normal((4, 3, 3, 3)).astype(np.float32)
    Ho = Wo = 8 - 2
    offset = np.zeros((2, 2 * 9, Ho, Wo), np.float32)
    got = ops.deform_conv2d(T(x), T(offset), T(w)).numpy()
    want = F.conv2d(T(x), T(w)).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_deform_conv2d_mask_scales_output():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((1, 2, 6, 6)).astype(np.float32)
    w = rng.standard_normal((2, 2, 3, 3)).astype(np.float32)
    offset = np.zeros((1, 18, 4, 4), np.float32)
    half = np.full((1, 9, 4, 4), 0.5, np.float32)
    full = np.ones((1, 9, 4, 4), np.float32)
    got_half = ops.deform_conv2d(T(x), T(offset), T(w), mask=T(half)).numpy()
    got_full = ops.deform_conv2d(T(x), T(offset), T(w), mask=T(full)).numpy()
    np.testing.assert_allclose(got_half, got_full * 0.5, rtol=1e-4, atol=1e-5)


def test_deform_conv2d_layer():
    layer = ops.DeformConv2D(3, 5, 3)
    x = T(np.random.default_rng(2).standard_normal((1, 3, 7, 7)), np.float32)
    offset = T(np.zeros((1, 18, 5, 5), np.float32))
    assert layer(x, offset).shape == [1, 5, 5, 5]


# ----------------------------------------------------------------- yolo


def test_yolo_box_decode():
    N, S, cls, H = 1, 2, 3, 4
    x = np.zeros((N, S * (5 + cls), H, H), np.float32)
    x[0, 4] = 10.0  # anchor 0: objectness ~1 everywhere
    out_boxes, out_scores = ops.yolo_box(
        T(x), paddle.to_tensor(np.array([[128, 128]], np.int32)),
        anchors=[10, 13, 16, 30], class_num=cls, conf_thresh=0.5,
        downsample_ratio=32)
    b = np.asarray(out_boxes.numpy())
    s = np.asarray(out_scores.numpy())
    assert b.shape == (1, H * H * S, 4) and s.shape == (1, H * H * S, cls)
    # anchor-0 entries survive the threshold, anchor-1 (conf=0.5 sigmoid(0))
    # fails 0.5 and is zeroed
    assert (np.abs(b).sum(-1) > 0).sum() == H * H
    # cell (0,0) anchor 0: center = (0.5/4)*128 = 16
    first = b[0, 0]
    cx = (first[0] + first[2]) / 2
    assert abs(cx - 16.0) < 1e-3


def test_yolo_loss_prefers_correct_prediction():
    rng = np.random.default_rng(0)
    N, S, cls, H = 1, 3, 2, 4
    anchors = [10, 13, 16, 30, 33, 23]
    gt_box = np.zeros((N, 2, 4), np.float32)
    gt_box[0, 0] = [0.4, 0.4, 0.2, 0.3]  # one real gt
    gt_label = np.zeros((N, 2), np.int32)
    random_pred = rng.standard_normal((N, S * (5 + cls), H, H)).astype(np.float32)
    loss_rand = float(np.asarray(ops.yolo_loss(
        T(random_pred), T(gt_box), paddle.to_tensor(gt_label), anchors,
        [0, 1, 2], cls, 0.7, 32).numpy())[0])
    assert np.isfinite(loss_rand) and loss_rand > 0
    # an all-negative-objectness prediction scores lower than random when
    # there is just one gt (most cells are background)
    neg = np.zeros_like(random_pred)
    neg[:, 4::5 + cls] = -10.0
    loss_neg = float(np.asarray(ops.yolo_loss(
        T(neg), T(gt_box), paddle.to_tensor(gt_label), anchors,
        [0, 1, 2], cls, 0.7, 32).numpy())[0])
    assert loss_neg < loss_rand


# -------------------------------------------------------- priors & coder


def test_prior_box_counts_and_range():
    feat = T(np.zeros((1, 8, 4, 4), np.float32))
    img = T(np.zeros((1, 3, 32, 32), np.float32))
    boxes, var = ops.prior_box(feat, img, min_sizes=[8.0], max_sizes=[16.0],
                               aspect_ratios=[2.0], flip=True, clip=True)
    b = np.asarray(boxes.numpy())
    # priors per cell: ar {1, 2, 0.5} on min + 1 sqrt(min*max) = 4
    assert b.shape == (4, 4, 4, 4)
    assert (b >= 0).all() and (b <= 1).all()
    assert np.asarray(var.numpy()).shape == b.shape
    np.testing.assert_allclose(np.asarray(var.numpy())[0, 0, 0],
                               [0.1, 0.1, 0.2, 0.2])


def test_box_coder_roundtrip():
    priors = np.array([[0, 0, 10, 10], [5, 5, 20, 25]], np.float32)
    targets = np.array([[1, 2, 8, 9], [6, 4, 18, 28]], np.float32)
    var = [0.1, 0.1, 0.2, 0.2]
    enc = ops.box_coder(T(priors), var, T(targets),
                        code_type="encode_center_size")
    dec = ops.box_coder(T(priors), var, enc, code_type="decode_center_size",
                        axis=0)
    d = np.asarray(dec.numpy())
    for i in range(2):
        np.testing.assert_allclose(d[i, i], targets[i], rtol=1e-4, atol=1e-4)


# ----------------------------------------------- fpn / proposals / io


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10],      # small -> low level
                     [0, 0, 300, 300]],   # large -> high level
                    np.float32)
    multi, restore = ops.distribute_fpn_proposals(T(rois), 2, 5, 4, 224)
    assert len(multi) == 4
    sizes = [m.shape[0] for m in multi]
    assert sum(sizes) == 2
    assert sizes[0] == 1 and sizes[-2] == 1 or sizes[-1] == 1
    r = np.asarray(restore.numpy()).ravel()
    assert sorted(r.tolist()) == [0, 1]


def test_generate_proposals():
    rng = np.random.default_rng(0)
    H = W = 4
    A = 2
    scores = rng.random((1, A, H, W)).astype(np.float32)
    deltas = (rng.standard_normal((1, 4 * A, H, W)) * 0.1).astype(np.float32)
    anchors = np.zeros((H, W, A, 4), np.float32)
    for i in range(H):
        for j in range(W):
            anchors[i, j, 0] = [j * 8, i * 8, j * 8 + 16, i * 8 + 16]
            anchors[i, j, 1] = [j * 8, i * 8, j * 8 + 24, i * 8 + 24]
    var = np.full((H, W, A, 4), 0.1, np.float32)
    rois, probs, num = ops.generate_proposals(
        T(scores), T(deltas), T([[32, 32]]), T(anchors), T(var),
        pre_nms_top_n=10, post_nms_top_n=5, return_rois_num=True)
    r = np.asarray(rois.numpy())
    p = np.asarray(probs.numpy())
    assert r.shape[0] == int(np.asarray(num.numpy())[0]) <= 5
    assert p.shape == (r.shape[0], 1)
    assert (np.diff(p[:, 0]) <= 1e-6).all()  # sorted by score
    assert (r[:, 0] >= 0).all() and (r[:, 2] <= 32).all()


def test_read_file_decode_jpeg(tmp_path):
    img = Image.fromarray(
        np.arange(64, dtype=np.uint8).reshape(8, 8), mode="L").convert("RGB")
    p = tmp_path / "t.jpg"
    img.save(p)
    raw = ops.read_file(str(p))
    assert raw.numpy().dtype == np.uint8
    dec = ops.decode_jpeg(raw, mode="rgb")
    assert np.asarray(dec.numpy()).shape == (3, 8, 8)


def test_conv_norm_activation():
    block = ops.ConvNormActivation(3, 8, kernel_size=3, stride=2)
    x = T(np.random.default_rng(3).standard_normal((2, 3, 16, 16)), np.float32)
    assert block(x).shape == [2, 8, 8, 8]


def test_roi_align_and_deform_conv_gradients_flow():
    """The detection heads must train: gradients reach the backbone feature
    map through roi_align, and DeformConv2D's own weights get grads."""
    rng = np.random.default_rng(4)
    x = paddle.to_tensor(
        rng.standard_normal((1, 2, 8, 8)).astype(np.float32))
    x.stop_gradient = False
    out = ops.roi_align(x, T([[1.0, 1.0, 6.0, 6.0]]), T([1], np.int32), 2)
    out.sum().backward()
    assert x.grad is not None
    assert float(np.abs(x.grad.numpy()).sum()) > 0

    layer = ops.DeformConv2D(2, 3, 3)
    offset = T(np.zeros((1, 18, 6, 6), np.float32))
    y = layer(x, offset)
    y.sum().backward()
    assert layer.weight.grad is not None
    assert float(np.abs(layer.weight.grad.numpy()).sum()) > 0

"""Wide&Deep / DeepFM sparse recommender models (benchmark config 5)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import DeepFM, WideDeep


def _batch(rng, b=32, fields=8, dense=4):
    ids = rng.integers(0, 1 << 40, (b, fields))  # arbitrary feature hashes
    x = rng.standard_normal((b, dense)).astype(np.float32)
    y = rng.integers(0, 2, (b, 1)).astype(np.float32)
    return ids, x, y


def test_widedeep_trains():
    paddle.seed(0)
    rng = np.random.default_rng(0)
    model = WideDeep(num_fields=8, num_dense=4, num_buckets=10007,
                     embedding_dim=8, hidden_sizes=(32, 32))
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
    step = TrainStep(lambda i, x, y: model.loss(model(i, x), y), opt, layers=model)
    ids, x, y = _batch(rng)
    losses = [float(step(paddle.to_tensor(ids), paddle.to_tensor(x),
                         paddle.to_tensor(y)).numpy()) for _ in range(10)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_deepfm_trains():
    paddle.seed(0)
    rng = np.random.default_rng(1)
    model = DeepFM(num_fields=8, num_dense=4, num_buckets=10007,
                   embedding_dim=8, hidden_sizes=(32,))
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
    step = TrainStep(lambda i, x, y: model.loss(model(i, x), y), opt, layers=model)
    ids, x, y = _batch(rng)
    losses = [float(step(paddle.to_tensor(ids), paddle.to_tensor(x),
                         paddle.to_tensor(y)).numpy()) for _ in range(10)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_widedeep_sharded_table():
    """Embedding table row-sharded over the model axis; step compiles."""
    paddle.seed(0)
    dist.init_hybrid_mesh(mp=4, dp=2)
    rng = np.random.default_rng(0)
    model = WideDeep(num_fields=8, num_dense=4, num_buckets=10008,
                     embedding_dim=8, hidden_sizes=(16,))
    assert "model" in str(model.embedding.weight._data.sharding.spec)
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
    step = TrainStep(lambda i, x, y: model.loss(model(i, x), y), opt, layers=model)
    ids, x, y = _batch(rng, b=16)
    loss = float(step(paddle.to_tensor(ids), paddle.to_tensor(x),
                      paddle.to_tensor(y)).numpy())
    assert np.isfinite(loss)

#!/usr/bin/env python
"""Framework lint CLI (``paddle_tpu.analysis``).

Usage:

    python tools/analyze.py                      # full suite + baseline gate
    python tools/analyze.py --changed            # only files modified vs main
    python tools/analyze.py paddle_tpu/serving   # explicit paths
    python tools/analyze.py --rules broad-except,unguarded-mutation
    python tools/analyze.py --json               # machine-readable findings
    python tools/analyze.py --no-baseline        # raw findings, no gate
    python tools/analyze.py --update-baseline    # accept current findings

Exit status: 0 = clean (no non-baseline findings), 1 = findings, 2 = usage
/ internal error.

``--changed`` lints only Python files modified vs the merge base with
``main`` (plus staged/unstracked changes) — the fast pre-commit loop. The
global-view ``dead-flag`` rule is disabled there (a subset of files cannot
prove a flag unread); everything else runs normally.

``--update-baseline`` rewrites ``tools/analysis_baseline.json`` from the
current findings, carrying existing ``why`` justifications forward by
``(rule, path, scope)`` key and stamping ``TODO: justify`` on new entries —
the gate test fails until every entry has a real one. Prefer inline
``# analysis: allow(<rule>) — <reason>`` for new code; the baseline exists
for pre-existing findings only. See docs/static_analysis.md.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import subprocess
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# the analyzers are pure AST — they must not import the framework they
# lint (no jax import cost, and a syntax error in the analyzed code can't
# take the linter down with it). Register a stub parent package so
# ``paddle_tpu.analysis`` loads WITHOUT executing ``paddle_tpu/__init__``.
if "paddle_tpu" not in sys.modules:
    _pkg = types.ModuleType("paddle_tpu")
    _pkg.__path__ = [os.path.join(_REPO, "paddle_tpu")]
    sys.modules["paddle_tpu"] = _pkg

analysis = importlib.import_module("paddle_tpu.analysis")
common = importlib.import_module("paddle_tpu.analysis.common")

DEFAULT_BASELINE = os.path.join(_REPO, "tools", "analysis_baseline.json")


def _changed_files() -> list:
    """Python files modified vs the merge base with main, plus working-tree
    changes (the pre-commit view)."""
    files = set()
    try:
        base = subprocess.run(
            ["git", "merge-base", "HEAD", "main"], cwd=_REPO,
            capture_output=True, text=True, check=True).stdout.strip()
        diff = subprocess.run(
            ["git", "diff", "--name-only", base], cwd=_REPO,
            capture_output=True, text=True, check=True).stdout
        files.update(diff.splitlines())
        # untracked files individually (`status --porcelain` collapses a
        # new DIRECTORY to one `dir/` entry, hiding every file inside it)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=_REPO, capture_output=True, text=True, check=True).stdout
        files.update(untracked.splitlines())
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        print(f"analyze: --changed needs git ({e})", file=sys.stderr)
        raise SystemExit(2)
    return sorted(f for f in files
                  if f.endswith(".py") and not f.startswith("tests/")
                  and os.path.exists(os.path.join(_REPO, f)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="analyze.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the framework)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files modified vs main (pre-commit)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule filter")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default tools/analysis_baseline"
                         ".json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report raw findings without the baseline gate")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(existing justifications carried forward)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for a in analysis.all_analyzers():
            for r in a.rules:
                print(f"{r:28s} ({a.name})")
        return 0

    rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    known = set(analysis.all_rules()) | {"suppression-missing-reason"}
    unknown = [r for r in rules if r not in known]
    if unknown:
        print(f"analyze: unknown rule(s) {unknown}; --list-rules shows "
              f"the set", file=sys.stderr)
        return 2

    paths = args.paths or None
    full = paths is None
    if args.changed:
        paths = _changed_files()
        full = False
        if not paths:
            print("analyze: no changed Python files vs main")
            return 0
        # the flag registry itself must always be in the corpus so
        # undefined-flag can resolve references from the changed files
        if "paddle_tpu/core/flags.py" not in paths:
            paths = list(paths) + ["paddle_tpu/core/flags.py"]

    report = analysis.run_analysis(paths, root=_REPO, rules=rules or None,
                                   full_corpus=full)

    if args.update_baseline:
        if not full:
            # rewriting from a subset view would silently DELETE every
            # baseline entry for files outside the scanned corpus (and
            # their hand-written justifications)
            print("analyze: --update-baseline requires a full run — drop "
                  "--changed / explicit paths, or baseline by hand",
                  file=sys.stderr)
            return 2
        old = {e.key(): e for e in common.load_baseline(args.baseline)}
        entries = {}
        for f in report.findings:
            if f.key() in entries:
                continue
            prev = old.get(f.key())
            entries[f.key()] = common.BaselineEntry(
                f.rule, f.path, f.scope,
                prev.why if prev is not None and prev.why else
                "TODO: justify")
        common.save_baseline(args.baseline, entries.values())
        print(f"analyze: wrote {len(entries)} baseline entries to "
              f"{os.path.relpath(args.baseline, _REPO)}")
        return 0

    new, stale = report.findings, []
    if not args.no_baseline and os.path.exists(args.baseline):
        baseline = common.load_baseline(args.baseline)
        new, stale = report.apply_baseline(baseline)
        if full and stale:
            for e in stale:
                print(f"stale baseline entry (matches nothing): "
                      f"[{e.rule}] {e.path} :: {e.scope}", file=sys.stderr)

    if args.as_json:
        print(json.dumps({
            "files": report.files,
            "elapsed_sec": round(report.elapsed, 3),
            "findings": [{"rule": f.rule, "path": f.path, "line": f.line,
                          "scope": f.scope, "message": f.message}
                         for f in new],
            "suppressed": len(report.suppressed),
            "stale_baseline": [{"rule": e.rule, "path": e.path,
                                "scope": e.scope} for e in stale],
            "parse_errors": report.parse_errors,
        }, indent=1))
    else:
        for f in new:
            print(str(f))
        for path, err in report.parse_errors.items():
            print(f"{path}: parse error: {err}", file=sys.stderr)
        print(f"analyze: {len(new)} finding(s) "
              f"({len(report.suppressed)} suppressed inline, "
              f"{len(report.findings) - len(new)} baselined) over "
              f"{report.files} files in {report.elapsed:.2f}s")
    return 1 if new or (full and stale) else 0


if __name__ == "__main__":
    raise SystemExit(main())

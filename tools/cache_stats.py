#!/usr/bin/env python
"""Compile-cache stats CLI: print hit/miss/compile-time counters for the
persistent XLA cache and the in-process caches, or inspect/clear the cache
directory itself.

Usage:
    python tools/cache_stats.py                 # inspect the on-disk cache
    python tools/cache_stats.py --run CMD ...   # run CMD..., then report the
                                                # run's counters (in-process)
    python tools/cache_stats.py --clear         # delete cache entries
    python tools/cache_stats.py --json          # machine-readable output

Without --run this only inspects the directory (entry count / bytes /
newest entry age) — it never initializes a jax backend, so it is safe on a
host whose TPU tunnel is down. With --run, CMD executes in-process via
runpy with the framework imported first, and the delta of
``core.compile_cache.stats()`` across the run is reported — warm runs show
``persistent.hits`` > 0 and near-zero ``compile.backend_secs``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _dir_report(d: str) -> dict:
    out = {"dir": d, "exists": os.path.isdir(d), "entries": 0, "bytes": 0,
           "newest_age_secs": None}
    if not out["exists"]:
        return out
    newest = 0.0
    for name in os.listdir(d):
        if not name.endswith("-cache"):
            continue
        p = os.path.join(d, name)
        try:
            st = os.stat(p)
        except OSError:
            continue
        out["entries"] += 1
        out["bytes"] += st.st_size
        newest = max(newest, st.st_mtime)
    if newest:
        out["newest_age_secs"] = round(time.time() - newest, 1)
    return out


def _resolve_dir(args) -> str:
    if args.dir:
        return args.dir
    # mirror core.compile_cache precedence without importing jax
    return (os.environ.get("FLAGS_xla_compile_cache_dir")
            or os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                            "xla"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", help="cache directory (default: the framework's "
                                  "resolution order)")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--clear", action="store_true",
                    help="delete cache entries in the directory")
    ap.add_argument("--run", nargs=argparse.REMAINDER,
                    help="script [args...] to execute in-process; counters "
                         "are reported for that run")
    args = ap.parse_args(argv)
    d = _resolve_dir(args)

    if args.clear:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from paddle_tpu.core import compile_cache

        n = compile_cache.clear(d)
        print(f"removed {n} cache file(s) from {d}")
        return 0

    if args.run:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import runpy

        from paddle_tpu.core import compile_cache

        before = compile_cache.stats()
        t0 = time.perf_counter()
        sys.argv = list(args.run)
        runpy.run_path(args.run[0], run_name="__main__")
        wall = time.perf_counter() - t0
        delta = {k: v for k, v in compile_cache.stats_delta(
                     before, compile_cache.stats(), drop_zero=True).items()
                 if isinstance(v, (int, float)) and not isinstance(v, bool)}
        rec = {"wall_secs": round(wall, 3), "stats": delta,
               "cache_dir": compile_cache.cache_dir(), **_dir_report(d)}
        print(json.dumps(rec) if args.json else
              "\n".join([f"wall_secs: {rec['wall_secs']}"]
                        + [f"{k}: {v}" for k, v in sorted(delta.items())]))
        return 0

    rep = _dir_report(d)
    if args.json:
        print(json.dumps(rep))
    else:
        for k, v in rep.items():
            print(f"{k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

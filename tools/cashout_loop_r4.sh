#!/bin/bash
# Probe-gated retry loop for the remaining round-4 TPU bank. The tunnel came
# up once this round (bench.py cashed: MFU 0.159 at b16 s1024), then died
# mid-sequence. Probe every ~50 min; on success run the remaining stages in
# value order. Stages that already succeeded are skipped via marker files.
set -u
cd "$(dirname "$0")/.."
LOGS=benches/tpu_logs
MARKS=$LOGS/done
mkdir -p "$LOGS" "$MARKS"

probe() {
  timeout 180 python - <<'PY'
import jax, numpy as np, time
t0 = time.time()
y = jax.jit(lambda a: a @ a)(np.ones((256, 256), np.float32))
y.block_until_ready()
d = jax.devices()[0]
assert d.platform != "cpu", f"probe landed on {d.platform}"
print(f"TPU alive: {d} matmul in {time.time()-t0:.1f}s")
PY
}

run() {  # run <name> <timeout_s> <cmd...> — skipped once marked done
  local name=$1 t=$2; shift 2
  [ -f "$MARKS/$name" ] && { echo "[loop] $name already done"; return 0; }
  local STAMP=$(date +%Y%m%d_%H%M%S)
  echo "[loop] $name ..."
  timeout "$t" "$@" > "$LOGS/${name}_$STAMP.log" 2>&1
  local rc=$?
  tail -2 "$LOGS/${name}_$STAMP.log"
  echo "[loop] $name rc=$rc"
  # mark done only on success so a hang retries next window
  [ "$rc" -eq 0 ] && touch "$MARKS/$name"
  return $rc
}

attempt=0
while true; do
  attempt=$((attempt + 1))
  echo "[loop] attempt $attempt $(date)"
  if probe > "$LOGS/probe_loop_$attempt.log" 2>&1; then
    cat "$LOGS/probe_loop_$attempt.log"
    run flash_tpu 2400 python benches/flash_tpu_bench.py
    run sweep    10800 python benches/sweep.py
    run baseline  7200 python benches/baseline.py lenet resnet50 ernie gpt-hybrid widedeep
    run decode    2400 python benches/decode_bench.py
    run eager     1800 python tools/eager_bench.py
    run hlo_tpu   2400 env HLO_PLATFORM=tpu python tools/hlo_analysis.py
    run native    1800 env PADDLE_TPU_NATIVE_TPU_TEST=1 python -m pytest tests/test_native_infer.py -k real_plugin -q
    if [ -f "$MARKS/flash_tpu" ] && [ -f "$MARKS/sweep" ] && [ -f "$MARKS/baseline" ] \
       && [ -f "$MARKS/decode" ] && [ -f "$MARKS/eager" ] && [ -f "$MARKS/hlo_tpu" ] \
       && [ -f "$MARKS/native" ]; then
      echo "[loop] all stages done"
      break
    fi
  else
    echo "[loop] tunnel down (see $LOGS/probe_loop_$attempt.log)"
  fi
  sleep 3000
done

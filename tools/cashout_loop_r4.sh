#!/bin/bash
# Probe-gated retry loop for the round-4 TPU bank. The tunnel flaps between
# alive / fast-fail / hang many times a round, so the probe runs BEFORE
# EVERY STAGE — a mid-pass tunnel death costs at most the one stage that
# was running, not the sum of all remaining stage timeouts. Stages that
# succeeded are skipped via marker files, so passes resume where they left
# off on the next window.
set -u
cd "$(dirname "$0")/.."
LOGS=benches/tpu_logs
MARKS=$LOGS/done
mkdir -p "$LOGS" "$MARKS"

probe() {
  timeout 180 python - <<'PY'
import jax, numpy as np, time
t0 = time.time()
y = jax.jit(lambda a: a @ a)(np.ones((256, 256), np.float32))
y.block_until_ready()
d = jax.devices()[0]
assert d.platform != "cpu", f"probe landed on {d.platform}"
print(f"TPU alive: {d} matmul in {time.time()-t0:.1f}s")
PY
}

run() {  # run <name> <timeout_s> <cmd...> — marked done only on success
  local name=$1 t=$2; shift 2
  local STAMP=$(date +%Y%m%d_%H%M%S)
  echo "[loop] $name ..."
  timeout "$t" "$@" > "$LOGS/${name}_$STAMP.log" 2>&1
  local rc=$?
  tail -2 "$LOGS/${name}_$STAMP.log"
  echo "[loop] $name rc=$rc"
  [ "$rc" -eq 0 ] && touch "$MARKS/$name"
  return $rc
}

# value order; "name timeout cmd..." — bench_routed first: the headline
# number with the measured attention routing is the highest-value datum
# per tunnel minute (one compile, ~15 min), so it lands in ANY window
# before the multi-hour sweep starts eating the rest
STAGES=(
  "bench_routed 2400 python bench.py"
  "flash_tpu 2400 python benches/flash_tpu_bench.py"
  "sweep 10800 python benches/sweep.py"
  "baseline 7200 python benches/baseline.py lenet resnet50 ernie gpt-hybrid widedeep"
  "decode 2400 python benches/decode_bench.py"
  "eager 1800 python tools/eager_bench.py"
  "hlo_tpu 2400 env HLO_PLATFORM=tpu python tools/hlo_analysis.py"
  "native 1800 env PADDLE_TPU_NATIVE_TPU_TEST=1 python -m pytest tests/test_native_infer.py -k real_plugin -q"
)

attempt=0
while true; do
  attempt=$((attempt + 1))
  echo "[loop] pass $attempt $(date)"
  for spec in "${STAGES[@]}"; do
    read -r name t cmd <<<"$spec"
    [ -f "$MARKS/$name" ] && continue
    if ! probe > "$LOGS/probe_${attempt}_${name}.log" 2>&1; then
      echo "[loop] tunnel down before $name (pass $attempt)"
      break
    fi
    cat "$LOGS/probe_${attempt}_${name}.log"
    run "$name" "$t" $cmd || true
  done
  remaining=0
  for spec in "${STAGES[@]}"; do
    read -r name t cmd <<<"$spec"
    [ -f "$MARKS/$name" ] || remaining=1
  done
  if [ "$remaining" -eq 0 ]; then
    echo "[loop] all stages done $(date)"
    break
  fi
  sleep 3000
done

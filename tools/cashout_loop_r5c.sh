#!/bin/bash
# Round-5 probe-gated TPU cashout loop. Priority per VERDICT r4 "Next round":
#   1. sweep (807M over-bar config is POINTS[0]) — the round's one job
#   2. bench (default headline; also primes the persistent compile cache so
#      the driver's end-of-round `python bench.py` is warm + fast)
#   3. bench_tuned (re-run with the sweep's winning knobs → warms ITS cache
#      entry, which the driver's run now picks up by default)
#   4. flash_tune → re-measure break-even → rest of the bank
# Probe runs before EVERY stage; marker files make passes resumable.
set -u
cd "$(dirname "$0")/.."
LOGS=benches/tpu_logs
MARKS=$LOGS/done_r5
mkdir -p "$LOGS" "$MARKS"

probe() {
  timeout 180 python - <<'PY'
import jax, numpy as np, time
t0 = time.time()
y = jax.jit(lambda a: a @ a)(np.ones((256, 256), np.float32))
y.block_until_ready()
d = jax.devices()[0]
assert d.platform != "cpu", f"probe landed on {d.platform}"
print(f"TPU alive: {d} matmul in {time.time()-t0:.1f}s")
PY
}

run() {  # run <name> <timeout_s> <cmd...> — marked done only on success
  local name=$1 t=$2; shift 2
  local STAMP=$(date +%Y%m%d_%H%M%S)
  echo "[loop] $name ..."
  timeout "$t" "$@" > "$LOGS/r5_${name}_$STAMP.log" 2>&1
  local rc=$?
  tail -2 "$LOGS/r5_${name}_$STAMP.log"
  echo "[loop] $name rc=$rc"
  [ "$rc" -eq 0 ] && touch "$MARKS/$name"
  return $rc
}

STAGES=(
  "sweep 14400 python benches/sweep.py"
  "sweep2 10800 env SWEEP_POINTS_JSON=benches/sweep2_points.json python benches/sweep.py"
  "sweep3 10800 env SWEEP_POINTS_JSON=benches/sweep3_points.json python benches/sweep.py"
  "bench_headline 2400 env BENCH_USE_TUNED=0 python bench.py"
  "bench_tuned 2400 python bench.py"
  "flash_tune 2400 python benches/flash_tune.py"
  "flash_tpu 2400 python benches/flash_tpu_bench.py"
  "baseline 7200 python benches/baseline.py lenet resnet50 ernie gpt-hybrid widedeep"
  "decode 2400 python benches/decode_bench.py"
  "eager 1800 python tools/eager_bench.py"
  "hlo_tpu 2400 env HLO_PLATFORM=tpu python tools/hlo_analysis.py"
  "native 1800 env PADDLE_TPU_NATIVE_TPU_TEST=1 python -m pytest tests/test_native_infer.py -k real_plugin -q"
)

attempt=0
while true; do
  attempt=$((attempt + 1))
  echo "[loop] pass $attempt $(date)"
  for spec in "${STAGES[@]}"; do
    read -r name t cmd <<<"$spec"
    [ -f "$MARKS/$name" ] && continue
    # bench_tuned only means something after the sweep published a winner
    # that bench.py's mfu>0.16 gate will actually adopt — running earlier
    # (or on an under-bar winner) would just duplicate bench_headline and
    # never warm the tuned config's cache entry. Mirror the gate here.
    if [ "$name" = bench_tuned ]; then
      # plain json check — strip the axon env so sitecustomize's register()
      # (which dials the tunnel at interpreter start and can hang) is skipped
      timeout 60 env -u PALLAS_AXON_POOL_IPS python - <<'PY' || continue
import json, sys
try:
    rec = json.load(open("benches/BENCH_TUNED.json"))
except Exception:
    sys.exit(1)
sys.exit(0 if not rec.get("error") and (rec.get("mfu") or 0) > 0.16 else 1)
PY
    fi
    if ! probe > "$LOGS/r5_probe_${attempt}_${name}.log" 2>&1; then
      echo "[loop] tunnel down before $name (pass $attempt)"
      break
    fi
    cat "$LOGS/r5_probe_${attempt}_${name}.log"
    run "$name" "$t" $cmd || true
  done
  remaining=0
  for spec in "${STAGES[@]}"; do
    read -r name t cmd <<<"$spec"
    [ -f "$MARKS/$name" ] || remaining=1
  done
  if [ "$remaining" -eq 0 ]; then
    echo "[loop] all stages done $(date)"
    break
  fi
  sleep 600
done

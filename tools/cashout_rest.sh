#!/bin/bash
# Post-sweep remainder of the banked TPU sequence (tools/tpu_cashout.sh
# stages minus sweep/bench which ran first this round). Waits for any
# running sweep/bench process to exit so two processes never contend for
# the single tunneled chip.
set -u
cd "$(dirname "$0")/.."
LOGS=benches/tpu_logs
mkdir -p "$LOGS"
STAMP=$(date +%Y%m%d_%H%M%S)

while pgrep -f "benches/sweep.py|/bench.py" > /dev/null; do sleep 30; done

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 t=$2; shift 2
  echo "[cashout-rest] $name ..."
  timeout "$t" "$@" > "$LOGS/${name}_$STAMP.log" 2>&1
  local rc=$?
  tail -2 "$LOGS/${name}_$STAMP.log"
  echo "[cashout-rest] $name rc=$rc"
}

run flash_tpu 3600 python benches/flash_tpu_bench.py
run baseline  7200 python benches/baseline.py lenet resnet50 ernie gpt-hybrid widedeep
run decode    2400 python benches/decode_bench.py
run eager     1800 python tools/eager_bench.py
run hlo_tpu   2400 env HLO_PLATFORM=tpu python tools/hlo_analysis.py
run native    1800 env PADDLE_TPU_NATIVE_TPU_TEST=1 python -m pytest tests/test_native_infer.py -k real_plugin -q
echo "[cashout-rest] done"

"""Eager-dispatch overhead microbenchmark.

The reference gates per-op perf in CI (ref:tools/ci_op_benchmark.sh). Here
the eager hot loop is Python -> dispatch.apply -> per-(op, shape) jax.jit
cache -> PJRT; this tool measures µs/op for representative ops, the same
chain fully compiled (one program), and the framework overhead ratio.

Writes one JSON line; run with BENCH_RECORD=path to append to a budget file.
A budget: eager dispatch should stay under ~150µs/op on CPU-class hosts
(SURVEY.md §3.1 flags the per-op boundary as the dygraph hot-loop risk).
"""
from __future__ import annotations

import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "benches"))

import numpy as np


def main():
    import jax

    from _common import enable_compile_cache  # benches/ shared setup

    enable_compile_cache()
    # the sandbox sitecustomize force-pins a (possibly wedged) remote TPU
    # platform; EAGER_BENCH_PLATFORM=cpu pins the backend BEFORE any device
    # touch so a dead tunnel can't hang the tool
    plat = os.environ.get("EAGER_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    import paddle_tpu as paddle

    dev = jax.devices()[0]
    x = paddle.to_tensor(np.random.rand(256, 256).astype(np.float32))
    y = paddle.to_tensor(np.random.rand(256, 256).astype(np.float32))
    # unique inputs per iteration: through the tunneled backend an
    # identical (program, inputs) execution can be served from the
    # relay's replay cache; the host-side dispatch being measured is
    # identical either way, but the device part must be real too
    n = 200
    xs = [paddle.to_tensor(np.random.rand(256, 256).astype(np.float32))
          for _ in range(n)]
    # materialize every input on device BEFORE timing: the first op over a
    # lazily-uploaded tensor would otherwise absorb 200 H2D transfers into
    # whichever op runs first (observed: "add" at 10.4 ms/op on TPU)
    for t in xs:
        t._data = jax.device_put(t._data)
    jax.block_until_ready([t._data for t in xs])

    ops = {
        "add": lambda xi: paddle.add(xi, y),
        "matmul": lambda xi: paddle.matmul(xi, y),
        "relu": lambda xi: paddle.nn.functional.relu(xi),
        "sum": lambda xi: paddle.sum(xi),
        "transpose": lambda xi: paddle.transpose(xi, [1, 0]),
    }

    results = {}
    first = True
    for name, f in ops.items():
        f(x)  # compile/cache
        if first:
            # one untimed pass: the first sustained burst after session
            # start pays a relay ramp-up (~10 ms/op observed) that is not
            # steady-state dispatch; prime it off the clock
            for xi in xs:
                out = f(xi)
            np.asarray(out._data if hasattr(out, "_data") else out)
            first = False
        t0 = time.perf_counter()
        for xi in xs:
            out = f(xi)
        np.asarray(out._data if hasattr(out, "_data") else out)
        results[name] = (time.perf_counter() - t0) / n * 1e6  # µs/op

    # raw jax.jit equivalents: same math, no framework — the difference IS
    # the dispatch overhead (per-op timings above include real compute,
    # e.g. the 256x256 matmul itself)
    import jax.numpy as jnp

    raw_ops = {
        "add": jax.jit(lambda a, b: a + b),
        "matmul": jax.jit(lambda a, b: a @ b),
        "relu": jax.jit(lambda a, b: jnp.maximum(a, 0)),
        "sum": jax.jit(lambda a, b: a.sum()),
        "transpose": jax.jit(lambda a, b: a.T),
    }
    raw = {}
    xds = [t._data for t in xs]
    for name, f in raw_ops.items():
        f(x._data, y._data)
        t0 = time.perf_counter()
        for xd in xds:
            out = f(xd, y._data)
        np.asarray(out)
        raw[name] = (time.perf_counter() - t0) / n * 1e6
    overhead = {k: max(results[k] - raw[k], 0.0) for k in results}

    # the same 5-op chain as ONE compiled program
    def chain(xa, ya):
        import jax.numpy as jnp

        a = xa + ya
        b = a @ ya
        c = jnp.maximum(b, 0)
        return c.sum() + xa.T.sum()

    cf = jax.jit(chain)
    cf(x._data, y._data)
    t0 = time.perf_counter()
    for xd in xds:
        out = cf(xd, y._data)
    np.asarray(out)
    compiled_us = (time.perf_counter() - t0) / n * 1e6

    eager_mean = float(np.mean(list(results.values())))
    overhead_mean = float(np.mean(list(overhead.values())))
    rec = {
        "metric": "eager dispatch overhead",
        "unit": "us/op",
        "platform": dev.platform,
        "per_op_us": {k: round(v, 1) for k, v in results.items()},
        "raw_jax_us": {k: round(v, 1) for k, v in raw.items()},
        "overhead_us": {k: round(v, 1) for k, v in overhead.items()},
        "eager_mean_us": round(eager_mean, 1),
        "overhead_mean_us": round(overhead_mean, 1),
        "compiled_chain_us": round(compiled_us, 1),
        "overhead_ratio": round(eager_mean * len(results) / max(compiled_us, 1e-9), 2),
        "budget_us": 150.0,
        "within_budget": overhead_mean <= 150.0,
    }
    line = json.dumps(rec)
    print(line)
    path = os.environ.get("BENCH_RECORD")
    if path:
        with open(path, "a") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()

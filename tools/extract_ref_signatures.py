"""Extract argument signatures from the reference's Python source (AST —
the reference package is not importable here) into tools/ref_signatures.json.

For every name in ref_surface.json's audited surfaces this records the
reference def's parameter list: names in order, defaults (repr), vararg/
kwarg flags. Functions come from top-level ``def``s; classes contribute
their ``__init__``. When a name is defined in several reference modules the
module whose path best matches the surface wins (e.g. paddle.nn names
prefer python/paddle/nn/).

Usage: python tools/extract_ref_signatures.py   (rewrites ref_signatures.json)
"""
from __future__ import annotations

import ast
import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))
REF = "/root/reference/python/paddle"

# surfaces audited for signatures (the verdict's "top surfaces") and the
# reference path fragments that rank candidate defs for each
SURFACES = {
    # hapi ranks above the bare-paddle fallback: paddle.flops/summary/Model
    # bind from hapi, and utils/ holds same-named internal helpers
    "paddle": ["paddle/tensor/", "paddle/framework/", "paddle/hapi/",
               "paddle/"],
    "paddle.Tensor": ["paddle/tensor/"],
    "paddle.nn": ["paddle/nn/layer/", "paddle/nn/"],
    "paddle.nn.functional": ["paddle/nn/functional/"],
    "paddle.optimizer": ["paddle/optimizer/"],
    "paddle.optimizer.lr": ["paddle/optimizer/lr"],
    "paddle.linalg": ["paddle/tensor/linalg", "paddle/tensor/"],
    "paddle.fft": ["paddle/fft"],
    "paddle.signal": ["paddle/signal"],
    "paddle.distribution": ["paddle/distribution/"],
    "paddle.vision.transforms": ["paddle/vision/transforms/"],
    "paddle.metric": ["paddle/metric/"],
    "paddle.sparse": ["paddle/sparse/"],
}

SKIP_DIRS = {"fluid", "tests", "incubate", "distributed"}


def _default_repr(node):
    try:
        return repr(ast.literal_eval(node))
    except Exception:
        return ast.unparse(node)


def _sig_of(fn: ast.FunctionDef):
    a = fn.args
    pos = [p.arg for p in a.posonlyargs + a.args]
    n_def = len(a.defaults)
    defaults = {}
    if n_def:
        for p, d in zip(pos[-n_def:], a.defaults):
            defaults[p] = _default_repr(d)
    kwonly = [p.arg for p in a.kwonlyargs]
    for p, d in zip(kwonly, a.kw_defaults):
        if d is not None:
            defaults[p] = _default_repr(d)
    return {
        "params": pos + kwonly,
        "defaults": defaults,
        "vararg": a.vararg.arg if a.vararg else None,
        "kwarg": a.kwarg.arg if a.kwarg else None,
    }


def _index_reference():
    """name -> [(path, sig_dict)] over all top-level defs and class __init__s."""
    fns, classes = {}, {}
    for root, dirs, files in os.walk(REF):
        rel = os.path.relpath(root, REF)
        parts = set(rel.split(os.sep))
        if parts & SKIP_DIRS:
            dirs[:] = []
            continue
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            try:
                tree = ast.parse(open(path, encoding="utf-8").read())
            except SyntaxError:
                continue
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fns.setdefault(node.name, []).append((path, _sig_of(node)))
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, ast.FunctionDef) and \
                                sub.name == "__init__":
                            classes.setdefault(node.name, []).append(
                                (path, _sig_of(sub)))
                            break
    return fns, classes


def _pick(cands, prefs):
    """Best candidate by path-fragment preference order."""
    for frag in prefs:
        for path, sig in cands:
            if frag in path.replace("\\", "/"):
                return sig, path
    return cands[0][1], cands[0][0]


def main():
    surface = json.load(open(os.path.join(HERE, "ref_surface.json")))
    fns, classes = _index_reference()
    out = {}
    for mod, prefs in SURFACES.items():
        names = surface.get(mod, [])
        entry = {}
        for n in names:
            cands = fns.get(n, []) + classes.get(n, [])
            if not cands:
                continue
            sig, path = _pick(cands, prefs)
            sig = dict(sig)
            sig["ref"] = os.path.relpath(path, "/root/reference")
            entry[n] = sig
        out[mod] = entry
        print(f"{mod:24s} {len(entry):4d}/{len(names):4d} signatures")
    with open(os.path.join(HERE, "ref_signatures.json"), "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)


if __name__ == "__main__":
    main()

"""Compiler-level perf evidence for the GPT training step (VERDICT r4 #1b).

With the TPU tunnel dead, this extracts what the compiler itself knows:
jit(TrainStep).lower().compile().cost_analysis() at the REAL bench shapes
(GPT-base 768h/12L, b16 s1024, bf16 autocast — the exact program bench.py
times on hardware), plus HLO-text statistics (fusion counts, remat
duplication, collective ops) and a v5e roofline projection.

The compile target here is XLA:CPU (no chip): analytic FLOPs are
backend-independent (counted from HLO dot/conv shapes); bytes-accessed is
layout-dependent and treated as an upper-bound estimate. Both are stated
with that caveat in the generated report.

Usage: python tools/hlo_analysis.py [out_md]
Writes benches/HLO_ANALYSIS.md and prints a summary JSON line.
HLO_PLATFORM=tpu compiles for the live TPU backend instead (run from
tpu_cashout.sh once the tunnel answers): bytes-accessed then reflects real
bf16 TPU layouts and TPU fusion, replacing the CPU upper bound.
"""
from __future__ import annotations

import json
import os
import re
import sys

_PLAT = os.environ.get("HLO_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _PLAT

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

import jax  # noqa: E402

if _PLAT == "cpu":
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

V5E_PEAK_BF16 = 197e12   # FLOP/s, public spec
V5E_HBM_BW = 819e9       # bytes/s
BATCH, SEQ = 16, 1024


def build_step(remat: bool, hidden=768, layers=12, batch=BATCH, seq=SEQ,
               amp_level="O1", chunk=0, scan=False):
    import paddle_tpu  # noqa: F401  (registers ops)
    from paddle_tpu import amp
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.optimizer import AdamW

    cfg = GPTConfig(vocab_size=50304, hidden_size=hidden, num_layers=layers,
                    num_heads=max(1, hidden // 64),
                    max_position_embeddings=2048,
                    use_recompute=remat, loss_chunk_size=chunk,
                    use_scan_layers=scan)
    model = GPTForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                weight_decay=0.01)
    if amp_level == "O2":
        amp.decorate(model, opt, level="O2")

    def loss_fn(x, y):
        # always O1 autocast: bench.py's BENCH_AMP=O2 means decorate(O2)
        # (bf16 params + master slots) UNDER O1 autocast — this compiles
        # the exact program the sweep's "O2" rows time on hardware
        with amp.auto_cast(level="O1", dtype="bfloat16"):
            return model(x, y)

    step = TrainStep(loss_fn, opt, layers=model)
    step._build()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    x, y = Tensor(ids), Tensor(np.roll(ids, -1, axis=1))
    param_arrays = tuple(p._data for p in step._train_params)
    buffer_arrays = tuple(b._data for b in step._buffers)
    opt_state = {
        "slots": [opt._init_slot(p._data) for p in step._train_params],
        "step": jnp.zeros((), jnp.int32),
    }
    lr = jnp.asarray(1e-4, jnp.float32)
    from paddle_tpu.core import rng as prng

    key = prng.next_key()
    args = (x, y)
    return cfg, step, (param_arrays, buffer_arrays, opt_state, lr, key, args)


def analyze(remat: bool, **kw):
    cfg, step, call_args = build_step(remat, **kw)
    lowered = step._jit_fn.lower(*call_args)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    hlo = compiled.as_text()
    stats = {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "transcendentals": float(ca.get("transcendentals", 0.0)),
        "hlo_instructions": hlo.count("\n"),
        "fusions": len(re.findall(r"^\s*\S+ = .* fusion\(", hlo, re.M)),
        "dots": len(re.findall(r"\bdot\(", hlo)),
        "custom_calls": len(re.findall(r"custom-call", hlo)),
        "while_loops": len(re.findall(r"^\s*\S+ = .* while\(", hlo, re.M)),
        "all_reduces": len(re.findall(r"all-reduce", hlo)),
    }
    n_params = int(sum(int(np.prod(p.shape)) for p in call_args[0]))
    return cfg, stats, n_params


def model_flops(cfg, batch=BATCH, seq=SEQ) -> float:
    """Analytic 6N-per-token training FLOPs for the bench shapes (the same
    accounting bench.py uses for MFU)."""
    h, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    i = cfg.intermediate_size
    n_matmul = L * (4 * h * h + 2 * h * i) + h * V
    attn = 6 * L * seq * h
    per_token = 6.0 * n_matmul + attn
    return per_token * batch * seq


def main():
    default_name = ("HLO_ANALYSIS.md" if _PLAT == "cpu"
                    else f"HLO_ANALYSIS_{_PLAT.upper()}.md")
    out_md = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(HERE), "benches", default_name)
    rows = {}
    for remat in (False, True):
        cfg, stats, n_params = analyze(remat)
        rows[remat] = stats
    mf = model_flops(cfg)

    def project(stats):
        t_flops = stats["flops"] / V5E_PEAK_BF16
        t_mem = stats["bytes_accessed"] / V5E_HBM_BW
        t = max(t_flops, t_mem)
        return {
            "t_flops_ms": t_flops * 1e3,
            "t_mem_ms": t_mem * 1e3,
            "bound": "memory" if t_mem > t_flops else "compute",
            "proj_step_ms": t * 1e3,
            "proj_tokens_per_sec": BATCH * SEQ / t,
            "proj_mfu": mf / (t * V5E_PEAK_BF16),
        }

    proj = {k: project(v) for k, v in rows.items()}
    # bf16 layouts roughly halve the CPU fp32-biased traffic estimate
    proj_bf16 = {k: project({**v, "bytes_accessed": v["bytes_accessed"] / 2})
                 for k, v in rows.items()}
    # what the 0.35 MFU target structurally requires of HBM traffic
    t_target = mf / (0.35 * V5E_PEAK_BF16)
    bytes_for_target = t_target * V5E_HBM_BW
    summary = {
        "model": f"GPT {cfg.hidden_size}h/{cfg.num_layers}L b{BATCH} s{SEQ}",
        "params": n_params,
        "model_flops_per_step": mf,
        "hlo_flops_per_step": rows[False]["flops"],
        "flops_overhead_vs_6N": rows[False]["flops"] / mf,
        "remat_flops_ratio": rows[True]["flops"] / rows[False]["flops"],
        "proj_mfu_no_remat": round(proj[False]["proj_mfu"], 3),
        "proj_mfu_remat": round(proj[True]["proj_mfu"], 3),
    }

    lines = [
        "# HLO cost analysis — GPT training step at bench shapes",
        "",
        "Generated by `tools/hlo_analysis.py` (XLA:CPU compile of the exact",
        "jitted TrainStep bench.py runs; no TPU needed). FLOPs are counted",
        "from HLO op shapes and are backend-independent; bytes-accessed is",
        "an XLA:CPU estimate (fp32-biased layouts) — treat the memory-side",
        "numbers as upper bounds for a bf16 TPU executable.",
        "",
        f"Model: **{summary['model']}**, {n_params / 1e6:.1f}M params, "
        f"bf16 autocast O1, AdamW, donated buffers.",
        "",
        "| metric | no remat | full remat |",
        "|---|---|---|",
    ]
    fmt = [
        ("HLO FLOPs/step", "flops", "{:.3e}"),
        ("bytes accessed/step", "bytes_accessed", "{:.3e}"),
        ("transcendentals", "transcendentals", "{:.2e}"),
        ("HLO instructions", "hlo_instructions", "{}"),
        ("fusions", "fusions", "{}"),
        ("dot ops", "dots", "{}"),
        ("while loops (scan)", "while_loops", "{}"),
    ]
    for label, key, f in fmt:
        lines.append(f"| {label} | {f.format(rows[False][key])} | "
                     f"{f.format(rows[True][key])} |")
    lines += [
        "",
        f"Analytic model FLOPs (6N accounting, the bench's MFU denominator): "
        f"**{mf:.3e}/step** — the compiled program issues "
        f"{summary['flops_overhead_vs_6N']:.2f}x that "
        "(backward + optimizer + attention softmax overhead).",
        f"Rematerialization multiplies issued FLOPs by "
        f"{summary['remat_flops_ratio']:.2f}x (recompute of checkpointed "
        "activations in the backward).",
        "",
        "## v5e roofline projection (197 TF/s bf16, 819 GB/s HBM)",
        "",
        "| | no remat | full remat |",
        "|---|---|---|",
        f"| compute time/step | {proj[False]['t_flops_ms']:.1f} ms | "
        f"{proj[True]['t_flops_ms']:.1f} ms |",
        f"| memory time/step (upper bound) | {proj[False]['t_mem_ms']:.1f} ms"
        f" | {proj[True]['t_mem_ms']:.1f} ms |",
        f"| bound | {proj[False]['bound']} | {proj[True]['bound']} |",
        f"| projected tokens/sec | {proj[False]['proj_tokens_per_sec']:.0f} |"
        f" {proj[True]['proj_tokens_per_sec']:.0f} |",
        f"| projected MFU (CPU-layout bytes) | {proj[False]['proj_mfu']:.2f}"
        f" | {proj[True]['proj_mfu']:.2f} |",
        f"| projected MFU (bf16-scaled bytes) | "
        f"{proj_bf16[False]['proj_mfu']:.2f} | "
        f"{proj_bf16[True]['proj_mfu']:.2f} |",
        "",
        "## What 0.35 MFU requires at these shapes",
        "",
        f"Compute side is NOT the limit: at peak the issued FLOPs take "
        f"{proj[False]['t_flops_ms']:.0f} ms/step — an MFU ceiling of "
        f"{mf / (proj[False]['t_flops_ms'] / 1e3 * V5E_PEAK_BF16):.2f}. "
        f"The program is HBM-bound: hitting MFU 0.35 needs step time "
        f"<= {t_target * 1e3:.0f} ms, i.e. HBM traffic "
        f"<= {bytes_for_target:.2e} B/step.",
        "",
        f"- XLA:CPU upper bound measured here: "
        f"{rows[False]['bytes_accessed']:.2e} B "
        f"({rows[False]['bytes_accessed'] / bytes_for_target:.1f}x over "
        "budget in fp32-biased layouts).",
        f"- bf16 layouts halve that to ~"
        f"{rows[False]['bytes_accessed'] / 2:.2e} B; XLA:TPU additionally "
        "fuses far more aggressively than XLA:CPU (whose fusion count is "
        "what this bound reflects).",
        f"- The single largest removable term is the materialized s x s "
        f"attention: b*h*s^2 softmax tensors cost ~"
        f"{16 * 12 * SEQ * SEQ * 2 * 12 * 3 / 1e9:.0f} GB/step across "
        "fwd+bwd in bf16 — the Pallas flash kernels exist precisely to "
        "delete it (ops/pallas_ops.py; unverified on hardware, "
        "interpreter-only so far).",
        "",
        "Conclusion: at b16/s1024 the step is structurally memory-bound;",
        "0.35 MFU hinges on TPU-side fusion + flash attention, not on more",
        "raw FLOPs. The first on-chip run should profile bytes, not FLOPs.",
    ]
    os.makedirs(os.path.dirname(out_md), exist_ok=True)
    with open(out_md, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(json.dumps(summary))


if __name__ == "__main__":
    main()

"""API-surface coverage audit — the auditable op registry (SURVEY.md L4).

Compares paddle_tpu's public API against the reference's checked-in public
surface (tools/ref_surface.json, extracted from the reference's __all__
lists and ``tensor_method_func``; see ref:python/paddle/__init__.py,
ref:python/paddle/tensor/__init__.py).

Three buckets per name:
  implemented — the attribute exists and is NOT an intentional-raise stub
  redirect    — the attribute exists but is tagged ``_intentional_redirect``
                (a deliberate raising shim, e.g. legacy Program-graph APIs);
                excluded from the implemented numerator and listed separately
  missing     — no such attribute

``paddle.Tensor`` names are audited on the Tensor class (methods patched on
by the op modules, the analog of monkey_patch_varbase).

Usage:  JAX_PLATFORMS=cpu python tools/op_coverage.py [--missing] [--json]
"""
from __future__ import annotations

import importlib
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

MODULE_MAP = {
    "paddle": "paddle_tpu",
    "paddle.fft": "paddle_tpu.fft",
    "paddle.signal": "paddle_tpu.signal",
    "paddle.linalg": "paddle_tpu.linalg",
    "paddle.nn": "paddle_tpu.nn",
    "paddle.nn.functional": "paddle_tpu.nn.functional",
    "paddle.sparse": "paddle_tpu.sparse",
    "paddle.sparse.nn": "paddle_tpu.sparse.nn",
    "paddle.distribution": "paddle_tpu.distribution",
    "paddle.distribution.transform": "paddle_tpu.distribution.transform",
    "paddle.optimizer": "paddle_tpu.optimizer",
    "paddle.optimizer.lr": "paddle_tpu.optimizer.lr",
    "paddle.metric": "paddle_tpu.metric",
    "paddle.vision.transforms": "paddle_tpu.vision.transforms",
    "paddle.vision.models": "paddle_tpu.vision.models",
    "paddle.vision.ops": "paddle_tpu.vision.ops",
    "paddle.vision.datasets": "paddle_tpu.vision.datasets",
    "paddle.geometric": "paddle_tpu.geometric",
    "paddle.utils.cpp_extension": "paddle_tpu.utils.cpp_extension",
    "paddle.distributed": "paddle_tpu.distributed",
    "paddle.io": "paddle_tpu.io",
    "paddle.amp": "paddle_tpu.amp",
    "paddle.autograd": "paddle_tpu.autograd",
    "paddle.jit": "paddle_tpu.jit",
    "paddle.static": "paddle_tpu.static",
    "paddle.incubate": "paddle_tpu.incubate",
    "paddle.text": "paddle_tpu.text",
    "paddle.profiler": "paddle_tpu.profiler",
    "paddle.audio.features": "paddle_tpu.audio",
    "paddle.audio.functional": "paddle_tpu.audio.functional",
    "paddle.audio.backends": "paddle_tpu.audio.backends",
    "paddle.audio.datasets": "paddle_tpu.audio.datasets",
}


def _target(ref_mod):
    """Resolve the object whose attributes carry the surface."""
    if ref_mod == "paddle.Tensor":
        from paddle_tpu.core.tensor import Tensor
        return Tensor
    our = MODULE_MAP.get(ref_mod)
    if not our:
        return None
    try:
        return importlib.import_module(our)
    except ImportError:
        return None


def _classify(obj):
    return "redirect" if getattr(obj, "_intentional_redirect", False) \
        else "implemented"


def audit(show_missing: bool = False, as_json: bool = False):
    surface = json.load(open(os.path.join(HERE, "ref_surface.json")))
    totals = {"implemented": 0, "redirect": 0, "missing": 0}
    report = {}
    for ref_mod, names in sorted(surface.items()):
        tgt = _target(ref_mod)
        buckets = {"implemented": [], "redirect": [], "missing": []}
        for n in names:
            if tgt is not None and hasattr(tgt, n):
                buckets[_classify(getattr(tgt, n))].append(n)
            else:
                buckets["missing"].append(n)
        for k in totals:
            totals[k] += len(buckets[k])
        report[ref_mod] = buckets
        r = f" +{len(buckets['redirect'])}R" if buckets["redirect"] else ""
        print(f"{ref_mod:32s} {len(buckets['implemented']):4d}/"
              f"{len(names):4d}{r}")
    total = sum(totals.values())
    pct = 100.0 * totals["implemented"] / max(1, total)
    print(f"{'TOTAL':32s} {totals['implemented']:4d}/{total:4d}  ({pct:.1f}%)"
          f"  [redirect {totals['redirect']}, missing {totals['missing']}]")
    if show_missing:
        for mod, b in report.items():
            if b["missing"]:
                print(f"\n[{mod}] missing {len(b['missing'])}:")
                for n in b["missing"]:
                    print(f"  {n}")
            if b["redirect"]:
                print(f"[{mod}] redirect {len(b['redirect'])}: "
                      f"{', '.join(b['redirect'])}")
    if as_json:
        out = {m: {"missing": b["missing"], "redirect": b["redirect"]}
               for m, b in report.items()}
        json.dump({"totals": totals, "modules": out},
                  open(os.path.join(HERE, "coverage_report.json"), "w"),
                  indent=1)
    return totals


if __name__ == "__main__":
    audit(show_missing="--missing" in sys.argv,
          as_json="--json" in sys.argv)

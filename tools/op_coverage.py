"""API-surface coverage audit — the auditable op registry (SURVEY.md L4).

Compares paddle_tpu's public API against the reference's checked-in public
surface (tools/ref_surface.json, extracted from the reference's __all__
lists; see ref:python/paddle/__init__.py, fft.py, signal.py, ...).

Usage:  JAX_PLATFORMS=cpu python tools/op_coverage.py [--missing]

Prints per-module implemented/total and the grand total; --missing lists
the names still absent (the work queue for op-surface parity).
"""
from __future__ import annotations

import importlib
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

MODULE_MAP = {
    "paddle": "paddle_tpu",
    "paddle.fft": "paddle_tpu.fft",
    "paddle.signal": "paddle_tpu.signal",
    "paddle.linalg": "paddle_tpu.linalg",
    "paddle.nn": "paddle_tpu.nn",
    "paddle.nn.functional": "paddle_tpu.nn.functional",
    "paddle.sparse": "paddle_tpu.sparse",
    "paddle.sparse.nn": "paddle_tpu.sparse.nn",
    "paddle.distribution": "paddle_tpu.distribution",
    "paddle.optimizer": "paddle_tpu.optimizer",
    "paddle.optimizer.lr": "paddle_tpu.optimizer.lr",
    "paddle.metric": "paddle_tpu.metric",
    "paddle.vision.transforms": "paddle_tpu.vision.transforms",
    "paddle.vision.models": "paddle_tpu.vision.models",
    "paddle.vision.ops": "paddle_tpu.vision.ops",
    "paddle.geometric": "paddle_tpu.geometric",
    "paddle.utils.cpp_extension": "paddle_tpu.utils.cpp_extension",
    "paddle.distributed": "paddle_tpu.distributed",
    "paddle.io": "paddle_tpu.io",
    "paddle.amp": "paddle_tpu.amp",
    "paddle.autograd": "paddle_tpu.autograd",
    "paddle.jit": "paddle_tpu.jit",
    "paddle.static": "paddle_tpu.static",
    "paddle.incubate": "paddle_tpu.incubate",
}


def audit(show_missing: bool = False):
    surface = json.load(open(os.path.join(HERE, "ref_surface.json")))
    grand_impl, grand_total = 0, 0
    all_missing = {}
    for ref_mod, names in sorted(surface.items()):
        our_mod = MODULE_MAP.get(ref_mod)
        have = set()
        if our_mod:
            try:
                m = importlib.import_module(our_mod)
                have = {n for n in names if hasattr(m, n)}
            except ImportError:
                pass
        missing = sorted(set(names) - have)
        grand_impl += len(have)
        grand_total += len(names)
        print(f"{ref_mod:28s} {len(have):4d}/{len(names):4d}")
        if missing:
            all_missing[ref_mod] = missing
    pct = 100.0 * grand_impl / max(1, grand_total)
    print(f"{'TOTAL':28s} {grand_impl:4d}/{grand_total:4d}  ({pct:.1f}%)")
    if show_missing:
        for mod, names in all_missing.items():
            print(f"\n[{mod}] missing {len(names)}:")
            for n in names:
                print(f"  {n}")
    return grand_impl, grand_total


if __name__ == "__main__":
    audit(show_missing="--missing" in sys.argv)

#!/bin/bash
# Second-tier TPU measurements, strictly AFTER the main round-4 bank
# (tools/cashout_loop_r4.sh) finishes all its stages — never competes with
# it for the single chip. Uses the same probe-gate + marker-file pattern:
#   flash_tune    — Pallas flash block-size autotune (benches/flash_tune.py)
#   bench_routed  — headline bench rerun at default config to confirm the
#                   measured attention-routing gain (flash->XLA at s1024)
set -u
cd "$(dirname "$0")/.."
LOGS=benches/tpu_logs
MARKS=$LOGS/done
mkdir -p "$LOGS" "$MARKS"

probe() {
  timeout 180 python - <<'PY'
import jax, numpy as np, time
t0 = time.time()
y = jax.jit(lambda a: a @ a)(np.ones((256, 256), np.float32))
y.block_until_ready()
d = jax.devices()[0]
assert d.platform != "cpu", f"probe landed on {d.platform}"
print(f"TPU alive: {d} matmul in {time.time()-t0:.1f}s")
PY
}

run() {
  local name=$1 t=$2; shift 2
  [ -f "$MARKS/$name" ] && { echo "[post] $name already done"; return 0; }
  local STAMP=$(date +%Y%m%d_%H%M%S)
  echo "[post] $name ..."
  timeout "$t" "$@" > "$LOGS/${name}_$STAMP.log" 2>&1
  local rc=$?
  tail -2 "$LOGS/${name}_$STAMP.log"
  echo "[post] $name rc=$rc"
  [ "$rc" -eq 0 ] && touch "$MARKS/$name"
  return $rc
}

echo "[post] waiting for the main bank to finish..."
while true; do
  all=1
  for m in flash_tpu sweep baseline decode eager hlo_tpu native; do
    [ -f "$MARKS/$m" ] || { all=0; break; }
  done
  [ "$all" -eq 1 ] && break
  sleep 600
done
echo "[post] main bank complete $(date); starting second tier"

attempt=0
while true; do
  attempt=$((attempt + 1))
  echo "[post] attempt $attempt $(date)"
  if probe > "$LOGS/post_probe_$attempt.log" 2>&1; then
    cat "$LOGS/post_probe_$attempt.log"
    run flash_tune   2400 python benches/flash_tune.py
    run bench_routed 2400 python bench.py
    # only meaningful once the sweep has published a winner; skip quietly
    if [ -f benches/BENCH_TUNED.json ]; then
      run bench_tuned 2400 env BENCH_USE_TUNED=1 python bench.py
    fi
    ok=1
    for m in flash_tune bench_routed; do [ -f "$MARKS/$m" ] || ok=0; done
    [ -f benches/BENCH_TUNED.json ] && { [ -f "$MARKS/bench_tuned" ] || ok=0; }
    [ "$ok" -eq 1 ] && { echo "[post] all second-tier stages done"; break; }
  else
    echo "[post] tunnel down"
  fi
  sleep 3000
done

#!/usr/bin/env python
"""Resilience stats CLI: dump skip/rollback/retry/preemption counters and
inspect a checkpoint directory's integrity state (mirrors
tools/cache_stats.py for core.resilience).

Usage:
    python tools/resilience_stats.py --ckpt DIR     # steps / manifests /
                                                    # resume marker of a
                                                    # TrainCheckpointer dir
    python tools/resilience_stats.py --run CMD ...  # run CMD..., report the
                                                    # run's counters
    python tools/resilience_stats.py --json         # machine-readable output

Without --run this only inspects the filesystem — it never initializes a
jax backend, so it is safe on a host whose TPU tunnel is down. With --run,
CMD executes in-process via runpy with the framework imported first, and the
delta of ``core.resilience.stats()`` across the run is reported — a healthy
chaos run shows ``sentinel.skipped`` / ``retry.*`` / ``fault.*`` counters
matching the faults it injected. Serving-side resilience lands on the same
surface: ``serving.preemptions`` (priority-admission victim evictions),
``serving.rebuilds`` / ``serving.replays`` (supervisor rebuild-and-replay
recovery), ``serving.drains`` / ``serving.drain_stragglers`` (graceful
drain) — see docs/robustness.md, "Serving under failure".
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _ckpt_report(d: str) -> dict:
    """Filesystem-only view of a TrainCheckpointer directory: step dirs,
    which steps carry a manifest, and the resume marker (no orbax import —
    validity here means "manifest present", not a data read)."""
    out = {"dir": d, "exists": os.path.isdir(d), "steps": [],
           "manifest_steps": [], "resume_marker": None}
    if not out["exists"]:
        return out
    for name in sorted(os.listdir(d)):
        if name.isdigit() and os.path.isdir(os.path.join(d, name)):
            out["steps"].append(int(name))
    mdir = os.path.join(d, "manifests")
    if os.path.isdir(mdir):
        for name in sorted(os.listdir(mdir)):
            stem = name.rsplit(".", 1)[0]
            if stem.isdigit():
                out["manifest_steps"].append(int(stem))
    marker = os.path.join(d, "RESUME.json")
    if os.path.exists(marker):
        try:
            with open(marker) as f:
                out["resume_marker"] = json.load(f)
        except (OSError, ValueError):
            out["resume_marker"] = "unreadable"
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ckpt", help="TrainCheckpointer directory to inspect")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--run", nargs=argparse.REMAINDER,
                    help="script [args...] to execute in-process; resilience "
                         "counters are reported for that run")
    args = ap.parse_args(argv)

    if args.run:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import runpy

        from paddle_tpu.core import resilience

        before = resilience.stats()
        t0 = time.perf_counter()
        sys.argv = list(args.run)
        try:
            runpy.run_path(args.run[0], run_name="__main__")
        finally:
            # if the script served traffic, exit through the graceful path:
            # drain any ServingAPI it left open so serving.drain_* counters
            # reflect a real drain and no engine exits holding live slots
            if "paddle_tpu.serving.api" in sys.modules:
                sys.modules["paddle_tpu.serving.api"].drain_all()
        wall = time.perf_counter() - t0
        delta = {k: v for k, v in resilience.stats_delta(
                     before, resilience.stats(), drop_zero=True).items()
                 if isinstance(v, (int, float)) and not isinstance(v, bool)}
        rec = {"wall_secs": round(wall, 3), "stats": delta}
        if args.ckpt:
            rec.update(_ckpt_report(args.ckpt))
        print(json.dumps(rec) if args.json else
              "\n".join([f"wall_secs: {rec['wall_secs']}"]
                        + [f"{k}: {v}" for k, v in sorted(delta.items())]))
        return 0

    if args.ckpt:
        rep = _ckpt_report(args.ckpt)
        if args.json:
            print(json.dumps(rep))
        else:
            for k, v in rep.items():
                print(f"{k}: {v}")
        return 0

    ap.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Serving stats CLI: dump the continuous-batching engine's counters/gauges
(tokens, queue depth, slot occupancy, KV-arena blocks) for a run, or show
the flag-configured engine sizing (mirrors tools/cache_stats.py /
tools/resilience_stats.py for paddle_tpu.serving).

Usage:
    python tools/serving_stats.py                # engine sizing from flags
                                                 # (no jax backend init)
    python tools/serving_stats.py --run CMD ...  # run CMD..., report the
                                                 # run's serving counters
    python tools/serving_stats.py --json         # machine-readable output

Without --run this only reports the FLAGS_serving_* / FLAGS_kv_block_size
configuration and the KV-arena bytes they imply for a given model shape —
it never initializes a jax backend, so it is safe on a host whose TPU
tunnel is down. With --run, CMD executes in-process via runpy with the
framework imported first, and the delta of ``serving.metrics.stats()``
across the run is reported — a healthy serving run shows
``tokens.generated`` climbing with ``engine.decode_compiles`` frozen after
warmup. The resilience layer's counters ride the same delta:
``scheduler.preemptions`` (starvation-triggered victim evictions),
``supervisor.rebuilds`` / ``supervisor.replays`` (transient-failure
recovery), ``api.drains`` / ``api.drain_stragglers`` / ``api.recoveries``.
So do the radix prefix cache's (``FLAGS_serving_prefix_cache``):
``prefix.hits`` / ``prefix.hit_tokens`` (prefill tokens avoided) /
``prefix.inserted_blocks`` / ``prefix.evictions`` / ``prefix.cow_copies``.
The tiered KV cache (``FLAGS_serving_kv_tiering``, ``serving.tiered``)
adds ``tier.spilled_blocks`` / ``tier.restored_blocks`` (evictions
demoted to host/disk and their compiled-scatter restores),
``tier.host_hits`` / ``tier.disk_hits`` / ``tier.misses``,
``tier.disk_corrupt`` (crc-failed loads — recomputed, never served), and
the end-of-run occupancy gauges ``tier.host_bytes`` / ``tier.host_entries``
/ ``tier.disk_bytes`` / ``tier.disk_entries``;
``FLAGS_serving_host_cache_bytes`` / ``FLAGS_serving_disk_cache_dir``
size the tiers in config mode.
Speculative decoding (``FLAGS_serving_spec_k``) adds ``spec.proposed`` /
``spec.accepted`` / ``spec.rollback_tokens`` / ``spec.emitted`` /
``spec.iterations`` (+ the ``spec.acceptance_rate`` end-of-run gauge),
and chunked prefill (``FLAGS_serving_chunked_prefill``) adds
``chunk.admits`` / ``chunk.chunks`` / ``chunk.tokens``. Quantized
serving (``FLAGS_serving_quant_weights`` / ``_kv`` / ``_draft``) adds
``quant.weight_layers`` / ``quant.draft_layers`` plus the end-of-run
mode gauges (``quant.weights`` / ``quant.kv`` / ``quant.draft`` /
``quant.draft_acceptance``) and the per-namespace arena byte gauges
(``arena.kv_bytes`` / ``arena.scale_bytes`` / ``arena.bytes.<ns>`` /
``arena.dtype.<ns>``) — the int8 memory win, observable per run.
Scenario diversity (ISSUE 12) adds per-slot sampling
(``sampling.admits`` / ``sampling.spec_fallback_slots``), constrained
decoding (``constrain.admits`` / ``constrain.mask_updates`` /
``constrain.dead_ends``), and the multi-LoRA arena (``lora.registered`` /
``lora.admits``, plus the end-of-run ``lora.slots`` / ``lora.live`` /
``lora.arena_bytes`` and per-scenario ``*.active_slots`` gauges);
``FLAGS_serving_lora_rank`` / ``FLAGS_serving_lora_adapters`` size the
arena in config mode.
The Pallas paged-attention kernels (``FLAGS_serving_paged_kernel``,
``ops.paged_attention``) add the trace-time ``kernel.decode_traces`` /
``kernel.prefill_traces`` / ``kernel.verify_traces`` counters (frozen
after warmup in a healthy run — churn never re-lowers a kernel) and the
end-of-run ``kernel.paged`` / ``kernel.tuned_entries`` gauges (mode +
tuning-store coverage for this chip, benches/TUNED_KERNELS.json).
The mesh-sharded execution core (ISSUE 14, docs/distributed.md) adds the
``mesh.devices`` / ``mesh.model_axis`` / ``mesh.data_axis`` topology
gauges — a tensor-parallel run shows ``mesh.model_axis`` > 1 with the
same frozen compile counters as a single chip. ``kernel.mesh`` /
``kernel.mesh.<namespace>`` (ISSUE 16) state the EFFECTIVE attention
route x topology per arena namespace — ``kernel@data1.model4``,
``gather@single``, ... — so a silent fallback to the gather path (Pallas
unavailable, flag off) is observable per run instead of inferred from
step times; on a multi-device mesh ``kernel@...`` means the sharded
(per-model-shard) Pallas route served every decode/prefill/spec
sub-step.
The multi-tenant gateway's counters ride it too (``serving.gateway``):
``gateway.routed`` / ``gateway.rerouted`` (journaled fail-over) /
``gateway.ejected`` / ``gateway.respawned`` (replica health) /
``gateway.affinity_routes`` / ``gateway.drains``, plus tenant admission:
``tenant.admitted`` / ``tenant.shed_rate`` / ``tenant.shed_concurrency`` /
``tenant.shed_share`` and the per-tenant ``tenant.<name>.tokens_out``
goodput counters.
The process-isolated replica fleet (``FLAGS_gateway_process_replicas``,
``serving.gateway.procpool``) adds the ``worker.*`` namespace:
``worker.spawns`` / ``worker.exits`` / ``worker.kills`` (processes that
died to a signal — a kill -9'd worker shows up here, not as a hang) /
``worker.hangs`` (missed-heartbeat or RPC-deadline ejections) /
``worker.heartbeats`` / ``worker.heartbeat_misses`` /
``worker.protocol_errors`` (malformed RPC frames — classified eject,
never a hung handle), plus the per-worker end-of-run gauges
``worker.<i>.pid`` / ``worker.<i>.heartbeat_age_ms`` /
``worker.<i>.restarts`` — a healthy fleet shows every heartbeat age far
under ``FLAGS_gateway_heartbeat_interval * FLAGS_gateway_heartbeat_misses``
and restart counts flat after warmup.
Disaggregated prefill/decode serving (``FLAGS_gateway_prefill_replicas``
/ ``FLAGS_gateway_decode_replicas``, ``serving.disagg``) adds the
``disagg.*`` namespace: ``disagg.handoffs`` (prefill → decode moves) /
``disagg.prefill_routes`` / ``disagg.decode_routes`` /
``disagg.degraded_routes`` (a role pool was empty and the request ran
unified), the restore-ahead planner's ``disagg.prefetches`` /
``disagg.prefetched_chains`` / ``disagg.prefetched_blocks``, and the
publish side's ``tier.published_blocks`` (full KV blocks write-through-
published to the shared disk tier during chunked prefill).
The crash-safe gateway (``FLAGS_gateway_wal``, ``serving.gateway.wal``)
adds the ``wal.*`` namespace: ``wal.records`` / ``wal.accepted`` /
``wal.emitted_tokens`` / ``wal.terminals`` (journal writes),
``wal.commits`` (batched fsyncs — one per pump sweep, not per token),
``wal.rotations`` / ``wal.compactions`` / ``wal.carried``
(segment lifecycle: sealed segments whose every stream is terminal are
deleted, live/result records carried forward), ``wal.replayed`` /
``wal.replayed_live`` / ``wal.replayed_results`` (restart recovery) and
``wal.torn_tail`` (crc/length-truncated tail records discarded on
replay — also bumped on the resilience surface), plus the end-of-run
``wal.segments`` / ``wal.bytes`` occupancy gauges.
The observability plane (ISSUE 17, docs/observability.md) adds the
``latency.*`` histograms (ttft, inter_token, queue_wait, prefill,
decode_step, restore, e2e, ... — recorded host-side around compiled
calls) rendered as a per-run p50/p95/p99 percentile table, plus the
``telemetry.spans`` / ``telemetry.spans_dropped`` trace-ring counters
(the headline ``serving.ttft_p50_ms`` / ``serving.inter_token_p99_ms``
percentiles live on the shared ``memory_stats`` surface).
A run report also prints the end-of-run arena/prefix/gateway gauges
(occupancy, cached/resident blocks, high-water, fragmentation, replica
health) next to the delta — point-in-time state, not differenced.
After the script returns, every ServingAPI it left open is drained
(``serving.drain_all``) so the reported run always exercises the graceful
shutdown path and no engine exits holding live slots or arena blocks.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _flag_env(name: str, default):
    raw = os.environ.get("FLAGS_" + name)
    if raw is None:
        return default
    try:
        return type(default)(raw)
    except ValueError:
        return default


def _config_report() -> dict:
    # mirror core.flags defaults without importing the framework
    slots = _flag_env("serving_slots", 8)
    block = _flag_env("kv_block_size", 16)
    return {
        "serving_slots": slots,
        "kv_block_size": block,
        "serving_max_queue": _flag_env("serving_max_queue", 0),
        "serving_prefill_bucket_min": _flag_env("serving_prefill_bucket_min",
                                                16),
        "decode_donate": _flag_env("decode_donate", 1),
        # resilience layer (priority preemption / supervisor / drain)
        "serving_starvation_steps": _flag_env("serving_starvation_steps", 8),
        "serving_max_rebuilds": _flag_env("serving_max_rebuilds", 3),
        "serving_rebuild_window": _flag_env("serving_rebuild_window", 200),
        "serving_drain_grace": _flag_env("serving_drain_grace", 30.0),
        # radix prefix cache (content-addressed KV block sharing)
        "serving_prefix_cache": _flag_env("serving_prefix_cache", 0),
        "serving_cache_affinity": _flag_env("serving_cache_affinity", 0),
        # tiered KV cache (serving.tiered: host-RAM/disk spill + restore)
        "serving_kv_tiering": _flag_env("serving_kv_tiering", 0),
        "serving_host_cache_bytes": _flag_env("serving_host_cache_bytes",
                                              256 * 1024 * 1024),
        "serving_disk_cache_dir": _flag_env("serving_disk_cache_dir", ""),
        "serving_disk_cache_bytes": _flag_env(
            "serving_disk_cache_bytes", 8 * 1024 * 1024 * 1024),
        "serving_arena_invariants": _flag_env("serving_arena_invariants", 0),
        # speculative decoding + chunked prefill (serving.spec_decode)
        "serving_spec_k": _flag_env("serving_spec_k", 0),
        "serving_chunked_prefill": _flag_env("serving_chunked_prefill", 0),
        # quantized serving (int8 weights / int8 KV arena / int8 draft)
        "serving_quant_weights": _flag_env("serving_quant_weights", 0),
        "serving_quant_kv": _flag_env("serving_quant_kv", 0),
        "serving_quant_draft": _flag_env("serving_quant_draft", 0),
        # multi-LoRA adapter arena (serving.adapters; 0 rank = off)
        "serving_lora_rank": _flag_env("serving_lora_rank", 0),
        "serving_lora_adapters": _flag_env("serving_lora_adapters", 4),
        # Pallas paged-attention kernels (ops.paged_attention; 0 = the
        # XLA gather path)
        "serving_paged_kernel": _flag_env("serving_paged_kernel", 0),
        # multi-tenant gateway (serving.gateway: router/tenancy/front door)
        "serving_replicas": _flag_env("serving_replicas", 2),
        "gateway_port": _flag_env("gateway_port", 8100),
        "gateway_affinity_slack": _flag_env("gateway_affinity_slack", 2),
        "gateway_max_reroutes": _flag_env("gateway_max_reroutes", 3),
        "gateway_respawn_backoff": _flag_env("gateway_respawn_backoff", 0.5),
        "gateway_tenant_rate": _flag_env("gateway_tenant_rate", 0.0),
        "gateway_tenant_burst": _flag_env("gateway_tenant_burst", 0.0),
        "gateway_tenant_concurrency": _flag_env("gateway_tenant_concurrency",
                                                0),
        "gateway_fair_share": _flag_env("gateway_fair_share", 1),
        # process-isolated replica fleet (serving.gateway.procpool;
        # 0 = in-process thread replicas, bit-for-bit the same routing)
        "gateway_process_replicas": _flag_env("gateway_process_replicas", 0),
        "gateway_heartbeat_interval": _flag_env("gateway_heartbeat_interval",
                                                0.2),
        "gateway_heartbeat_misses": _flag_env("gateway_heartbeat_misses", 3),
        "gateway_worker_timeout": _flag_env("gateway_worker_timeout", 10.0),
        # disaggregated prefill/decode serving (serving.disagg; both role
        # counts > 0 turns the process fleet into a DisaggReplicaPool)
        "gateway_prefill_replicas": _flag_env("gateway_prefill_replicas", 0),
        "gateway_decode_replicas": _flag_env("gateway_decode_replicas", 0),
        "gateway_prefetch": _flag_env("gateway_prefetch", 0),
        "serving_tier_publish": _flag_env("serving_tier_publish", 0),
        "serving_publish_chunks": _flag_env("serving_publish_chunks", 0),
        # crash-safe gateway WAL (serving.gateway.wal; 0 = no journal,
        # bit-for-bit the non-durable gateway)
        "gateway_wal": _flag_env("gateway_wal", 0),
        "gateway_wal_dir": _flag_env("gateway_wal_dir", ""),
        "gateway_wal_segment_bytes": _flag_env("gateway_wal_segment_bytes",
                                               1 << 20),
        "gateway_wal_results": _flag_env("gateway_wal_results", 256),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--drain-grace", type=float, default=0.0,
                    help="grace budget (seconds) for the post-run drain of "
                         "any ServingAPI the script left open (default 0: "
                         "stragglers fail with the retriable "
                         "RequestDrainedError)")
    ap.add_argument("--run", nargs=argparse.REMAINDER,
                    help="script [args...] to execute in-process; serving "
                         "counters are reported for that run, and every "
                         "ServingAPI left open is drained afterwards")
    args = ap.parse_args(argv)

    if args.run:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import runpy

        from paddle_tpu.serving import metrics, telemetry

        before = metrics.stats()
        hists_before = telemetry.histograms()
        t0 = time.perf_counter()
        sys.argv = list(args.run)
        try:
            runpy.run_path(args.run[0], run_name="__main__")
        finally:
            # shutdown epilogue: drain every ServingAPI the script left
            # open so the run always exits through the graceful path (no
            # engine holding live slots/blocks) and the drain counters are
            # part of the reported delta
            from paddle_tpu import serving

            serving.drain_all(grace=args.drain_grace)
        wall = time.perf_counter() - t0
        delta = {k: v for k, v in metrics.stats_delta(
                     before, metrics.stats(), drop_zero=True).items()
                 if isinstance(v, (int, float)) and not isinstance(v, bool)}
        toks = delta.get("tokens.generated", 0)
        # end-of-run arena/prefix gauges: point-in-time occupancy picture
        # (cached blocks, high-water, fragmentation), NOT differenced
        gauges = {k: v for k, v in metrics.gauges().items()
                  if k.split(".")[0] in ("arena", "prefix", "slots",
                                         "spec", "queue", "quant",
                                         "gateway", "tenant", "sampling",
                                         "constrain", "lora", "kernel",
                                         "mesh", "tier", "telemetry",
                                         "serving", "worker", "disagg",
                                         "wal")}
        # latency histograms recorded during the run (ISSUE 17): the same
        # per-run delta discipline as the counters, rendered as percentiles
        hists = telemetry.histograms_delta(hists_before)
        latency = {name: {"count": h.n,
                          "p50_ms": round(h.percentile(50) * 1e3, 3),
                          "p95_ms": round(h.percentile(95) * 1e3, 3),
                          "p99_ms": round(h.percentile(99) * 1e3, 3),
                          "mean_ms": round(h.mean() * 1e3, 3)}
                   for name, h in sorted(hists.items())}
        rec = {"wall_secs": round(wall, 3), "stats": delta,
               "gauges": gauges, "latency": latency,
               "tokens_per_sec": round(toks / wall, 2) if wall > 0 else None}
        if args.json:
            print(json.dumps(rec))
        else:
            print("\n".join([f"wall_secs: {rec['wall_secs']}",
                             f"tokens_per_sec: {rec['tokens_per_sec']}"]
                            + [f"{k}: {v}" for k, v in sorted(delta.items())]
                            + [f"gauge {k}: {v}"
                               for k, v in sorted(gauges.items())]))
            table = telemetry.percentile_table(hists)
            if table:
                print(table)
        return 0

    rep = _config_report()
    if args.json:
        print(json.dumps(rep))
    else:
        for k, v in rep.items():
            print(f"{k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

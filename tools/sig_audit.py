"""Signature-parity audit: argument names + defaults vs the reference.

The name audit (op_coverage.py) can say "matmul exists" while our matmul
silently lacks ``transpose_y`` or defaults it differently — invisible drift
(VERDICT r3 missing #5). This audit compares, per surface in
tools/ref_signatures.json (extracted by extract_ref_signatures.py from
ref:python/paddle — e.g. ref:python/paddle/tensor/__init__.py:302's method
surface and the yaml-generated arg contracts in ref:paddle/phi/api/yaml/
ops.yaml), every reference parameter against our live signature:

  pass     — every reference param is accepted: same-name param present
             (defaults equal after normalization), or absorbed by **kwargs
  diverge  — a reference param is missing, or its default differs

Positional ORDER is not enforced beyond the reference params appearing in
relative order among our named params; our extra params (TPU knobs) are
allowed. The first arg of Tensor methods (x/self) is skipped on both sides.

Usage: JAX_PLATFORMS=cpu python tools/sig_audit.py [--diverging] [--json]
"""
from __future__ import annotations

import importlib
import inspect
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

MODULE_MAP = {
    "paddle": "paddle_tpu",
    "paddle.nn": "paddle_tpu.nn",
    "paddle.nn.functional": "paddle_tpu.nn.functional",
    "paddle.optimizer": "paddle_tpu.optimizer",
    "paddle.optimizer.lr": "paddle_tpu.optimizer.lr",
    "paddle.linalg": "paddle_tpu.linalg",
    "paddle.fft": "paddle_tpu.fft",
    "paddle.signal": "paddle_tpu.signal",
    "paddle.distribution": "paddle_tpu.distribution",
    "paddle.vision.transforms": "paddle_tpu.vision.transforms",
    "paddle.metric": "paddle_tpu.metric",
    "paddle.sparse": "paddle_tpu.sparse",
}

# normalized default equivalences: the reference writes these spellings
# interchangeably across its own modules
_EQUIV = [
    {"None", "'None'"},
    {"'float32'", "'float32'"},
    {"0", "0.0"}, {"1", "1.0"}, {"-1", "-1.0"},
    {"False", "0"}, {"True", "1"},
]


def _norm(r: str) -> str:
    r = r.strip()
    if r.startswith("'") and r.endswith("'"):
        return r
    try:
        v = eval(r, {"__builtins__": {}}, {})  # literals only
        if isinstance(v, float) and v == int(v):
            return repr(int(v))
        if isinstance(v, (list, tuple)):  # [0, 1] and (0, 1) are one default
            return repr(tuple(v))
        return repr(v)
    except Exception:
        return r


def _defaults_equal(ref: str, ours) -> bool:
    if ours is inspect.Parameter.empty:
        return False
    o = _norm(repr(ours))
    rn = _norm(ref)
    if o == rn:
        return True
    for eq in _EQUIV:
        if o in eq and rn in eq:
            return True
    return False


def _target(mod):
    if mod == "paddle.Tensor":
        from paddle_tpu.core.tensor import Tensor

        return Tensor
    name = MODULE_MAP.get(mod)
    if name is None:
        return None
    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def _our_sig(obj):
    if inspect.isclass(obj):
        obj = obj.__init__
    try:
        return inspect.signature(obj)
    except (TypeError, ValueError):
        return None


def _check(name, ref_sig, obj, skip_first):
    sig = _our_sig(obj)
    if sig is None:
        return ["uninspectable"]
    params = list(sig.parameters.values())
    if params and params[0].name in ("self", "cls"):
        params = params[1:]
    ref_params = list(ref_sig["params"])
    if ref_params and ref_params[0] in ("self", "cls"):
        ref_params = ref_params[1:]
    if skip_first and ref_params:
        ref_params = ref_params[1:]
        if params and params[0].kind not in (
                inspect.Parameter.VAR_POSITIONAL,
                inspect.Parameter.VAR_KEYWORD):
            params = params[1:]
    ours = {p.name: p for p in params}
    has_kwargs = any(p.kind == inspect.Parameter.VAR_KEYWORD for p in params)
    has_varargs = any(p.kind == inspect.Parameter.VAR_POSITIONAL
                      for p in params)
    issues = []
    order = [p.name for p in params]
    last_idx = -1
    for rp in ref_params:
        if rp in ("name",):  # debug-name arg: cosmetic everywhere
            if rp not in ours and not has_kwargs:
                issues.append("missing name kwarg")
            continue
        if rp not in ours:
            if has_kwargs or has_varargs:
                continue  # absorbed
            issues.append(f"missing param '{rp}'")
            continue
        idx = order.index(rp)
        if idx < last_idx:
            issues.append(f"param '{rp}' out of order")
        last_idx = idx
        rdef = ref_sig["defaults"].get(rp)
        odef = ours[rp].default
        if rdef is None:
            continue  # reference has no default -> nothing to compare
        if not _defaults_equal(rdef, odef):
            issues.append(
                f"default '{rp}': ref {rdef} != ours "
                f"{'<required>' if odef is inspect.Parameter.empty else repr(odef)}")
    return issues


def audit(show_diverging=False, as_json=False):
    ref = json.load(open(os.path.join(HERE, "ref_signatures.json")))
    totals = {"pass": 0, "diverge": 0, "unchecked": 0}
    report = {}
    for mod, entries in sorted(ref.items()):
        tgt = _target(mod)
        skip_first = mod == "paddle.Tensor"
        ok, div = [], {}
        for name, rsig in sorted(entries.items()):
            obj = getattr(tgt, name, None) if tgt is not None else None
            if obj is None or not callable(obj):
                totals["unchecked"] += 1  # name-audit's territory
                continue
            if getattr(obj, "_intentional_redirect", False):
                totals["unchecked"] += 1
                continue
            issues = _check(name, rsig, obj, skip_first)
            if issues:
                div[name] = issues
                totals["diverge"] += 1
            else:
                ok.append(name)
                totals["pass"] += 1
        report[mod] = {"pass": ok, "diverge": div}
        n = len(ok) + len(div)
        pct = 100.0 * len(ok) / max(1, n)
        print(f"{mod:24s} {len(ok):4d}/{n:4d} signatures match ({pct:.1f}%)")
    n = totals["pass"] + totals["diverge"]
    pct = 100.0 * totals["pass"] / max(1, n)
    print(f"{'TOTAL':24s} {totals['pass']:4d}/{n:4d}  ({pct:.1f}%)  "
          f"[unchecked {totals['unchecked']}]")
    if show_diverging:
        for mod, r in report.items():
            for name, issues in r["diverge"].items():
                print(f"  {mod}.{name}: {'; '.join(issues)}")
    if as_json:
        json.dump({"totals": totals,
                   "modules": {m: r["diverge"] for m, r in report.items()}},
                  open(os.path.join(HERE, "sig_report.json"), "w"), indent=1)
    return pct, report


if __name__ == "__main__":
    audit("--diverging" in sys.argv, "--json" in sys.argv)

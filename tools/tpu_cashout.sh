#!/bin/bash
# Cash out the banked TPU perf work the moment the axon tunnel answers.
# Run from the repo root: bash tools/tpu_cashout.sh
# Probes the chip with a short-timeout matmul, then runs the full recorded
# sequence (sweep -> bench.py -> all baseline configs -> decode -> eager ->
# native real-plugin test), logging to benches/tpu_logs/ and appending
# results to benches/BASELINE_RESULTS.jsonl. Every stage has its own
# timeout so a mid-sequence tunnel drop cannot hang the run.
set -u
cd "$(dirname "$0")/.."
LOGS=benches/tpu_logs
mkdir -p "$LOGS"
STAMP=$(date +%Y%m%d_%H%M%S)

probe() {
  timeout 180 python - <<'PY'
import jax, numpy as np, time
t0 = time.time()
x = np.ones((256, 256), np.float32)
y = jax.jit(lambda a: a @ a)(x)
y.block_until_ready()
d = jax.devices()[0]
assert d.platform != "cpu", f"probe landed on {d.platform}"
print(f"TPU alive: {d} matmul in {time.time()-t0:.1f}s")
PY
}

echo "[cashout] probing tunnel..."
if ! probe > "$LOGS/probe_$STAMP.log" 2>&1; then
  echo "[cashout] tunnel DOWN (see $LOGS/probe_$STAMP.log)"
  exit 3
fi
cat "$LOGS/probe_$STAMP.log"

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 t=$2; shift 2
  echo "[cashout] $name ..."
  timeout "$t" "$@" > "$LOGS/${name}_$STAMP.log" 2>&1
  local rc=$?
  tail -2 "$LOGS/${name}_$STAMP.log"
  echo "[cashout] $name rc=$rc"
}

run sweep     5400 python benches/sweep.py
run bench     2400 python bench.py
run baseline  7200 python benches/baseline.py lenet resnet50 ernie gpt-hybrid widedeep
run decode    2400 python benches/decode_bench.py
run eager     1800 python tools/eager_bench.py
run ps_spill  3600 python benches/ps_spill_bench.py 2.0 256
run native   1800 env PADDLE_TPU_NATIVE_TPU_TEST=1 python -m pytest tests/test_native_infer.py -k real_plugin -q
run flash     2400 python -m pytest tests/test_flash_attention.py -q
# TPU-compiled cost analysis: real bf16 bytes-accessed + TPU fusion counts,
# written to benches/HLO_ANALYSIS_TPU.md (compare against the CPU
# upper-bound report in benches/HLO_ANALYSIS.md)
run hlo_tpu   2400 env HLO_PLATFORM=tpu python tools/hlo_analysis.py
run ps_async  1200 python benches/ps_async_bench.py 5 40
echo "[cashout] done; records in benches/BASELINE_RESULTS.jsonl, logs in $LOGS/"

#!/bin/bash
# Probe the TPU tunnel every 120s; log status; on success touch a flag file.
LOG=/root/repo/benches/tpu_logs/probe_r5.log
mkdir -p /root/repo/benches/tpu_logs
while true; do
  ts=$(date -u +%FT%TZ)
  out=$(timeout 90 python -c "import jax; d=jax.devices(); print('PLAT', d[0].platform, len(d))" 2>&1 | grep "^PLAT" | tail -1)
  # the axon tunnel reports the chip under the experimental 'axon' platform
  # name (core/device.py maps axon->tpu); anything non-cpu that answered is live
  if echo "$out" | grep -Eq "^PLAT (tpu|axon)"; then
    echo "$ts LIVE $out" >> "$LOG"
    touch /root/repo/benches/tpu_logs/TPU_LIVE
  else
    echo "$ts DEAD $out" >> "$LOG"
    rm -f /root/repo/benches/tpu_logs/TPU_LIVE
  fi
  sleep 120
done

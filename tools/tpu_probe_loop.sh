#!/bin/bash
# Probe the TPU tunnel every 120s; log status; on success touch a flag file.
LOG=/root/repo/benches/tpu_logs/probe_r5.log
mkdir -p /root/repo/benches/tpu_logs
while true; do
  ts=$(date -u +%FT%TZ)
  out=$(timeout 90 python -c "import jax; d=jax.devices(); print(d[0].platform, len(d))" 2>&1 | tail -1)
  if echo "$out" | grep -q "^tpu"; then
    echo "$ts LIVE $out" >> "$LOG"
    touch /root/repo/benches/tpu_logs/TPU_LIVE
  else
    echo "$ts DEAD $out" >> "$LOG"
    rm -f /root/repo/benches/tpu_logs/TPU_LIVE
  fi
  sleep 120
done

#!/usr/bin/env python
"""Request-lifecycle trace export: dump buffered span events as Chrome
trace-event JSON (``chrome://tracing`` / Perfetto's ``traceEvents``
format) so one request's SUBMITTED -> QUEUED -> ADMITTED -> FIRST_TOKEN
-> ... -> FINISHED timeline — preemptions, re-routes and replays
included — renders as a swimlane per trace_id.

Usage:
    python tools/trace_dump.py --run CMD [args...] [-o trace.json]
        Execute CMD in-process with FLAGS_serving_telemetry forced on,
        then export every span the run buffered (the
        tools/serving_stats.py --run harness, pointed at the trace ring
        instead of the counters).
    python tools/trace_dump.py --url http://HOST:PORT --request-id ID
        Fetch one trace from a live gateway's ``GET /v1/trace/<id>``
        (the gateway resolves a request_id to its trace_id).
    python tools/trace_dump.py --input spans.json
        Convert an already-captured span-event array (the ``events``
        field of a ``/v1/trace`` response, or a prior --raw dump).

With ``--raw`` the untranslated span dicts are written instead of the
Chrome form — the lossless capture to convert or diff later. Spans are
only buffered while ``FLAGS_serving_telemetry`` is on and the ring
(``FLAGS_serving_trace_events``) drops oldest-first, so an empty export
from a live system means "flag off or spans aged out", not "no traffic"
(``telemetry.spans_dropped`` counts the aged-out tail).
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _fetch(url: str, request_id: str) -> list:
    from urllib.request import urlopen

    full = url.rstrip("/") + "/v1/trace/" + request_id
    with urlopen(full, timeout=10.0) as resp:
        body = json.loads(resp.read().decode())
    return list(body.get("events", []))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-o", "--output", default="",
                    help="output path (default: stdout)")
    ap.add_argument("--raw", action="store_true",
                    help="write the raw span dicts, not Chrome trace JSON")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--run", nargs=argparse.REMAINDER,
                     help="script [args...] to execute in-process with "
                          "telemetry forced on; its span ring is exported")
    src.add_argument("--url", default="",
                     help="gateway base URL to fetch one trace from "
                          "(requires --request-id)")
    src.add_argument("--input", default="",
                     help="JSON file holding a span-event array (or a "
                          "/v1/trace response object)")
    ap.add_argument("--request-id", default="",
                    help="request_id (or trace_id) to fetch with --url")
    args = ap.parse_args(argv)

    # force the span gate BEFORE the framework import reads the env
    if args.run:
        os.environ.setdefault("FLAGS_serving_telemetry", "1")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.serving import telemetry

    if args.run:
        import runpy

        sys.argv = list(args.run)
        try:
            runpy.run_path(args.run[0], run_name="__main__")
        finally:
            from paddle_tpu import serving

            serving.drain_all(grace=0.0)
        events = telemetry.trace_events()
    elif args.url:
        if not args.request_id:
            ap.error("--url requires --request-id")
        events = _fetch(args.url, args.request_id)
    else:
        with open(args.input, "r", encoding="utf-8") as f:
            body = json.load(f)
        events = list(body.get("events", []) if isinstance(body, dict)
                      else body)

    payload = (events if args.raw
               else {"traceEvents": telemetry.chrome_events(events),
                     "displayTimeUnit": "ms"})
    text = json.dumps(payload, indent=None, separators=(",", ":"))
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"{len(events)} span(s) -> {args.output}", file=sys.stderr)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
